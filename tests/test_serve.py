"""Continuous-batching inference subsystem (serve/, serve.py; ISSUE 3):

- the tier-1 acceptance smoke: 8 staggered mixed-length requests through
  a 4-slot engine — greedy outputs token-identical to one-shot
  generate(), completions interleaving across admission waves, the
  emitted JSONL passing metrics_lint and serve_report,
- per-slot top-k sampling (determinism under a fixed rng; top_k=1 ==
  greedy),
- checkpoint -> serve round trip (CheckpointManager save, template-free
  restore in serve.py, served == generate() on the restored params),
- schema v3 records + v1/v2 back-compat,
- queue/slot-pool unit coverage and the serve.py CLI surface.

All engine tests share one slot geometry (SLOTS=4, MAX_LEN=32) and one
generate() max_len so the compiled decode programs are built once per
session — the suite rides tier-1 and must stay cheap.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import serve as serve_mod
from apex_example_tpu import obs
from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.serve import (Request, RequestQueue, ServeEngine,
                                    SlotPool, parse_range,
                                    synthetic_requests)

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SLOTS, MAX_LEN = 4, 32


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _run_engine(model, params, requests, rng_seed=0, sink=None,
                run_id=None, max_steps=2000):
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(rng_seed), sink=sink,
                      run_id=run_id)
    eng.queue.submit_all(requests)
    eng.queue.close()
    comps = eng.run(max_steps=max_steps)
    return eng, comps


# ------------------------------------------- tier-1 acceptance smoke

def test_continuous_batching_smoke(model_and_params, tmp_path, capsys):
    """The acceptance bar: >= 8 synthetic requests, staggered arrivals,
    mixed prompt/output lengths, SLOTS=4 — greedy outputs token-identical
    to one-shot generate(), completions interleaved across admission
    waves, JSONL lints, serve_report shows nonzero TTFT/TPOT."""
    model, params = model_and_params
    path = str(tmp_path / "serve.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={"slots": SLOTS, "max_len": MAX_LEN},
                       arch="gpt_tiny")
    reqs = synthetic_requests(8, vocab_size=model.vocab_size, seed=3,
                              prompt_len=(3, 8), max_new=(3, 12),
                              stagger=4)
    # mixed lengths actually present
    assert len({len(r.prompt) for r in reqs}) > 1
    assert len({r.max_new_tokens for r in reqs}) > 1
    eng, comps = _run_engine(model, params, reqs, sink=sink,
                             run_id=emitter.run_id)
    sink.write(eng.summary_record())
    sink.close()
    assert len(comps) == 8

    # (a) token-identical to the one-shot decode path: generate() at the
    # shared max_len, compared on the request's output budget prefix.
    by_uid = {c.request.uid: c for c in comps}
    for r in reqs:
        c = by_uid[r.uid]
        P = len(r.prompt)
        n = len(c.tokens)
        assert n == min(r.max_new_tokens, MAX_LEN - P)
        ref = generate(model, params, jnp.asarray([r.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(np.asarray(ref)[0, P:P + n],
                                      np.asarray(c.tokens, np.int32),
                                      err_msg=r.uid)

    # (b) continuous batching actually happened: some request was
    # admitted while an earlier-admitted one was still decoding, and
    # slots were reused across admission waves.
    assert any(a.admitted_step < b.admitted_step <= a.finished_step
               for a in comps for b in comps)
    slot_uses = [c.slot for c in comps]
    assert len(slot_uses) > len(set(slot_uses))      # some slot reused
    assert eng.pool.free_count == SLOTS              # all evicted

    # (c) the stream is schema-valid and the report derives nonzero
    # latency percentiles from it.
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(path)
    assert code == 0, errors
    records = obs.read_jsonl(path)
    reqs_rec = [r for r in records if r["record"] == "request_complete"]
    assert len(reqs_rec) == 8
    assert all(r["ttft_ms"] > 0 and r["tpot_ms"] > 0 for r in reqs_rec)
    summary = records[-1]
    assert summary["record"] == "serve_summary"
    assert summary["requests"] == 8
    assert summary["ttft_ms"]["p50"] > 0
    assert summary["tpot_ms"]["p50"] > 0
    report = _load_tool("serve_report")
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "ttft_ms" in out and "tpot_ms" in out
    assert "finish reasons: length x8" in out


# ------------------------------------------------- per-slot sampling

def test_topk_sampling_deterministic_and_topk1_greedy(model_and_params):
    """Satellite: per-slot top-k — fixed rng => identical streams;
    top_k=1 collapses to greedy regardless of temperature."""
    model, params = model_and_params
    mk = lambda k, t: synthetic_requests(
        4, vocab_size=model.vocab_size, seed=5, prompt_len=(3, 6),
        max_new=(4, 8), temperature=t, top_k=k, stagger=2)
    _, c1 = _run_engine(model, params, mk(3, 1.0), rng_seed=11)
    _, c2 = _run_engine(model, params, mk(3, 1.0), rng_seed=11)
    toks = lambda comps: [c.tokens for c in
                          sorted(comps, key=lambda c: c.request.uid)]
    assert toks(c1) == toks(c2)                      # deterministic
    _, ck = _run_engine(model, params, mk(1, 1.5), rng_seed=11)
    _, cg = _run_engine(model, params, mk(0, 0.0), rng_seed=7)
    assert toks(ck) == toks(cg)                      # top_k=1 == greedy


def test_eos_finishes_request(model_and_params):
    model, params = model_and_params
    prompt = [5, 9, 13]
    ref = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_len=MAX_LEN)
    first = int(np.asarray(ref)[0, len(prompt)])
    req = Request(prompt=prompt, max_new_tokens=10, eos_id=first)
    _, comps = _run_engine(model, params, [req])
    assert len(comps) == 1
    assert comps[0].finish_reason == "eos"
    assert comps[0].tokens == [first]


# -------------------------------------- checkpoint -> serve round trip

def test_checkpoint_serve_round_trip(model_and_params, tmp_path, capsys):
    """Satellite: save a tiny trained GPT state with CheckpointManager,
    restore in serve.py (template-free), served greedy outputs match
    direct generate() on the restored params."""
    import optax

    from apex_example_tpu import amp
    from apex_example_tpu.data import lm_batch
    from apex_example_tpu.engine import create_train_state, make_train_step
    from apex_example_tpu.utils.checkpoint import (CheckpointManager,
                                                   restore_params)
    from apex_example_tpu.workloads import lm_loss

    model, _ = model_and_params
    V = model.vocab_size
    policy, scaler = amp.initialize("O0")
    toks = lm_batch(jnp.asarray(0, jnp.int32), batch_size=4, seq_len=16,
                    vocab_size=V, seed=0)
    batch = (toks[:, :-1], toks[:, 1:])
    state = create_train_state(jax.random.PRNGKey(0), model,
                               optax.adam(1e-3), batch[0][:1], policy,
                               scaler)
    step_fn = jax.jit(make_train_step(model, optax.adam(1e-3), policy,
                                      loss_fn=lm_loss,
                                      compute_accuracy=False))
    for _ in range(2):                       # "trained", cheaply
        state, _metrics = step_fn(state, batch)
    ckpt_dir = str(tmp_path / "ckpt")
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(state)
    mgr.close()

    restored = restore_params(ckpt_dir)
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    argv = ["--arch", "gpt_tiny", "--checkpoint-dir", ckpt_dir,
            "--requests", "4", "--slots", str(SLOTS), "--max-len",
            str(MAX_LEN), "--prompt-len", "3:6", "--max-new", "4:8",
            "--stagger", "2", "--seed", "9"]
    comps, summary, rc = serve_mod.run_serve(
        serve_mod.build_parser().parse_args(argv))
    assert rc == 0 and len(comps) == 4
    assert "checkpoint" in capsys.readouterr().out
    for c in comps:
        P = len(c.request.prompt)
        n = len(c.tokens)
        ref = generate(model, restored,
                       jnp.asarray([c.request.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(np.asarray(ref)[0, P:P + n],
                                      np.asarray(c.tokens, np.int32))


# -------------------------------------------------- serve.py CLI

def test_serve_cli_smoke(tmp_path, capsys):
    """Random-init smoke from the CLI: rc 0, JSONL lints, report runs."""
    path = str(tmp_path / "cli.jsonl")
    rc = serve_mod.main(["--requests", "6", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN), "--prompt-len", "3:8",
                         "--max-new", "3:12", "--stagger", "3",
                         "--metrics-jsonl", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "6/6 completed" in out and "ttft_ms" in out
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(path)
    assert code == 0, errors
    records = obs.read_jsonl(path)
    assert records[0]["record"] == "run_header"
    assert records[0]["schema"] == obs_schema.SCHEMA_VERSION
    assert records[-1]["record"] == "serve_summary"


def test_serve_cli_steps_cap(tmp_path, capsys):
    """A --steps cap that strands requests exits 1 and says so."""
    rc = serve_mod.main(["--requests", "4", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN), "--prompt-len", "4",
                         "--max-new", "8", "--steps", "3"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "unfinished" in captured.err


def test_serve_cli_rejects_prompt_longer_than_cache():
    with pytest.raises(SystemExit):
        serve_mod.main(["--prompt-len", "40", "--max-len", "32"])


# ------------------------------------------------------- schema v3

def test_schema_v3_serving_records_validate():
    req = {"record": "request_complete", "time": 1.0, "request_id": "r-1",
           "prompt_tokens": 5, "output_tokens": 7, "ttft_ms": 12.5,
           "tpot_ms": 1.5, "finish_reason": "length", "slot": 2,
           "queue_wait_ms": 3.0, "e2e_ms": 25.0, "admitted_step": 4,
           "finished_step": 11, "temperature": 0.0, "top_k": 0,
           "run_id": "x"}
    summ = {"record": "serve_summary", "time": 1.0, "requests": 8,
            "output_tokens": 64, "tokens_per_sec": 100.0, "steps": 40,
            "compute_steps": 39, "slots": 4, "max_len": 32,
            "duration_s": 1.0, "occupancy": 0.6,
            "ttft_ms": {"p50": 1.0, "p95": 2.0, "max": 2.0},
            "tpot_ms": {"p50": 1.0, "p95": 2.0, "max": 2.0},
            "queue_wait_ms": {"p50": 0.0, "p95": 1.0, "max": 1.0}}
    header = {"record": "run_header", "schema": 3, "time": 0.0,
              "run_id": "x", "num_devices": 1, "process_index": 0,
              "platform": "cpu", "config": {}}
    assert obs.validate_record(req) == []
    assert obs.validate_record(summ) == []
    assert obs_schema.validate_stream([header, req, summ]) == []
    # malformed: missing required field / unknown field still rejected
    assert obs.validate_record({"record": "request_complete"})
    assert obs.validate_record(dict(summ, typo=1))


def test_schema_v1_v2_streams_still_validate():
    """v3 is a strict superset: pre-PR streams keep validating."""
    v1 = [{"record": "run_header", "schema": 1, "time": 0.0, "run_id": "r",
           "num_devices": 1, "process_index": 0, "platform": "cpu",
           "config": {}},
          {"record": "step", "step": 1, "epoch": 0, "loss": 1.0,
           "scale": 1.0, "step_time_ms": 5.0, "items_per_sec": 10.0},
          {"record": "run_summary", "steps": 1, "overflow_count": 0}]
    assert obs_schema.validate_stream(v1) == []
    v2 = [dict(v1[0], schema=2), v1[1],
          {"record": "crash_dump", "time": 1.0, "reason": "signal:SIGTERM"},
          {"record": "run_summary", "steps": 1, "overflow_count": 0,
           "aborted": True, "abort_reason": "signal:SIGTERM"}]
    assert obs_schema.validate_stream(v2) == []


# ------------------------------------------------ queue + slot pool

def test_request_validation():
    with pytest.raises(ValueError, match="empty prompt"):
        Request(prompt=[], max_new_tokens=1)
    with pytest.raises(ValueError, match="max_new_tokens"):
        Request(prompt=[1], max_new_tokens=0)
    with pytest.raises(ValueError, match="temperature"):
        Request(prompt=[1], max_new_tokens=1, temperature=-1.0)
    with pytest.raises(ValueError, match="top_k"):
        Request(prompt=[1], max_new_tokens=1, top_k=-1)


def test_queue_fifo_and_arrival_gating():
    q = RequestQueue()
    a = Request(prompt=[1], max_new_tokens=1, arrival_step=0)
    b = Request(prompt=[2], max_new_tokens=1, arrival_step=5)
    c = Request(prompt=[3], max_new_tokens=1)      # ungated, behind b
    q.submit_all([a, b, c])
    assert q.pop(0) is a
    assert q.pop(3) is None        # b's gate holds the line (FIFO)
    assert q.pending() == 2
    assert q.pop(5) is b
    assert q.pop(5) is c
    assert q.pop(5) is None
    assert not q.drained()
    q.close()
    assert q.drained()
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(a)


def test_slot_pool_admit_evict(model_and_params):
    model, _ = model_and_params
    pool = SlotPool(model, num_slots=2, max_len=16)
    r = lambda: Request(prompt=[1, 2, 3], max_new_tokens=4)
    s0 = pool.admit(r(), step=0)
    s1 = pool.admit(r(), step=0)
    assert {s0, s1} == {0, 1} and pool.free_count == 0
    with pytest.raises(RuntimeError, match="no free slot"):
        pool.admit(r(), step=1)
    pool.evict(s0)
    assert pool.free_count == 1 and pool.live == [s1]
    with pytest.raises(RuntimeError, match="already free"):
        pool.evict(s0)
    with pytest.raises(ValueError, match="prompt length"):
        pool.admit(Request(prompt=list(range(16)), max_new_tokens=1),
                   step=2)
    # output budget clamps to the cache row
    assert pool.max_new_for(Request(prompt=[1] * 10,
                                    max_new_tokens=50)) == 6
    with pytest.raises(ValueError, match="position table"):
        SlotPool(model, num_slots=1, max_len=model.max_position + 1)


def test_parse_range():
    assert parse_range("8", "x") == (8, 8)
    assert parse_range("4:12", "x") == (4, 12)
    for bad in ("a", "4:2", "0:3", "1:2:3"):
        with pytest.raises(ValueError):
            parse_range(bad, "x")
