"""Continuous-batching inference subsystem (serve/, serve.py; ISSUE 3)
and its resilience layer (ISSUE 5):

- the tier-1 acceptance smoke: 8 staggered mixed-length requests through
  a 4-slot engine — greedy outputs token-identical to one-shot
  generate(), completions interleaving across admission waves, the
  emitted JSONL passing metrics_lint and serve_report,
- per-slot top-k sampling (determinism under a fixed rng; top_k=1 ==
  greedy),
- checkpoint -> serve round trip (CheckpointManager save, template-free
  restore in serve.py, served == generate() on the restored params),
- request lifecycle hardening: deadlines (queued expiry + mid-flight
  evict), bounded admission with deterministic shedding, cancellation,
- failure isolation: slot_fail fails exactly one request with every
  other greedy output token-identical to the fault-free run; the
  degenerate-token guard on the nan fault,
- graceful drain: run_serve + sigterm@tick => serve_drain record,
  un-aborted serve_summary with per-status counts, exit EX_TEMPFAIL,
- schema v3/v5 records + v1-v4 back-compat,
- queue/slot-pool/loadgen unit coverage and the serve.py CLI surface.

All engine tests share one slot geometry (SLOTS=4, MAX_LEN=32, the
default 8-token blocks) and one generate() max_len so the compiled
decode programs are built once per session — the suite rides tier-1 and
must stay cheap.  The KV cache is block-paged as of ISSUE 8
(tests/test_paged_kv.py holds the allocator/prefix-sharing/chunked-
prefill coverage; this file keeps the serving + resilience contract).
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import serve as serve_mod
from apex_example_tpu import obs
from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.resilience import EX_TEMPFAIL, FaultPlan
from apex_example_tpu.resilience.faults import SERVE_KINDS
from apex_example_tpu.serve import (BlockPool, Request, RequestQueue,
                                    ServeEngine, parse_range,
                                    synthetic_requests)

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SLOTS, MAX_LEN = 4, 32


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _run_engine(model, params, requests, rng_seed=0, sink=None,
                run_id=None, max_steps=2000):
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(rng_seed), sink=sink,
                      run_id=run_id)
    eng.queue.submit_all(requests)
    eng.queue.close()
    comps = eng.run(max_steps=max_steps)
    return eng, comps


# ------------------------------------------- tier-1 acceptance smoke

def test_continuous_batching_smoke(model_and_params, tmp_path, capsys):
    """The acceptance bar: >= 8 synthetic requests, staggered arrivals,
    mixed prompt/output lengths, SLOTS=4 — greedy outputs token-identical
    to one-shot generate(), completions interleaved across admission
    waves, JSONL lints, serve_report shows nonzero TTFT/TPOT.

    Runs WITH --trace armed (ISSUE 11): the same smoke also proves the
    trace stratum is a pure observer — token identity holds, the stream
    exports to valid Chrome JSON, the structural lint passes, and the
    per-request critical-path components sum to each request's e2e
    latency within 1%."""
    from apex_example_tpu.obs import trace as trace_lib
    model, params = model_and_params
    path = str(tmp_path / "serve.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={"slots": SLOTS, "max_len": MAX_LEN},
                       arch="gpt_tiny")
    reqs = synthetic_requests(8, vocab_size=model.vocab_size, seed=3,
                              prompt_len=(3, 8), max_new=(3, 12),
                              stagger=4)
    # mixed lengths actually present
    assert len({len(r.prompt) for r in reqs}) > 1
    assert len({r.max_new_tokens for r in reqs}) > 1
    trace_lib.set_default(obs.Tracer(sink, run_id=emitter.run_id))
    try:
        eng, comps = _run_engine(model, params, reqs, sink=sink,
                                 run_id=emitter.run_id)
    finally:
        trace_lib.set_default(None)
    sink.write(eng.summary_record())
    sink.close()
    assert len(comps) == 8

    # (a) token-identical to the one-shot decode path: generate() at the
    # shared max_len, compared on the request's output budget prefix.
    by_uid = {c.request.uid: c for c in comps}
    for r in reqs:
        c = by_uid[r.uid]
        P = len(r.prompt)
        n = len(c.tokens)
        assert n == min(r.max_new_tokens, MAX_LEN - P)
        ref = generate(model, params, jnp.asarray([r.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(np.asarray(ref)[0, P:P + n],
                                      np.asarray(c.tokens, np.int32),
                                      err_msg=r.uid)

    # (b) continuous batching actually happened: some request was
    # admitted while an earlier-admitted one was still decoding, and
    # slots were reused across admission waves.
    assert any(a.admitted_step < b.admitted_step <= a.finished_step
               for a in comps for b in comps)
    slot_uses = [c.slot for c in comps]
    assert len(slot_uses) > len(set(slot_uses))      # some slot reused
    assert eng.pool.free_count == SLOTS              # all evicted

    # (c) the stream is schema-valid and the report derives nonzero
    # latency percentiles from it.
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(path)
    assert code == 0, errors
    records = obs.read_jsonl(path)
    reqs_rec = [r for r in records if r["record"] == "request_complete"]
    assert len(reqs_rec) == 8
    assert all(r["ttft_ms"] > 0 and r["tpot_ms"] > 0 for r in reqs_rec)
    summary = records[-1]
    assert summary["record"] == "serve_summary"
    assert summary["requests"] == 8
    assert summary["ttft_ms"]["p50"] > 0
    assert summary["tpot_ms"]["p50"] > 0
    report = _load_tool("serve_report")
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "ttft_ms" in out and "tpot_ms" in out
    assert "finish reasons: length x8" in out
    assert "kv blocks:" in out                   # v7 block line rendered

    # (d) the ISSUE 8 acceptance bar: block-accurate kv_waste_pct on
    # THIS smoke workload drops from the dense layout's ~92% to <= 40%
    # (blocks are allocated as sequences grow and freed at completion,
    # so held-block bytes track live bytes to within block rounding).
    assert summary["kv_waste_pct"] <= 40.0
    assert summary["blocks_total"] == SLOTS * (MAX_LEN // 8)
    assert 0 < summary["blocks_live"]["max"] <= summary["blocks_total"]

    # (e) the ISSUE 11 acceptance bar: the traced stream exports to
    # valid Chrome trace JSON, passes the structural lint, carries the
    # per-tick + per-request span vocabulary, and serve_report's
    # critical-path components sum to each request's e2e within 1%.
    evs = [r for r in records if r["record"] == "trace_event"]
    assert evs and sum(1 for r in records
                       if r["record"] == "clock_sync") == 1
    names = {e["name"] for e in evs}
    assert {"tick", "admit", "dispatch", "harvest", "request", "queued",
            "prefill", "decode", "first_token", "ok"} <= names
    # these requests are all arrival_step-GATED: mature() re-stamps
    # t_submit with t_arrival, so no "submit" span may appear — the
    # deliberate stagger must not masquerade as client handoff
    # (review regression)
    assert "submit" not in names
    # one request root per request, each with its lifecycle children
    roots = [e for e in evs if e["name"] == "request"]
    assert len(roots) == 8
    assert all(e["args"]["status"] == "ok" and e["args"]["blocks"] > 0
               and e["args"]["slot"] >= 0 for e in roots)
    export = _load_tool("trace_export")
    assert export.main(["--check", path]) == 0
    out_json = str(tmp_path / "trace.json")
    assert export.main([path, "-o", out_json]) == 0
    doc = json.loads(open(out_json).read())      # valid JSON
    assert any(e.get("ph") == "s" for e in doc["traceEvents"])  # flows
    rows = report.critical_path(records)
    assert len(rows) == 8
    for row in rows:
        total = row["queue_ms"] + row["prefill_ms"] \
            + row["decode_ms"] + row["stall_ms"]
        assert total == pytest.approx(row["e2e_ms"], rel=0.01), row
    capsys.readouterr()                          # drop the tool stdout


# ------------------------------------------------- per-slot sampling

def test_topk_sampling_deterministic_and_topk1_greedy(model_and_params):
    """Satellite: per-slot top-k — fixed rng => identical streams;
    top_k=1 collapses to greedy regardless of temperature."""
    model, params = model_and_params
    mk = lambda k, t: synthetic_requests(
        4, vocab_size=model.vocab_size, seed=5, prompt_len=(3, 6),
        max_new=(4, 8), temperature=t, top_k=k, stagger=2)
    _, c1 = _run_engine(model, params, mk(3, 1.0), rng_seed=11)
    _, c2 = _run_engine(model, params, mk(3, 1.0), rng_seed=11)
    toks = lambda comps: [c.tokens for c in
                          sorted(comps, key=lambda c: c.request.uid)]
    assert toks(c1) == toks(c2)                      # deterministic
    _, ck = _run_engine(model, params, mk(1, 1.5), rng_seed=11)
    _, cg = _run_engine(model, params, mk(0, 0.0), rng_seed=7)
    assert toks(ck) == toks(cg)                      # top_k=1 == greedy


def test_eos_finishes_request(model_and_params):
    model, params = model_and_params
    prompt = [5, 9, 13]
    ref = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_len=MAX_LEN)
    first = int(np.asarray(ref)[0, len(prompt)])
    req = Request(prompt=prompt, max_new_tokens=10, eos_id=first)
    _, comps = _run_engine(model, params, [req])
    assert len(comps) == 1
    assert comps[0].finish_reason == "eos"
    assert comps[0].tokens == [first]


# -------------------------------------- checkpoint -> serve round trip

def test_checkpoint_serve_round_trip(model_and_params, tmp_path, capsys):
    """Satellite: save a tiny trained GPT state with CheckpointManager,
    restore in serve.py (template-free), served greedy outputs match
    direct generate() on the restored params."""
    import optax

    from apex_example_tpu import amp
    from apex_example_tpu.data import lm_batch
    from apex_example_tpu.engine import create_train_state, make_train_step
    from apex_example_tpu.utils.checkpoint import (CheckpointManager,
                                                   restore_params)
    from apex_example_tpu.workloads import lm_loss

    model, _ = model_and_params
    V = model.vocab_size
    policy, scaler = amp.initialize("O0")
    toks = lm_batch(jnp.asarray(0, jnp.int32), batch_size=4, seq_len=16,
                    vocab_size=V, seed=0)
    batch = (toks[:, :-1], toks[:, 1:])
    state = create_train_state(jax.random.PRNGKey(0), model,
                               optax.adam(1e-3), batch[0][:1], policy,
                               scaler)
    step_fn = jax.jit(make_train_step(model, optax.adam(1e-3), policy,
                                      loss_fn=lm_loss,
                                      compute_accuracy=False))
    for _ in range(2):                       # "trained", cheaply
        state, _metrics = step_fn(state, batch)
    ckpt_dir = str(tmp_path / "ckpt")
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(state)
    mgr.close()

    restored = restore_params(ckpt_dir)
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    argv = ["--arch", "gpt_tiny", "--checkpoint-dir", ckpt_dir,
            "--requests", "4", "--slots", str(SLOTS), "--max-len",
            str(MAX_LEN), "--prompt-len", "3:6", "--max-new", "4:8",
            "--stagger", "2", "--seed", "9"]
    comps, summary, rc = serve_mod.run_serve(
        serve_mod.build_parser().parse_args(argv))
    assert rc == 0 and len(comps) == 4
    assert "checkpoint" in capsys.readouterr().out
    for c in comps:
        P = len(c.request.prompt)
        n = len(c.tokens)
        ref = generate(model, restored,
                       jnp.asarray([c.request.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(np.asarray(ref)[0, P:P + n],
                                      np.asarray(c.tokens, np.int32))


# -------------------------------------------------- serve.py CLI

def test_serve_cli_smoke(tmp_path, capsys):
    """Random-init smoke from the CLI: rc 0, JSONL lints, report runs."""
    path = str(tmp_path / "cli.jsonl")
    rc = serve_mod.main(["--requests", "6", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN), "--prompt-len", "3:8",
                         "--max-new", "3:12", "--stagger", "3",
                         "--metrics-jsonl", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "6/6 completed" in out and "ttft_ms" in out
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(path)
    assert code == 0, errors
    records = obs.read_jsonl(path)
    assert records[0]["record"] == "run_header"
    assert records[0]["schema"] == obs_schema.SCHEMA_VERSION
    assert records[-1]["record"] == "serve_summary"
    # --trace off: not a single trace-stratum record in the stream
    # (the v9 contract — byte-identical streams without the flag)
    assert not any(r["record"] in ("trace_event", "clock_sync")
                   for r in records)


def test_serve_cli_steps_cap(tmp_path, capsys):
    """A --steps cap that strands requests exits 1 and says so."""
    rc = serve_mod.main(["--requests", "4", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN), "--prompt-len", "4",
                         "--max-new", "8", "--steps", "3"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "unfinished" in captured.err


def test_serve_cli_rejects_prompt_longer_than_cache():
    with pytest.raises(SystemExit):
        serve_mod.main(["--prompt-len", "40", "--max-len", "32"])
    with pytest.raises(SystemExit, match="shared-prefix"):
        serve_mod.main(["--prompt-len", "3:8", "--max-len", "32",
                        "--shared-prefix", "30"])
    with pytest.raises(SystemExit, match="block-size"):
        serve_mod.main(["--block-size", "0"])
    with pytest.raises(SystemExit, match="num-blocks"):
        serve_mod.main(["--num-blocks", "0"])


# ------------------------------------------------------- schema v3

def test_schema_v3_serving_records_validate():
    req = {"record": "request_complete", "time": 1.0, "request_id": "r-1",
           "prompt_tokens": 5, "output_tokens": 7, "ttft_ms": 12.5,
           "tpot_ms": 1.5, "finish_reason": "length", "slot": 2,
           "queue_wait_ms": 3.0, "e2e_ms": 25.0, "admitted_step": 4,
           "finished_step": 11, "temperature": 0.0, "top_k": 0,
           "run_id": "x"}
    summ = {"record": "serve_summary", "time": 1.0, "requests": 8,
            "output_tokens": 64, "tokens_per_sec": 100.0, "steps": 40,
            "compute_steps": 39, "slots": 4, "max_len": 32,
            "duration_s": 1.0, "occupancy": 0.6,
            "ttft_ms": {"p50": 1.0, "p95": 2.0, "max": 2.0},
            "tpot_ms": {"p50": 1.0, "p95": 2.0, "max": 2.0},
            "queue_wait_ms": {"p50": 0.0, "p95": 1.0, "max": 1.0}}
    header = {"record": "run_header", "schema": 3, "time": 0.0,
              "run_id": "x", "num_devices": 1, "process_index": 0,
              "platform": "cpu", "config": {}}
    assert obs.validate_record(req) == []
    assert obs.validate_record(summ) == []
    assert obs_schema.validate_stream([header, req, summ]) == []
    # malformed: missing required field / unknown field still rejected
    assert obs.validate_record({"record": "request_complete"})
    assert obs.validate_record(dict(summ, typo=1))


def test_schema_v1_v2_streams_still_validate():
    """v3 is a strict superset: pre-PR streams keep validating."""
    v1 = [{"record": "run_header", "schema": 1, "time": 0.0, "run_id": "r",
           "num_devices": 1, "process_index": 0, "platform": "cpu",
           "config": {}},
          {"record": "step", "step": 1, "epoch": 0, "loss": 1.0,
           "scale": 1.0, "step_time_ms": 5.0, "items_per_sec": 10.0},
          {"record": "run_summary", "steps": 1, "overflow_count": 0}]
    assert obs_schema.validate_stream(v1) == []
    v2 = [dict(v1[0], schema=2), v1[1],
          {"record": "crash_dump", "time": 1.0, "reason": "signal:SIGTERM"},
          {"record": "run_summary", "steps": 1, "overflow_count": 0,
           "aborted": True, "abort_reason": "signal:SIGTERM"}]
    assert obs_schema.validate_stream(v2) == []


# ------------------------------------------------ queue + slot pool

def test_request_validation():
    with pytest.raises(ValueError, match="empty prompt"):
        Request(prompt=[], max_new_tokens=1)
    with pytest.raises(ValueError, match="max_new_tokens"):
        Request(prompt=[1], max_new_tokens=0)
    with pytest.raises(ValueError, match="temperature"):
        Request(prompt=[1], max_new_tokens=1, temperature=-1.0)
    with pytest.raises(ValueError, match="top_k"):
        Request(prompt=[1], max_new_tokens=1, top_k=-1)


def test_queue_fifo_and_arrival_gating():
    q = RequestQueue()
    a = Request(prompt=[1], max_new_tokens=1, arrival_step=0)
    b = Request(prompt=[2], max_new_tokens=1, arrival_step=5)
    c = Request(prompt=[3], max_new_tokens=1)      # ungated, behind b
    q.submit_all([a, b, c])
    assert q.pop(0) is a
    assert q.pop(3) is None        # b's gate holds the line (FIFO)
    assert q.pending() == 2
    assert q.pop(5) is b
    assert q.pop(5) is c
    assert q.pop(5) is None
    assert not q.drained()
    q.close()
    assert q.drained()
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(a)


def test_block_pool_admit_evict(model_and_params):
    model, _ = model_and_params
    pool = BlockPool(model, num_slots=2, max_len=16, block_size=8)
    assert pool.num_blocks == 4                  # dense-capacity default
    r = lambda: Request(prompt=[1, 2, 3], max_new_tokens=4)
    s0 = pool.admit(r(), step=0)
    s1 = pool.admit(r(), step=0)
    assert {s0, s1} == {0, 1} and pool.free_count == 0
    with pytest.raises(RuntimeError, match="no free slot"):
        pool.admit(r(), step=1)
    pool.evict(s0)
    assert pool.free_count == 1 and pool.live == [s1]
    with pytest.raises(RuntimeError, match="already free"):
        pool.evict(s0)
    with pytest.raises(ValueError, match="prompt length"):
        pool.admit(Request(prompt=list(range(16)), max_new_tokens=1),
                   step=2)
    # output budget clamps to the slot's logical capacity
    assert pool.max_new_for(Request(prompt=[1] * 10,
                                    max_new_tokens=50)) == 6
    with pytest.raises(ValueError, match="position table"):
        BlockPool(model, num_slots=1, max_len=model.max_position + 1)


def test_parse_range():
    assert parse_range("8", "x") == (8, 8)
    assert parse_range("4:12", "x") == (4, 12)
    for bad in ("a", "4:2", "0:3", "1:2:3"):
        with pytest.raises(ValueError):
            parse_range(bad, "x")


# ============= cost observability + KV gauges (ISSUE 7) =============

def test_cost_model_decode_compiles_once_and_kv_gauges(
        model_and_params, tmp_path, compile_events):
    """The serving half of the ISSUE 7 recompile guard, on the PAGED
    decode step (ISSUE 8): block tables, fill levels, COW pairs and
    chunk widths are all DATA, so the program still compiles exactly
    once per geometry (a second compile_event is the regression — and
    ``compile_events.gate`` runs the actual cost_report
    --fail-on-recompile CI command over the stream).  Also checks the
    serve_summary KV gauges, v6 + the v7 block stratum.  Rides the
    session's SLOTS=4/MAX_LEN=32 decode geometry.

    --trace rides along (ISSUE 11): tracing is host-only, so the ONE
    compile_event is also the proof that arming the tracer adds ZERO
    compiled programs — the decode step is untouched.

    --slo rides along too (ISSUE 16): the streaming SLO plane is the
    same kind of host-only fold, so the ONE compile_event doubles as
    its zero-new-programs proof — and the summary's ONLINE sketch
    percentiles are checked against the EXACT percentiles recomputed
    from the raw request_complete records (the declared relative-error
    bound, asserted on the tier-1 smoke)."""
    from apex_example_tpu.obs import costmodel
    from apex_example_tpu.obs import trace as trace_lib
    model, params = model_and_params
    path = str(tmp_path / "cm_serve.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={"slots": SLOTS, "max_len": MAX_LEN},
                       arch="gpt_tiny")
    costmodel.set_default(obs.CostModel(
        sink=sink, registry=emitter.registry, run_id=emitter.run_id))
    trace_lib.set_default(obs.Tracer(sink, run_id=emitter.run_id))
    try:
        reqs = synthetic_requests(6, vocab_size=model.vocab_size, seed=5,
                                  prompt_len=(3, 6), max_new=(3, 6),
                                  stagger=2)
        eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                          rng=jax.random.PRNGKey(0), sink=sink,
                          run_id=emitter.run_id,
                          registry=emitter.registry,
                          slo={"ttft_ms": 60_000.0, "tpot_ms": 60_000.0,
                               "availability": 0.5},
                          slo_window_ticks=8)
        eng.queue.submit_all(reqs)
        eng.queue.close()
        comps = eng.run(max_steps=2000)
    finally:
        costmodel.set_default(None)
        trace_lib.set_default(None)
    sink.write(eng.summary_record())
    sink.close()
    assert len(comps) == 6

    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    # recompile guard: one engine, one decode program, one compilation —
    # asserted on the counter AND through the CI gate command itself.
    # The tracer was armed for the whole run: ZERO new compiled
    # programs with tracing on.
    assert compile_events(records) == {"serve_decode_step": 1}
    assert compile_events.gate(path) == 0
    assert any(r["record"] == "trace_event" for r in records)
    cm = next(r for r in records if r["record"] == "cost_model")
    assert cm["name"] == "serve_decode_step"
    assert cm["flops"] > 0 and cm["bytes_accessed"] > 0

    # KV accounting: per-token cost is layers x (K+V) x hidden x 4B;
    # the default arena reserves exactly the dense layout's capacity
    per_token = 2 * model.num_layers * model.hidden_size * 4
    assert eng.pool.kv_bytes_per_token() == per_token
    reserved = SLOTS * MAX_LEN * per_token
    summary = records[-1]
    assert summary["record"] == "serve_summary"
    assert summary["kv_bytes_reserved"] == reserved
    kv = summary["kv_bytes_live"]
    assert 0 < kv["max"] <= reserved
    assert kv["max"] % per_token == 0         # whole cached tokens
    occ = summary["slot_occupancy"]
    assert 0 < occ["max"] <= SLOTS
    assert 0 <= summary["kv_waste_pct"] <= 100
    # v7 block stratum: held blocks never exceed the arena, committed
    # bytes cover what admission reserved, and this no-shared-prefix
    # workload neither hits the prefix index nor copies a block
    blk = summary["blocks_live"]
    assert 0 < blk["max"] <= summary["blocks_total"] == SLOTS * MAX_LEN // 8
    assert summary["block_size"] == 8
    assert summary["kv_bytes_committed"]["max"] <= reserved
    assert summary["kv_bytes_committed"]["min"] >= kv["min"]
    assert summary["prefix_hit_rate"] == 0.0
    assert summary["cow_copies"] == 0 and summary["rejected"] == 0
    # per-tick registry gauges saw the run (last tick: pool drained)
    snap = emitter.registry.snapshot()
    assert snap["serve.slots_live"] == 0
    assert snap["serve.kv_bytes_live"] == 0
    assert snap["serve.blocks_live"] == 0
    # v14 SLO plane: every terminal landed in some tumbling window
    # (the trailing partial closes at summary time), the generous spec
    # passes, and the online sketch is honest — each percentile within
    # the declared relative-error bound alpha of the exact nearest-rank
    # percentile over the raw per-request records (same rank
    # convention; +0.01 ms absolute slack for the records' 3-decimal
    # rounding).
    slo_windows = [r for r in records if r["record"] == "slo_window"]
    assert slo_windows and all(w["requests"] >= 1 for w in slo_windows)
    assert sum(w["requests"] for w in slo_windows) == 6
    slo = summary["slo"]
    assert slo["verdict"] == "pass" and slo["breaches"] == 0
    assert slo["good"] == 6 and slo["bad"] == 0
    assert slo["windows"] == len(slo_windows)
    assert not any(r["record"] == "slo_breach" for r in records)
    exact = sorted(r["ttft_ms"] for r in records
                   if r["record"] == "request_complete")
    sk = slo["ttft_ms"]
    assert sk["count"] == len(exact) == 6
    for q in (50, 90, 99):
        rank = min(max(-(-q * len(exact) // 100), 1), len(exact))
        ex = exact[rank - 1]
        assert abs(sk[f"p{q}"] - ex) <= slo["alpha"] * ex + 0.01, q


# ==================== serving resilience (ISSUE 5) ====================

def _run_engine_res(model, params, requests, queue=None, fault=None,
                    sink=None, run_id=None, max_steps=2000):
    """Engine helper for the resilience tests — same shared slot
    geometry as _run_engine so the decode program compiles once."""
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0), queue=queue, sink=sink,
                      run_id=run_id, fault=fault)
    eng.queue.submit_all(requests)
    eng.queue.close()
    eng.run(max_steps=max_steps)
    return eng


def _by_order(engine):
    """Completions in submission order (uids are a monotonic counter
    within one process, so sorting aligns two runs' streams)."""
    return sorted(engine.completions, key=lambda c: c.request.uid)


# ------------------------------------------------ deadlines / timeout

def test_deadline_expires_queued_request_without_admitting(
        model_and_params):
    """A queued request whose deadline passes before a slot frees up
    terminates with status "timeout", slot -1, never admitted — the
    hogs are untouched."""
    model, params = model_and_params
    hogs = [Request(prompt=[1 + i, 2, 3], max_new_tokens=20)
            for i in range(SLOTS)]
    late = Request(prompt=[5, 6], max_new_tokens=4, deadline_step=5)
    eng = _run_engine_res(model, params, hogs + [late])
    assert eng.counts == {"ok": SLOTS, "timeout": 1, "shed": 0,
                          "cancelled": 0, "failed": 0, "drained": 0,
                          "rejected": 0, "handoff": 0}
    comp = next(c for c in eng.completions if c.request is late)
    assert comp.status == "timeout" and comp.finish_reason == "timeout"
    assert comp.slot == -1 and comp.admitted_step == -1
    assert comp.tokens == [] and comp.ttft_s is None


def test_deadline_evicts_decoding_slot_midflight(model_and_params,
                                                 tmp_path):
    """A decoding request hitting its deadline is evicted mid-flight:
    partial tokens kept, request_failed emitted, stream lints."""
    model, params = model_and_params
    path = str(tmp_path / "t.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={}, arch="gpt_tiny")
    req = Request(prompt=[1, 2, 3], max_new_tokens=20, deadline_step=6)
    eng = _run_engine_res(model, params, [req], sink=sink,
                          run_id=emitter.run_id)
    sink.write(eng.summary_record())
    sink.close()
    comp = eng.completions[0]
    assert comp.status == "timeout" and comp.slot == 0
    # one chunked-prefill tick then decode: fewer tokens than asked,
    # more than 0 by the deadline
    assert 0 < len(comp.tokens) < 20
    recs = obs.read_jsonl(path)
    assert obs_schema.validate_stream(recs) == []
    failed = next(r for r in recs if r["record"] == "request_failed")
    assert failed["status"] == "timeout"
    assert failed["output_tokens"] == len(comp.tokens)
    assert failed["slot"] == 0
    summary = recs[-1]
    assert summary["timed_out"] == 1 and summary["completed"] == 0
    assert summary["availability"] == 0.0
    lint = _load_tool("metrics_lint")
    assert lint.lint(path)[0] == 0


# ------------------------------------------- admission control / shed

def test_bounded_queue_sheds_newest_deterministically(model_and_params,
                                                      tmp_path):
    """A burst past max_pending sheds the newest arrivals (reject-newest
    default), deterministically: same uids shed on every run, shed
    records emitted, availability reflects the loss."""
    model, params = model_and_params
    path = str(tmp_path / "s.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={}, arch="gpt_tiny")
    mk = lambda: synthetic_requests(
        10, vocab_size=model.vocab_size, seed=4, prompt_len=(3, 5),
        max_new=(3, 5), stagger=0)
    reqs = mk()
    eng = _run_engine_res(model, params, reqs,
                          queue=RequestQueue(max_pending=4), sink=sink,
                          run_id=emitter.run_id)
    sink.write(eng.summary_record())
    sink.close()
    assert eng.counts["shed"] == 6 and eng.counts["ok"] == 4
    shed_uids = [c.request.uid for c in eng.completions
                 if c.status == "shed"]
    # reject-NEWEST: the last 6 submitted are the ones shed
    assert shed_uids == [r.uid for r in reqs[4:]]
    # deterministic: a rerun sheds the same submission indices
    reqs2 = mk()
    eng2 = _run_engine_res(model, params, reqs2,
                           queue=RequestQueue(max_pending=4))
    assert [c.request.uid for c in eng2.completions
            if c.status == "shed"] == [r.uid for r in reqs2[4:]]
    assert eng2.counts == eng.counts
    recs = obs.read_jsonl(path)
    assert obs_schema.validate_stream(recs) == []
    shed_recs = [r for r in recs if r["record"] == "shed"]
    assert len(shed_recs) == 6
    assert all(r["reason"] == "queue_full" and r["max_pending"] == 4
               for r in shed_recs)
    summary = recs[-1]
    assert summary["shed"] == 6 and summary["completed"] == 4
    assert summary["availability"] == 0.4


def test_shed_record_pending_is_arrived_backlog(model_and_params,
                                                tmp_path):
    """A shed record's ``pending`` counts the ARRIVED backlog (what the
    bound actually limits), not the whole deque — future-gated waves
    must not make admission control look broken (pending > bound)."""
    model, params = model_and_params
    wave1 = [Request(prompt=[i + 1, 2, 3], max_new_tokens=3)
             for i in range(6)]
    wave2 = [Request(prompt=[i + 1, 3, 4], max_new_tokens=3,
                     arrival_step=100) for i in range(8)]
    path = str(tmp_path / "p.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    eng = _run_engine_res(model, params, wave1 + wave2,
                          queue=RequestQueue(max_pending=2), sink=sink)
    sink.close()
    shed_recs = [r for r in obs.read_jsonl(path) if r["record"] == "shed"]
    assert shed_recs
    assert all(r["pending"] <= r["max_pending"] == 2 for r in shed_recs)


def test_sink_failure_is_engine_level_not_slot_mislabel(model_and_params):
    """A sink whose write() raises inside _finish must surface as an
    ENGINE-level error (it would hit every record), not be caught by
    the slot-isolation try — which would re-terminate the already-
    evicted slot and mislabel an IO fault as a request failure."""
    model, params = model_and_params

    class BrokenSink:
        def write(self, rec):
            raise OSError("disk full")

    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0), sink=BrokenSink())
    eng.queue.submit(Request(prompt=[1, 2, 3], max_new_tokens=2))
    eng.queue.close()
    with pytest.raises(OSError, match="disk full"):
        eng.run()
    # the completion itself was recorded exactly once, slot freed
    assert eng.counts["ok"] == 1 and eng.counts["failed"] == 0
    assert len(eng.completions) == 1
    assert eng.pool.free_count == SLOTS


def test_expired_queued_requests_free_capacity_before_shed(
        model_and_params):
    """Expiry runs before the bound check: a backlog of already-dead
    requests must not get a healthy arrival shed over capacity that
    frees this very tick."""
    model, params = model_and_params
    # hogs arrive in bound-respecting waves of 2 and fill every slot
    hogs = [Request(prompt=[i + 1, 2, 3], max_new_tokens=12,
                    arrival_step=i // 2) for i in range(SLOTS)]
    # two queued requests whose deadline passes at tick 5...
    dead = [Request(prompt=[7, 8], max_new_tokens=2, arrival_step=2,
                    deadline_step=5) for _ in range(2)]
    # ...and a healthy arrival AT tick 5, into a bound of 2: the old
    # shed-before-expire order counted the dead pair and shed it
    fresh = Request(prompt=[9, 9, 9], max_new_tokens=2, arrival_step=5)
    eng = _run_engine_res(model, params, hogs + dead + [fresh],
                          queue=RequestQueue(max_pending=2))
    st = {c.request.uid: c.status for c in eng.completions}
    assert st[fresh.uid] == "ok"                  # NOT shed
    assert all(st[d.uid] == "timeout" for d in dead)
    assert eng.counts["shed"] == 0


def test_shed_policy_oldest_drops_head(model_and_params):
    model, params = model_and_params
    reqs = [Request(prompt=[i + 1, 2, 3], max_new_tokens=3)
            for i in range(6)]
    eng = _run_engine_res(model, params, reqs,
                          queue=RequestQueue(max_pending=2,
                                             shed_policy="oldest"))
    shed_uids = {c.request.uid for c in eng.completions
                 if c.status == "shed"}
    assert shed_uids == {r.uid for r in reqs[:4]}   # head dropped


# ------------------------------------------------------- cancellation

def test_cancel_queued_and_inflight(model_and_params):
    model, params = model_and_params
    a = Request(prompt=[1, 2, 3], max_new_tokens=8)
    hogs = [Request(prompt=[2 + i, 3, 4], max_new_tokens=8)
            for i in range(SLOTS - 1)]
    b = Request(prompt=[9, 9], max_new_tokens=8, arrival_step=30)
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0))
    eng.queue.submit_all([a] + hogs + [b])
    eng.queue.close()
    eng.step()
    eng.step()
    assert eng.cancel(b.uid)            # still queued (gated): immediate
    assert eng.cancel(a.uid)            # decoding: evicted mid-flight
    assert not eng.cancel(a.uid)        # already terminal
    assert not eng.cancel("req-unknown")
    eng.run()
    assert eng.counts["cancelled"] == 2 and eng.counts["ok"] == len(hogs)
    ca = next(c for c in eng.completions if c.request is a)
    cb = next(c for c in eng.completions if c.request is b)
    assert ca.slot >= 0 and cb.slot == -1
    assert ca.status == cb.status == "cancelled"


# ------------------------------------------------- failure isolation

def test_slot_fail_isolates_one_request(model_and_params, tmp_path):
    """The acceptance bar: slot_fail@tick fails exactly one request
    (request_failed with the injected traceback digest) while every
    other request's greedy output is token-identical to the fault-free
    run — the engine keeps ticking."""
    model, params = model_and_params
    mk = lambda: synthetic_requests(
        6, vocab_size=model.vocab_size, seed=5, prompt_len=(3, 6),
        max_new=(4, 8), stagger=2)
    ref = _run_engine_res(model, params, mk())
    assert ref.counts["ok"] == 6
    path = str(tmp_path / "f.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={}, arch="gpt_tiny")
    eng = _run_engine_res(model, params, mk(),
                          fault=FaultPlan("slot_fail", 6,
                                          kinds=SERVE_KINDS),
                          sink=sink, run_id=emitter.run_id)
    sink.write(eng.summary_record())
    sink.close()
    assert eng.counts["failed"] == 1 and eng.counts["ok"] == 5
    for c_ref, c in zip(_by_order(ref), _by_order(eng)):
        assert len(c_ref.request.prompt) == len(c.request.prompt)
        if c.status == "ok":
            assert c.tokens == c_ref.tokens, c.request.uid
    failed = next(c for c in eng.completions if c.status == "failed")
    assert "injected slot_fail at tick 6" in failed.error
    recs = obs.read_jsonl(path)
    assert obs_schema.validate_stream(recs) == []
    frec = next(r for r in recs if r["record"] == "request_failed")
    assert frec["status"] == "failed"
    assert frec["request_id"] == failed.request.uid
    assert "FaultInjected" in frec["error"]
    summary = recs[-1]
    assert summary["failed"] == 1 and summary["completed"] == 5
    assert summary["availability"] == round(5 / 6, 3)


def test_fault_on_idle_tick_still_fires(model_and_params):
    """A drill scheduled in an idle gap between arrival waves must not
    be silently skipped: engine-level kinds fire on the idle tick
    itself, slot-level kinds defer to the next tick that can express
    them (FaultPlan.due is >=)."""
    model, params = model_and_params
    # wave 1 (ticks 0..~6), idle gap, wave 2 arrives at tick 20
    reqs = [Request(prompt=[1, 2, 3], max_new_tokens=3),
            Request(prompt=[4, 5, 6], max_new_tokens=3, arrival_step=20)]
    fault = FaultPlan("slot_fail", 12, kinds=SERVE_KINDS)  # idle tick
    eng = _run_engine_res(model, params, reqs, fault=fault)
    assert fault.fired
    assert eng.counts["failed"] == 1 and eng.counts["ok"] == 1
    failed = next(c for c in eng.completions if c.status == "failed")
    assert failed.request is reqs[1]              # fired on wave 2


def test_nan_fault_fires_on_first_token_keeping_tick(model_and_params):
    """The nan drill is only consumed on a tick some slot KEEPS a
    token.  Under chunked prefill a 5-token prompt completes inside
    tick 1's chunk, so nan@1 fires immediately and poisons the first
    kept token; a drill landing on a tick whose chunks all stop short
    of their prompt end still defers (FaultPlan.due is >=)."""
    model, params = model_and_params
    req = Request(prompt=[1, 2, 3, 4, 5], max_new_tokens=4)
    fault = FaultPlan("nan", 1, kinds=SERVE_KINDS)
    eng = _run_engine_res(model, params, [req], fault=fault)
    assert fault.fired
    assert eng.counts["failed"] == 1 and eng.counts["ok"] == 0
    failed = eng.completions[0]
    assert "degenerate sampled token" in failed.error
    assert failed.tokens == []                    # first kept token poisoned
    # the defer path proper: a 20-token prompt needs ticks 1-3 of pure
    # prefill (block chunks of 8), so nan@1 must wait for tick 3's
    # prompt-crossing chunk instead of burning on a discarded output
    req2 = Request(prompt=list(range(1, 21)), max_new_tokens=4)
    fault2 = FaultPlan("nan", 1, kinds=SERVE_KINDS)
    eng2 = _run_engine_res(model, params, [req2], fault=fault2)
    assert fault2.fired
    failed2 = eng2.completions[0]
    assert failed2.status == "failed" and failed2.tokens == []
    assert failed2.finished_step == 2             # tick 3, 0-based step 2


def test_real_nan_params_trip_nonfinite_logits_guard(model_and_params):
    """Not just the drill: actually-poisoned params produce NaN logits,
    and argmax over NaN yields an IN-RANGE token — the per-slot finite
    mask (computed inside the compiled step) must catch it, fail the
    slot, and never feed the garbage token onward as status ok."""
    model, params = model_and_params
    bad = jax.tree_util.tree_map(lambda x: jnp.full_like(x, jnp.nan),
                                 params)
    eng = _run_engine_res(model, bad,
                          [Request(prompt=[1, 2, 3], max_new_tokens=4)])
    assert eng.counts == {"ok": 0, "timeout": 0, "shed": 0,
                          "cancelled": 0, "failed": 1, "drained": 0,
                          "rejected": 0, "handoff": 0}
    comp = eng.completions[0]
    assert comp.status == "failed" and comp.tokens == []
    assert "non-finite logits" in comp.error


def test_nan_fault_trips_degenerate_token_guard(model_and_params):
    """The nan serve fault degenerates the tick's sampled tokens; the
    guard fails the affected slots instead of feeding garbage into the
    cache, and later arrivals still complete."""
    model, params = model_and_params
    reqs = synthetic_requests(6, vocab_size=model.vocab_size, seed=5,
                              prompt_len=(3, 6), max_new=(4, 8),
                              stagger=4)
    eng = _run_engine_res(model, params, reqs,
                          fault=FaultPlan("nan", 6, kinds=SERVE_KINDS))
    assert eng.counts["failed"] >= 1
    assert eng.counts["ok"] + eng.counts["failed"] == 6
    assert eng.counts["ok"] >= 1                  # engine kept serving
    for c in eng.completions:
        if c.status == "failed":
            assert "degenerate sampled token" in c.error
            # failed during decode of tick 6 (1-based)
            assert c.finished_step == 5


# --------------------------------------------------- graceful drain

def test_sigterm_drain_graceful_exit(model_and_params, tmp_path, capsys):
    """run_serve + sigterm@tick: admission stops, in-flight requests
    resolve, queued ones are requeued (status drained), the stream
    closes serve_drain -> un-aborted serve_summary, rc == EX_TEMPFAIL,
    and serve_report renders the drain."""
    path = str(tmp_path / "drain.jsonl")
    argv = ["--requests", "8", "--slots", str(SLOTS), "--max-len",
            str(MAX_LEN), "--prompt-len", "3:6", "--max-new", "6:10",
            "--stagger", "3", "--seed", "3", "--metrics-jsonl", path,
            "--inject-fault", "sigterm@6"]
    comps, summary, rc = serve_mod.run_serve(
        serve_mod.build_parser().parse_args(argv))
    assert rc == EX_TEMPFAIL == 75
    assert len(comps) == 8                        # every request terminal
    recs = obs.read_jsonl(path)
    assert obs_schema.validate_stream(recs) == []
    drain = next(r for r in recs if r["record"] == "serve_drain")
    assert drain["signal"] == "SIGTERM"
    assert drain["requeued"] == len(drain["requeued_ids"]) > 0
    assert drain["in_flight"] == drain["completed"] + drain["evicted"]
    # no admission after the drain began
    assert all(c.admitted_step <= drain["step"] for c in comps
               if c.admitted_step >= 0)
    assert {c.status for c in comps} <= {"ok", "timeout", "drained"}
    last = recs[-1]
    assert last["record"] == "serve_summary" and "aborted" not in last
    assert last["drained"] == drain["requeued"]
    assert last["completed"] + last["timed_out"] + last["drained"] == 8
    out = capsys.readouterr().out
    assert "drain (SIGTERM)" in out and "exiting 75" in out
    lint = _load_tool("metrics_lint")
    assert lint.lint(path)[0] == 0
    report = _load_tool("serve_report")
    assert report.main([path]) == 0
    rep = capsys.readouterr().out
    assert "DRAIN: SIGTERM" in rep
    assert "drained x" in rep


def test_serve_cli_overload_shed_and_deadlines(tmp_path, capsys):
    """CLI overload drill: burst past slots+bound sheds, tight virtual
    deadlines time out — all deterministic, availability reported."""
    path = str(tmp_path / "over.jsonl")
    rc = serve_mod.main(["--requests", "12", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN), "--prompt-len", "3:5",
                         "--max-new", "3:6", "--stagger", "0",
                         "--burst", "12", "--max-pending", "5",
                         "--deadline-steps", "25",
                         "--metrics-jsonl", path])
    assert rc == 0                        # resolved != stranded
    out = capsys.readouterr().out
    assert "shed=" in out and "availability=" in out
    recs = obs.read_jsonl(path)
    assert obs_schema.validate_stream(recs) == []
    summary = recs[-1]
    # the bound is evaluated at arrival, before the tick's admissions:
    # a 12-burst against max_pending 5 sheds 7 on the spot
    assert summary["shed"] == 12 - 5
    assert summary["completed"] + summary["timed_out"] \
        + summary["shed"] == 12
    assert 0 < summary["availability"] < 1


def test_serve_cli_rejects_bad_fault():
    with pytest.raises(SystemExit):
        serve_mod.main(["--inject-fault", "bogus@3"])
    with pytest.raises(SystemExit):
        serve_mod.main(["--inject-fault", "slot_fail"])
    with pytest.raises(SystemExit, match="flight-recorder"):
        serve_mod.main(["--flight-recorder"])     # needs --metrics-jsonl
    with pytest.raises(SystemExit, match="trace"):
        serve_mod.main(["--trace"])               # needs --metrics-jsonl


# ------------------------------------------------------- schema v5

def test_schema_v5_serving_resilience_records_validate():
    failed = {"record": "request_failed", "time": 1.0, "request_id": "r-1",
              "status": "timeout", "slot": 2, "admitted_step": 3,
              "failed_step": 9, "prompt_tokens": 4, "output_tokens": 2,
              "queue_wait_ms": 1.0, "e2e_ms": 20.0, "error": "x",
              "run_id": "x"}
    shed = {"record": "shed", "time": 1.0, "request_id": "r-2",
            "reason": "queue_full", "step": 4, "pending": 5,
            "max_pending": 4, "run_id": "x"}
    drain = {"record": "serve_drain", "time": 1.0, "signal": "SIGTERM",
             "step": 12, "in_flight": 2, "completed": 1, "evicted": 1,
             "requeued": 3, "requeued_ids": ["a", "b", "c"],
             "run_id": "x"}
    summ = {"record": "serve_summary", "time": 1.0, "requests": 8,
            "output_tokens": 64, "tokens_per_sec": 100.0,
            "completed": 4, "timed_out": 1, "shed": 2, "cancelled": 0,
            "failed": 1, "drained": 0, "availability": 0.5}
    header = {"record": "run_header", "schema": 5, "time": 0.0,
              "run_id": "x", "num_devices": 1, "process_index": 0,
              "platform": "cpu", "config": {}}
    for rec in (failed, shed, drain, summ):
        assert obs.validate_record(rec) == [], rec["record"]
    assert obs_schema.validate_stream(
        [header, failed, shed, drain, summ]) == []
    # malformed still rejected
    assert obs.validate_record({"record": "request_failed", "time": 1.0})
    assert obs.validate_record(dict(shed, typo=1))
    assert obs.validate_record(dict(drain, signal=7))


def test_schema_v1_v4_streams_still_validate():
    """v5 is a strict superset: pre-PR streams keep validating."""
    header = {"record": "run_header", "schema": 1, "time": 0.0,
              "run_id": "r", "num_devices": 1, "process_index": 0,
              "platform": "cpu", "config": {}}
    step = {"record": "step", "step": 1, "epoch": 0, "loss": 1.0,
            "scale": 1.0, "step_time_ms": 5.0, "items_per_sec": 10.0}
    v1 = [header, step,
          {"record": "run_summary", "steps": 1, "overflow_count": 0}]
    v2 = [dict(header, schema=2), step,
          {"record": "crash_dump", "time": 1.0, "reason": "signal:SIGTERM"},
          {"record": "run_summary", "steps": 1, "overflow_count": 0,
           "aborted": True, "abort_reason": "signal:SIGTERM"}]
    v3 = [dict(header, schema=3),
          {"record": "request_complete", "time": 1.0, "request_id": "r-0",
           "prompt_tokens": 4, "output_tokens": 6, "ttft_ms": 10.0,
           "tpot_ms": 1.5, "finish_reason": "length"},
          {"record": "serve_summary", "time": 2.0, "requests": 1,
           "output_tokens": 6, "tokens_per_sec": 50.0}]
    v4 = [dict(header, schema=4), step,
          {"record": "preemption", "time": 1.0, "signal": "SIGTERM",
           "step": 1, "saved": True, "checkpoint_step": 1},
          {"record": "run_summary", "steps": 1, "overflow_count": 0}]
    for stream in (v1, v2, v3, v4):
        assert obs_schema.validate_stream(stream) == []


# --------------------------------------- queue / loadgen resilience

def test_queue_bounds_and_deadline_validation():
    with pytest.raises(ValueError, match="max_pending"):
        RequestQueue(max_pending=0)
    with pytest.raises(ValueError, match="shed_policy"):
        RequestQueue(shed_policy="bogus")
    with pytest.raises(ValueError, match="deadline_s"):
        Request(prompt=[1], max_new_tokens=1, deadline_s=0.0)
    with pytest.raises(ValueError, match="deadline_step"):
        Request(prompt=[1], max_new_tokens=1, deadline_step=0)


def test_queue_expire_shed_drain_cancel():
    q = RequestQueue(max_pending=2)
    a = Request(prompt=[1], max_new_tokens=1)
    b = Request(prompt=[2], max_new_tokens=1, deadline_step=3)
    c = Request(prompt=[3], max_new_tokens=1)
    d = Request(prompt=[4], max_new_tokens=1, arrival_step=50)
    q.submit_all([a, b, c, d])
    # bound counts ARRIVED requests only: a, b, c arrived; d is future
    shed = q.shed_overflow(0)
    assert shed == [c]                       # reject-newest
    assert q.expire(0, 0.0) == []
    assert q.expire(3, 0.0) == [b]           # deadline_step hit
    assert q.cancel(a.uid) is a
    assert q.cancel(a.uid) is None
    assert q.pending() == 1                  # d, still gated
    left = q.drain()
    assert left == [d] and q.closed
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(a)


def test_loadgen_burst_and_deadlines():
    reqs = synthetic_requests(6, vocab_size=100, seed=0, stagger=4,
                              burst=3, deadline_steps=10)
    assert [r.arrival_step for r in reqs] == [0, 0, 0, 4, 4, 4]
    assert [r.deadline_step for r in reqs] == [10, 10, 10, 14, 14, 14]
    reqs = synthetic_requests(2, vocab_size=100, seed=0, stagger=0,
                              deadline_s=1.5)
    assert all(r.arrival_step is None and r.deadline_s == 1.5
               and r.deadline_step is None for r in reqs)
    with pytest.raises(ValueError, match="burst"):
        synthetic_requests(2, vocab_size=100, burst=0)
    with pytest.raises(ValueError, match="deadline_steps"):
        synthetic_requests(2, vocab_size=100, deadline_steps=0)
