"""The full {O0, O2} × {1, 8 devices} convergence matrix at accuracy.py's
ci-preset scale, as a CI-on-request target (SURVEY.md §5 integration tier;
VERDICT r2 item 8): ``pytest -m slow tests/test_convergence_slow.py``.
Measured green 2026-07-30: 75 min uncontended on the 8-logical-CPU rig
(budget ≥2 h when sharing the box).

The fast suite's matrix (test_convergence_matrix.py) uses a tiny model; this
one runs the REAL ci preset cells through accuracy.run_one — the same code
path the ACCURACY.json artifact comes from — with label noise so the task
cannot saturate, and asserts the loss/top-1 bands instead of relying on a
hand-run.
"""

import pytest

from apex_example_tpu.data import CIFAR10

LABEL_NOISE = 0.3
# ci preset, shortened: enough steps for the band to be meaningful, small
# enough that the 4-cell matrix stays in tens of minutes on the CPU rig.
KW = dict(arch="resnet18", spec=CIFAR10, steps=150, batch_size=64,
          eval_batches=8, lr=0.1, warmup=10, seed=0,
          label_noise=LABEL_NOISE)
CEILING = 100.0 * (1.0 - LABEL_NOISE + LABEL_NOISE / 10)   # 73%


@pytest.mark.slow
def test_full_convergence_matrix(devices8):
    from accuracy import run_one
    cells = {}
    for opt_level in ("O0", "O2"):
        for n_dev in (1, 8):
            cells[(opt_level, n_dev)] = run_one(
                opt_level=opt_level, num_devices=n_dev, **KW)

    for (lvl, n), r in cells.items():
        # every cell learns well past chance (10%) toward the noise ceiling
        assert r["top1"] > 40.0, ((lvl, n), r)
        assert r["top1"] < CEILING + 10.0, ((lvl, n), r)
        assert r["eval_loss"] < 2.0, ((lvl, n), r)

    # O0 vs O2 top-1 band, per device count: short runs are noisier than
    # the converged <0.1% contract — the band here is the integration-tier
    # check (full-convergence evidence lives in ACCURACY_CI_NOISE.json,
    # and on-chip in ACCURACY_FULL.json when the tunnel allows it).
    for n in (1, 8):
        gap = cells[("O0", n)]["top1"] - cells[("O2", n)]["top1"]
        assert abs(gap) < 5.0, (n, gap, cells)

    # 1-dev vs 8-dev band, per opt level (sharding must not change learning)
    for lvl in ("O0", "O2"):
        gap = cells[(lvl, 1)]["top1"] - cells[(lvl, 8)]["top1"]
        assert abs(gap) < 5.0, (lvl, gap, cells)
