"""Tensor/sequence/pipeline parallelism tests (8 logical CPU devices).

Strategy (SURVEY.md §5): run the real pjit/shard_map collective code paths on
8 XLA CPU devices and compare numerics + gradients against single-device
dense goldens — exceeding the reference's "needs ≥2 physical GPUs" test gap
for apex.transformer (SURVEY.md §3.2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn
from flax.core import meta
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_example_tpu.parallel.mesh import MODEL_AXIS, PIPE_AXIS
from apex_example_tpu.transformer import parallel_state
from apex_example_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving)
from apex_example_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    param_partition_specs,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    vocab_parallel_cross_entropy)


@pytest.fixture()
def model_mesh(devices8):
    mesh = Mesh(np.asarray(devices8), (MODEL_AXIS,))
    old = parallel_state.get_mesh()
    parallel_state.set_mesh(mesh)
    yield mesh
    parallel_state.set_mesh(old)


# ---------------------------------------------------------------------------
# Explicit shard_map mappings: Megatron column->row MLP vs dense golden.
# ---------------------------------------------------------------------------

def test_mappings_column_row_mlp(model_mesh):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 16), jnp.float32)
    w1 = jnp.asarray(rng.randn(16, 32), jnp.float32) * 0.1
    w2 = jnp.asarray(rng.randn(32, 16), jnp.float32) * 0.1

    def golden_loss(w1, w2):
        return jnp.sum((jnp.tanh(x @ w1) @ w2) ** 2)

    def tp_loss_fn(w1s, w2s):
        xi = copy_to_tensor_model_parallel_region(x)
        h = jnp.tanh(xi @ w1s)              # column shard: [4, 32/8]
        y = reduce_from_tensor_model_parallel_region(h @ w2s)
        return lax.pmean(jnp.sum(y ** 2), MODEL_AXIS)

    tp = shard_map(
        tp_loss_fn, mesh=model_mesh,
        in_specs=(P(None, MODEL_AXIS), P(MODEL_AXIS, None)), out_specs=P())
    np.testing.assert_allclose(tp(w1, w2), golden_loss(w1, w2), rtol=1e-5)

    g_tp = jax.grad(lambda ws: tp(*ws))((w1, w2))
    g_ref = jax.grad(lambda ws: golden_loss(*ws))((w1, w2))
    for a, b in zip(jax.tree_util.tree_leaves(g_tp),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_sequence_parallel_mappings_roundtrip(model_mesh):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 16, 8), jnp.float32)  # [B, S, D], S%8==0

    def f(xs):
        full = gather_from_sequence_parallel_region(xs, seq_dim=1)
        # partial sums on each device -> reduce-scatter back to seq shards
        return reduce_scatter_to_sequence_parallel_region(
            full / lax.axis_size(MODEL_AXIS), seq_dim=1)

    out = shard_map(f, mesh=model_mesh,
                              in_specs=P(None, MODEL_AXIS, None),
                              out_specs=P(None, MODEL_AXIS, None))(x)
    np.testing.assert_allclose(out, x, rtol=1e-6)


# ---------------------------------------------------------------------------
# Vocab-parallel cross entropy vs full-vocab golden (value + grad).
# ---------------------------------------------------------------------------

def test_vocab_parallel_cross_entropy(model_mesh):
    rng = np.random.RandomState(2)
    V, B = 64, 12
    logits = jnp.asarray(rng.randn(B, V), jnp.float32)
    labels = jnp.asarray(rng.randint(0, V, size=(B,)), jnp.int32)

    def full_ce(lg):
        lse = jax.nn.logsumexp(lg, axis=-1)
        tgt = jnp.take_along_axis(lg, labels[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - tgt)

    def tp_ce(lg_shard):
        per_tok = vocab_parallel_cross_entropy(lg_shard, labels,
                                               axis_name=MODEL_AXIS)
        return lax.pmean(jnp.mean(per_tok), MODEL_AXIS)

    tp = shard_map(tp_ce, mesh=model_mesh,
                             in_specs=P(None, MODEL_AXIS), out_specs=P())
    np.testing.assert_allclose(tp(logits), full_ce(logits), rtol=1e-5)
    g_tp = jax.grad(tp)(logits)
    g_ref = jax.grad(full_ce)(logits)
    np.testing.assert_allclose(g_tp, g_ref, rtol=1e-4, atol=1e-6)


def test_vocab_parallel_cross_entropy_gspmd_form():
    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(5, 33), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 33, size=(5,)), jnp.int32)
    loss = vocab_parallel_cross_entropy(logits, labels)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    np.testing.assert_allclose(loss, lse - tgt, rtol=1e-5)


# ---------------------------------------------------------------------------
# GSPMD layers: params really shard; numerics match the no-mesh run.
# ---------------------------------------------------------------------------

class _TpMlp(nn.Module):
    hidden: int
    sequence_parallel: bool = False

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelLinear(self.hidden, gather_output=False,
                                 sequence_parallel=self.sequence_parallel,
                                 name="fc1")(x)
        h = nn.gelu(h)
        return RowParallelLinear(x.shape[-1], input_is_parallel=True,
                                 sequence_parallel=self.sequence_parallel,
                                 name="fc2")(h)


def _init_sharded(model, rng, x, mesh):
    variables = model.init(rng, x)
    specs = param_partition_specs(variables)
    unboxed = meta.unbox(variables)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda v: isinstance(v, P))
    return jax.device_put(unboxed, shardings), specs


def test_gspmd_column_row_mlp(model_mesh):
    model = _TpMlp(hidden=64)
    x = jnp.asarray(np.random.RandomState(4).randn(8, 4, 32), jnp.float32)
    sharded_vars, specs = _init_sharded(model, jax.random.PRNGKey(0), x,
                                        model_mesh)
    # The column kernel must actually be sharded 8-ways on its output dim.
    k1 = sharded_vars["params"]["fc1"]["kernel"]
    assert k1.sharding.spec == P(None, MODEL_AXIS)
    assert k1.addressable_shards[0].data.shape == (32, 64 // 8)

    out = jax.jit(model.apply)(sharded_vars, x)

    # Golden: same params, no mesh registered -> constraints no-op.
    parallel_state.set_mesh(None)
    try:
        ref = jax.jit(model.apply)(
            jax.device_put(sharded_vars, jax.devices("cpu")[0]), x)
    finally:
        parallel_state.set_mesh(model_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_gspmd_mlp_grads_match(model_mesh):
    model = _TpMlp(hidden=64, sequence_parallel=True)
    x = jnp.asarray(np.random.RandomState(5).randn(4, 8, 32), jnp.float32)
    sharded_vars, _ = _init_sharded(model, jax.random.PRNGKey(1), x,
                                    model_mesh)

    loss = lambda v: jnp.sum(model.apply(v, x) ** 2)
    g = jax.jit(jax.grad(loss))(sharded_vars)

    parallel_state.set_mesh(None)
    try:
        host_vars = jax.device_put(sharded_vars, jax.devices("cpu")[0])
        g_ref = jax.jit(jax.grad(loss))(host_vars)
    finally:
        parallel_state.set_mesh(model_mesh)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_vocab_parallel_embedding_gspmd(model_mesh):
    model = VocabParallelEmbedding(num_embeddings=64, features=16)
    ids = jnp.asarray(np.random.RandomState(6).randint(0, 64, (4, 10)))
    sharded_vars, _ = _init_sharded(model, jax.random.PRNGKey(2), ids,
                                    model_mesh)
    table = sharded_vars["params"]["embedding"]
    assert table.sharding.spec == P(MODEL_AXIS, None)
    out = jax.jit(model.apply)(sharded_vars, ids)
    ref = jnp.take(np.asarray(table), np.asarray(ids), axis=0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)


# ---------------------------------------------------------------------------
# Pipeline schedules.
# ---------------------------------------------------------------------------

def test_no_pipelining_matches_full_batch():
    rng = np.random.RandomState(7)
    w = jnp.asarray(rng.randn(8, 8), jnp.float32) * 0.3
    xs = jnp.asarray(rng.randn(4, 6, 8), jnp.float32)  # 4 microbatches
    ys = jnp.asarray(rng.randn(4, 6, 8), jnp.float32)

    def loss_fn(p, mb):
        x, y = mb
        return jnp.mean((jnp.tanh(x @ p) - y) ** 2)

    loss, grads = forward_backward_no_pipelining(loss_fn, w, (xs, ys))
    full_loss = jnp.mean(jnp.stack(
        [loss_fn(w, (xs[i], ys[i])) for i in range(4)]))
    full_grad = jax.grad(
        lambda p: jnp.mean(jnp.stack(
            [loss_fn(p, (xs[i], ys[i])) for i in range(4)])))(w)
    np.testing.assert_allclose(loss, full_loss, rtol=1e-6)
    np.testing.assert_allclose(grads, full_grad, rtol=1e-5, atol=1e-7)


def test_spmd_pipeline_matches_sequential(devices8):
    S, M, B, D = 8, 16, 4, 8
    mesh = Mesh(np.asarray(devices8), (PIPE_AXIS,))
    rng = np.random.RandomState(8)
    stacked_w = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.3
    xs = jnp.asarray(rng.randn(M, B, D), jnp.float32)
    ys = jnp.asarray(rng.randn(M, B, D), jnp.float32)

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    def last_stage_fn(y, t):
        return jnp.mean((y - t) ** 2)

    def pipeline(w):
        # shard_map hands each device its [1, D, D] slice of the stage stack.
        return forward_backward_pipelining_without_interleaving(
            lambda p, x: stage_fn(p[0], x), last_stage_fn, w, xs, ys)

    loss, grads = shard_map(
        pipeline, mesh=mesh,
        in_specs=P(PIPE_AXIS, None, None),
        out_specs=(P(), P(PIPE_AXIS, None, None)))(stacked_w)

    def sequential_loss(stacked):
        def one(mb_x, mb_y):
            h = mb_x
            for s in range(S):
                h = stage_fn(stacked[s], h)
            return last_stage_fn(h, mb_y)
        return jnp.mean(jnp.stack([one(xs[i], ys[i]) for i in range(M)]))

    ref_loss = sequential_loss(stacked_w)
    ref_grads = jax.grad(sequential_loss)(stacked_w)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=1e-4, atol=1e-6)


def test_1f1b_interleaved_matches_sequential(devices8):
    """Interleaved-virtual-stage 1F1B: 8 global stages as V=2 chunks on
    S=4 devices; loss/grads must match the sequential 8-layer model."""
    from apex_example_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving)
    S, V, M, B, D = 4, 2, 8, 2, 8
    mesh = Mesh(np.asarray(devices8[:S]), (PIPE_AXIS,))
    rng = np.random.RandomState(11)
    w_global = jnp.asarray(rng.randn(V * S, D, D), jnp.float32) * 0.3
    # device s owns global stages {v*S + s} -> [S, V, D, D]
    w_dev = jnp.transpose(w_global.reshape(V, S, D, D), (1, 0, 2, 3))
    xs = jnp.asarray(rng.randn(M, B, D), jnp.float32)
    ys = jnp.asarray(rng.randn(M, B, D), jnp.float32)

    def stage_fn(w, x):          # w: one chunk's [D, D]
        return jnp.tanh(x @ w)

    def last_stage_fn(y, t):
        return jnp.mean((y - t) ** 2)

    def pipeline(w):             # w: [1, V, D, D] per device
        loss, grads = forward_backward_pipelining_with_interleaving(
            stage_fn, last_stage_fn, w[0], xs, ys, num_chunks=V)
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    loss, grads = shard_map(
        pipeline, mesh=mesh,
        in_specs=P(PIPE_AXIS, None, None, None),
        out_specs=(P(), P(PIPE_AXIS, None, None, None)))(w_dev)

    def sequential_loss(stacked):
        def one(mb_x, mb_y):
            h = mb_x
            for j in range(V * S):
                h = stage_fn(stacked[j], h)
            return last_stage_fn(h, mb_y)
        return jnp.mean(jnp.stack([one(xs[i], ys[i]) for i in range(M)]))

    ref_loss = sequential_loss(w_global)
    ref_grads = jax.grad(sequential_loss)(w_global)
    # back to device layout for comparison
    ref_dev = jnp.transpose(ref_grads.reshape(V, S, D, D), (1, 0, 2, 3))
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_dev),
                               rtol=1e-4, atol=1e-6)


def test_1f1b_schedule_tables_are_sound():
    """The schedule simulator: tick counts and per-stage work for the
    non-interleaved form (T = 2(M+S-1); every stage does M F's + M B's)."""
    from apex_example_tpu.transformer.pipeline_parallel.schedules import (
        _simulate_1f1b)
    for M, S in [(4, 2), (8, 4), (16, 8), (2, 2)]:
        f, b, fd, bd, xd = _simulate_1f1b(M, S)
        # combined F+B ticks: never worse than the serial 2(M+S-1) slots,
        # and at least the 1F1B steady-state bound (~2M: the in-flight cap
        # ties each stage's forward rate to its backward-return rate).
        assert 2 * M <= len(f) <= 2 * (M + S - 1), (M, S, len(f))
        for s in range(S):
            assert sum(r[s] >= 0 for r in f) == M
            assert sum(r[s] >= 0 for r in b) == M
    # interleaved: still M*V per direction per device
    f, b, fd, bd, xd = _simulate_1f1b(8, 4, V=2)
    assert xd > 4   # interleaving carries more in-flight stash than V=1
    for s in range(4):
        assert sum(r[s] >= 0 for r in f) == 16
        assert sum(r[s] >= 0 for r in b) == 16


def test_spmd_pipeline_direct(devices8):
    """spmd_pipeline exercised directly (the reference-named wrapper now
    routes to pipeline_1f1b, so the ring form needs its own coverage)."""
    from apex_example_tpu.transformer.pipeline_parallel import spmd_pipeline
    S, M, B, D = 8, 16, 4, 8
    mesh = Mesh(np.asarray(devices8), (PIPE_AXIS,))
    rng = np.random.RandomState(9)
    stacked_w = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.3
    xs = jnp.asarray(rng.randn(M, B, D), jnp.float32)
    ys = jnp.asarray(rng.randn(M, B, D), jnp.float32)

    def stage_fn(w, x):
        return jnp.tanh(x @ w[0])

    def last_stage_fn(y, t):
        return jnp.mean((y - t) ** 2)

    def pipeline(w):
        return jax.value_and_grad(
            lambda p: spmd_pipeline(stage_fn, last_stage_fn, p, xs, ys))(w)

    loss, grads = shard_map(
        pipeline, mesh=mesh,
        in_specs=P(PIPE_AXIS, None, None),
        out_specs=(P(), P(PIPE_AXIS, None, None)))(stacked_w)

    def sequential_loss(stacked):
        def one(mb_x, mb_y):
            h = mb_x
            for s in range(S):
                h = jnp.tanh(h @ stacked[s])
            return last_stage_fn(h, mb_y)
        return jnp.mean(jnp.stack([one(xs[i], ys[i]) for i in range(M)]))

    np.testing.assert_allclose(loss, sequential_loss(stacked_w), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads), np.asarray(jax.grad(sequential_loss)(stacked_w)),
        rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_ce_matches_fused_xentropy(model_mesh, smoothing):
    """Cross-validation of two independent CE implementations: the TP
    vocab-sharded form (psum-of-partials over the model axis) must equal
    the single-device fused op (ops/xentropy.py), values and gradients,
    with and without label smoothing."""
    from apex_example_tpu.ops.xentropy import softmax_cross_entropy
    rng = np.random.RandomState(9)
    V, B = 64, 12
    logits = jnp.asarray(rng.randn(B, V), jnp.float32)
    labels = jnp.asarray(rng.randint(0, V, size=(B,)), jnp.int32)

    def fused(lg):
        return jnp.mean(softmax_cross_entropy(lg, labels, smoothing))

    def tp_ce(lg_shard):
        per_tok = vocab_parallel_cross_entropy(lg_shard, labels,
                                               axis_name=MODEL_AXIS,
                                               label_smoothing=smoothing)
        return lax.pmean(jnp.mean(per_tok), MODEL_AXIS)

    tp = shard_map(tp_ce, mesh=model_mesh,
                   in_specs=P(None, MODEL_AXIS), out_specs=P())
    np.testing.assert_allclose(float(tp(logits)), float(fused(logits)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(jax.grad(tp)(logits)),
                               np.asarray(jax.grad(fused)(logits)),
                               rtol=1e-4, atol=1e-6)
