"""DistributedFusedAdam (ZeRO-1 state sharding): equivalence with the
replicated FusedAdam DDP step on the 8-device rig, and the 1/N state-memory
contract (SURVEY.md §3.4 contrib row / §3.3 weight-update sharding)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_example_tpu import amp
from apex_example_tpu.data import image_batch
from apex_example_tpu.engine import (create_train_state,
                                     make_sharded_train_step)
from apex_example_tpu.models import resnet18
from apex_example_tpu.optim import FusedAdam
from apex_example_tpu.optim.distributed import (DistributedFusedAdam,
                                                ZeroAdamState, _flat_size,
                                                _padded_size,
                                                make_zero_train_step)
from apex_example_tpu.parallel.mesh import make_data_mesh


def _setup(devices8, opt):
    policy, scaler = amp.initialize("O0")
    model = resnet18(num_classes=10, bn_axis_name="data")
    batch = image_batch(jnp.asarray(0), batch_size=16, image_size=32,
                        channels=3, num_classes=10, seed=0)
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               batch[0][:1], policy, scaler)
    return policy, model, batch, state


def test_zero_matches_replicated_adam(devices8):
    mesh = make_data_mesh(devices=devices8)
    hp = dict(lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=1e-2)

    policy, model, batch, state_ref = _setup(devices8, FusedAdam(**hp))
    ref_step = make_sharded_train_step(mesh, model, FusedAdam(**hp), policy,
                                       donate=False)

    zopt = DistributedFusedAdam(**hp, world=8, axis_name="data")
    _, _, _, state_z = _setup(devices8, zopt)
    zero_step = make_zero_train_step(mesh, model, zopt, policy, donate=False)

    for i in range(3):
        b = image_batch(jnp.asarray(i), batch_size=16, image_size=32,
                        channels=3, num_classes=10, seed=0)
        state_ref, m_ref = ref_step(state_ref, b)
        state_z, m_z = zero_step(state_z, b)

    # fp32 reduction-order noise only (flatten-then-slice vs per-leaf psum):
    # the earlier double-reduction bug showed up here as a 5e-3 loss drift.
    # Params get an absolute-only bound: Adam behaves like sign(g)·lr where
    # grads are near zero, so order-of-reduction noise can flip individual
    # updates (bounded by ~lr per step) without the trajectories diverging —
    # exact elementwise agreement is checked by the fixed-grads test below.
    np.testing.assert_allclose(float(m_ref["loss"]), float(m_z["loss"]),
                               rtol=1e-4)
    diffs = np.concatenate([
        np.abs(np.asarray(a) - np.asarray(b)).ravel()
        for a, b in zip(jax.tree_util.tree_leaves(state_ref.params),
                        jax.tree_util.tree_leaves(state_z.params))])
    # A handful of near-zero-grad elements may differ by up to ~lr per step
    # (sign flip); everything else must agree tightly.
    assert float((diffs < 5e-3).mean()) > 0.999
    assert float(diffs.max()) < 3 * 1e-2        # 3 steps x lr


def test_zero_apply_matches_fused_adam_fixed_grads(devices8):
    """One sharded apply on fixed (params, grads) == replicated FusedAdam
    elementwise — no model in the loop, so no sign-flip amplification."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map as smap
    except ImportError:
        from jax.experimental.shard_map import shard_map as smap

    mesh = make_data_mesh(devices=devices8)
    hp = dict(lr=3e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=1e-2)
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(40, 37), jnp.float32),
              "b": jnp.asarray(rng.randn(33), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(40, 37), jnp.float32),
             "b": jnp.asarray(rng.randn(33), jnp.float32)}

    ref = FusedAdam(**hp)
    st_ref = ref.init(params)
    p_ref, _ = ref.apply(grads, st_ref, params)

    zopt = DistributedFusedAdam(**hp, world=8, axis_name="data")
    st_z = zopt.init(params)

    def step(params, grads, st):
        # replicated grads stand in for the engine's already-psum-ed grads;
        # pre-multiply by world so the /world averaging is a no-op.
        g = jax.tree_util.tree_map(
            lambda g: g * jax.lax.axis_size("data"), grads)
        return zopt.apply(g, st, params)

    p_z, _ = jax.jit(smap(
        step, mesh=mesh,
        in_specs=(P(), P(), zopt.state_spec()),
        out_specs=(P(), zopt.state_spec())))(params, grads, st_z)

    for k in params:
        np.testing.assert_allclose(np.asarray(p_ref[k]), np.asarray(p_z[k]),
                                   atol=1e-6, rtol=1e-6)


def test_zero_state_is_one_nth(devices8):
    zopt = DistributedFusedAdam(lr=1e-3, world=8)
    params = {"a": jnp.zeros((1000, 37)), "b": jnp.zeros((13,))}
    st = zopt.init(params)
    padded = _padded_size(_flat_size(params), 8)
    assert st.mu.shape == (padded,) and padded % (8 * 128) == 0
    # Global buffer sharded over 8 devices => per-device bytes are 1/8 of
    # FusedAdam's per-device replicated state.
    mesh = make_data_mesh(devices=devices8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    mu = jax.device_put(st.mu, NamedSharding(mesh, P("data")))
    shard_bytes = mu.addressable_shards[0].data.nbytes
    assert shard_bytes == st.mu.nbytes // 8


def test_zero_fp16_dynamic_scaling_skips_in_lockstep(devices8):
    """fp16 + dynamic scaling + ZeRO: a nonfinite grad originating on ONE
    replica's microbatch must skip the step identically on all replicas —
    params, sharded (m, v) and the scaler all roll back together, and the
    next clean step trains normally.  (The finite check runs after the
    reduce; the flag is psum-ed so no replica can step alone.)"""
    mesh = make_data_mesh(devices=devices8)
    # Modest init scale: 2**10 keeps the CLEAN follow-up step overflowing in
    # fp16 (the scale must walk down first), which is correct scaler behavior
    # but not what this test pins — the lockstep skip is.  BN-free model: an
    # inf input permanently poisons BN *running stats* (apex semantics keep
    # forward-pass stat updates even on skipped steps), which would make
    # every later step nonfinite regardless of the optimizer's behavior.
    from flax import linen as fnn

    class _Mlp(fnn.Module):
        @fnn.compact
        def __call__(self, x, train: bool = True):
            x = x.reshape(x.shape[0], -1).astype(jnp.float16)
            x = fnn.relu(fnn.Dense(32, dtype=jnp.float16)(x))
            return fnn.Dense(10, dtype=jnp.float16)(x).astype(jnp.float32)

    policy, scaler = amp.initialize("O2", loss_scale="dynamic",
                                    half_dtype=jnp.float16,
                                    init_scale=2.0 ** 4)
    zopt = DistributedFusedAdam(lr=1e-2, world=8, axis_name="data")
    model = _Mlp()
    batch = image_batch(jnp.asarray(0), batch_size=16, image_size=32,
                        channels=3, num_classes=10, seed=0)
    state = create_train_state(jax.random.PRNGKey(0), model, zopt,
                               batch[0][:1], policy, scaler)
    step = make_zero_train_step(mesh, model, zopt, policy, donate=False)

    # Poison one element of shard 0's slice: only that replica's local grads
    # go nonfinite before the reduce.
    x, y = batch
    x_bad = x.at[0, 0, 0, 0].set(jnp.inf)
    p_before = jax.tree_util.tree_map(lambda p: np.asarray(p), state.params)
    mu_before = np.asarray(state.opt_state.mu)
    state, metrics = step(state, (x_bad, y))

    assert float(metrics["grads_finite"]) == 0.0
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(mu_before, np.asarray(state.opt_state.mu))
    assert int(state.opt_state.step) == 0
    assert float(state.scaler.scale) == 2.0 ** 3

    # Clean step afterwards: must actually train (params move, step counts).
    state, metrics = step(state, batch)
    assert float(metrics["grads_finite"]) == 1.0
    assert int(state.opt_state.step) == 1
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(p_before),
                        jax.tree_util.tree_leaves(state.params)))
    assert moved


def test_train_py_cli_bert_zero(devices8):
    """CLI end to end: BERT MLM under ZeRO-1 state sharding."""
    import train as train_mod
    assert train_mod.main(
        ["--arch", "bert_tiny", "--zero", "--opt", "adam",
         "--batch-size", "16", "--seq-len", "16", "--epochs", "1",
         "--steps-per-epoch", "3", "--opt-level", "O0",
         "--print-freq", "1"]) == 0


def test_train_py_zero_rejections():
    import train as train_mod
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "transformer_xl_tiny", "--zero",
                        "--opt", "adam"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "bert_tiny", "--zero", "--opt", "lamb"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "bert_tiny", "--zero", "--opt", "adam",
                        "--tensor-parallel", "2"])
