"""DistributedFusedAdam (ZeRO-1 state sharding): equivalence with the
replicated FusedAdam DDP step on the 8-device rig, and the 1/N state-memory
contract (SURVEY.md §3.4 contrib row / §3.3 weight-update sharding)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_example_tpu import amp
from apex_example_tpu.data import image_batch
from apex_example_tpu.engine import (create_train_state,
                                     make_sharded_train_step)
from apex_example_tpu.models import resnet18
from apex_example_tpu.optim import FusedAdam
from apex_example_tpu.optim.distributed import (DistributedFusedAdam,
                                                ZeroAdamState, _flat_size,
                                                _padded_size,
                                                make_zero_train_step)
from apex_example_tpu.parallel.mesh import make_data_mesh


def _setup(devices8, opt):
    policy, scaler = amp.initialize("O0")
    model = resnet18(num_classes=10, bn_axis_name="data")
    batch = image_batch(jnp.asarray(0), batch_size=16, image_size=32,
                        channels=3, num_classes=10, seed=0)
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               batch[0][:1], policy, scaler)
    return policy, model, batch, state


def test_zero_matches_replicated_adam(devices8):
    mesh = make_data_mesh(devices=devices8)
    hp = dict(lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=1e-2)

    policy, model, batch, state_ref = _setup(devices8, FusedAdam(**hp))
    ref_step = make_sharded_train_step(mesh, model, FusedAdam(**hp), policy,
                                       donate=False)

    zopt = DistributedFusedAdam(**hp, world=8, axis_name="data")
    _, _, _, state_z = _setup(devices8, zopt)
    zero_step = make_zero_train_step(mesh, model, zopt, policy, donate=False)

    for i in range(3):
        b = image_batch(jnp.asarray(i), batch_size=16, image_size=32,
                        channels=3, num_classes=10, seed=0)
        state_ref, m_ref = ref_step(state_ref, b)
        state_z, m_z = zero_step(state_z, b)

    # fp32 reduction-order noise only (flatten-then-slice vs per-leaf psum):
    # the earlier double-reduction bug showed up here as a 5e-3 loss drift.
    # Params get an absolute-only bound: Adam behaves like sign(g)·lr where
    # grads are near zero, so order-of-reduction noise can flip individual
    # updates (bounded by ~lr per step) without the trajectories diverging —
    # exact elementwise agreement is checked by the fixed-grads test below.
    np.testing.assert_allclose(float(m_ref["loss"]), float(m_z["loss"]),
                               rtol=1e-4)
    diffs = np.concatenate([
        np.abs(np.asarray(a) - np.asarray(b)).ravel()
        for a, b in zip(jax.tree_util.tree_leaves(state_ref.params),
                        jax.tree_util.tree_leaves(state_z.params))])
    # A handful of near-zero-grad elements may differ by up to ~lr per step
    # (sign flip); everything else must agree tightly.
    assert float((diffs < 5e-3).mean()) > 0.999
    assert float(diffs.max()) < 3 * 1e-2        # 3 steps x lr


def test_zero_apply_matches_fused_adam_fixed_grads(devices8):
    """One sharded apply on fixed (params, grads) == replicated FusedAdam
    elementwise — no model in the loop, so no sign-flip amplification."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map as smap
    except ImportError:
        from jax.experimental.shard_map import shard_map as smap

    mesh = make_data_mesh(devices=devices8)
    hp = dict(lr=3e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=1e-2)
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(40, 37), jnp.float32),
              "b": jnp.asarray(rng.randn(33), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(40, 37), jnp.float32),
             "b": jnp.asarray(rng.randn(33), jnp.float32)}

    ref = FusedAdam(**hp)
    st_ref = ref.init(params)
    p_ref, _ = ref.apply(grads, st_ref, params)

    zopt = DistributedFusedAdam(**hp, world=8, axis_name="data")
    st_z = zopt.init(params)

    def step(params, grads, st):
        # replicated grads stand in for the engine's already-psum-ed grads;
        # pre-multiply by world so the /world averaging is a no-op.
        g = jax.tree_util.tree_map(
            lambda g: g * jax.lax.axis_size("data"), grads)
        return zopt.apply(g, st, params)

    p_z, _ = jax.jit(smap(
        step, mesh=mesh,
        in_specs=(P(), P(), zopt.state_spec()),
        out_specs=(P(), zopt.state_spec())))(params, grads, st_z)

    for k in params:
        np.testing.assert_allclose(np.asarray(p_ref[k]), np.asarray(p_z[k]),
                                   atol=1e-6, rtol=1e-6)


def test_zero_state_is_one_nth(devices8):
    zopt = DistributedFusedAdam(lr=1e-3, world=8)
    params = {"a": jnp.zeros((1000, 37)), "b": jnp.zeros((13,))}
    st = zopt.init(params)
    padded = _padded_size(_flat_size(params), 8)
    assert st.mu.shape == (padded,) and padded % (8 * 128) == 0
    # Global buffer sharded over 8 devices => per-device bytes are 1/8 of
    # FusedAdam's per-device replicated state.
    mesh = make_data_mesh(devices=devices8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    mu = jax.device_put(st.mu, NamedSharding(mesh, P("data")))
    shard_bytes = mu.addressable_shards[0].data.nbytes
    assert shard_bytes == st.mu.nbytes // 8


def test_zero_fp16_dynamic_scaling_skips_in_lockstep(devices8):
    """fp16 + dynamic scaling + ZeRO: a nonfinite grad originating on ONE
    replica's microbatch must skip the step identically on all replicas —
    params, sharded (m, v) and the scaler all roll back together, and the
    next clean step trains normally.  (The finite check runs after the
    reduce; the flag is psum-ed so no replica can step alone.)"""
    mesh = make_data_mesh(devices=devices8)
    # Modest init scale: 2**10 keeps the CLEAN follow-up step overflowing in
    # fp16 (the scale must walk down first), which is correct scaler behavior
    # but not what this test pins — the lockstep skip is.  BN-free model: an
    # inf input permanently poisons BN *running stats* (apex semantics keep
    # forward-pass stat updates even on skipped steps), which would make
    # every later step nonfinite regardless of the optimizer's behavior.
    from flax import linen as fnn

    class _Mlp(fnn.Module):
        @fnn.compact
        def __call__(self, x, train: bool = True):
            x = x.reshape(x.shape[0], -1).astype(jnp.float16)
            x = fnn.relu(fnn.Dense(32, dtype=jnp.float16)(x))
            return fnn.Dense(10, dtype=jnp.float16)(x).astype(jnp.float32)

    policy, scaler = amp.initialize("O2", loss_scale="dynamic",
                                    half_dtype=jnp.float16,
                                    init_scale=2.0 ** 4)
    zopt = DistributedFusedAdam(lr=1e-2, world=8, axis_name="data")
    model = _Mlp()
    batch = image_batch(jnp.asarray(0), batch_size=16, image_size=32,
                        channels=3, num_classes=10, seed=0)
    state = create_train_state(jax.random.PRNGKey(0), model, zopt,
                               batch[0][:1], policy, scaler)
    step = make_zero_train_step(mesh, model, zopt, policy, donate=False)

    # Poison one element of shard 0's slice: only that replica's local grads
    # go nonfinite before the reduce.
    x, y = batch
    x_bad = x.at[0, 0, 0, 0].set(jnp.inf)
    p_before = jax.tree_util.tree_map(lambda p: np.asarray(p), state.params)
    mu_before = np.asarray(state.opt_state.mu)
    state, metrics = step(state, (x_bad, y))

    assert float(metrics["grads_finite"]) == 0.0
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(mu_before, np.asarray(state.opt_state.mu))
    assert int(state.opt_state.step) == 0
    assert float(state.scaler.scale) == 2.0 ** 3

    # Clean step afterwards: must actually train (params move, step counts).
    state, metrics = step(state, batch)
    assert float(metrics["grads_finite"]) == 1.0
    assert int(state.opt_state.step) == 1
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(p_before),
                        jax.tree_util.tree_leaves(state.params)))
    assert moved


def test_train_py_cli_bert_zero(devices8):
    """CLI end to end: BERT MLM under ZeRO-1 state sharding."""
    import train as train_mod
    assert train_mod.main(
        ["--arch", "bert_tiny", "--zero", "--opt", "adam",
         "--batch-size", "16", "--seq-len", "16", "--epochs", "1",
         "--steps-per-epoch", "3", "--opt-level", "O0",
         "--print-freq", "1"]) == 0


def test_train_py_zero_rejections():
    import train as train_mod
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "transformer_xl_tiny", "--zero",
                        "--opt", "adam"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "bert_tiny", "--zero", "--opt", "lamb"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "bert_tiny", "--zero", "--opt", "adam",
                        "--grad-accum", "2", "--batch-size", "16"])


# ---------------------------------------------------------------------------
# ZeRO-1 x tensor parallelism (VERDICT r4 item 2): under GSPMD the ZeRO
# contract is pure annotation — params keep their 'model'-axis TP specs,
# optimizer state (mu/nu) additionally shards over 'data'
# (engine.gspmd_state_shardings zero_axis) — and the partitioner derives
# reduce-scatter(grads) + data-sliced Adam + all-gather(params) from the
# sharding lattice, composed with the TP collectives in one jit program.
# ---------------------------------------------------------------------------

TP, SEQ, BATCH = 4, 16, 8


def _mlm(i, vocab):
    from apex_example_tpu.data import mlm_batch
    ids, labels, w = mlm_batch(jnp.asarray(i, jnp.int32), batch_size=BATCH,
                               seq_len=SEQ, vocab_size=vocab,
                               mask_token_id=vocab - 1, seed=0)
    return ids, (labels, w)


@pytest.fixture()
def tp_mesh(devices8):
    from apex_example_tpu.transformer import parallel_state
    mesh = parallel_state.initialize_model_parallel(tensor_parallel=TP,
                                                    devices=devices8)
    yield mesh
    parallel_state.set_mesh(None)


def test_zero_tp_matches_dense_trajectory(tp_mesh):
    """30 Adam steps of ZeRO-1 x TP BERT on the (data=2, model=4) mesh ==
    30 single-device dense steps from the same init and batches.  Same
    tolerance design as test_zero_matches_replicated_adam: Adam near zero
    grads behaves like sign(g)*lr, so partitioning-order noise can flip
    individual elements by ~lr/step without the trajectories diverging."""
    from apex_example_tpu.engine import (create_gspmd_train_state,
                                         create_train_state as mk_state,
                                         make_gspmd_train_step,
                                         make_train_step)
    from apex_example_tpu.models.bert import bert_tiny
    from apex_example_tpu.parallel.mesh import DATA_AXIS
    from apex_example_tpu.workloads import mlm_loss

    steps, lr = 30, 1e-3
    policy, scaler = amp.initialize("O0")
    dense = bert_tiny()
    tp_model = bert_tiny(tensor_parallel=True)
    V = dense.vocab_size
    opt = lambda: FusedAdam(lr=lr, weight_decay=1e-2)

    sample = _mlm(0, V)[0][:1]
    state_d = mk_state(jax.random.PRNGKey(0), dense, opt(), sample, policy,
                       scaler)
    step_d = jax.jit(make_train_step(dense, opt(), policy, loss_fn=mlm_loss,
                                     compute_accuracy=False))

    state_z, shardings = create_gspmd_train_state(
        jax.random.PRNGKey(0), tp_mesh, tp_model, opt(), sample, policy,
        scaler, zero_axis=DATA_AXIS)
    state_z = state_z.replace(
        params=jax.device_put(state_d.params, shardings.params))
    step_z = make_gspmd_train_step(tp_mesh, tp_model, opt(), policy,
                                   shardings, loss_fn=mlm_loss,
                                   compute_accuracy=False, donate=False)

    for i in range(steps):
        b = _mlm(i, V)
        state_d, m_d = step_d(state_d, b)
        state_z, m_z = step_z(state_z, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_z["loss"]),
                                   rtol=1e-4)

    diffs = np.concatenate([
        np.abs(np.asarray(a) - np.asarray(b)).ravel()
        for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                        jax.tree_util.tree_leaves(state_z.params))])
    assert float((diffs < 5e-3).mean()) > 0.999
    assert float(diffs.max()) < steps * lr * 3


def test_zero_tp_state_shards_both_axes(tp_mesh):
    """Params provably shard over 'model' AND opt state over 'data': the
    live buffers carry 1/TP param bytes and 1/(DP*TP) mu/nu bytes per
    device — the ZeRO-1 memory contract on top of TP's."""
    from jax.sharding import PartitionSpec as P

    from apex_example_tpu.engine import create_gspmd_train_state
    from apex_example_tpu.models.bert import bert_tiny
    from apex_example_tpu.parallel.mesh import DATA_AXIS

    dp = 8 // TP
    policy, scaler = amp.initialize("O0")
    model = bert_tiny(tensor_parallel=True)
    sample = _mlm(0, model.vocab_size)[0][:1]
    state, shardings = create_gspmd_train_state(
        jax.random.PRNGKey(0), tp_mesh, model, FusedAdam(lr=1e-3), sample,
        policy, scaler, zero_axis=DATA_AXIS)

    k = state.params["layer_0"]["intermediate"]["kernel"]
    mu = state.opt_state.mu["layer_0"]["intermediate"]["kernel"]
    nu = state.opt_state.nu["layer_0"]["intermediate"]["kernel"]
    # param: TP only (replicated over data — ZeRO-1, not ZeRO-3)
    assert k.addressable_shards[0].data.shape[1] == k.shape[1] // TP
    assert k.addressable_shards[0].data.nbytes == k.nbytes // TP
    # mu/nu: data x model
    for s in (mu, nu):
        assert s.addressable_shards[0].data.nbytes == s.nbytes // (dp * TP)
        assert DATA_AXIS in s.sharding.spec
    # the sharding spec tree says the same thing statically
    mu_spec = shardings.opt_state.mu["layer_0"]["intermediate"]["kernel"].spec
    assert DATA_AXIS in mu_spec and "model" in mu_spec
    # scalar step stays replicated
    assert state.opt_state.step.sharding.spec == P()


def test_zero_tp_fp16_dynamic_scaling_skips_globally(tp_mesh):
    """fp16 dynamic scaling under ZeRO-1 x TP: one jit program, so the
    finite flag is global by construction — a poisoned batch rolls back
    params AND the data-sharded (mu, nu) everywhere and halves the scale;
    a clean step then trains."""
    from apex_example_tpu.engine import (create_gspmd_train_state,
                                         make_gspmd_train_step)
    from apex_example_tpu.models.bert import bert_tiny
    from apex_example_tpu.parallel.mesh import DATA_AXIS
    from apex_example_tpu.workloads import mlm_loss

    policy, scaler = amp.initialize("O2", loss_scale="dynamic",
                                    half_dtype=jnp.float16,
                                    init_scale=2.0 ** 4)
    model = bert_tiny(tensor_parallel=True, dtype=jnp.float16)
    V = model.vocab_size
    opt = FusedAdam(lr=1e-3)
    sample = _mlm(0, V)[0][:1]
    state, shardings = create_gspmd_train_state(
        jax.random.PRNGKey(0), tp_mesh, model, opt, sample, policy, scaler,
        zero_axis=DATA_AXIS)
    step = make_gspmd_train_step(tp_mesh, model, opt, policy, shardings,
                                 loss_fn=mlm_loss, compute_accuracy=False,
                                 donate=False)

    ids, (labels, w) = _mlm(0, V)
    w_bad = w.at[0, 0].set(jnp.inf)
    p_before = jax.tree_util.tree_map(lambda p: np.asarray(p), state.params)
    o_before = jax.tree_util.tree_map(lambda p: np.asarray(p),
                                      state.opt_state)
    state, m = step(state, (ids, (labels, w_bad)))
    assert float(m["grads_finite"]) == 0.0
    assert float(state.scaler.scale) == 2.0 ** 3
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(o_before),
                    jax.tree_util.tree_leaves(state.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    state, m = step(state, (ids, (labels, w)))
    assert float(m["grads_finite"]) == 1.0
    assert int(state.opt_state.step) == 1
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(p_before),
                        jax.tree_util.tree_leaves(state.params)))
    assert moved


def test_train_py_cli_bert_zero_tensor_parallel(devices8):
    """The VERDICT contract: --zero --tensor-parallel 2 accepted and trains
    through the CLI on the (data=4, model=2) CPU mesh."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--zero", "--tensor-parallel", "2",
            "--batch-size", "16", "--seq-len", "16", "--epochs", "1",
            "--steps-per-epoch", "3", "--opt", "adam", "--opt-level", "O0",
            "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_train_py_cli_gpt_zero_tensor_parallel(devices8):
    """Same cell for the GPT causal-LM family (shared GSPMD path)."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "gpt_tiny", "--zero", "--tensor-parallel", "2",
            "--batch-size", "16", "--seq-len", "16", "--epochs", "1",
            "--steps-per-epoch", "3", "--opt", "adam", "--opt-level", "O0",
            "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


# ---------------------------------------------------------------------------
# ZeRO-1 x context parallelism (round 5): the flat (mu, nu) buffers shard
# over 'data' INSIDE the CP shard_map (workloads._cp_state_spec) while
# params replicate over (data, context) — long context with 1/N optimizer
# state.
# ---------------------------------------------------------------------------

def test_zero_cp_matches_cp_adam(devices8):
    """5 ZeRO x CP steps == 5 plain-FusedAdam CP steps from the same init
    (same tolerance design as test_zero_matches_replicated_adam: Adam's
    near-zero-grad sign flips bound elementwise diffs by ~lr/step), and
    the sharded (mu, nu) really live 1/data-axis per device."""
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_example_tpu.data import lm_batch
    from apex_example_tpu.models.gpt import gpt_tiny
    from apex_example_tpu.workloads import make_gpt_cp_train_step

    mesh = Mesh(np.array(devices8).reshape(2, 4), ("data", "context"))
    hp = dict(lr=1e-3, weight_decay=1e-2)
    dense = gpt_tiny()
    cp_model = gpt_tiny(context_parallel=True)
    V = dense.vocab_size
    policy, scaler = amp.initialize("O0")

    def batch(i):
        toks = lm_batch(jnp.asarray(i, jnp.int32), batch_size=8,
                        seq_len=16, vocab_size=V, seed=0)
        return toks[:, :-1], toks[:, 1:]

    sample = batch(0)[0][:1]
    state_a = create_train_state(jax.random.PRNGKey(0), dense,
                                 FusedAdam(**hp), sample, policy, scaler)
    step_a = make_gpt_cp_train_step(mesh, cp_model, FusedAdam(**hp),
                                    policy, donate=False)

    # grads_global_mean: the CP losses psum-normalize GLOBALLY, so the
    # implicitly psum-ed grads arrive as the true global mean — without
    # the flag the optimizer would divide by world again (Adam's scale
    # invariance would hide it from the loss/param comparison; the mu
    # norm check below would not).
    zopt = DistributedFusedAdam(**hp, world=2, axis_name="data",
                                grads_global_mean=True)
    state_z = create_train_state(jax.random.PRNGKey(0), dense, zopt,
                                 sample, policy, scaler)
    state_z = state_z.replace(params=state_a.params)
    step_z = make_gpt_cp_train_step(mesh, cp_model, zopt, policy,
                                    donate=False)

    for i in range(5):
        b = batch(i)
        state_a, m_a = step_a(state_a, b)
        state_z, m_z = step_z(state_z, b)
        np.testing.assert_allclose(float(m_a["loss"]), float(m_z["loss"]),
                                   rtol=1e-4)
    diffs = np.concatenate([
        np.abs(np.asarray(a) - np.asarray(b)).ravel()
        for a, b in zip(jax.tree_util.tree_leaves(state_a.params),
                        jax.tree_util.tree_leaves(state_z.params))])
    assert float((diffs < 5e-3).mean()) > 0.999
    assert float(diffs.max()) < 5 * 1e-3 * 3
    # The first-moment buffers must agree in NORM with the reference
    # adam's tree (Adam's update is scale-invariant, so a silently
    # rescaled gradient would pass the param comparison but not this).
    mu_ref = np.sqrt(sum(
        float(jnp.sum(m.astype(jnp.float32) ** 2))
        for m in jax.tree_util.tree_leaves(state_a.opt_state.mu)))
    mu_z = np.sqrt(float(jnp.sum(state_z.opt_state.mu ** 2)))
    np.testing.assert_allclose(mu_ref, mu_z, rtol=1e-3)
    # 1/N state: mu sharded over 'data', replicated over 'context'
    mu = state_z.opt_state.mu
    assert mu.addressable_shards[0].data.size * 2 == mu.size
    assert "data" in mu.sharding.spec


def test_train_py_cli_zero_context_parallel(devices8):
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "gpt_tiny", "--zero", "--context-parallel", "2",
            "--batch-size", "8", "--seq-len", "16", "--epochs", "1",
            "--steps-per-epoch", "3", "--opt", "adam", "--opt-level", "O0",
            "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        parallel_state.set_mesh(None)
