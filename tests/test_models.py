"""C4/C5 workload tests: BERT-MLM + FusedLAMB, Transformer-XL recurrence +
grad clip (SURVEY.md §1 configs 4-5), at test scale on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_example_tpu import amp
from apex_example_tpu.data import lm_batch, mlm_batch
from apex_example_tpu.engine import create_train_state, make_train_step
from apex_example_tpu.models.bert import bert_tiny
from apex_example_tpu.models.transformer_xl import transformer_xl_tiny
from apex_example_tpu.optim import FusedAdam, FusedLAMB
from apex_example_tpu.workloads import (lm_loss, make_txl_train_step,
                                        make_sharded_txl_train_step, mlm_loss)


def bert_batch(i, bs=8, L=16, V=256):
    ids, labels, weights = mlm_batch(jnp.asarray(i), batch_size=bs,
                                     seq_len=L, vocab_size=V,
                                     mask_token_id=V - 1, seed=3)
    return ids, (labels, weights)


class TestBertMLM:
    def test_forward_shapes(self):
        model = bert_tiny()
        ids, _ = bert_batch(0)
        vars_ = model.init(jax.random.PRNGKey(0), ids, train=False)
        logits = model.apply(vars_, ids, train=False)
        assert logits.shape == (*ids.shape, 256)
        assert logits.dtype == jnp.float32

    def test_c4_lamb_o2_loss_decreases(self):
        policy, scaler = amp.initialize("O2")
        model = bert_tiny(dtype=policy.compute_dtype,
                          param_dtype=policy.param_dtype)
        opt = FusedLAMB(lr=5e-3, weight_decay=0.01, max_grad_norm=1.0)
        ids, _ = bert_batch(0)
        state = create_train_state(jax.random.PRNGKey(0), model, opt, ids,
                                   policy, scaler)
        step = jax.jit(make_train_step(model, opt, policy, loss_fn=mlm_loss,
                                       compute_accuracy=False))
        losses = []
        for i in range(8):
            state, m = step(state, bert_batch(i))
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses

    def test_mlm_loss_only_counts_masked(self):
        logits = jnp.zeros((2, 4, 8))
        labels = jnp.zeros((2, 4), jnp.int32)
        # uniform logits -> CE = log(8) at every position
        w_all = jnp.ones((2, 4))
        w_none = jnp.zeros((2, 4))
        assert np.isclose(float(mlm_loss(logits, (labels, w_all))),
                          np.log(8), atol=1e-6)
        # no masked positions: loss defined (0), not NaN
        assert float(mlm_loss(logits, (labels, w_none))) == 0.0


class TestTransformerXL:
    def test_recurrence_carries_context(self):
        """Memory must change the prediction: same segment with fresh vs
        warmed mems gives different logits (the TXL capability)."""
        model = transformer_xl_tiny()
        toks = lm_batch(jnp.asarray(0), batch_size=2, seq_len=8,
                        vocab_size=256, seed=1)
        inp = toks[:, :8]
        vars_ = model.init(jax.random.PRNGKey(0), inp)
        logits0, mems1 = model.apply(vars_, inp)
        assert mems1.shape == (2, 2, 16, 64)   # (layers, B, mem, d)
        # warmed memories -> different output for the same input
        logits1, _ = model.apply(vars_, inp, mems=mems1)
        assert not np.allclose(np.asarray(logits0), np.asarray(logits1))

    def test_mems_gradient_stopped(self):
        model = transformer_xl_tiny()
        toks = lm_batch(jnp.asarray(0), batch_size=2, seq_len=8,
                        vocab_size=256, seed=2)
        inp, tgt = toks[:, :8], toks[:, 1:9]
        vars_ = model.init(jax.random.PRNGKey(0), inp)

        def loss_via_mems(params):
            _, mems = model.apply({"params": params}, inp)
            # grads through new mems must be zero (stop_gradient)
            return jnp.sum(mems ** 2)

        g = jax.grad(loss_via_mems)(vars_["params"])
        total = sum(float(jnp.abs(l).sum())
                    for l in jax.tree_util.tree_leaves(g))
        assert total == 0.0

    def test_c5_train_step_converges_with_clip(self):
        policy, scaler = amp.initialize("O0")
        model = transformer_xl_tiny()
        opt = FusedAdam(lr=3e-3)
        toks = lm_batch(jnp.asarray(0), batch_size=4, seq_len=9,
                        vocab_size=256, seed=5)
        inp = toks[:, :8]
        state = create_train_state(jax.random.PRNGKey(0), model, opt, inp,
                                   policy, scaler,
                                   train_kwargs={})
        mems = model.init_mems(4)
        step = jax.jit(make_txl_train_step(model, opt, policy,
                                           max_grad_norm=0.25))
        losses, norms = [], []
        for i in range(10):
            toks = lm_batch(jnp.asarray(i), batch_size=4, seq_len=9,
                            vocab_size=256, seed=5)
            batch = (toks[:, :8], toks[:, 1:9])
            state, mems, m = step(state, mems, batch)
            losses.append(float(m["loss"]))
            norms.append(float(m["grad_norm"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
        # clip path live: post-clip grad norm metric present and finite
        assert all(np.isfinite(norms))

    def test_txl_ddp_sharded(self, devices8):
        from apex_example_tpu.parallel import make_data_mesh
        policy, scaler = amp.initialize("O0")
        model = transformer_xl_tiny()
        opt = FusedAdam(lr=1e-3)
        mesh = make_data_mesh(devices=devices8)
        toks = lm_batch(jnp.asarray(0), batch_size=8, seq_len=9,
                        vocab_size=256, seed=6)
        inp = toks[:, :8]
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   inp[:1], policy, scaler, train_kwargs={})
        mems = model.init_mems(8)
        step = make_sharded_txl_train_step(mesh, model, opt, policy,
                                           donate=False)
        for i in range(2):
            toks = lm_batch(jnp.asarray(i), batch_size=8, seq_len=9,
                            vocab_size=256, seed=6)
            state, mems, m = step(state, mems, (toks[:, :8], toks[:, 1:9]))
        assert np.isfinite(float(m["loss"]))
        assert int(state.step) == 2


class TestSpaceToDepthStem:
    def test_equivalent_to_7x7_stem(self):
        """The s2d stem is a reparametrization: same param tree, same math
        (MLPerf-style; apex_example_tpu/models/resnet.py)."""
        from apex_example_tpu.models.resnet import ResNet, Bottleneck
        kw = dict(stage_sizes=[1, 1], block_cls=Bottleneck, num_classes=10,
                  num_filters=8)
        m_plain = ResNet(stem_space_to_depth=False, **kw)
        m_s2d = ResNet(stem_space_to_depth=True, **kw)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                        jnp.float32)
        v_plain = m_plain.init(jax.random.PRNGKey(7), x, train=False)
        v_s2d = m_s2d.init(jax.random.PRNGKey(7), x, train=False)
        # identical param trees (same names, shapes, and init values)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            v_plain["params"], v_s2d["params"])
        y_plain = m_plain.apply(v_plain, x, train=False)
        y_s2d = m_s2d.apply(v_plain, x, train=False)
        np.testing.assert_allclose(np.asarray(y_plain), np.asarray(y_s2d),
                                   atol=1e-5, rtol=1e-5)

    def test_odd_input_falls_back(self):
        from apex_example_tpu.models.resnet import ResNet, BasicBlock
        m = ResNet(stage_sizes=[1, 1], block_cls=BasicBlock, num_classes=4,
                   num_filters=8, stem_space_to_depth=True)
        x = jnp.zeros((1, 31, 31, 3), jnp.float32)
        v = m.init(jax.random.PRNGKey(0), x, train=False)
        out = m.apply(v, x, train=False)
        assert out.shape == (1, 4)


def test_txl_grad_accum_matches_full_batch():
    """grad_accum=K over batch streams == one full-batch step (same math:
    recurrence is per-stream, grads average)."""
    from apex_example_tpu.models.transformer_xl import transformer_xl_tiny
    from apex_example_tpu.optim import FusedSGD
    from apex_example_tpu.workloads import make_txl_train_step
    from apex_example_tpu.engine import create_train_state

    policy, scaler = amp.initialize("O0")
    model = transformer_xl_tiny()
    # SGD: the update is linear in the grads, so the K=1 vs K=2 comparison
    # measures the accumulation math itself (Adam's first-step m/sqrt(v) is
    # a sign() for near-zero grads and would amplify fp32 summation-order
    # noise to +-lr).
    opt = FusedSGD(lr=3e-2, momentum=0.0)
    toks = lm_batch(jnp.asarray(0), batch_size=4, seq_len=9,
                    vocab_size=256, seed=7)
    batch = (toks[:, :8], toks[:, 1:9])
    mems = model.init_mems(4)

    def run(k):
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   batch[0], policy, scaler, train_kwargs={})
        step = jax.jit(make_txl_train_step(model, opt, policy,
                                           max_grad_norm=0.25,
                                           grad_accum=k))
        state, new_mems, m = step(state, mems, batch)
        return state, new_mems, m

    s1, m1, met1 = run(1)
    s2, m2, met2 = run(2)
    np.testing.assert_allclose(float(met1["loss"]), float(met2["loss"]),
                               rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-4),
        s1.params, s2.params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4), m1, m2)


def test_resnet_family_builders():
    """torchvision family parity: every ARCHS entry builds and produces
    fp32 logits (tiny spatial size keeps CPU cost trivial)."""
    from apex_example_tpu.models import ARCHS
    assert set(ARCHS) == {"resnet18", "resnet34", "resnet50", "resnet101",
                          "resnet152"}
    x = jnp.ones((1, 32, 32, 3))
    for name in ("resnet34", "resnet101", "resnet152"):  # 18/50 covered
        model = ARCHS[name](num_classes=7, num_filters=8, small_stem=True)
        params = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(params, x, train=False)
        assert out.shape == (1, 7) and out.dtype == jnp.float32


@pytest.mark.parametrize("remat", ["conv", "block"])
def test_resnet_remat_is_pure_schedule_choice(remat):
    """remat variants (PERF.md HBM-traffic experiments) must not change the
    math: identical param tree (names pinned through nn.remat's wrapper) and
    identical loss/grads vs remat='none'."""
    from apex_example_tpu.models.resnet import Bottleneck, ResNet
    kw = dict(stage_sizes=[1, 1], block_cls=Bottleneck, num_filters=8,
              small_stem=True, num_classes=5)
    x = jnp.asarray(np.random.RandomState(3).randn(2, 16, 16, 3),
                    jnp.float32)

    def run(r):
        m = ResNet(remat=r, **kw)
        v = m.init(jax.random.PRNGKey(0), x, train=False)

        def loss(p):
            out, _ = m.apply({"params": p,
                              "batch_stats": v["batch_stats"]},
                             x, train=True, mutable=["batch_stats"])
            return jnp.sum(out ** 2)
        l, g = jax.jit(jax.value_and_grad(loss))(v["params"])
        return v["params"], float(l), g

    p0, l0, g0 = run("none")
    p1, l1, g1 = run(remat)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)), p0, p1)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


class TestFlashAutoCrossover:
    """fused_attention="auto" keys on the measured ~2k crossover
    (models/bert.py FLASH_AUTO_MIN_SEQ; PERF.md attention table)."""

    def test_policy_resolution(self):
        from apex_example_tpu.models.bert import (FLASH_AUTO_MIN_SEQ,
                                                  _resolve_fused_attention)
        f32, bf16 = jnp.float32, jnp.bfloat16
        assert _resolve_fused_attention("auto", 128, f32) is False
        assert _resolve_fused_attention("auto", FLASH_AUTO_MIN_SEQ, f32) \
            is True
        assert _resolve_fused_attention("auto", 8192, f32) is True
        # explicit bool wins over the crossover
        assert _resolve_fused_attention(True, 128, f32) is True
        assert _resolve_fused_attention(False, 8192, f32) is False
        # half softmax (O3) always forces the naive path
        assert _resolve_fused_attention("auto", 8192, bf16) is False
        assert _resolve_fused_attention(True, 8192, bf16) is False
        with pytest.raises(ValueError):
            _resolve_fused_attention("yes", 128, f32)

    def test_auto_routes_through_kernel_above_crossover(self, monkeypatch):
        """Count flash_attention op invocations at trace time: 0 below the
        crossover, one per layer at/above it."""
        from apex_example_tpu.models import bert as bert_mod
        from apex_example_tpu.ops import attention as attn_mod
        calls = []
        real = attn_mod.flash_attention

        def spy(*a, **k):
            calls.append(a[0].shape)
            return real(*a, **k)
        monkeypatch.setattr(attn_mod, "flash_attention", spy)

        monkeypatch.setattr(bert_mod, "FLASH_AUTO_MIN_SEQ", 32)
        model = bert_tiny()    # fused_attention defaults to "auto"
        ids16 = jnp.zeros((2, 16), jnp.int32)
        v = model.init(jax.random.PRNGKey(0), ids16, train=False)
        jax.eval_shape(lambda: model.apply(v, ids16, train=False))
        assert calls == []
        ids32 = jnp.zeros((2, 32), jnp.int32)
        jax.eval_shape(lambda: model.apply(v, ids32, train=False))
        assert len(calls) == model.num_layers
