"""Hot-path overhead attribution (obs/tickprof.py, schema v15;
ISSUE 17):

- TickProfiler fold semantics: phase sketches, sampling cadence,
  host-gap arithmetic, schema-valid tick_profile / overhead_summary
  emission, unknown-phase and bad-kind rejection,
- the jax-free contract: tickprof.py loads BY FILE PATH on a bare
  host (no package __init__, no jax in sys.modules) — the loader
  perf_ledger itself uses,
- the armed serve smoke on the session's SLOTS=4/MAX_LEN=32 compiled
  geometry: greedy outputs token-identical to one-shot generate(),
  phase components sum to tick wall within 1%, ONE compile_event with
  the profiler + tracer + cost model all armed (zero new compiled
  programs), trace_export --check clean with the host_gap_ms counter
  track present in the export, serve_summary carrying the v15 idle +
  host_overhead_frac fields, serve_report's OVERHEAD lines rendered,
- idle-spin accounting: a staggered workload accrues idle_ticks and
  (with idle_wait_s) idle_wait_ms in the summary,
- the perf-regression ledger over the checked-in recorded fixtures
  (tests/fixtures/perf/): schema-valid, ci_gate --perf-stream PASS,
  a tampered host fraction FAILS, missing stream exits 2,
  PERF_BASELINE.json round-trips and compares clean at HEAD while a
  shifted baseline value is flagged as a regression,
- report degradation: pre-v15 streams render no OVERHEAD line; the
  train fixture renders one via telemetry_report,
- v15 back-compat: every older checked-in fixture stream (v10-v14)
  still validates, and the two hard-coded jax-free SCHEMA constants
  (resilience/supervisor.py, fleet/router.py) moved in lockstep,
- graftlint's schema-emission rule covers the two new record types
  (an undeclared field on either fires statically).
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import obs
from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.obs.tickprof import (DEVICE_PHASE, SERVE_PHASES,
                                           TRAIN_PHASES, TickProfiler)
from apex_example_tpu.serve import ServeEngine, synthetic_requests

pytestmark = pytest.mark.tickprof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE_FIXTURE = os.path.join(REPO, "tests", "fixtures", "perf",
                             "serve_perf.jsonl")
TRAIN_FIXTURE = os.path.join(REPO, "tests", "fixtures", "perf",
                             "train_perf.jsonl")
BASELINE = os.path.join(REPO, "PERF_BASELINE.json")
SLOTS, MAX_LEN = 4, 32          # the session-shared decode geometry


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fixture_records(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


class ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)

    def close(self):
        pass


# ====================================== profiler fold semantics (unit)

def test_profiler_folds_phases_and_samples_every_nth():
    sink = ListSink()
    prof = TickProfiler(kind="serve", sample_every=3,
                        emit=sink.write, run_id="r0")
    for i in range(7):
        rec = prof.observe_tick(i * 0.01, 10.0, admit=1.0,
                                dispatch_enqueue=0.5, device_wait=7.0,
                                harvest=1.0, spool_io=0.2,
                                telemetry=0.3)
        # sampled on ticks 0, 3, 6; None in between
        assert (rec is not None) == (i % 3 == 0)
    assert prof.ticks == 7 and prof.sampled == 3
    assert len(sink.records) == 3
    for rec in sink.records:
        assert rec["record"] == "tick_profile"
        assert rec["kind"] == "serve" and rec["run_id"] == "r0"
        assert set(rec["phases"]) == set(SERVE_PHASES)
        assert sum(rec["phases"].values()) == pytest.approx(10.0)
        # host gap = wall - device_wait, per tick
        assert rec["host_gap_ms"] == pytest.approx(3.0)
    # cumulative accessors: every tick folded, not just the sampled ones
    assert prof.wall_ms == pytest.approx(70.0)
    assert prof.device_ms() == pytest.approx(49.0)
    assert prof.host_gap_ms() == pytest.approx(21.0)
    assert prof.host_overhead_frac() == pytest.approx(0.3)

    summ = prof.summary_record()
    sink.write(summ)
    assert summ["record"] == "overhead_summary"
    assert summ["ticks"] == 7 and summ["sampled"] == 3
    assert summ["host_overhead_frac"] == pytest.approx(0.3)
    assert set(summ["phases"]) == set(SERVE_PHASES)
    for name in SERVE_PHASES:
        ph = summ["phases"][name]
        assert ph["count"] == 7
        assert ph["p50"] > 0 or ph["total_ms"] >= 0
    assert summ["phases"]["device_wait"]["total_ms"] == \
        pytest.approx(summ["device_ms"])
    # constant per-tick inputs: the sketch percentiles sit on the value
    assert summ["wall"]["count"] == 7
    assert summ["wall"]["p50"] == pytest.approx(10.0, rel=0.02)
    assert summ["host_gap"]["p50"] == pytest.approx(3.0, rel=0.02)
    # everything emitted is schema-valid v15
    assert obs_schema.validate_stream(sink.records) == []


def test_profiler_rejects_unknown_phase_and_bad_kind():
    with pytest.raises(ValueError):
        TickProfiler(kind="mystery")
    with pytest.raises(ValueError):
        TickProfiler(kind="serve", sample_every=0)
    prof = TickProfiler(kind="train")
    with pytest.raises(ValueError):
        prof.observe_tick(0.0, 1.0, admit=1.0)   # a SERVE phase
    ok = dict.fromkeys(TRAIN_PHASES, 0.2)
    prof.observe_tick(0.0, 1.0, **ok)
    assert prof.device_ms() == pytest.approx(0.2)
    assert DEVICE_PHASE["train"] == "device"
    assert DEVICE_PHASE["serve"] == "device_wait"
    # no emit wired: observe_tick still folds, returns None
    assert prof.observe_tick(0.1, 1.0, **ok) is None
    assert prof.host_overhead_frac() == pytest.approx(0.8)


def test_tickprof_loads_jax_free_by_file_path():
    """The contract perf_ledger depends on: tickprof.py (and its slo.py
    fallback import) must load by file path on a host with no package
    import — and pull in NO jax."""
    code = (
        "import importlib.util, sys\n"
        "spec = importlib.util.spec_from_file_location('tp', "
        f"{os.path.join(REPO, 'apex_example_tpu', 'obs', 'tickprof.py')!r})\n"
        "tp = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(tp)\n"
        "prof = tp.TickProfiler(kind='serve')\n"
        "prof.observe_tick(0.0, 2.0, admit=0.5, dispatch_enqueue=0.5,\n"
        "                  device_wait=0.5, harvest=0.25, spool_io=0.0,\n"
        "                  telemetry=0.25)\n"
        "assert prof.summary_record()['record'] == 'overhead_summary'\n"
        "assert 'jax' not in sys.modules, 'tickprof pulled in jax'\n"
        "assert 'apex_example_tpu' not in sys.modules\n"
        "print('JAXFREE-OK')\n")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "JAXFREE-OK" in out.stdout


# =================== armed serve smoke (shared compiled geometry)

@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def test_armed_serve_smoke_decomposes_without_perturbing(
        model_and_params, tmp_path, compile_events, capsys):
    """The acceptance bar: the profiler armed on the session's
    SLOTS=4/MAX_LEN=32 smoke — greedy outputs stay token-identical to
    one-shot generate(), every tick's phase components sum to its wall
    within 1%, the explicit block-until-ready boundary adds ZERO
    compiled programs (one compile_event, cost_report gate passes),
    trace_export --check stays clean and the export carries the
    host_gap_ms counter track, and the summary/report surface the v15
    fields."""
    from apex_example_tpu.obs import costmodel
    from apex_example_tpu.obs import trace as trace_lib
    model, params = model_and_params
    path = str(tmp_path / "armed.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={"slots": SLOTS, "max_len": MAX_LEN},
                       arch="gpt_tiny")
    prof = TickProfiler(kind="serve", sample_every=1, emit=sink.write,
                        run_id=emitter.run_id)
    costmodel.set_default(obs.CostModel(
        sink=sink, registry=emitter.registry, run_id=emitter.run_id))
    trace_lib.set_default(obs.Tracer(sink, run_id=emitter.run_id))
    try:
        reqs = synthetic_requests(8, vocab_size=model.vocab_size,
                                  seed=3, prompt_len=(3, 8),
                                  max_new=(3, 12), stagger=4)
        eng = ServeEngine(model, params, num_slots=SLOTS,
                          max_len=MAX_LEN, rng=jax.random.PRNGKey(0),
                          sink=sink, run_id=emitter.run_id,
                          registry=emitter.registry,
                          tick_profiler=prof)
        eng.queue.submit_all(reqs)
        eng.queue.close()
        comps = eng.run(max_steps=2000)
    finally:
        costmodel.set_default(None)
        trace_lib.set_default(None)
    summary = eng.summary_record()
    sink.write(summary)
    sink.write(prof.summary_record())
    sink.close()
    assert len(comps) == 8

    # (a) the profiler is a pure observer: token-identical to one-shot
    # generate() on every request's output-budget prefix.
    by_uid = {c.request.uid: c for c in comps}
    for r in reqs:
        c = by_uid[r.uid]
        P, n = len(r.prompt), len(c.tokens)
        assert n == min(r.max_new_tokens, MAX_LEN - P)
        ref = generate(model, params,
                       jnp.asarray([r.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(np.asarray(ref)[0, P:P + n],
                                      np.asarray(c.tokens, np.int32),
                                      err_msg=r.uid)

    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []

    # (b) the 1% decomposition invariant, per sampled tick AND on the
    # cumulative summary — enforced by the contiguous-boundary design,
    # asserted here against the recorded stream.
    ticks = [r for r in records if r["record"] == "tick_profile"]
    assert len(ticks) == prof.ticks == prof.sampled > 0
    for t in ticks:
        assert set(t["phases"]) == set(SERVE_PHASES)
        total = sum(t["phases"].values())
        assert abs(total - t["wall_ms"]) <= 0.01 * t["wall_ms"] + 1e-6
        gap = t["wall_ms"] - t["phases"]["device_wait"]
        assert t["host_gap_ms"] == pytest.approx(gap, abs=1e-6)
    ov = next(r for r in records if r["record"] == "overhead_summary")
    assert ov["ticks"] == len(ticks)
    phase_total = sum(p["total_ms"] for p in ov["phases"].values())
    assert abs(phase_total - ov["wall_ms"]) <= 0.01 * ov["wall_ms"]
    assert ov["host_gap_ms"] == \
        pytest.approx(ov["wall_ms"] - ov["device_ms"], abs=1e-6)
    assert ov["host_overhead_frac"] == \
        pytest.approx(ov["host_gap_ms"] / ov["wall_ms"], abs=1e-9)
    # ... which is exactly what perf_ledger's always-on gate recomputes
    perf_ledger = _load_tool("perf_ledger")
    assert perf_ledger.consistency_errors(records) == []

    # (c) compile-once with the profiler armed: the block-until-ready
    # boundary syncs values the tick was about to sync anyway — ONE
    # compile_event, and the actual CI gate command agrees.
    assert compile_events(records) == {"serve_decode_step": 1}
    assert compile_events.gate(path) == 0
    capsys.readouterr()

    # (d) the trace stratum: --check clean, and the export carries the
    # host-gap counter track (Perfetto ph "C") from the tick_profile
    # samples.
    trace_export = _load_tool("trace_export")
    assert trace_export.main(["--check", path]) == 0
    trace_out = str(tmp_path / "trace.json")
    assert trace_export.main([path, "-o", trace_out]) == 0
    doc = json.load(open(trace_out))
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert len(counters) == len(ticks)
    assert {e["name"] for e in counters} == {"host_gap_ms"}
    assert all("host_gap_ms" in e["args"] for e in counters)
    capsys.readouterr()

    # (e) the v15 summary fields + serve_report's OVERHEAD rendering.
    assert summary["idle_ticks"] >= 0
    assert summary["host_overhead_frac"] == \
        pytest.approx(ov["host_overhead_frac"], abs=1e-5)
    serve_report = _load_tool("serve_report")
    assert serve_report.report(path) == 0
    out = capsys.readouterr().out
    assert "OVERHEAD: host_overhead_frac" in out
    assert "phases (p50/p99 ms):" in out
    for name in SERVE_PHASES:
        assert name in out
    assert "idle:" in out


def test_idle_spin_accounting_lands_in_summary(model_and_params):
    """Satellite 1: a staggered workload (second arrival 40 virtual
    ticks after the first wave finishes) accrues idle_ticks, and
    idle_wait_s-throttled spins accrue idle_wait_ms — both in the
    serve_summary, profiler armed or not."""
    model, params = model_and_params
    reqs = synthetic_requests(2, vocab_size=model.vocab_size, seed=7,
                              prompt_len=(3, 4), max_new=(3, 4),
                              stagger=40)
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0))
    eng.queue.submit_all(reqs)
    eng.queue.close()
    comps = eng.run(max_steps=2000, idle_wait_s=0.0005)
    assert len(comps) == 2
    summary = eng.summary_record()
    assert summary["idle_ticks"] > 0
    assert summary["idle_wait_ms"] > 0.0
    assert eng.idle_ticks + eng.compute_steps == eng.step_count
    # no profiler on this engine: the fraction accessor stays None and
    # the summary omits the field rather than claiming 0.0
    assert eng.host_overhead_frac() is None
    assert "host_overhead_frac" not in summary


# ============== ledger + gates over the recorded perf fixtures

def test_perf_fixtures_validate_and_carry_the_decomposition():
    for path, kind, phases in ((SERVE_FIXTURE, "serve", SERVE_PHASES),
                               (TRAIN_FIXTURE, "train", TRAIN_PHASES)):
        records = _fixture_records(path)
        assert obs_schema.validate_stream(records) == [], path
        ticks = [r for r in records if r["record"] == "tick_profile"]
        assert ticks, path
        ov = next(r for r in records
                  if r["record"] == "overhead_summary")
        assert ov["kind"] == kind
        assert set(ov["phases"]) == set(phases), path
        assert 0.0 <= ov["host_overhead_frac"] <= 1.0


def test_ci_gate_perf_stream_passes_on_fixtures(capsys):
    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--perf-stream", SERVE_FIXTURE,
                         "--perf-stream", TRAIN_FIXTURE,
                         "--perf-baseline", BASELINE]) == 0
    out = capsys.readouterr().out
    assert f"ci_gate: perf gate {SERVE_FIXTURE}: PASS" in out
    assert f"ci_gate: perf gate {TRAIN_FIXTURE}: PASS" in out
    assert ci_gate.main(
        ["--perf-stream", SERVE_FIXTURE + ".missing"]) == 2
    assert ci_gate.main(["--perf-stream", SERVE_FIXTURE,
                         "--perf-baseline",
                         BASELINE + ".missing"]) == 2


def test_ci_gate_perf_stream_fails_on_tamper(tmp_path, capsys):
    """The tamper gate: an edited host fraction (or phase totals that
    stop summing to wall) must FAIL no matter how wide the baseline's
    noise bands are — consistency is checked against the stream's own
    arithmetic."""
    ci_gate = _load_tool("ci_gate")
    records = _fixture_records(SERVE_FIXTURE)

    def rewrite(mutate):
        out = []
        for rec in records:
            rec = json.loads(json.dumps(rec))     # deep copy
            mutate(rec)
            out.append(rec)
        p = tmp_path / "tampered.jsonl"
        p.write_text("".join(json.dumps(r) + "\n" for r in out))
        return str(p)

    def forge_fraction(rec):
        if rec["record"] == "overhead_summary":
            rec["host_overhead_frac"] = 0.01      # "we're efficient"

    assert ci_gate.main(["--perf-stream", rewrite(forge_fraction)]) == 1
    assert "tampered" in capsys.readouterr().err

    def shrink_a_phase(rec):
        if rec["record"] == "tick_profile":
            rec["phases"]["dispatch_enqueue"] *= 0.5

    assert ci_gate.main(["--perf-stream", rewrite(shrink_a_phase)]) == 1
    assert "sum to wall" in capsys.readouterr().err

    def drop_summary(rec):
        if rec["record"] == "overhead_summary":
            rec["record"] = "tick_profile"        # will also fail lint

    assert ci_gate.main(["--perf-stream", rewrite(drop_summary)]) == 1
    assert "overhead_summary" in capsys.readouterr().err


def test_perf_baseline_round_trips_and_flags_regressions(tmp_path,
                                                         capsys):
    """PERF_BASELINE.json is generated FROM the checked-in fixtures, so
    comparing the fixtures against it is exact — exit 0 at HEAD.  A
    re-derived baseline matches the checked-in one, and shifting a
    value past its noise band is flagged."""
    perf_ledger = _load_tool("perf_ledger")
    assert perf_ledger.main([SERVE_FIXTURE, TRAIN_FIXTURE,
                             "--compare", BASELINE]) == 0
    assert "compare vs" in capsys.readouterr().out

    # round-trip: snapshot -> make_baseline == the checked-in file
    snaps = [perf_ledger.snapshot(_fixture_records(p), p)
             for p in (SERVE_FIXTURE, TRAIN_FIXTURE)]
    assert json.load(open(BASELINE)) == perf_ledger.make_baseline(snaps)
    assert perf_ledger.compare(snaps, json.load(open(BASELINE))) == []

    # regression: a host fraction drifting past its band is named
    shifted = perf_ledger.make_baseline(snaps)
    m = shifted["streams"]["serve"]["metrics"]["host_overhead_frac"]
    m["value"] = m["value"] * 0.5                  # 50% drop, 10% band
    failures = perf_ledger.compare(snaps, shifted)
    assert any("host_overhead_frac" in f and "regression" in f
               for f in failures)
    # exact-band counters catch any drift at all
    shifted2 = perf_ledger.make_baseline(snaps)
    shifted2["streams"]["serve"]["metrics"]["requests"]["value"] += 1
    assert perf_ledger.compare(snaps, shifted2) != []
    # unusable inputs exit 2
    assert perf_ledger.main([str(tmp_path / "nope.jsonl")]) == 2
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json\n")
    assert perf_ledger.main([str(bad)]) == 2


# ===================== report degradation + schema back-compat

def test_reports_degrade_gracefully_on_pre_v15_streams(capsys):
    """Pre-v15 streams carry no overhead_summary / idle fields: both
    report tools must render WITHOUT an OVERHEAD line, not crash — and
    the v15 train fixture must render one."""
    old_serve = os.path.join(REPO, "tests", "fixtures", "slo",
                             "serve_slo.jsonl")
    serve_report = _load_tool("serve_report")
    assert serve_report.report(old_serve) == 0
    assert "OVERHEAD" not in capsys.readouterr().out
    telemetry_report = _load_tool("telemetry_report")
    assert telemetry_report.report(old_serve) == 0
    assert "OVERHEAD" not in capsys.readouterr().out
    assert telemetry_report.report(TRAIN_FIXTURE) == 0
    out = capsys.readouterr().out
    assert "OVERHEAD: kind train" in out
    assert "data_wait" in out and "dispatch" in out


def test_v17_validates_every_older_fixture_stream():
    """v17 is a strict superset: every checked-in v10-v16 fixture
    stream still validates unchanged, and the two hard-coded jax-free
    SCHEMA constants moved in lockstep with SCHEMA_VERSION."""
    assert obs_schema.SCHEMA_VERSION == 17
    fixture_root = os.path.join(REPO, "tests", "fixtures")
    seen = 0
    for sub in ("slo", "fleet", "quant", "disagg", "perf", "spec",
                "sched"):
        d = os.path.join(fixture_root, sub)
        for name in sorted(os.listdir(d)):
            if not name.endswith(".jsonl"):
                continue
            records = _fixture_records(os.path.join(d, name))
            assert obs_schema.validate_stream(records) == [], name
            seen += 1
    assert seen >= 7            # the older strata are actually covered
    sup = _load_tool_pkg("apex_example_tpu/resilience/supervisor.py",
                         "_sup")
    router = _load_tool_pkg("apex_example_tpu/fleet/router.py",
                            "_router")
    assert sup.SCHEMA == obs_schema.SCHEMA_VERSION
    assert router.SCHEMA == obs_schema.SCHEMA_VERSION


def _load_tool_pkg(rel, name):
    """Grep-light SCHEMA extraction: both files are jax-free by
    contract but import their package siblings, so read the constant
    textually instead of executing them here."""
    class _C:
        pass

    with open(os.path.join(REPO, rel)) as fh:
        for line in fh:
            if line.startswith("SCHEMA = "):
                c = _C()
                c.SCHEMA = int(line.split("=")[1].split("#")[0])
                return c
    raise AssertionError(f"no SCHEMA constant in {rel}")


def test_schema_emission_rule_covers_v15_record_types():
    """graftlint's static schema-emission rule knows tick_profile and
    overhead_summary: valid emitters are quiet, an undeclared field on
    either fires with the bump-the-schema message."""
    from tools.graftlint import schema_rules
    from tools.graftlint.base import tree_from_sources
    with open(os.path.join(REPO, "apex_example_tpu", "obs",
                           "schema.py")) as fh:
        real_schema = fh.read()
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": real_schema,
        "pkg/emit.py": """
def emit(sink, ts, phases):
    sink.write({"record": "tick_profile", "time": 1.0, "ts": ts,
                "kind": "serve", "tick": 3, "wall_ms": 2.0,
                "host_gap_ms": 1.0, "phases": phases})
    sink.write({"record": "overhead_summary", "time": 1.0,
                "kind": "serve", "ticks": 4, "wall_ms": 8.0,
                "device_ms": 4.0, "host_gap_ms": 4.0,
                "host_overhead_frac": 0.5, "phases": phases})
"""})
    assert schema_rules.check(tree) == []
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": real_schema,
        "pkg/emit.py": """
def emit(sink, ts, phases):
    rec = {"record": "tick_profile", "time": 1.0, "ts": ts,
           "kind": "serve", "tick": 3, "wall_ms": 2.0,
           "host_gap_ms": 1.0, "phases": phases}
    rec["gpu_ms"] = 0.5            # undeclared: needs a schema bump
    sink.write(rec)
    sink.write({"record": "overhead_summary", "time": 1.0,
                "kind": "serve", "ticks": 4})   # missing required
"""})
    msgs = [f.message for f in schema_rules.check(tree)]
    assert any("'tick_profile' emits field 'gpu_ms'" in m
               and "bump the schema" in m for m in msgs)
    assert any("never sets required field 'host_overhead_frac'" in m
               for m in msgs)


def test_fleet_tick_profile_advertises_worst_replica(tmp_path, capsys):
    """fleet.py --tick-profile (thread transport, the session's
    SLOTS=4/MAX_LEN=32 geometry): every replica engine gets an
    ACCUMULATE-only profiler (no per-engine sink), heartbeats advertise
    the cumulative host_overhead_frac, the router's close emits one
    final replica_state per armed replica carrying it, the stream stays
    schema-valid with NO v15 tick records leaking into the router
    stream, fleet_report names the worst-host-overhead replica, and
    perf_ledger's fleet snapshot ranks on the same number."""
    import fleet as fleet_cli

    path = str(tmp_path / "fleet.jsonl")
    rc = fleet_cli.main(["--transport", "thread", "--replicas", "2",
                         "--requests", "6", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN),
                         "--tick-profile", "--tick-profile-every", "4",
                         "--metrics-jsonl", path])
    assert rc == 0
    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    kinds = {r["record"] for r in records}
    assert "tick_profile" not in kinds       # router stream stays
    assert "overhead_summary" not in kinds   # fleet-only (emit=None)
    fracs = [r for r in records if r["record"] == "replica_state"
             and "host_overhead_frac" in r]
    assert {r["replica"] for r in fracs} == {"r0", "r1"}
    assert all(0.0 < r["host_overhead_frac"] <= 1.0 for r in fracs)

    fleet_report = _load_tool("fleet_report")
    capsys.readouterr()
    assert fleet_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "host overhead: worst replica" in out
    assert "2 replica(s) reporting" in out

    perf_ledger = _load_tool("perf_ledger")
    snap = perf_ledger.snapshot(records, path)
    assert snap["kind"] == "fleet"
    worst = max(r["host_overhead_frac"] for r in fracs)
    assert snap["metrics"]["worst_host_overhead_frac"] == \
        pytest.approx(worst)
