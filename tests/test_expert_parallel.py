"""Expert parallelism (switch-MoE over the expert axis): sharded all_to_all
dispatch == dense per-shard golden, gradients, capacity-overflow semantics.

EP is a beyond-reference extension (SURVEY.md §3.2 marks it absent there);
these tests define and pin its semantics the way the CP tests do."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_example_tpu.transformer.expert_parallel import (
    EXPERT_AXIS, MoEParams, _dispatch_masks, init_moe_params,
    moe_forward, moe_forward_dense_reference)

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def _mesh(devices8):
    return Mesh(np.asarray(devices8), (EXPERT_AXIS,))


def test_sharded_matches_dense_reference(devices8):
    mesh = _mesh(devices8)
    E, T, d, h = 8, 16, 32, 64          # T per device
    params = init_moe_params(jax.random.PRNGKey(0), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(1), (E * T, d), jnp.float32)

    sharded = jax.jit(shard_map(
        lambda p, x: moe_forward(p, x),
        mesh=mesh,
        in_specs=(MoEParams(P(), P(EXPERT_AXIS), P(EXPERT_AXIS)),
                  P(EXPERT_AXIS)),
        out_specs=(P(EXPERT_AXIS), P())))
    y, aux = sharded(params, x)

    # dense golden, shard by shard (routing/capacity are per-device)
    ys, auxs = [], []
    for s in range(E):
        ref_y, ref_aux = moe_forward_dense_reference(
            params, x[s * T:(s + 1) * T])
        ys.append(ref_y)
        auxs.append(ref_aux)
    np.testing.assert_allclose(np.asarray(y), np.concatenate(ys),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(aux), np.mean(auxs), rtol=1e-6)


def test_gradients_match_dense_reference(devices8):
    mesh = _mesh(devices8)
    E, T, d, h = 8, 8, 16, 32
    params = init_moe_params(jax.random.PRNGKey(2), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(3), (E * T, d), jnp.float32)

    def sharded_loss(p, x):
        def inner(p, xs):
            y, aux = moe_forward(p, xs)
            return lax.psum(jnp.sum(y.astype(jnp.float32) ** 2),
                            EXPERT_AXIS) + 0.01 * aux
        return shard_map(
            inner, mesh=mesh,
            in_specs=(MoEParams(P(), P(EXPERT_AXIS), P(EXPERT_AXIS)),
                      P(EXPERT_AXIS)),
            out_specs=P())(p, x)

    def dense_loss(p, x):
        total = 0.0
        auxs = []
        for s in range(E):
            y, aux = moe_forward_dense_reference(p, x[s * T:(s + 1) * T])
            total = total + jnp.sum(y.astype(jnp.float32) ** 2)
            auxs.append(aux)
        return total + 0.01 * jnp.mean(jnp.stack(auxs))

    g_sh = jax.grad(sharded_loss)(params, x)
    g_ref = jax.grad(dense_loss)(params, x)
    for a, b in zip(g_sh, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_capacity_overflow_drops_tokens():
    """Tokens beyond an expert's capacity get zero dispatch AND zero combine
    weight (the switch static-shape drop)."""
    T, E, C = 12, 2, 4
    # all tokens prefer expert 0
    logits = jnp.stack([jnp.ones(T), -jnp.ones(T)], axis=1)
    dispatch, combine, _ = _dispatch_masks(logits, C)
    # first C tokens occupy expert 0 slots 0..C-1; rest dropped
    total_dispatched = float(dispatch.sum())
    assert total_dispatched == C
    assert float(dispatch[C:].sum()) == 0.0
    assert float(combine[C:].sum()) == 0.0
    # kept tokens land in distinct slots
    slots = np.asarray(dispatch[:C, 0]).argmax(axis=1)
    assert sorted(slots.tolist()) == list(range(C))


def test_dropped_tokens_output_zero(devices8):
    """A dropped token's MoE output is exactly zero (identity residual adds
    happen outside the block)."""
    mesh = _mesh(devices8)
    E, T, d, h = 8, 32, 16, 32
    params = init_moe_params(jax.random.PRNGKey(4), d, h, E)
    # identical tokens all pick the same expert; capacity_factor 0.25 over
    # 32 tokens -> 8 slots (after lane rounding) -> 24 of 32 dropped.
    x = jnp.tile(jax.random.normal(jax.random.PRNGKey(5), (1, d)), (E * T, 1))

    sharded = jax.jit(shard_map(
        lambda p, xs: moe_forward(p, xs, capacity_factor=0.25)[0],
        mesh=mesh,
        in_specs=(MoEParams(P(), P(EXPERT_AXIS), P(EXPERT_AXIS)),
                  P(EXPERT_AXIS)),
        out_specs=P(EXPERT_AXIS)))
    y = np.asarray(sharded(params, x))
    # identical tokens all route to one expert; capacity 8*0.25/8 -> 8 slots
    # min => some rows kept, the rest exactly zero
    nonzero = np.abs(y).sum(axis=1) > 0
    assert nonzero.any() and (~nonzero).any()
    np.testing.assert_array_equal(y[~nonzero], 0.0)


def test_top2_sharded_matches_dense_reference(devices8):
    """GShard-style top-2: the all_to_all dispatch must equal the dense
    per-shard golden with the same (two-slot) masks."""
    mesh = _mesh(devices8)
    E, T, d, h = 8, 16, 32, 64
    params = init_moe_params(jax.random.PRNGKey(4), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(5), (E * T, d), jnp.float32)

    sharded = jax.jit(shard_map(
        lambda p, x: moe_forward(p, x, top_k=2),
        mesh=mesh,
        in_specs=(MoEParams(P(), P(EXPERT_AXIS), P(EXPERT_AXIS)),
                  P(EXPERT_AXIS)),
        out_specs=(P(EXPERT_AXIS), P())))
    y, aux = sharded(params, x)
    ys, auxs = [], []
    for s in range(E):
        ref_y, ref_aux = moe_forward_dense_reference(
            params, x[s * T:(s + 1) * T], top_k=2)
        ys.append(ref_y)
        auxs.append(ref_aux)
    np.testing.assert_allclose(np.asarray(y), np.concatenate(ys),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(aux), np.mean(auxs), rtol=1e-6)


def test_top2_semantics():
    """Top-2 invariants on the masks directly: every un-dropped token is
    dispatched to its two distinct top experts with renormalized gates
    summing to 1; at generous capacity nothing is dropped."""
    from apex_example_tpu.transformer.expert_parallel import _dispatch_masks
    T, E, C = 16, 4, 16                       # capacity >> T: no drops
    logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
    dispatch, combine, _aux = _dispatch_masks(logits, C, top_k=2)
    d_np, c_np = np.asarray(dispatch), np.asarray(combine)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    order = np.argsort(-probs, axis=-1)
    for t in range(T):
        experts = set(np.argwhere(d_np[t].sum(-1) > 0)[:, 0])
        assert experts == {order[t, 0], order[t, 1]}, t
        np.testing.assert_allclose(c_np[t].sum(), 1.0, rtol=1e-6)
    # each expert's capacity slots hold at most one token
    assert (d_np.sum(axis=0) <= 1.0 + 1e-6).all()


def test_top2_capacity_drops_second_choices_first():
    """Under capacity pressure the second opinions are dropped before any
    kept first choice (the GShard queueing convention)."""
    from apex_example_tpu.transformer.expert_parallel import _dispatch_masks
    T, E = 8, 2
    # every token's first choice is expert 0, second expert 1
    logits = jnp.tile(jnp.asarray([[2.0, 1.0]]), (T, 1))
    C = 4
    dispatch, combine, _ = _dispatch_masks(logits, C, top_k=2)
    d_np = np.asarray(dispatch)
    # expert 0: exactly C first-choice tokens kept (tokens 0..C-1)
    assert d_np[:C, 0].sum() == C and d_np[C:, 0].sum() == 0
    # expert 1: its queue is all second choices, first C kept
    assert d_np[:C, 1].sum() == C and d_np[C:, 1].sum() == 0


def test_multi_expert_per_device_matches_dense(devices8):
    """E = 2 experts per device x 8 devices = 16 experts: the grouped
    all_to_all (sender-major <-> expert-major transposes around the
    batched local FFN) must equal the dense per-shard golden."""
    mesh = _mesh(devices8)
    E, T, d, h = 16, 16, 32, 64          # T per device
    params = init_moe_params(jax.random.PRNGKey(6), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(7), (8 * T, d), jnp.float32)

    sharded = jax.jit(shard_map(
        lambda p, x: moe_forward(p, x),
        mesh=mesh,
        in_specs=(MoEParams(P(), P(EXPERT_AXIS), P(EXPERT_AXIS)),
                  P(EXPERT_AXIS)),
        out_specs=(P(EXPERT_AXIS), P())))
    y, aux = sharded(params, x)
    ys, auxs = [], []
    for s in range(8):
        ref_y, ref_aux = moe_forward_dense_reference(
            params, x[s * T:(s + 1) * T])
        ys.append(ref_y)
        auxs.append(ref_aux)
    np.testing.assert_allclose(np.asarray(y), np.concatenate(ys),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(aux), np.mean(auxs), rtol=1e-6)
