"""Diagnostics-stratum coverage (obs/flight.py, obs/watchdog.py,
obs/numerics.py, tools/fleet_report.py; ISSUE 2):

- schema v2 records + v1 back-compat,
- Histogram.percentile nearest-rank regression (the off-by-one fix),
- flight-recorder crash dumps (unit + a SIGTERM'd subprocess C1 run),
- stall-watchdog stall records and disarm,
- overflow provenance: NaN-injection naming the poisoned module,
- fleet_report straggler / overflow-divergence detection,
- metrics_lint --require-summary exit codes, telemetry_report abort
  summaries.  (The jax-free guard for the tools/ thin clients is now
  STATIC: graftlint's import-graph rule, tests/test_graftlint.py —
  ISSUE 9 retired the per-tool poisoned-jax subprocess loop here.)

Subprocess tests carry the ``diag`` marker (pytest.ini) so the crash-path
suite is selectable with ``-m diag``; everything here rides tier-1.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import pytest

import train as train_mod
from apex_example_tpu import amp, obs
from apex_example_tpu.obs import schema as obs_schema

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _header(rank=0):
    return {"record": "run_header", "schema": obs_schema.SCHEMA_VERSION,
            "time": 0.0, "run_id": "r", "num_devices": 1,
            "process_index": rank, "platform": "cpu", "config": {}}


def _step(i, ms=10.0, loss=1.0, finite=1.0):
    return {"record": "step", "step": i, "epoch": 0, "loss": loss,
            "scale": 1.0, "step_time_ms": ms, "items_per_sec": 100.0,
            "grads_finite": finite}


def _write_stream(path, records):
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")


# ------------------------------------------------------- schema v2

def test_schema_v2_diagnostics_records_validate():
    crash = {"record": "crash_dump", "time": 1.0, "reason": "signal:SIGTERM",
             "step": 7, "thread_stacks": "...", "last_steps": [_step(7)],
             "registry": {}, "env": {"python": "3"}, "config": {}}
    stall = {"record": "stall", "time": 1.0, "seconds_since_step": 12.5,
             "step": 3, "deadline_s": 10.0, "thread_stacks": "..."}
    overflow = {"record": "overflow_event", "time": 1.0, "step": 4,
                "modules": ["branch_a"], "module_stats":
                {"branch_a": {"nonfinite": 3, "grad_norm": 1.0}},
                "mode": "overflow", "scale": 65536.0}
    aborted = {"record": "run_summary", "steps": 7, "overflow_count": 0,
               "aborted": True, "abort_reason": "signal:SIGTERM"}
    for rec in (crash, stall, overflow, aborted):
        assert obs.validate_record(rec) == [], rec["record"]
    assert obs_schema.validate_stream(
        [_header(), _step(1), overflow, crash, aborted]) == []


def test_schema_v1_streams_still_validate():
    """v2 is a strict superset: a pre-PR stream (schema field 1, no
    diagnostics records) must keep validating byte-for-byte."""
    v1_header = dict(_header(), schema=1)
    v1_summary = {"record": "run_summary", "steps": 2, "overflow_count": 0,
                  "first_step_ms": 50.0, "steady_step_ms": 5.0,
                  "compile_est_ms": 45.0}
    assert obs_schema.validate_stream(
        [v1_header, _step(1), _step(2), v1_summary]) == []


def test_schema_still_rejects_unknown_and_malformed():
    assert obs.validate_record({"record": "crash_dump"})   # missing fields
    assert obs.validate_record(
        {"record": "overflow_event", "time": 1.0, "step": 1,
         "modules": "branch_a"})                           # str, not list
    assert obs.validate_record(
        {"record": "stall", "time": 1.0, "seconds_since_step": 1.0,
         "typo_field": 1})                                 # unknown field


# --------------------------------------- percentile (satellite fix)

def test_histogram_percentile_nearest_rank():
    """int(q/100*n) biased high on small even samples: p50 of [1,2,3,4]
    returned 3.  Nearest-rank is ceil(q/100*n)-1: the 2nd value, 2."""
    h = obs.Histogram("t")
    for v in [1.0, 2.0, 3.0, 4.0]:
        h.observe(v)
    assert h.percentile(50) == 2.0
    assert h.percentile(100) == 4.0
    assert h.percentile(0) == 1.0
    h5 = obs.Histogram("t5")
    for v in [5.0, 1.0, 3.0, 2.0, 4.0]:    # unsorted on purpose
        h5.observe(v)
    assert h5.percentile(50) == 3.0
    h1 = obs.Histogram("t1")
    h1.observe(7.0)
    assert h1.percentile(50) == 7.0 and h1.percentile(95) == 7.0
    h100 = obs.Histogram("t100")
    for v in range(1, 101):
        h100.observe(float(v))
    assert h100.percentile(95) == 95.0
    assert h100.percentile(50) == 50.0


# -------------------------------------------------- flight recorder

def test_flight_recorder_crash_dump_and_aborted_summary(tmp_path):
    path = str(tmp_path / "f.jsonl")
    emitter = obs.TelemetryEmitter(obs.JsonlSink(path, rank=0))
    emitter.run_header(config={"arch": "x"})
    recorder = obs.FlightRecorder(emitter, keep=3,
                                  config={"arch": "x", "fn": print})
    emitter.add_observer(recorder.on_record)
    for i in range(5):
        emitter.on_step(global_step=i + 1, epoch=0,
                        metrics={"loss": 1.0, "scale": 1.0},
                        items=4, t_start=time.perf_counter())
    rec = recorder.crash_dump("signal:SIGTERM", thread_stacks=True)
    assert rec is not None
    assert recorder.crash_dump("again") is None        # dump-once

    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    crash = next(r for r in records if r["record"] == "crash_dump")
    assert crash["reason"] == "signal:SIGTERM"
    assert crash["step"] == 5
    assert [s["step"] for s in crash["last_steps"]] == [3, 4, 5]  # ring
    assert "fn" not in crash["config"]                 # JSON-safe subset
    assert "MainThread" in crash["thread_stacks"]
    summary = records[-1]
    assert summary["record"] == "run_summary"
    assert summary["aborted"] is True
    assert summary["abort_reason"] == "signal:SIGTERM"
    assert summary["steps"] == 5
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(path, require_summary=True)
    assert code == 0, errors


def test_flight_recorder_install_close_restores_hooks():
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    prev_hook = sys.excepthook
    sink = obs.JsonlSink("/tmp/unused_diag.jsonl", rank=1)   # inactive rank
    recorder = obs.FlightRecorder(sink=sink)
    recorder.install()
    assert signal.getsignal(signal.SIGTERM) == recorder._on_signal
    assert sys.excepthook == recorder._on_excepthook
    recorder.close()
    assert signal.getsignal(signal.SIGTERM) == prev_term
    assert signal.getsignal(signal.SIGINT) == prev_int
    assert sys.excepthook == prev_hook


def test_flight_recorder_sink_only_mode(tmp_path):
    """bench.py/accuracy.py form: no emitter — crash_dump plus a minimal
    aborted summary."""
    path = str(tmp_path / "b.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    recorder = obs.FlightRecorder(sink=sink)
    recorder.crash_dump("exception:RuntimeError")
    records = obs.read_jsonl(path)
    assert [r["record"] for r in records] == ["crash_dump", "run_summary"]
    assert records[1]["aborted"] is True
    assert obs_schema.validate_stream(records) == []


def test_close_telemetry_dumps_on_unwinding_exception(tmp_path):
    """train.py's finally path: an exception unwinding through
    close_telemetry yields crash_dump + aborted summary, not a clean
    close."""
    path = str(tmp_path / "u.jsonl")
    emitter = obs.TelemetryEmitter(obs.JsonlSink(path, rank=0))
    emitter.run_header(config={})
    recorder = obs.FlightRecorder(emitter)
    recorder.install()
    with pytest.raises(RuntimeError):
        try:
            raise RuntimeError("boom")
        finally:
            train_mod.close_telemetry(emitter, None, recorder, None)
    records = obs.read_jsonl(path)
    kinds = [r["record"] for r in records]
    assert "crash_dump" in kinds
    crash = next(r for r in records if r["record"] == "crash_dump")
    assert crash["reason"] == "exception:RuntimeError"
    assert "boom" in crash["traceback"]
    assert records[-1]["aborted"] is True
    # hooks restored by the close inside close_telemetry
    assert signal.getsignal(signal.SIGTERM) != recorder._on_signal


# ---------------------------------------------------- stall watchdog

def test_watchdog_emits_stall_and_rearms(tmp_path):
    path = str(tmp_path / "w.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    wd = obs.StallWatchdog(sink, deadline_s=0.05, run_id="r", poll_s=0.01)
    wd.start()
    try:
        time.sleep(0.2)                       # first gap: must fire ONCE
        assert wd.stall_count == 1
        wd.notify_step(7)                     # recover + re-arm
        time.sleep(0.2)                       # second gap: fires again
        assert wd.stall_count == 2
    finally:
        wd.close()
    count_at_close = wd.stall_count
    time.sleep(0.15)                          # disarmed: no more records
    assert wd.stall_count == count_at_close
    records = obs.read_jsonl(path)
    assert [r["record"] for r in records] == ["stall", "stall"]
    assert all(obs.validate_record(r) == [] for r in records)
    assert records[0]["seconds_since_step"] >= 0.05
    assert "MainThread" in records[0]["thread_stacks"]
    assert records[1]["step"] == 7            # last completed step
    assert records[0]["run_id"] == "r"


def test_watchdog_quiet_while_steps_flow(tmp_path):
    path = str(tmp_path / "q.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    wd = obs.StallWatchdog(sink, deadline_s=0.2, poll_s=0.01)
    wd.start()
    try:
        for i in range(10):
            time.sleep(0.02)
            wd.notify_step(i + 1)
    finally:
        wd.close()
    assert wd.stall_count == 0
    assert not os.path.exists(path)           # nothing ever written


def test_watchdog_rejects_nonpositive_deadline(tmp_path):
    with pytest.raises(ValueError):
        obs.StallWatchdog(obs.JsonlSink(str(tmp_path / "x"), rank=0),
                          deadline_s=0.0)


# ------------------------------------------------ overflow provenance

class _TwoBranch:
    """Built lazily: flax import kept inside the factory."""

    @staticmethod
    def build():
        import flax.linen as nn

        class TwoBranch(nn.Module):
            @nn.compact
            def __call__(self, x, train=True):
                a = nn.Dense(4, name="branch_a")(x)
                b = nn.Dense(4, name="branch_b")(x)
                # tanh: its backward multiplies by values computed FROM
                # branch_a's params, so poisoned params yield NaN grads
                # (a linear branch's grads would stay finite).
                return jnp.tanh(a) + b

        return TwoBranch()


def test_module_grad_stats_names_nonfinite_module():
    grads = {"branch_a": {"kernel": jnp.array([1.0, jnp.nan, jnp.inf])},
             "branch_b": {"kernel": jnp.array([3.0, 4.0])}}
    stats = obs.module_grad_stats(grads)
    assert int(stats["branch_a"]["nonfinite"]) == 2
    assert int(stats["branch_b"]["nonfinite"]) == 0
    assert float(stats["branch_b"]["grad_norm"]) == pytest.approx(5.0)


def test_nan_injection_overflow_event_names_poisoned_module(tmp_path):
    """The acceptance bar: a NaN-poisoned module is NAMED by the
    overflow_event the engine + NumericsMonitor emit."""
    from apex_example_tpu.engine import create_train_state, make_train_step

    model = _TwoBranch.build()
    policy, scaler = amp.initialize("O0", loss_scale="dynamic")
    import optax
    x = jnp.ones((4, 8), jnp.float32)
    state = create_train_state(jax.random.PRNGKey(0), model,
                               optax.sgd(0.1), x, policy, scaler,
                               train_kwargs={"train": False})
    step_fn = jax.jit(make_train_step(
        model, optax.sgd(0.1), policy, compute_accuracy=False,
        loss_fn=lambda logits, y: logits.astype(jnp.float32).mean(),
        numerics=True))

    # Clean step first: grads finite, no overflow_event in overflow mode.
    path = str(tmp_path / "n.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    monitor = obs.NumericsMonitor(sink, mode="overflow", run_id="r")
    new_state, metrics = step_fn(state, (x, jnp.zeros((4,), jnp.int32)))
    assert monitor.on_step(1, metrics) is None
    assert float(metrics["grads_finite"]) == 1.0

    # Poison branch_a's params; branch_b's grads stay finite (additive
    # heads: the NaN branch's cotangent never reaches branch_b).
    params = dict(state.params)
    params["branch_a"] = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, jnp.nan), dict(params["branch_a"]))
    poisoned = state.replace(params=params)
    _, metrics = step_fn(poisoned, (x, jnp.zeros((4,), jnp.int32)))
    assert float(metrics["grads_finite"]) == 0.0
    rec = monitor.on_step(2, metrics)
    assert rec is not None
    assert rec["modules"] == ["branch_a"]
    assert rec["module_stats"]["branch_a"]["nonfinite"] > 0
    assert rec["module_stats"]["branch_b"]["nonfinite"] == 0
    assert obs.validate_record(rec) == []
    sink.close()
    records = obs.read_jsonl(path)
    assert [r["record"] for r in records] == ["overflow_event"]


def test_numerics_monitor_always_mode_and_bounds(tmp_path):
    sink = obs.JsonlSink(str(tmp_path / "a.jsonl"), rank=0)
    monitor = obs.NumericsMonitor(sink, mode="always", max_events=2)
    metrics = {"grads_finite": 1.0, "numerics":
               {"m": {"nonfinite": jnp.asarray(0), "grad_norm":
                      jnp.asarray(1.0)}}}
    assert monitor.on_step(1, metrics)["modules"] == []   # finite, named no-one
    assert monitor.on_step(2, metrics) is not None
    assert monitor.on_step(3, metrics) is None            # max_events cap
    with pytest.raises(ValueError):
        obs.NumericsMonitor(sink, mode="bogus")


# ----------------------------------------------------- fleet report

def _rank_stream(path, rank, n=12, steady_ms=10.0, overflow_at=(),
                 summary=True, tail_ms=None):
    recs = [_header(rank)]
    for i in range(1, n + 1):
        ms = steady_ms * 10 if i == 1 else steady_ms       # compile step
        if tail_ms is not None and i > n // 2:
            ms = tail_ms
        recs.append(_step(i, ms=ms,
                          finite=0.0 if i in overflow_at else 1.0))
    if summary:
        recs.append({"record": "run_summary", "steps": n,
                     "overflow_count": len(overflow_at)})
    _write_stream(path, recs)


def test_fleet_report_flags_injected_straggler(tmp_path, capsys):
    """The acceptance bar: a 2-rank fixture with one injected straggler
    (3x the step time) gets flagged, with rank auto-discovery."""
    base = str(tmp_path / "out.jsonl")
    _rank_stream(base, 0, steady_ms=10.0)
    _rank_stream(base + ".rank1", 1, steady_ms=31.0)
    fleet = _load_tool("fleet_report")
    rc = fleet.main([base])
    out = capsys.readouterr().out
    assert rc == 1
    assert "STRAGGLER: rank 1" in out
    assert "anomalies: 1" in out


def test_fleet_report_clean_fleet_exits_zero(tmp_path, capsys):
    base = str(tmp_path / "out.jsonl")
    _rank_stream(base, 0, steady_ms=10.0)
    _rank_stream(base + ".rank1", 1, steady_ms=10.5)
    fleet = _load_tool("fleet_report")
    assert fleet.main([base]) == 0
    assert "anomalies: 0" in capsys.readouterr().out


def test_fleet_report_overflow_divergence_and_abort(tmp_path, capsys):
    base = str(tmp_path / "out.jsonl")
    _rank_stream(base, 0, overflow_at=(3,))
    _rank_stream(base + ".rank1", 1, overflow_at=(), summary=False)
    fleet = _load_tool("fleet_report")
    rc = fleet.main([base])
    out = capsys.readouterr().out
    assert rc == 1
    assert "OVERFLOW DIVERGENCE" in out
    assert "ABORTED: rank 1" in out


def test_fleet_report_step_time_regression(tmp_path, capsys):
    base = str(tmp_path / "solo.jsonl")
    _rank_stream(base, 0, n=16, steady_ms=10.0, tail_ms=20.0)
    fleet = _load_tool("fleet_report")
    assert fleet.main([base]) == 1
    assert "STEP-TIME REGRESSION" in capsys.readouterr().out


def test_fleet_report_ignores_non_rank_siblings(tmp_path, capsys):
    """A stale out.jsonl.rank1.bak next to the real files must be skipped
    by discovery, not crash the sort."""
    base = str(tmp_path / "out.jsonl")
    _rank_stream(base, 0, steady_ms=10.0)
    _rank_stream(base + ".rank1", 1, steady_ms=10.0)
    with open(base + ".rank1.bak", "w") as fh:
        fh.write("garbage\n")
    fleet = _load_tool("fleet_report")
    assert fleet.main([base]) == 0
    assert "fleet: 2 rank(s)" in capsys.readouterr().out


def test_fleet_report_unusable_input(tmp_path, capsys):
    empty = str(tmp_path / "empty.jsonl")
    _write_stream(empty, [_header()])
    fleet = _load_tool("fleet_report")
    assert fleet.main([empty]) == 2


# ----------------------------------------- lint + report satellites

def test_metrics_lint_require_summary_exit_codes(tmp_path):
    lint = _load_tool("metrics_lint")
    complete = str(tmp_path / "ok.jsonl")
    _write_stream(complete, [_header(), _step(1),
                             {"record": "run_summary", "steps": 1,
                              "overflow_count": 0}])
    truncated = str(tmp_path / "cut.jsonl")
    _write_stream(truncated, [_header(), _step(1)])
    invalid = str(tmp_path / "bad.jsonl")
    _write_stream(invalid, [{"record": "nope"}])

    assert lint.lint(complete, require_summary=True)[0] == 0
    assert lint.lint(truncated)[0] == 0                 # valid, no demand
    code, errors = lint.lint(truncated, require_summary=True)
    assert code == 2 and "run_summary" in errors[0]
    assert lint.lint(invalid, require_summary=True)[0] == 1
    assert lint.main([truncated, "--require-summary"]) == 2


def test_telemetry_report_flags_aborted_runs(tmp_path, capsys):
    report = _load_tool("telemetry_report")
    # (a) stream that just stops — no summary at all
    cut = str(tmp_path / "cut.jsonl")
    _write_stream(cut, [_header(), _step(1), _step(2, finite=0.0)])
    assert report.main([cut]) == 0
    out = capsys.readouterr().out
    assert "ABORTED RUN" in out
    assert "overflow steps" in out and "(at 2)" in out  # indices listed
    # (b) flight-recorder stream: crash_dump + aborted summary
    crashed = str(tmp_path / "crash.jsonl")
    _write_stream(crashed, [
        _header(), _step(1),
        {"record": "crash_dump", "time": 1.0, "reason": "signal:SIGTERM",
         "step": 1},
        {"record": "stall", "time": 1.0, "seconds_since_step": 33.0},
        {"record": "run_summary", "steps": 1, "overflow_count": 0,
         "aborted": True, "abort_reason": "signal:SIGTERM"}])
    assert report.main([crashed]) == 0
    out = capsys.readouterr().out
    assert "ABORTED RUN: signal:SIGTERM" in out
    assert "crash_dump at step 1" in out
    assert "stalls: 1" in out


def test_telemetry_report_bench_stream_is_not_aborted(tmp_path, capsys):
    """bench.py/accuracy.py streams never carry a run_summary by design —
    they must not be labeled ABORTED."""
    report = _load_tool("telemetry_report")
    bench = str(tmp_path / "bench.jsonl")
    _write_stream(bench, [{"record": "bench", "metric": "m", "value": 1.0,
                           "unit": "img/s"}])
    report.main([bench])
    assert "ABORTED" not in capsys.readouterr().out


# ------------------------------------------------- CLI flag guards

def test_diag_flags_require_metrics_jsonl():
    for extra in (["--flight-recorder"], ["--stall-timeout", "5"],
                  ["--numerics-check", "overflow"]):
        with pytest.raises(SystemExit):
            train_mod.main(["--arch", "resnet18"] + extra)
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "resnet18", "--metrics-jsonl", "/tmp/x",
                        "--stall-trace"])                 # needs timeout
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "transformer_xl_tiny", "--metrics-jsonl",
                        "/tmp/x", "--numerics-check", "overflow"])


# --------------------------------- tier-1 CLI smoke (clean diag run)

C1_DIAG_ARGS = ["--arch", "resnet18", "--dataset", "cifar10", "--opt-level",
                "O0", "--epochs", "1", "--steps-per-epoch", "4",
                "--batch-size", "8", "--num-devices", "1",
                "--print-freq", "4"]


def test_c1_clean_run_with_diagnostics_armed(tmp_path, capsys):
    """Recorder + watchdog + numerics armed on a clean run: zero crash/
    stall records, an UN-aborted summary, per-step overflow_events in
    'always' mode (empty modules — nothing overflowed), hooks disarmed,
    stdout meters intact.  Also the IMAGE-loop --save-every-steps wiring
    (ISSUE 4): interval checkpoints + host-state sidecars ride this run
    rather than paying a second resnet compile in test_resilience.py."""
    path = str(tmp_path / "clean.jsonl")
    ck = str(tmp_path / "ck")
    prev_term = signal.getsignal(signal.SIGTERM)
    rc = train_mod.main(C1_DIAG_ARGS + [
        "--metrics-jsonl", path, "--flight-recorder",
        "--stall-timeout", "600", "--numerics-check", "always",
        "--checkpoint-dir", ck, "--save-every-steps", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "epoch 0 step 4/4" in out
    assert "saved checkpoint at step 2" in out             # interval save
    assert signal.getsignal(signal.SIGTERM) == prev_term   # disarmed
    from apex_example_tpu.utils.checkpoint import CheckpointManager
    mgr = CheckpointManager(ck)
    assert mgr.latest_step() == 4                 # epoch-end save, once
    assert mgr.load_host_state(2)["step_in_epoch"] == 2
    assert mgr.load_host_state(4)["data_index"] == 4
    mgr.close()
    records = obs.read_jsonl(path)
    kinds = [r["record"] for r in records]
    assert "crash_dump" not in kinds and "stall" not in kinds
    assert kinds.count("overflow_event") == 4              # always mode
    events = [r for r in records if r["record"] == "overflow_event"]
    assert all(r["modules"] == [] for r in events)         # all finite
    summary = records[-1]
    assert summary["record"] == "run_summary"
    assert "aborted" not in summary
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(path, steps=4, require_summary=True)
    assert code == 0, errors


# ------------------------------------- subprocess crash-path (diag)

@pytest.mark.diag
def test_sigterm_mid_flight_yields_crash_dump(tmp_path):
    """The acceptance bar: SIGTERM a C1 run mid-flight; the JSONL must
    hold a schema-valid crash_dump + aborted run_summary and pass
    metrics_lint --require-summary."""
    path = str(tmp_path / "killed.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, os.path.join(REPO, "train.py"),
           "--arch", "resnet18", "--dataset", "cifar10", "--opt-level",
           "O0", "--epochs", "1", "--steps-per-epoch", "2000",
           "--batch-size", "8", "--num-devices", "1",
           "--metrics-jsonl", path, "--flight-recorder",
           "--flight-recorder-keep", "8"]
    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.time() + 300
        steps_seen = 0
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if os.path.exists(path):
                with open(path) as fh:
                    steps_seen = sum(1 for line in fh
                                     if '"record":"step"' in line)
                if steps_seen >= 3:
                    break
            time.sleep(0.25)
        assert proc.poll() is None, (
            f"run ended before it could be killed:\n"
            f"{proc.communicate()[1].decode(errors='replace')[-2000:]}")
        assert steps_seen >= 3, "no steps within the deadline"
        proc.send_signal(signal.SIGTERM)
        proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    # SIG_DFL re-delivery: conventional 128+15 (or raw -15 from wait4)
    assert proc.returncode in (-signal.SIGTERM, 128 + signal.SIGTERM)

    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    kinds = [r["record"] for r in records]
    assert "crash_dump" in kinds
    crash = next(r for r in records if r["record"] == "crash_dump")
    assert crash["reason"] == "signal:SIGTERM"
    assert 1 <= len(crash["last_steps"]) <= 8              # bounded ring
    summary = records[-1]
    assert summary["record"] == "run_summary"
    assert summary["aborted"] is True
    assert summary["abort_reason"] == "signal:SIGTERM"
    assert summary["steps"] >= 3
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(path, require_summary=True)
    assert code == 0, errors
    report = _load_tool("telemetry_report")
    assert report.main([path]) == 0


# ---------------------------------------- jax-free tools guard (diag)
#
# RETIRED (ISSUE 9): the runtime poisoned-jax guard — one subprocess
# per tools/ thin client with a broken ``jax`` module first on
# PYTHONPATH — is replaced by graftlint's static ``jax-free`` rule: an
# exhaustive transitive import-graph proof over the WHOLE tools/
# directory plus resilience/supervisor.py and obs/schema.py, covering
# every import edge rather than the code paths the smoke arguments
# happened to execute, at AST-parse cost instead of ~20 s of
# interpreter startups.  See tools/graftlint/imports.py and
# tests/test_graftlint.py::test_jax_free_contract_covers_the_retired_
# runtime_guard_set (which pins the same required-client list the
# runtime guard asserted).  The tools' behavior (real args, real
# streams) remains covered by their own in-process tests here and in
# test_obs/test_costmodel/test_serve/test_resilience.
