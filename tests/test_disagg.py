"""Sharded + disaggregated serving (ISSUE 14): TP-sharded decode on a
mesh, prefill/decode role split with paged-KV handoff.

- TP-sharded serving: under a registered (data=2, model=4) mesh the
  engine shards weights and per-layer paged-KV arenas over heads on
  'model' (block tables/admission stay host-side) and greedy served
  output is token-identical to dense generate() — fp AND int8
  (weights + KV), with the compile-once gate intact.
- Disaggregation: a prefill-role engine chunk-prefills, samples the
  first token and ships KV blocks; a decode-role engine scatters them
  into its own arena and decodes with a [SLOTS, 1]-wide step.  On a
  mixed long-prompt/short-decode workload the decode role's TPOT p99
  beats the interleaved baseline at comparable total ticks, outputs
  stay token-identical, and zero handoffs are lost.
- Handoff edge cases: COW-shared prefix blocks ship as deep copies
  with refcounts consistent on both sides; a decode worker short on
  slots/blocks requeues deterministically (never crashes), and an
  unservable handoff terminates first-class as "rejected".
- Transport + tools: FileTransport round-trips int8 payloads
  byte-exactly; ci_gate --disagg-stream enforces handoff conservation
  over the checked-in prefill+decode fixture pair; serve_report
  renders the HANDOFF line; trace_export joins a prefill-worker
  request span with its decode-worker continuation across streams.

All in-process engines ride the session's SLOTS=4 / MAX_LEN=32 / BS=8
geometry (the [4, 8] step is shared with test_serve via the lru
cache); the new compiled programs this file adds are the [4, 1]
decode-role step and the TP-sharded variants.  The one new subprocess
e2e is the serve.py --role prefill / --role decode pair.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import obs
from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.obs import trace as trace_lib
from apex_example_tpu.obs.metrics import nearest_rank
from apex_example_tpu.parallel.mesh import parse_serve_mesh, serve_mesh
from apex_example_tpu.resilience.faults import (SERVE_KINDS,
                                                FaultInjected, FaultPlan)
from apex_example_tpu.serve import (FileTransport, KvHandoff,
                                    QueueTransport, Request, ServeEngine,
                                    run_decode_role, run_disagg,
                                    run_prefill_role)
from apex_example_tpu.transformer import parallel_state

pytestmark = pytest.mark.disagg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SLOTS, MAX_LEN = 4, 32          # the session serve geometry (test_serve)
FIXTURES = os.path.join(REPO, "tests", "fixtures", "disagg")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _mixed_requests(n_long=3, n_short=6, seed=5, stagger=0):
    """The disagg acceptance workload: long prompts (3 prefill chunks)
    mixed with short prompts that mostly decode."""
    rs = np.random.RandomState(seed)
    reqs = []
    for i in range(n_long):
        reqs.append(Request(
            prompt=[int(t) for t in rs.randint(0, 256, 22 + i)],
            max_new_tokens=6,
            arrival_step=None if not stagger else i * stagger))
    for i in range(n_short):
        reqs.append(Request(
            prompt=[int(t) for t in rs.randint(0, 256, 3 + (i % 3))],
            max_new_tokens=16,
            arrival_step=None if not stagger
            else (i % n_long) * stagger))
    return reqs


def _clone(requests):
    """Fresh Request objects (same prompts/budgets, new uids) so each
    engine run owns un-stamped arrival state."""
    return [Request(prompt=list(r.prompt),
                    max_new_tokens=r.max_new_tokens,
                    temperature=r.temperature, top_k=r.top_k,
                    eos_id=r.eos_id, arrival_step=r.arrival_step)
            for r in requests]


def _assert_ref_tokens(model, params, comps, err=""):
    """Every ok completion's greedy tokens == dense generate() at the
    shared MAX_LEN, on the request's clamped output budget."""
    for c in comps:
        assert c.status == "ok", (err, c.request.uid, c.status)
        P = len(c.request.prompt)
        n = len(c.tokens)
        assert n == min(c.request.max_new_tokens, MAX_LEN - P)
        ref = generate(model, params,
                       jnp.asarray([c.request.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(
            np.asarray(ref)[0, P:P + n],
            np.asarray(c.tokens, np.int32),
            err_msg=f"{err} {c.request.uid}")


# ------------------------------------------------------ mesh plumbing


def test_parse_serve_mesh():
    assert parse_serve_mesh("2,4") == (2, 4)
    assert parse_serve_mesh("1,1") == (1, 1)
    for bad in ("", "8", "2,4,1", "a,b", "0,4", "2,-1"):
        with pytest.raises(ValueError):
            parse_serve_mesh(bad)


def test_serve_mesh_shape(devices8):
    mesh = serve_mesh(2, 4, devices=devices8)
    assert mesh.shape["data"] == 2 and mesh.shape["model"] == 4
    with pytest.raises(ValueError):
        serve_mesh(4, 4, devices=devices8)      # needs 16 devices


def test_engine_rejects_mesh_model_mismatch(devices8, model_and_params):
    """A nontrivial 'model' axis demands a tensor_parallel model (and
    vice versa) — the same early guard the training mesh has."""
    model, params = model_and_params
    parallel_state.set_mesh(serve_mesh(2, 4, devices=devices8))
    try:
        with pytest.raises(ValueError, match="tensor_parallel"):
            ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN)
    finally:
        parallel_state.set_mesh(None)


# ------------------------------------------------- TP-sharded serving


def test_tp_sharded_serving_token_identity(devices8, model_and_params,
                                           tmp_path, compile_events):
    """The acceptance bar (fp): greedy output of the TP-sharded engine
    on the (data=2, model=4) virtual mesh is token-identical to dense
    generate(); weights AND arenas are really distributed; the decode
    program compiles exactly once with GSPMD shardings."""
    from apex_example_tpu.ops import _config as ops_config
    model, params = model_and_params
    path = str(tmp_path / "tp.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    parallel_state.set_mesh(serve_mesh(2, 4, devices=devices8))
    obs.costmodel.set_default(obs.CostModel(sink=sink))
    try:
        eng = ServeEngine(gpt_tiny(tensor_parallel=True), params,
                          num_slots=SLOTS, max_len=MAX_LEN)
        assert eng.tp == 4 and eng.dp == 2
        # a head-sharded param really is distributed under the mesh
        q = eng.params["layer_0"]["attention"]["query"]["kernel"]
        assert q.addressable_shards[0].data.shape[1] == q.shape[1] // 4
        # ... and so is the KV arena: [NB, BS, H, D] sharded over heads
        ck = next(leaf for p, leaf in
                  jax.tree_util.tree_flatten_with_path(eng.pool.cache)[0]
                  if "cached_key" in str(p[-1]) and "scale" not in str(p[-1]))
        assert ck.addressable_shards[0].data.shape[2] == ck.shape[2] // 4
        reqs = _mixed_requests(stagger=2)
        eng.queue.submit_all(reqs)
        eng.queue.close()
        comps = eng.run(max_steps=2000)
        assert len(comps) == len(reqs)
        _assert_ref_tokens(model, params, comps, err="tp-fp")
        summ = eng.summary_record()
        assert summ["mesh"] == "data=2,model=4"
        assert summ["tp"] == 4 and summ["dp"] == 2
        assert summ["role"] == "both"
        assert not obs_schema.validate_record(summ)
    finally:
        obs.costmodel.set_default(None)
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)
    sink.close()
    # generate() ran under the same armed instance for the refs, so its
    # loop shows up too — every instrumented program compiled ONCE.
    counts = compile_events(path)
    assert counts["serve_decode_step"] == 1, counts
    assert all(v == 1 for v in counts.values()), counts


def test_tp_quant_serving_token_identity(devices8, model_and_params):
    """Quantized serving UNDER TP (the ISSUE 13 'remaining ambition'):
    int8 weights + int8 paged KV on the sharded mesh produce exactly
    the tokens the unsharded quant engine produces."""
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.quant import quantize_params
    model, params = model_and_params
    qparams, _stats = quantize_params(params, "int8")
    reqs = _mixed_requests(n_long=2, n_short=4, seed=9)

    def run(m, p):
        eng = ServeEngine(m, p, num_slots=SLOTS, max_len=MAX_LEN,
                          kv_quant=True, weight_quant="int8")
        eng.queue.submit_all(_clone(reqs))
        eng.queue.close()
        comps = eng.run(max_steps=2000)
        assert {c.status for c in comps} == {"ok"}
        return {tuple(c.request.prompt): c.tokens for c in comps}

    base = run(model, qparams)              # unsharded quant serving
    parallel_state.set_mesh(serve_mesh(2, 4, devices=devices8))
    try:
        tp = run(gpt_tiny(tensor_parallel=True), qparams)
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)
    assert base == tp


# --------------------------------------------- disaggregated serving


def test_disagg_token_identity_and_tpot_win(model_and_params):
    """The perf acceptance bar: on a mixed long-prompt/short-decode
    workload, the disaggregated pair (prefill role + [SLOTS, 1]-wide
    decode role) serves token-identical output with ZERO lost
    handoffs, at comparable total ticks — and the decode role's TPOT
    p99 is strictly better than the interleaved baseline's, because
    decode ticks stop running the [SLOTS, block_size] prefill
    geometry.  (Wall-clock assertion on the CPU rig: the 8x per-tick
    FLOP gap gives it margin.)"""
    model, params = model_and_params
    reqs = _mixed_requests(stagger=0)

    # Warm BOTH compiled programs (the [4, 8] interleaved step and the
    # [4, 1] decode-role step) so neither side pays its one-time XLA
    # compile inside the measured TPOT — the lru-cached step functions
    # make every later engine at this geometry reuse these programs.
    warm = [Request(prompt=[1, 2, 3], max_new_tokens=2)]
    w = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN)
    w.queue.submit_all(_clone(warm))
    w.queue.close()
    w.run(max_steps=50)
    wt = QueueTransport()
    wp = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="prefill", handoff_sink=wt.send)
    wd = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="decode")
    run_disagg(wp, wd, _clone(warm))

    # interleaved baseline
    base = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN)
    base_reqs = _clone(reqs)
    base.queue.submit_all(base_reqs)
    base.queue.close()
    base_comps = base.run(max_steps=4000)
    _assert_ref_tokens(model, params, base_comps, err="baseline")

    # Disaggregated pair over an in-process transport, driven as the
    # deployment actually runs: each role OWNS its worker — the decode
    # engine's ticks are never interleaved with prefill work on the
    # same thread (run_disagg's lockstep driver is the convergence
    # harness; here each engine's wall-clock tick cost must be what a
    # dedicated worker would pay).
    transport = QueueTransport()
    pe = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="prefill", handoff_sink=transport.send)
    de = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="decode")
    assert de.chunk == 1 and pe.chunk == pe.pool.block_size
    pe.queue.submit_all(_clone(reqs))
    pe.queue.close()
    p_comps = run_prefill_role(pe, transport, max_steps=4000)
    d_comps = run_decode_role(de, transport, max_steps=4000)

    # conservation: every handoff terminated ok on the decode side
    handed = {c.request.uid for c in p_comps if c.status == "handoff"}
    done = {c.request.uid for c in d_comps}
    assert handed == done and len(handed) == len(reqs)
    assert pe.counts["handoff"] == len(reqs)
    assert de.handoffs_in == len(reqs)
    _assert_ref_tokens(model, params, d_comps, err="disagg")

    # comparable total ticks (the decode role does NOT win by just
    # spending more scheduler rounds)
    total = pe.step_count + de.step_count
    assert total <= base.step_count * 1.5 + 4, (total, base.step_count)

    # the perf claim: decode-role TPOT p99 strictly beats the
    # interleaved baseline's on the same workload
    def tpot_p99(comps):
        vals = sorted(c.tpot_s * 1e3 for c in comps
                      if c.status == "ok" and len(c.tokens) > 1)
        assert vals
        return nearest_rank(vals, 99)

    base_p99 = tpot_p99(base_comps)
    disagg_p99 = tpot_p99(d_comps)
    if not disagg_p99 < base_p99:
        # One re-measure before failing: wall-clock p99 on a loaded
        # 2-CPU CI box can eat the ~1.7x per-tick margin in a single
        # unlucky scheduling window.  Both sides re-run, same compiled
        # programs.
        base2 = ServeEngine(model, params, num_slots=SLOTS,
                            max_len=MAX_LEN)
        base2.queue.submit_all(_clone(reqs))
        base2.queue.close()
        base_p99 = tpot_p99(base2.run(max_steps=4000))
        t2 = QueueTransport()
        pe2 = ServeEngine(model, params, num_slots=SLOTS,
                          max_len=MAX_LEN, role="prefill",
                          handoff_sink=t2.send)
        de2 = ServeEngine(model, params, num_slots=SLOTS,
                          max_len=MAX_LEN, role="decode")
        pe2.queue.submit_all(_clone(reqs))
        pe2.queue.close()
        run_prefill_role(pe2, t2, max_steps=4000)
        disagg_p99 = tpot_p99(run_decode_role(de2, t2, max_steps=4000))
    assert disagg_p99 < base_p99, (disagg_p99, base_p99)


def test_handoff_cow_shared_prefix_deep_copy(model_and_params):
    """Handoff of requests whose prefix blocks are COW-shared: the
    payload is a deep copy (mutating it never touches the prefill
    arena), refcounts stay consistent on the prefill side (the shared
    block survives for the sibling and parks reusable at the end),
    and the decode side still produces exactly generate()'s tokens."""
    model, params = model_and_params
    rs = np.random.RandomState(2)
    # 24-token prompts: a 20-token shared prefix + 4 divergent tokens.
    # The first request's 3rd block fills during its own prefill (24 is
    # block-aligned), so later arrivals chain-match 2 full blocks AND
    # partially overlap into the 3rd — mapped immutable, so their first
    # divergent write COWs it inside the compiled step.  Arrivals are
    # staggered so each handoff completes (and registers its blocks)
    # before the next request admits.
    prefix = [int(t) for t in rs.randint(0, 256, 20)]
    reqs = [Request(prompt=prefix + [int(t) for t in rs.randint(0, 256,
                                                                4)],
                    max_new_tokens=6, arrival_step=i * 5)
            for i in range(3)]

    transport = QueueTransport()
    pe = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="prefill", handoff_sink=transport.send)
    de = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="decode")
    pe.queue.submit_all(reqs)
    pe.queue.close()
    pe.run(max_steps=200)
    handoffs = transport.poll()
    assert len(handoffs) == 3
    # prefix sharing AND a copy-on-write actually happened on the
    # prefill side: the later requests mapped the first one's blocks
    # (2 full + a partial overlap) and COW'd the partial block at
    # their first divergent write.
    assert pe.pool.prefix_hit_rate() > 0
    assert pe.pool.cow_copies >= 1
    # every slot evicted; the shared prefix blocks parked REUSABLE
    # (refcount 0 but indexed), nothing still mapped
    assert pe.pool.free_count == SLOTS
    assert pe.pool.alloc.blocks_in_use == 0
    assert all(r == 0 for r in pe.pool.alloc.refcount)

    # deep copy: corrupting one handoff's payload in place must not
    # leak into the prefill arena or into a SIBLING handoff that
    # shared the same prefix blocks
    h0, h1 = handoffs[0], handoffs[1]
    key = next(k for k in h0.payload if "cached_key" in k
               and "scale" not in k)
    before_arena = np.asarray(
        next(leaf for p, leaf in
             jax.tree_util.tree_flatten_with_path(pe.pool.cache)[0]
             if "cached_key" in str(p[-1])
             and "scale" not in str(p[-1])))
    before_sibling = h1.payload[key].copy()
    h0.payload[key][:] = 0
    after_arena = np.asarray(
        next(leaf for p, leaf in
             jax.tree_util.tree_flatten_with_path(pe.pool.cache)[0]
             if "cached_key" in str(p[-1])
             and "scale" not in str(p[-1])))
    np.testing.assert_array_equal(before_arena, after_arena)
    np.testing.assert_array_equal(before_sibling, h1.payload[key])

    # the UNtouched handoffs decode to generate()'s tokens (h0 was
    # deliberately corrupted above, so it is excluded)
    transport.close()
    for h in handoffs[1:]:
        assert de.admit_handoff(h)
    while de.pool.any_live():
        de.step()
    _assert_ref_tokens(model, params, de.completions, err="cow-handoff")
    assert len(de.completions) == 2


def test_handoff_reject_and_requeue(model_and_params):
    """Decode-side admission control: a handoff that can NEVER fit
    terminates first-class as "rejected" (consumed, no crash); one
    that exceeds the free capacity right now is requeued with no
    state left behind and admits cleanly after space frees."""
    model, params = model_and_params
    de = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="decode")

    def fake_handoff(prompt_len, max_new, fill=None):
        rs = np.random.RandomState(prompt_len)
        req = Request(prompt=[int(t) for t in rs.randint(0, 256,
                                                         prompt_len)],
                      max_new_tokens=max_new)
        fill = prompt_len if fill is None else fill
        n_blocks = -(-fill // de.pool.block_size)
        payload = {}
        for p, leaf in jax.tree_util.tree_flatten_with_path(
                de.pool.cache)[0]:
            name = str(p[-1])
            if "cached_" in name:
                key = "/".join(getattr(x, "key", str(x)) for x in p)
                payload[key] = np.zeros(
                    (n_blocks,) + tuple(leaf.shape[1:]),
                    dtype=np.asarray(leaf[:0]).dtype)
        return KvHandoff(
            uid=req.uid, request=req,
            tokens=[int(t) for t in req.prompt] + [0],
            fill=fill, block_size=de.pool.block_size,
            kv_dtype=de.pool.kv_dtype, payload=payload,
            payload_bytes=sum(int(a.nbytes) for a in payload.values()),
            t_out_wall=0.0, src="test")

    # (a) unservable: the prompt fills the whole cache, so the output
    # budget is zero -> rejected first-class, consumed, no state
    h_bad = fake_handoff(8, 4)
    h_bad.request = Request(prompt=[1] * MAX_LEN, max_new_tokens=4)
    assert de.admit_handoff(h_bad) is True
    assert de.counts["rejected"] == 1
    assert de.pool.free_count == SLOTS          # nothing left behind

    # (b) transient pressure: fill every slot, then one more handoff
    # defers (False, requeued once) and admits after an eviction
    live = [fake_handoff(8 + i, 6) for i in range(SLOTS)]
    for h in live:
        assert de.admit_handoff(h) is True
    extra = fake_handoff(20, 6)
    assert de.admit_handoff(extra) is False
    assert de.admit_handoff(extra) is False     # deterministic retry
    assert extra.requeued == 1                  # one episode, not two
    assert de.handoff_requeued == 1
    de.pool.evict(0)                            # space frees
    assert de.admit_handoff(extra) is True
    assert de.handoffs_in == SLOTS + 1
    # drop the live slots without stepping (host-side teardown)
    for i in de.pool.live:
        de.pool.evict(i)


def test_mismatched_geometry_handoff_raises(model_and_params):
    model, params = model_and_params
    de = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="decode")
    h = KvHandoff(uid="x", request=Request(prompt=[1, 2],
                                           max_new_tokens=2),
                  tokens=[1, 2, 3], fill=2, block_size=4,
                  kv_dtype="float32", payload={}, payload_bytes=0,
                  t_out_wall=0.0)
    with pytest.raises(ValueError, match="block_size"):
        de.admit_handoff(h)


def test_file_transport_round_trip_int8(model_and_params, tmp_path):
    """FileTransport ships int8 payload + bf16 scales byte-exactly:
    the decode side's tokens match the in-process int8 interleaved
    engine's, through a spool directory and process-shaped load."""
    model, params = model_and_params
    reqs = _mixed_requests(n_long=1, n_short=3, seed=13)

    base = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                       kv_quant=True)
    base.queue.submit_all(_clone(reqs))
    base.queue.close()
    base_map = {tuple(c.request.prompt): c.tokens
                for c in base.run(max_steps=2000)}

    spool = str(tmp_path / "spool")
    tx = FileTransport(spool)
    pe = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="prefill", handoff_sink=tx.send, kv_quant=True)
    pe.queue.submit_all(_clone(reqs))
    pe.queue.close()
    run_prefill_role(pe, tx)
    assert os.path.exists(os.path.join(spool, tx.SENTINEL))

    rx = FileTransport(spool)
    de = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="decode", kv_quant=True)
    comps = run_decode_role(de, rx)
    assert {c.status for c in comps} == {"ok"}
    assert {tuple(c.request.prompt): c.tokens for c in comps} == base_map
    # spool fully consumed
    assert not [n for n in os.listdir(spool) if n.endswith(".npz")]
    # int8 payloads were really what moved
    assert de.handoffs_in == len(reqs)
    summ = de.summary_record()
    assert summ["kv_dtype"] == "int8"
    assert summ["handoffs_in"] == len(reqs)
    assert "handoff_ms" in summ
    assert not obs_schema.validate_record(summ)


# ------------------------------------------------------- schema v12


def test_schema_v12_records_validate():
    assert obs_schema.SCHEMA_VERSION >= 12
    good = [
        {"record": "kv_handoff", "time": 1.0, "request_id": "r1",
         "direction": "out", "fill": 24, "blocks": 3,
         "payload_bytes": 9216, "kv_dtype": "int8",
         "prompt_tokens": 24, "first_token": 7, "src": "prefill",
         "run_id": "x"},
        {"record": "kv_handoff", "time": 1.0, "request_id": "r1",
         "direction": "in", "fill": 24, "blocks": 3,
         "payload_bytes": 9216, "handoff_ms": 1.25, "requeued": 1,
         "dst": "decode"},
        {"record": "serve_summary", "time": 1.0, "requests": 4,
         "output_tokens": 40, "tokens_per_sec": 10.0,
         "role": "decode", "mesh": "data=2,model=4", "dp": 2, "tp": 4,
         "handoffs_in": 4, "handoff_requeued": 1,
         "handoff_bytes": 36864,
         "handoff_ms": {"p50": 1.0, "p95": 2.0, "max": 2.0}},
        {"record": "replica_state", "time": 1.0, "replica": "r0",
         "state": "serving", "kv_bytes_live": 8448},
    ]
    for rec in good:
        assert not obs_schema.validate_record(rec), rec
    bad = [
        {"record": "kv_handoff", "time": 1.0, "request_id": "r1",
         "direction": "out"},                      # missing fill/blocks
        {"record": "kv_handoff", "time": 1.0, "request_id": "r1",
         "direction": "out", "fill": 1, "blocks": 1,
         "payload_bytes": 2, "surprise": True},    # unknown field
    ]
    for rec in bad:
        assert obs_schema.validate_record(rec), rec
    # v11 streams (no role/mesh/handoff fields) still validate
    assert not obs_schema.validate_record(
        {"record": "serve_summary", "time": 1.0, "requests": 1,
         "output_tokens": 2, "tokens_per_sec": 1.0})


# ------------------------------------------------- trace continuation


def test_trace_export_joins_handoff_across_streams(model_and_params,
                                                   tmp_path):
    """The satellite bugfix: a prefill-worker request span and its
    decode-worker continuation join into one timeline via the handoff
    uid — a cross-stream flow arrow pair (cat "handoff"), on a merged
    export that stays --check clean."""
    model, params = model_and_params
    p_path = str(tmp_path / "p.jsonl")
    d_path = str(tmp_path / "d.jsonl")
    p_sink = obs.JsonlSink(p_path, rank=0)
    d_sink = obs.JsonlSink(d_path, rank=0)
    reqs = _mixed_requests(n_long=1, n_short=2, seed=21)

    transport = QueueTransport()
    # each engine snapshots the process-default tracer at construction:
    # two engines, two sinks, two streams — the cross-process shape,
    # in-process.
    trace_lib.set_default(obs.Tracer(p_sink, run_id="pre"))
    pe = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="prefill", handoff_sink=transport.send,
                     sink=p_sink, run_id="pre")
    trace_lib.set_default(obs.Tracer(d_sink, run_id="dec"))
    de = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="decode", sink=d_sink, run_id="dec")
    trace_lib.set_default(None)
    p_comps, d_comps = run_disagg(pe, de, reqs)
    p_sink.close()
    d_sink.close()
    assert len(d_comps) == len(reqs)

    trace_export = _load_tool("trace_export")
    assert trace_export.main(["--check", p_path]) == 0
    assert trace_export.main(["--check", d_path]) == 0
    merged = trace_export.export(
        [(p_path, trace_export.read_stream(p_path)),
         (d_path, trace_export.read_stream(d_path))])
    evs = merged["traceEvents"]
    flows_s = [e for e in evs if e.get("ph") == "s"
               and e.get("cat") == "handoff"]
    flows_f = [e for e in evs if e.get("ph") == "f"
               and e.get("cat") == "handoff"]
    assert len(flows_s) == len(reqs) and len(flows_f) == len(reqs)
    # the arrow really crosses processes (prefill pid -> decode pid)
    pids = {(s["pid"], f["pid"]) for s, f in zip(flows_s, flows_f)}
    assert all(a != b for a, b in pids)
    # arrows bind by id, end-of-prefill-root -> start-of-decode-root
    by_id = {}
    for e in flows_s + flows_f:
        by_id.setdefault(e["id"], []).append(e)
    assert all(len(v) == 2 for v in by_id.values())


# ------------------------------------------------------ tools + gate


def _read_fixture(name):
    with open(os.path.join(FIXTURES, name)) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def test_ci_gate_disagg_fixture_pair(tmp_path):
    """The checked-in recorded prefill+decode pair passes the gate;
    a lost handoff (terminal record removed) fails it."""
    ci_gate = _load_tool("ci_gate")
    pre = os.path.join(FIXTURES, "prefill.jsonl")
    dec = os.path.join(FIXTURES, "decode.jsonl")
    assert ci_gate.main(["--disagg-stream", pre,
                         "--disagg-stream", dec]) == 0

    # tamper: drop one decode-side request_complete -> LOST -> exit 1
    records = _read_fixture("decode.jsonl")
    dropped = False
    tampered = []
    for r in records:
        if not dropped and r.get("record") == "request_complete":
            dropped = True
            continue
        tampered.append(r)
    assert dropped
    bad = str(tmp_path / "decode_lost.jsonl")
    with open(bad, "w") as fh:
        for r in tampered:
            fh.write(json.dumps(r) + "\n")
    assert ci_gate.main(["--disagg-stream", pre,
                         "--disagg-stream", bad]) == 1


def test_serve_report_handoff_line(capsys):
    serve_report = _load_tool("serve_report")
    assert serve_report.main([os.path.join(FIXTURES,
                                           "decode.jsonl")]) == 0
    out = capsys.readouterr().out
    assert "HANDOFF:" in out
    assert "transit p50" in out and "p99" in out
    assert serve_report.main([os.path.join(FIXTURES,
                                           "prefill.jsonl")]) == 0
    out = capsys.readouterr().out
    assert "HANDOFF:" in out and "out /" in out


def test_metrics_lint_fixture_streams():
    lint = _load_tool("metrics_lint")
    for name in ("prefill.jsonl", "decode.jsonl"):
        code, errors = lint.lint(os.path.join(FIXTURES, name))
        assert code == 0, errors


# ------------------------------------- leased handoff crash safety


def _reqs(n, seed, max_new=5):
    rs = np.random.RandomState(seed)
    return [Request(prompt=[int(t) for t in rs.randint(0, 256,
                                                       4 + i % 4)],
                    max_new_tokens=max_new) for i in range(n)]


def _spool_prefill(model, params, spool, reqs, sink=None, fault=None):
    """Chunk-prefill ``reqs`` into ``spool`` (sentinel written unless
    the fault eats it); returns the prefill engine."""
    tx = FileTransport(spool, worker="prefill", fault=fault)
    pe = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                     role="prefill", handoff_sink=tx.send, sink=sink,
                     rng=jax.random.PRNGKey(0))
    pe.queue.submit_all(reqs)
    pe.queue.close()
    run_prefill_role(pe, tx, max_steps=500)
    return pe


def _decode_engine(model, params, sink=None):
    return ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                       role="decode", sink=sink,
                       rng=jax.random.PRNGKey(0))


def _header(sink):
    obs.TelemetryEmitter(sink).run_header(
        config={"slots": SLOTS, "max_len": MAX_LEN}, argv=["serve.py"],
        arch="gpt_tiny")


def test_lease_claim_reclaim_and_adopt(model_and_params, tmp_path):
    """The lease protocol at the transport level: claims are exclusive
    while the lease holds, an expired claim is reclaimed by ANY peer
    (redelivered=1), and ack-by-delete drains the spool for the
    directory-wide finished()."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    _spool_prefill(model, params, spool, _reqs(2, seed=31))
    a = FileTransport(spool, worker="a", lease_s=0.05)
    got = a.poll()
    assert len(got) == 2 and all(h.redelivered == 0 for h in got)
    assert a.pending_on_disk() == 2         # claims still on disk
    b = FileTransport(spool, worker="b", lease_s=30.0)
    assert b.poll() == []                   # a's lease still holds
    time.sleep(0.08)                        # ...until it expires
    got_b = b.poll()
    assert len(got_b) == 2 and all(h.redelivered == 1 for h in got_b)
    assert b.reclaimed == 2
    for h in got_b:
        b.ack(h)
    assert b.pending_on_disk() == 0 and b.finished()


def test_lease_renewal_keeps_deferred_claims(model_and_params,
                                             tmp_path):
    """Review fix (ISSUE 15): a live worker whose admissions are
    deferred past the lease must RENEW its claims — without renewal a
    peer would reclaim and double-serve work the holder still owns."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    _spool_prefill(model, params, spool, _reqs(2, seed=41))
    a = FileTransport(spool, worker="a", lease_s=0.1)
    pending = a.poll()
    assert len(pending) == 2
    b = FileTransport(spool, worker="b", lease_s=30.0)
    for _ in range(4):                  # well past the original lease
        time.sleep(0.06)
        a.renew(pending)                # the drive loop's per-tick call
        assert b.poll() == []           # the peer never gets them
    for h in pending:
        a.ack(h)                        # renewal tracked the renamed
    assert a.pending_on_disk() == 0     #   claim files correctly


def test_lease_adopts_own_claims_without_wait(model_and_params,
                                              tmp_path):
    """A worker coming back under its OWN id (supervised restart)
    adopts its pre-crash claims immediately — no lease wait."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    _spool_prefill(model, params, spool, _reqs(1, seed=32))
    a1 = FileTransport(spool, worker="a", lease_s=60.0)
    assert len(a1.poll()) == 1              # claimed, never acked
    a2 = FileTransport(spool, worker="a", lease_s=60.0)
    got = a2.poll()
    assert len(got) == 1 and got[0].redelivered == 1


def test_ack_crash_window_exactly_once(model_and_params, tmp_path):
    """Satellite (ISSUE 15): kill the decode worker between
    ``admit_handoff`` and ``ack``.  The claim survives on disk; the
    restarted worker adopts it, the engine's seen-set detects the
    redelivery as a duplicate (acked, nothing scattered twice), and
    every request completes exactly once with tokens identical to the
    fault-free run — the recorded pair passing the v13
    ci_gate --disagg-stream."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    p_path = str(tmp_path / "prefill.jsonl")
    d_path = str(tmp_path / "decode.jsonl")
    reqs = _reqs(5, seed=33)
    p_sink = obs.JsonlSink(p_path, rank=0)
    _header(p_sink)
    pe = _spool_prefill(model, params, spool, reqs, sink=p_sink)
    p_sink.write(pe.summary_record())
    p_sink.close()

    d_sink = obs.JsonlSink(d_path, rank=0)
    _header(d_sink)
    de = _decode_engine(model, params, sink=d_sink)
    rx = FileTransport(spool, worker="d0")
    fault = FaultPlan("handoff_crash_preack", 2, kinds=SERVE_KINDS)
    with pytest.raises(FaultInjected):
        run_decode_role(de, rx, max_steps=500, fault=fault)
    assert rx.pending_on_disk() >= 1        # the unacked claim survived

    rx2 = FileTransport(spool, worker="d0")  # the restarted worker
    comps = run_decode_role(de, rx2, max_steps=500)
    assert len(comps) == len(reqs)
    assert {c.status for c in comps} == {"ok"}
    uids = [c.request.uid for c in comps]
    assert len(set(uids)) == len(reqs)      # exactly once, every uid
    assert de.handoff_duplicates == 1       # the redelivered admit-2
    assert de.handoffs_in == len(reqs)      # dup not double-counted
    _assert_ref_tokens(model, params, comps, err="ack-crash")
    summ = de.summary_record()
    assert summ["handoff_duplicates"] == 1
    assert summ.get("handoff_redelivered", 0) >= 1
    assert not obs_schema.validate_record(summ)
    d_sink.write(summ)
    d_sink.close()
    assert rx2.finished()                   # spool fully drained
    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--disagg-stream", p_path,
                         "--disagg-stream", d_path]) == 0


def test_torn_payload_quarantined_worker_alive(model_and_params,
                                               tmp_path, capsys):
    """Satellite bugfix (ISSUE 15): a truncated/corrupt spool payload
    must quarantine to *.bad with a warn record — the decode worker
    keeps ticking and finishes everything else; the stream stays
    v13-valid and passes the disagg gate."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    p_path = str(tmp_path / "prefill.jsonl")
    d_path = str(tmp_path / "decode.jsonl")
    reqs = _reqs(4, seed=34)
    p_sink = obs.JsonlSink(p_path, rank=0)
    _header(p_sink)
    fault = FaultPlan("handoff_torn", 2, kinds=SERVE_KINDS)
    pe = _spool_prefill(model, params, spool, reqs, sink=p_sink,
                        fault=fault)
    p_sink.write(pe.summary_record())
    p_sink.close()

    d_sink = obs.JsonlSink(d_path, rank=0)
    _header(d_sink)
    de = _decode_engine(model, params, sink=d_sink)
    quarantined = []

    def on_quarantine(uid, spool_name, error, nbytes):
        # The serve.py wiring, in miniature: record the disposition.
        quarantined.append(uid)
        d_sink.write({"record": "kv_handoff", "time": time.time(),
                      "request_id": uid, "direction": "quarantine",
                      "fill": 0, "blocks": 0,
                      "payload_bytes": int(nbytes),
                      "spool_file": spool_name,
                      "error": str(error)[:200]})

    rx = FileTransport(spool, worker="d0", on_quarantine=on_quarantine)
    comps = run_decode_role(de, rx, max_steps=500)
    assert len(comps) == len(reqs) - 1      # the torn one never admits
    assert {c.status for c in comps} == {"ok"}
    assert rx.quarantined == 1 and len(quarantined) == 1
    assert any(n.endswith(".bad") for n in os.listdir(spool))
    assert rx.finished()                    # .bad is a disposition
    _assert_ref_tokens(model, params, comps, err="torn")
    summ = de.summary_record()
    summ["handoff_quarantined"] = rx.quarantined   # the serve.py merge
    assert not obs_schema.validate_record(summ)
    d_sink.write(summ)
    d_sink.close()
    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--disagg-stream", p_path,
                         "--disagg-stream", d_path]) == 0
    serve_report = _load_tool("serve_report")
    assert serve_report.main([d_path]) == 0
    out = capsys.readouterr().out
    assert "REDELIVERY:" in out and "1 payload(s) quarantined" in out


def test_duplicate_delivery_drill(model_and_params, tmp_path):
    """The handoff_dup drill: the same payload delivered twice is
    detected against the seen-set, acked without a second scatter, and
    the request still completes exactly once."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    reqs = _reqs(3, seed=35)
    _spool_prefill(model, params, spool, reqs)
    de = _decode_engine(model, params)
    rx = FileTransport(spool, worker="d0")
    fault = FaultPlan("handoff_dup", 1, kinds=SERVE_KINDS)
    comps = run_decode_role(de, rx, max_steps=500, fault=fault)
    assert len(comps) == len(reqs)
    assert {c.status for c in comps} == {"ok"}
    assert de.handoff_duplicates == 1
    assert de.handoffs_in == len(reqs)
    assert rx.finished()
    _assert_ref_tokens(model, params, comps, err="dup")


def test_sentinel_lost_idle_timeout(model_and_params, tmp_path):
    """The sentinel_lost drill: the producer dies without closing the
    stream.  A decode worker with an idle timeout finishes what is
    spooled and exits instead of spinning forever."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    reqs = _reqs(3, seed=36)
    fault = FaultPlan("sentinel_lost", 1, kinds=SERVE_KINDS)
    _spool_prefill(model, params, spool, reqs, fault=fault)
    assert not os.path.exists(os.path.join(spool,
                                           FileTransport.SENTINEL))
    de = _decode_engine(model, params)
    rx = FileTransport(spool, worker="d0")
    comps = run_decode_role(de, rx, max_steps=2000,
                            idle_wait_s=0.01, idle_timeout_s=0.3)
    assert len(comps) == len(reqs)          # everything spooled served
    assert {c.status for c in comps} == {"ok"}
    assert not rx.finished()                # the stream never closed


def test_handoff_drill_requires_matching_role(tmp_path):
    """serve.py rejects a handoff drill on the wrong role (a silently
    inert drill is worse than an error)."""
    import serve as serve_cli
    args = serve_cli.build_parser().parse_args(
        ["--role", "decode", "--handoff-dir", str(tmp_path / "s"),
         "--inject-fault", "handoff_torn@1"])
    with pytest.raises(SystemExit, match="prefill-side"):
        serve_cli.run_serve(args)
    args = serve_cli.build_parser().parse_args(
        ["--role", "decode", "--handoff-dir", str(tmp_path / "s"),
         "--inbox", str(tmp_path / "in.jsonl")])
    with pytest.raises(SystemExit, match="no --inbox"):
        serve_cli.run_serve(args)


def test_schema_v13_records_validate():
    assert obs_schema.SCHEMA_VERSION >= 13
    good = [
        {"record": "kv_handoff", "time": 1.0, "request_id": "r1",
         "direction": "in", "fill": 24, "blocks": 3,
         "payload_bytes": 9216, "handoff_ms": 1.0, "requeued": 0,
         "redelivered": 1, "dst": "decode"},
        {"record": "kv_handoff", "time": 1.0, "request_id": "r1",
         "direction": "in", "fill": 24, "blocks": 0,
         "payload_bytes": 9216, "duplicate": True, "redelivered": 1,
         "dst": "decode"},
        {"record": "kv_handoff", "time": 1.0, "request_id": "r2",
         "direction": "quarantine", "fill": 0, "blocks": 0,
         "payload_bytes": 123, "spool_file": "handoff-000002-r2.npz",
         "error": "corrupt npz"},
        {"record": "serve_summary", "time": 1.0, "requests": 4,
         "output_tokens": 40, "tokens_per_sec": 10.0, "role": "decode",
         "handoffs_in": 4, "handoff_duplicates": 1,
         "handoff_redelivered": 2, "handoff_quarantined": 1},
        {"record": "replica_state", "time": 1.0, "replica": "d0",
         "state": "serving", "role": "decode", "kv_bytes_live": 64},
        {"record": "fleet_summary", "time": 1.0, "replicas": 3,
         "requests": 10, "availability": 1.0, "prefill_replicas": 1,
         "decode_replicas": 2, "handoffs": 10,
         "handoff_redelivered": 1, "in_spool": 0},
    ]
    for rec in good:
        assert not obs_schema.validate_record(rec), rec
    bad = [
        {"record": "kv_handoff", "time": 1.0, "request_id": "r1",
         "direction": "in", "fill": 1, "blocks": 1,
         "payload_bytes": 2, "redelivered": "yes"},   # wrong type
        {"record": "fleet_summary", "time": 1.0, "replicas": 1,
         "requests": 1, "availability": 1.0, "spool_leak": 1},
    ]
    for rec in bad:
        assert obs_schema.validate_record(rec), rec


def test_ci_gate_rejects_unflagged_double_admission(tmp_path):
    """The v13 conservation rule: redelivery episodes are tolerated,
    but two PLAIN admissions of one uid (no redelivered/duplicate
    provenance) mean two workers silently double-served it — the gate
    must fail."""
    ci_gate = _load_tool("ci_gate")
    records = _read_fixture("decode.jsonl")
    plain = next(r for r in records
                 if r.get("record") == "kv_handoff"
                 and r.get("direction") == "in"
                 and not r.get("duplicate") and not r.get("redelivered"))
    doubled = []
    for r in records:
        doubled.append(r)
        if r is plain:
            doubled.append(dict(plain))     # a second plain admission
    bad = str(tmp_path / "decode_double.jsonl")
    with open(bad, "w") as fh:
        for r in doubled:
            fh.write(json.dumps(r) + "\n")
    pre = os.path.join(FIXTURES, "prefill.jsonl")
    assert ci_gate.main(["--disagg-stream", pre,
                         "--disagg-stream", bad]) == 1


def test_fixture_pair_records_a_redelivery():
    """The checked-in pair IS a recorded redelivery episode: the
    decode stream carries redelivered admissions and a duplicate-ack,
    and still passes the gate (test_ci_gate_disagg_fixture_pair)."""
    records = _read_fixture("decode.jsonl")
    ins = [r for r in records if r.get("record") == "kv_handoff"
           and r.get("direction") == "in"]
    assert any(r.get("redelivered") and not r.get("duplicate")
               for r in ins)
    assert any(r.get("duplicate") for r in ins)
    summ = next(r for r in records
                if r.get("record") == "serve_summary")
    assert summ["handoff_duplicates"] == 1
    assert summ["handoff_redelivered"] >= 1


def test_supervisor_strips_handoff_drills_on_restart():
    """Satellite (ISSUE 15): --drop-flag-on-restart=--inject-fault
    strips handoff_*@N drills from restart attempts exactly like
    exact-tick serve drills — a restarted decode worker replays the
    spool from its claim set, so the drill would re-fire."""
    spec = importlib.util.spec_from_file_location(
        "apex_sup_test", os.path.join(REPO, "apex_example_tpu",
                                      "resilience", "supervisor.py"))
    sup = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sup)
    argv = ["python", "serve.py", "--role", "decode",
            "--inject-fault", "handoff_crash_preack@1", "--slots", "4"]
    out = sup._strip_flag(argv, "--inject-fault")
    assert out == ["python", "serve.py", "--role", "decode",
                   "--slots", "4"]
    out = sup._strip_flag(["x", "--inject-fault=handoff_torn@2", "y"],
                          "--inject-fault")
    assert out == ["x", "y"]


# --------------------------------------------------- subprocess e2e


def test_disagg_subprocess_pair_e2e(tmp_path):
    """THE one new subprocess e2e: a serve.py --role prefill process
    spools handoffs to disk, a --role decode process consumes them —
    each stream schema-v12 valid with exactly one serve_summary for
    its role, the compile-once gate holds PER ROLE (one prefill
    program, one decode program), zero handoffs lost, and the
    ci_gate/serve_report tooling passes over the recorded pair."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    spool = str(tmp_path / "spool")
    p_jsonl = str(tmp_path / "prefill.jsonl")
    d_jsonl = str(tmp_path / "decode.jsonl")
    common = ["--slots", "4", "--max-len", "32", "--seed", "3",
              "--cost-model"]
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "serve.py"),
         "--requests", "6", "--role", "prefill", "--handoff-dir", spool,
         "--metrics-jsonl", p_jsonl] + common,
        env=env, cwd=REPO, timeout=240).returncode
    assert rc == 0
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "serve.py"),
         "--role", "decode", "--handoff-dir", spool,
         "--metrics-jsonl", d_jsonl] + common,
        env=env, cwd=REPO, timeout=240).returncode
    assert rc == 0

    lint = _load_tool("metrics_lint")
    for path in (p_jsonl, d_jsonl):
        code, errors = lint.lint(path)
        assert code == 0, errors
    p_recs = [json.loads(l) for l in open(p_jsonl) if l.strip()]
    d_recs = [json.loads(l) for l in open(d_jsonl) if l.strip()]
    p_summ = [r for r in p_recs if r["record"] == "serve_summary"]
    d_summ = [r for r in d_recs if r["record"] == "serve_summary"]
    assert len(p_summ) == 1 and p_summ[0]["role"] == "prefill"
    assert len(d_summ) == 1 and d_summ[0]["role"] == "decode"
    assert p_summ[0]["handoffs_out"] == 6
    assert d_summ[0]["handoffs_in"] == 6
    assert d_summ[0]["completed"] == 6

    # compile-once PER ROLE: one program each, under its own name
    from apex_example_tpu.obs.costmodel import compile_counts
    assert compile_counts(p_recs) == {"serve_prefill_step": 1}
    assert compile_counts(d_recs) == {"serve_decode_step": 1}

    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--disagg-stream", p_jsonl,
                         "--disagg-stream", d_jsonl]) == 0
    serve_report = _load_tool("serve_report")
    assert serve_report.main([d_jsonl]) == 0
