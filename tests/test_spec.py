"""Speculative multi-token decoding (spec/ + --speculate; ISSUE 18).

- proposer unit coverage: the n-gram/prompt-lookup drafter (suffix
  match across prompt + history, window fallback, out-of-range k), the
  null drafter, the CLI factory,
- the --repetitive loadgen workload: deterministic per seed, prompts
  actually carry a looping motif,
- the tier-1 acceptance run: ONE module-scoped --speculate 3 engine on
  the repetitive workload at the shared SLOTS=4/MAX_LEN=32 geometry —
  tokens_per_tick strictly > 1.0, greedy outputs token-identical to
  one-shot generate(), the conservation ledger holds, the stream
  validates (schema v16), serve_report renders the SPEC line, and the
  compile-once gate sees exactly ONE new program (serve_spec_step),
- losslessness under adversarial drafts: a proposer that drafts WRONG
  tokens still yields token-identical output (rollback = not
  advancing; the rejected lanes' stale KV is masked and overwritten),
- the degenerate modes: --draft none drafts nothing and stays
  identical; an unarmed engine emits NO v16 fields (pre-v16 streams
  byte-identical),
- composition with quantization: int8 weights + int8 KV, armed vs
  unarmed token identity,
- the ci_gate --spec-stream conservation gate over the checked-in
  fixture (PASS) and tampered copies (FAIL),
- schema v16 validation: the spec summary validates, a spec-field-free
  summary still validates (v15 compat), an undeclared field is
  rejected, and perf_ledger's serve snapshot carries acceptance_rate.

Engine tests share the session's SLOTS=4/MAX_LEN=32 geometry so the
compiled programs stay cheap; the armed run is module-scoped and
reused by every assertion that only needs to READ it.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import obs
from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.serve import ServeEngine, synthetic_requests
from apex_example_tpu.spec import (DraftProposer, NgramProposer,
                                   NullProposer, get_proposer)

pytestmark = pytest.mark.spec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPEC_FIXTURE = os.path.join(REPO, "tests", "fixtures", "spec",
                            "spec_smoke.jsonl")
SLOTS, MAX_LEN = 4, 32
K = 3


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------- proposers

def test_ngram_proposer_prompt_lookup():
    """The drafter finds the most recent earlier occurrence of the
    running suffix and proposes what followed it — across the
    prompt/history boundary, with shorter windows as fallback."""
    p = NgramProposer(n=3)
    # suffix [1,2,3] occurred at position 0; continuation [9,1].
    assert p.propose("u", [1, 2, 3, 9, 1, 2, 3], [], 2) == [9, 1]
    # same context split across prompt and generated history.
    assert p.propose("u", [1, 2, 3, 9], [1, 2, 3], 3) == [9, 1, 2]
    # no repeated suffix at ANY window: no draft.
    assert p.propose("u", [1, 2, 3, 4, 5], [], 4) == []
    # window fallback: [7,1] never recurs but [1] does (after pos 0),
    # so the n=1 window proposes its continuation.
    assert p.propose("u", [1, 5, 6, 7, 1], [], 2) == [5, 6]
    # k caps the draft; k=0 is always empty.
    assert p.propose("u", [1, 2, 1, 2, 1, 2], [], 1) == [1]
    assert p.propose("u", [1, 2, 1, 2], [], 0) == []
    # a period-3 cycle drafts a full period ahead; deterministic.
    args = ("u", [1, 2, 3, 1], [2, 3, 1], 3)
    assert p.propose(*args) == p.propose(*args) == [2, 3, 1]


def test_null_proposer_and_factory():
    assert NullProposer().propose("u", [1, 2, 3], [4], 4) == []
    assert isinstance(get_proposer("none"), NullProposer)
    ng = get_proposer("ngram", ngram=2)
    assert isinstance(ng, NgramProposer) and ng.n == 2
    assert get_proposer("ngram").name == "ngram"
    with pytest.raises(ValueError):
        get_proposer("bigmodel")
    with pytest.raises(ValueError):
        NgramProposer(n=0)


# ------------------------------------------- --repetitive workload

def test_repetitive_workload_deterministic_and_motif():
    """--repetitive prompts loop a short motif (the honest demo
    workload for prompt-lookup drafting) and the whole request list is
    a pure function of the seed."""
    mk = lambda: synthetic_requests(8, vocab_size=199, seed=7,
                                    prompt_len=(6, 12), max_new=(4, 8),
                                    stagger=2, repetitive=True)
    a, b = mk(), mk()
    assert [r.prompt for r in a] == [r.prompt for r in b]
    assert [(r.max_new_tokens, r.arrival_step) for r in a] == \
           [(r.max_new_tokens, r.arrival_step) for r in b]
    for r in a:
        assert any(all(t == r.prompt[i % m]
                       for i, t in enumerate(r.prompt))
                   for m in range(3, 7)), r.prompt
    plain = synthetic_requests(8, vocab_size=199, seed=7,
                               prompt_len=(6, 12), max_new=(4, 8),
                               stagger=2)
    assert [r.prompt for r in plain] != [r.prompt for r in a]


# ------------------------------------- the armed tier-1 acceptance run

@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _run(model, params, reqs, *, sink=None, run_id=None, registry=None,
         speculate=0, proposer=None, kv_quant=False,
         weight_quant="none"):
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0), sink=sink,
                      run_id=run_id, registry=registry,
                      speculate=speculate, proposer=proposer,
                      kv_quant=kv_quant, weight_quant=weight_quant)
    eng.queue.submit_all(reqs)
    eng.queue.close()
    comps = eng.run(max_steps=2000)
    return eng, comps


def _repetitive_reqs(model, n=8, seed=3):
    return synthetic_requests(n, vocab_size=model.vocab_size, seed=seed,
                              prompt_len=(6, 12), max_new=(12, 24),
                              stagger=2, repetitive=True)


@pytest.fixture(scope="module")
def armed_run(model_and_params, tmp_path_factory):
    """ONE --speculate K run with the cost model armed, shared by every
    read-only assertion below (the suite rides tier-1: one armed
    engine, one compiled program, one workload)."""
    from apex_example_tpu.obs import costmodel
    model, params = model_and_params
    path = str(tmp_path_factory.mktemp("spec") / "spec.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={"slots": SLOTS, "max_len": MAX_LEN,
                               "speculate": K}, arch="gpt_tiny")
    costmodel.set_default(obs.CostModel(
        sink=sink, registry=emitter.registry, run_id=emitter.run_id))
    try:
        reqs = _repetitive_reqs(model)
        eng, comps = _run(model, params, reqs, sink=sink,
                          run_id=emitter.run_id,
                          registry=emitter.registry, speculate=K)
    finally:
        costmodel.set_default(None)
    sink.write(eng.summary_record())
    sink.close()
    return eng, comps, reqs, path


def test_speculation_breaks_one_token_per_tick(armed_run):
    """The headline number: tokens_per_tick strictly > 1.0 on the
    repetitive workload — the engine emitted MORE tokens than it ran
    compiled steps — with the conservation ledger intact."""
    eng, comps, reqs, _ = armed_run
    assert len(comps) == len(reqs)
    summary = eng.summary_record()
    assert summary["speculate_k"] == K
    assert summary["draft_kind"] == "ngram"
    assert summary["tokens_per_tick"] > 1.0
    assert summary["output_tokens"] > summary["compute_steps"]
    # conservation: every emitted token is an accepted draft lane or a
    # model sample (bonus lanes + plain-path ticks).
    assert 0 < summary["tokens_accepted"] <= summary["tokens_drafted"]
    assert summary["output_tokens"] == (summary["tokens_accepted"]
                                        + summary["tokens_sampled"])
    assert summary["acceptance_rate"] == pytest.approx(
        summary["tokens_accepted"] / summary["tokens_drafted"],
        abs=5e-4)


def test_speculation_is_lossless_greedy_identity(armed_run,
                                                 model_and_params):
    """The correctness bar: every accepted token is the token greedy
    decode would have produced — armed output is token-identical to
    one-shot generate() on every request."""
    model, params = model_and_params
    _, comps, reqs, _ = armed_run
    by_uid = {c.request.uid: c for c in comps}
    for r in reqs:
        c = by_uid[r.uid]
        P, n = len(r.prompt), len(c.tokens)
        assert n == min(r.max_new_tokens, MAX_LEN - P)
        ref = generate(model, params, jnp.asarray([r.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(np.asarray(ref)[0, P:P + n],
                                      np.asarray(c.tokens, np.int32),
                                      err_msg=r.uid)


def test_armed_engine_compiles_exactly_one_program(armed_run,
                                                   compile_events):
    """The compile-once contract, armed: --speculate K adds exactly ONE
    compiled program (serve_spec_step — prefill chunks and draft lanes
    share the [SLOTS, C] geometry), asserted on the counter AND through
    the cost_report --fail-on-recompile CI command."""
    _, _, _, path = armed_run
    records = obs.read_jsonl(path)
    assert compile_events(records) == {"serve_spec_step": 1}
    assert compile_events.gate(path) == 0


def test_armed_stream_validates_and_reports(armed_run, capsys):
    """The emitted stream is a valid v16 stream, serve_report renders
    the SPEC line, and telemetry_report passes the ledger through."""
    _, _, _, path = armed_run
    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    serve_report = _load_tool("serve_report")
    assert serve_report.report(path) == 0
    out = capsys.readouterr().out
    assert f"SPEC: K={K} draft=ngram" in out
    assert "tokens/tick" in out
    telemetry_report = _load_tool("telemetry_report")
    assert telemetry_report.report(path) == 0
    assert "spec K=3" in capsys.readouterr().out


def test_wrong_drafts_are_rolled_back_losslessly(model_and_params):
    """The mutation test for the rollback path: a proposer drafting
    deliberately WRONG tokens must not corrupt output — rejection is
    simply not advancing the cursor (stale lanes sit beyond it, masked
    off and overwritten next tick).  Identity holds while the ledger
    shows real rejections."""
    model, params = model_and_params

    class WrongProposer(DraftProposer):
        name = "wrong"

        def propose(self, uid, prompt_tokens, generated_tokens, k):
            last = (generated_tokens[-1] if generated_tokens
                    else prompt_tokens[-1])
            return [(int(last) + 1 + j) % model.vocab_size
                    for j in range(k)]

    reqs = _repetitive_reqs(model, n=4, seed=5)
    eng, comps = _run(model, params, reqs, speculate=K,
                      proposer=WrongProposer())
    assert len(comps) == 4
    summary = eng.summary_record()
    assert summary["draft_kind"] == "wrong"
    assert summary["tokens_drafted"] > 0
    assert summary["tokens_accepted"] < summary["tokens_drafted"]
    assert summary["output_tokens"] == (summary["tokens_accepted"]
                                        + summary["tokens_sampled"])
    by_uid = {c.request.uid: c for c in comps}
    for r in reqs:
        c = by_uid[r.uid]
        P, n = len(r.prompt), len(c.tokens)
        ref = generate(model, params, jnp.asarray([r.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(np.asarray(ref)[0, P:P + n],
                                      np.asarray(c.tokens, np.int32),
                                      err_msg=r.uid)


def test_null_drafter_degenerates_to_plain_path(model_and_params):
    """--draft none: the armed engine never receives a draft, every
    tick feeds one real lane, and output matches generate() with a
    zeroed ledger (the K=0-per-tick degenerate case)."""
    model, params = model_and_params
    reqs = _repetitive_reqs(model, n=4, seed=11)
    eng, comps = _run(model, params, reqs, speculate=K,
                      proposer=NullProposer())
    summary = eng.summary_record()
    assert summary["tokens_drafted"] == 0
    assert summary["tokens_accepted"] == 0
    assert summary["acceptance_rate"] == 0.0
    assert summary["output_tokens"] == summary["tokens_sampled"]
    by_uid = {c.request.uid: c for c in comps}
    for r in reqs:
        c = by_uid[r.uid]
        P, n = len(r.prompt), len(c.tokens)
        ref = generate(model, params, jnp.asarray([r.prompt], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(np.asarray(ref)[0, P:P + n],
                                      np.asarray(c.tokens, np.int32))


def test_unarmed_summary_carries_no_spec_fields(model_and_params):
    """--speculate 0 leaves the stream byte-identical to pre-v16
    output: NO speculation field reaches the summary."""
    model, params = model_and_params
    reqs = synthetic_requests(2, vocab_size=model.vocab_size, seed=9,
                              prompt_len=(4, 6), max_new=(3, 5))
    eng, comps = _run(model, params, reqs)
    assert len(comps) == 2
    summary = eng.summary_record()
    for field in ("speculate_k", "draft_kind", "tokens_drafted",
                  "tokens_accepted", "tokens_sampled",
                  "acceptance_rate", "tokens_per_tick"):
        assert field not in summary, field


def test_speculation_composes_with_int8_quantization(model_and_params):
    """Speculation is lossless relative to whatever numerics the engine
    runs: with int8 weights AND an int8 KV arena, the armed run is
    token-identical to the unarmed run on the same quantized stack."""
    from apex_example_tpu.quant import quantize_params
    model, params = model_and_params
    qp, _ = quantize_params(params, "int8")
    reqs = _repetitive_reqs(model, n=4, seed=13)
    eng_p, plain = _run(model, qp, reqs, kv_quant=True,
                        weight_quant="int8")
    eng_s, spec = _run(model, qp, reqs, speculate=K, kv_quant=True,
                       weight_quant="int8")
    assert len(plain) == len(spec) == 4
    p_uid = {c.request.uid: c.tokens for c in plain}
    s_uid = {c.request.uid: c.tokens for c in spec}
    assert p_uid == s_uid
    summary = eng_s.summary_record()
    assert summary["tokens_accepted"] > 0       # actually speculated
    assert summary["output_tokens"] == (summary["tokens_accepted"]
                                        + summary["tokens_sampled"])


# -------------------------------------------- ci_gate --spec-stream

def test_ci_gate_spec_stream_passes_on_fixture(capsys):
    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--spec-stream", SPEC_FIXTURE]) == 0
    out = capsys.readouterr().out
    assert f"spec gate {SPEC_FIXTURE}: PASS" in out


def test_ci_gate_spec_stream_fails_on_tamper(tmp_path, capsys):
    """The conservation gate actually fires: accepted > drafted and a
    broken output == accepted + sampled identity both FAIL."""
    ci_gate = _load_tool("ci_gate")
    with open(SPEC_FIXTURE) as fh:
        lines = [json.loads(ln) for ln in fh if ln.strip()]

    def tamper(edit, name):
        recs = [dict(r) for r in lines]
        summ = next(r for r in recs
                    if r.get("record") == "serve_summary")
        edit(summ)
        path = str(tmp_path / name)
        with open(path, "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
        return path

    def overdraw(s):
        s["tokens_accepted"] = s["tokens_drafted"] + 1

    def leak(s):
        s["tokens_sampled"] += 1

    assert ci_gate._spec_gate(tamper(overdraw, "overdraw.jsonl")) == 1
    assert "accepted a token nobody proposed" in capsys.readouterr().err
    assert ci_gate._spec_gate(tamper(leak, "leak.jsonl")) == 1
    assert "no provenance" in capsys.readouterr().err
    # an UNARMED stream is a usage error for this gate, not a pass.
    def disarm(s):
        for f in ("speculate_k", "draft_kind", "tokens_drafted",
                  "tokens_accepted", "tokens_sampled",
                  "acceptance_rate", "tokens_per_tick"):
            s.pop(f, None)
    assert ci_gate._spec_gate(tamper(disarm, "unarmed.jsonl")) == 1


# ------------------------------------------------- schema + ledger

def test_schema_v16_spec_fields():
    """The v16 contract: the fixture's armed summary validates, a
    summary WITHOUT the spec fields still validates (strict-superset
    back-compat), and an undeclared field is rejected."""
    with open(SPEC_FIXTURE) as fh:
        records = [json.loads(ln) for ln in fh if ln.strip()]
    assert obs_schema.validate_stream(records) == []
    summ = next(r for r in records if r["record"] == "serve_summary")
    assert summ["speculate_k"] >= 1
    bare = {k: v for k, v in summ.items()
            if k not in ("speculate_k", "draft_kind", "tokens_drafted",
                         "tokens_accepted", "tokens_sampled",
                         "acceptance_rate", "tokens_per_tick")}
    assert obs_schema.validate_record(bare) == []
    typo = dict(summ)
    typo["tokens_per_draft"] = 1.0
    errs = obs_schema.validate_record(typo)
    assert errs and any("tokens_per_draft" in e for e in errs)


def test_perf_ledger_snapshot_carries_acceptance():
    """perf_ledger folds the v16 ledger into the serve snapshot with
    the explicit 5% noise band (small-sample acceptance counts jitter
    more than throughput counters)."""
    perf_ledger = _load_tool("perf_ledger")
    records = obs.read_jsonl(os.path.join(
        REPO, "tests", "fixtures", "perf", "serve_perf.jsonl"))
    snap = perf_ledger.snapshot(records, "serve_perf.jsonl")
    assert snap["kind"] == "serve"
    assert 0.0 < snap["metrics"]["acceptance_rate"] <= 1.0
    assert snap["metrics"]["tokens_per_tick"] > 1.0
    assert perf_ledger.default_noise_pct("acceptance_rate") == 5.0
    assert perf_ledger.default_noise_pct("tokens_per_tick") == 5.0
