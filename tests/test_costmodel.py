"""Compiled-graph cost observability (obs/costmodel.py; ISSUE 7):

- the tier-1 acceptance smoke: a 10-step tiny-GPT train run with
  --cost-model emits lint-clean schema-v6 compile_event + cost_model
  records, the train step compiles EXACTLY once per process (the
  recompile-regression guard protecting the suite budget), the
  run_summary carries measured compile totals, and tools/cost_report.py
  renders a roofline table from the stream (jax-free — graftlint's
  static jax-free rule covers the import side),
- the two models policing each other: XLA's cost_analysis() flops vs
  the utils/flops.py analytic 6N model for tiny GPT (compiled, riding
  the smoke run's one compile) and bert_tiny (lowered only — no new
  backend compile), and compiled HLO bytes vs one
  tools/byte_accounting.py conv chain's touch-model floor,
- CostModel unit behavior: per-signature AOT caching, recompile
  detection (a new abstract signature => a second compile_event with a
  bumped ordinal), graceful degradation on un-lowerable callables, and
  the identity path when no default instance is installed.

Suite-budget note: the smoke run compiles the same tiny-GPT train step
a --cost-model-free run would compile (the AOT path replaces the
dispatch-cache compile, it does not add one); the bert_tiny cross-check
stops at lowering; the conv chain is a sub-second compile.
"""

import importlib.util
import json
import os

import pytest

import jax
import jax.numpy as jnp

import train as train_mod
from apex_example_tpu import obs
from apex_example_tpu.obs import costmodel
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.utils.flops import model_train_flops_per_token

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# Tiny-GPT geometry shared by the smoke run and the analytic
# cross-check: batch 8, 16 generated tokens -> 15 model positions
# (train.py shifts the pair by one).
GPT_BATCH, GPT_SEQ = 8, 16
GPT_ARGS = ["--arch", "gpt_tiny", "--epochs", "1", "--steps-per-epoch",
            "10", "--batch-size", str(GPT_BATCH), "--seq-len",
            str(GPT_SEQ), "--num-devices", "1", "--print-freq", "5"]


@pytest.fixture(scope="module")
def gpt_cost_run(tmp_path_factory):
    """ONE 10-step tiny-GPT --cost-model run per module; every smoke
    assertion rides its single compile."""
    path = str(tmp_path_factory.mktemp("costmodel") / "gpt.jsonl")
    assert train_mod.main(GPT_ARGS + ["--metrics-jsonl", path,
                                      "--cost-model"]) == 0
    return path


# ------------------------------------------------- schema v6 records

def test_schema_v6_records_validate():
    ce = {"record": "compile_event", "time": 1.0, "name": "train_step",
          "compile_ms": 3000.0, "lower_ms": 600.0, "n_compiles": 1,
          "lowering_hash": "sha256:ab", "platform": "cpu", "run_id": "r"}
    assert obs.validate_record(ce) == []
    cm = {"record": "cost_model", "time": 1.0, "name": "train_step",
          "flops": 1e8, "bytes_accessed": 2e7, "transcendentals": 1e5,
          "argument_bytes": 1, "output_bytes": 2, "temp_bytes": 3,
          "generated_code_bytes": None,       # CPU backend: explicit null
          "peak_flops": 197e12, "hbm_gbps": 375.0,
          "arithmetic_intensity": 5.0, "ridge_flops_per_byte": 525.3,
          "compute_ms": 0.1, "hbm_ms": 0.2, "analytic_min_ms": 0.2,
          "roofline": "hbm-bound", "mfu_ceiling_pct": 0.9}
    assert obs.validate_record(cm) == []
    # every analysis omitted -> all-null degradation still validates
    assert obs.validate_record(
        {"record": "cost_model", "time": 1.0, "name": "f", "flops": None,
         "bytes_accessed": None, "peak_flops": 1.0, "hbm_gbps": 1.0}) == []
    # unknown fields stay rejected (the schema is a contract, not a bag)
    assert obs.validate_record({**ce, "typo": 1})
    assert obs.validate_record({"record": "compile_event", "time": 1.0,
                                "name": "x"})          # missing compile_ms


# -------------------------------------- tier-1 smoke (ISSUE 7 gate)

def test_cost_model_stream_lints(gpt_cost_run):
    """The acceptance bar: the --cost-model stream is lint-clean v6 with
    exactly one compile_event + cost_model pair riding the run's one
    compile, joined by the lowering hash."""
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(gpt_cost_run, steps=10)
    assert code == 0, errors
    records = obs.read_jsonl(gpt_cost_run)
    kinds = [r["record"] for r in records]
    assert kinds.count("compile_event") == 1
    assert kinds.count("cost_model") == 1
    ce = next(r for r in records if r["record"] == "compile_event")
    cm = next(r for r in records if r["record"] == "cost_model")
    assert ce["name"] == cm["name"] == "train_step"
    assert ce["compile_ms"] > 0 and ce["lower_ms"] > 0
    assert ce["lowering_hash"] == cm["lowering_hash"]
    assert cm["bytes_accessed"] > 0
    assert cm["roofline"] in ("compute-bound", "hbm-bound")
    assert 0 < cm["mfu_ceiling_pct"] <= 100
    assert cm["analytic_min_ms"] == pytest.approx(
        max(cm["compute_ms"], cm["hbm_ms"]))


def test_recompile_guard_train_step_compiles_once(gpt_cost_run,
                                                  compile_events):
    """The recompile-regression guard: a 10-step run compiles the train
    step EXACTLY once (eval_step was instrumented but never called —
    instrumentation alone must not compile anything)."""
    assert compile_events(gpt_cost_run) == {"train_step": 1}


def test_flops_cross_check_gpt_vs_analytic(gpt_cost_run):
    """The two FLOPs models police each other: XLA's compiled-graph
    count must bracket the analytic 6N + attention model (utils/
    flops.py counts matmuls only; XLA adds layernorm/softmax/optimizer
    arithmetic — measured ratio ~1.3 on this geometry, so [1.0, 2.0] is
    the contract band)."""
    from apex_example_tpu.models.gpt import gpt_tiny
    cm = next(r for r in obs.read_jsonl(gpt_cost_run)
              if r["record"] == "cost_model")
    positions = GPT_SEQ - 1                 # lm shift: 16 tokens -> 15 positions
    analytic = model_train_flops_per_token(gpt_tiny(), positions) \
        * GPT_BATCH * positions
    ratio = cm["flops"] / analytic
    assert 1.0 <= ratio <= 2.0, (cm["flops"], analytic, ratio)


def test_summary_measured_compile_replaces_estimate(gpt_cost_run, capsys):
    """run_summary carries the MEASURED compile totals next to the
    first-vs-steady estimate, and telemetry_report prefers them."""
    records = obs.read_jsonl(gpt_cost_run)
    summary = records[-1]
    assert summary["record"] == "run_summary"
    ce = next(r for r in records if r["record"] == "compile_event")
    assert summary["compile_events"] == 1
    assert summary["compile_ms_total"] == pytest.approx(ce["compile_ms"],
                                                        abs=0.01)
    report = _load_tool("telemetry_report")
    assert report.main([gpt_cost_run]) == 0
    out = capsys.readouterr().out
    assert "COMPILE train_step" in out
    assert "COST train_step" in out
    assert "ms measured over 1 compilation(s)" in out


def test_cost_report_renders_roofline_table(gpt_cost_run, capsys):
    """tools/cost_report.py joins cost_model vs measured step times into
    the roofline table (jax-free import is proven by graftlint's static
    rule; here we check the rendering contract)."""
    report = _load_tool("cost_report")
    assert report.main([gpt_cost_run]) == 0
    out = capsys.readouterr().out
    assert "train_step" in out
    assert "roofline" in out and "meas_ms" in out
    assert "no recompiles" in out
    # the join actually happened: a measured column and a gap appear
    row = next(l for l in out.splitlines() if l.startswith("train_step"))
    assert "x" in row                        # the gap column rendered
    assert report.main([gpt_cost_run, "--fail-on-recompile"]) == 0


def test_cost_report_flags_recompiles(tmp_path, capsys):
    path = str(tmp_path / "re.jsonl")
    with open(path, "w") as fh:
        for n in (1, 2):
            fh.write(json.dumps(
                {"record": "compile_event", "time": float(n), "name": "f",
                 "compile_ms": 10.0, "n_compiles": n,
                 "lowering_hash": f"sha256:{n}"}) + "\n")
    report = _load_tool("cost_report")
    assert report.main([path]) == 0          # informative by default
    assert "RECOMPILE f: 2 compilations" in capsys.readouterr().out
    assert report.main([path, "--fail-on-recompile"]) == 1


# ------------------------------------ the models police each other

BERT_BS, BERT_SEQ = 8, 16


@pytest.fixture(scope="module")
def bert_o0_lowered():
    """ONE lowered (never compiled) bert_tiny O0 train step per module:
    the flops cross-check and the live upcast-leak smoke share the
    single trace, so the suite pays tracing once and compiling never."""
    from apex_example_tpu import amp
    from apex_example_tpu.data import mlm_batch
    from apex_example_tpu.engine import create_train_state, make_train_step
    from apex_example_tpu.models.bert import bert_tiny
    from apex_example_tpu.optim import FusedLAMB

    from apex_example_tpu.workloads import mlm_loss

    policy, scaler = amp.initialize("O0")
    model = bert_tiny()
    opt = FusedLAMB(lr=1e-3)
    V = model.vocab_size
    ids, labels, w = mlm_batch(jnp.asarray(0), batch_size=BERT_BS,
                               seq_len=BERT_SEQ, vocab_size=V,
                               mask_token_id=V - 1, seed=0)
    batch = (ids, (labels, w))
    state = create_train_state(jax.random.PRNGKey(0), model, opt, ids[:1],
                               policy, scaler, train_kwargs={})
    step = jax.jit(make_train_step(model, opt, policy, loss_fn=mlm_loss,
                                   compute_accuracy=False))
    return model, step.lower(state, batch)


def test_flops_cross_check_bert_lowered_no_compile(bert_o0_lowered):
    """bert_tiny's cross-check stops at LOWERING (hlo cost analysis on
    the unoptimized module — no backend compile, so the suite pays
    tracing only): same [1.0, 2.0] contract band as the compiled GPT
    check (measured ratio ~1.16)."""
    model, lowered = bert_o0_lowered
    cost = costmodel._first_computation(lowered.cost_analysis())
    analytic = model_train_flops_per_token(model, BERT_SEQ) \
        * BERT_BS * BERT_SEQ
    ratio = cost["flops"] / analytic
    assert 1.0 <= ratio <= 2.0, (cost["flops"], analytic, ratio)


@pytest.mark.lint
def test_upcast_leak_rule_live_smoke_bert_amp_o2(bert_o0_lowered):
    """The live HLO smoke (ISSUE 9): graftlint's upcast-leak rule over
    REAL lowerings, not just the checked-in fixtures.

    (a) bert_tiny under AMP O2 (bf16 compute, fp32 masters), forward
    lowered only — abstract params via eval_shape, no init, no backend
    compile: every one of its dot_generals must run bf16, so the rule
    stays QUIET on the policy the program claims.
    (b) the module's shared O0 train-step lowering is an f32 program:
    linted against a CLAIMED bf16 policy it must fire on the wide dots
    — the live seeded leak, at zero extra trace cost."""
    from apex_example_tpu import amp
    from apex_example_tpu.models.bert import bert_tiny
    from tools.graftlint.hlo import host_transfer, ops, upcast_leak

    policy, _ = amp.initialize("O2")
    md = amp.module_dtypes(policy)
    model = bert_tiny(dtype=md.compute, param_dtype=md.param,
                      ln_dtype=md.ln_io, softmax_dtype=md.softmax)
    ids = jnp.zeros((BERT_BS, BERT_SEQ), jnp.int32)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids,
                            train=False)
    fwd = jax.jit(lambda p, x: model.apply(p, x, train=False))
    text = fwd.lower(params, ids).as_text()
    dots = [op for _, op, _ in ops(text) if op == "dot_general"]
    assert len(dots) >= 10                  # a real program, not a stub
    assert upcast_leak(text, "bf16") == []  # O2 compute is leak-free
    assert host_transfer(text) == []        # pure device computation

    _, lowered = bert_o0_lowered
    leaks = upcast_leak(lowered.as_text(), "bf16")
    assert leaks, "an all-f32 program must trip a claimed-bf16 policy"
    assert all(f.rule == "hlo-upcast-leak" for f in leaks)
    assert any("dot_general" in f.message for f in leaks)


def test_bytes_cross_check_byte_accounting_chain():
    """Compiled HLO bytes vs one tools/byte_accounting.py chain: the
    chain's i+o touch model is a true floor (any correct program reads
    its input and writes its output once), and XLA CPU — which does NOT
    fuse the BN/ReLU epilogue into the conv the way the TPU floor
    assumes — lands at ~2x (conv writes + the elementwise pass re-reads
    and re-writes).  Contract band: floor <= bytes <= 3x floor."""
    ba = _load_tool("byte_accounting")
    batch = 2
    chain = ba.resnet50_chains(batch)[1]     # s0b0.conv1: 1x1, 56x56x64
    assert chain["name"] == "s0b0.conv1"

    def chain_fwd(x, w, scale, bias):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.maximum(y * scale + bias, 0)

    x = jnp.zeros((batch, 56, 56, 64), jnp.float32)
    w = jnp.zeros((1, 1, 64, 64), jnp.float32)
    s = jnp.ones((64,), jnp.float32)
    b = jnp.zeros((64,), jnp.float32)
    compiled = jax.jit(chain_fwd).lower(x, w, s, b).compile()
    cost = costmodel._first_computation(compiled.cost_analysis())
    hlo_bytes = cost["bytes accessed"]
    # the chain prices bf16 activations; this runs f32 => scale the floor
    floor = (chain["i"] + chain["o"]) * ba.FP32 // ba.BF16
    assert floor <= hlo_bytes <= 3 * floor, (hlo_bytes, floor)
    # and the flop models agree within a few percent on a bare conv
    conv_flops = 2.0 * 1 * 1 * 64 * 64 * 56 * 56 * batch
    assert 0.95 <= cost["flops"] / conv_flops <= 1.5


# --------------------------------------------------- CostModel units

def test_recompile_detection_and_registry(tmp_path):
    """A new abstract signature is a recompile: second compile_event
    with ordinal 2; an identical signature reuses the cached
    executable (no third event).  The registry histogram feeds the
    run-summary compile totals."""
    path = str(tmp_path / "u.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    registry = obs.MetricsRegistry()
    cm = obs.CostModel(sink=sink, registry=registry, run_id="unit")

    f = cm.instrument("f", jax.jit(lambda x: x * 2))
    assert float(f(jnp.ones((4,)))[0]) == 2.0
    assert float(f(jnp.zeros((4,)))[0]) == 0.0       # same sig: cached
    assert f(jnp.ones((8,))).shape == (8,)           # new sig: recompile
    sink.close()
    assert cm.compile_counts == {"f": 2}
    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    events = [r for r in records if r["record"] == "compile_event"]
    assert [e["n_compiles"] for e in events] == [1, 2]
    # distinct programs => distinct lowering hashes
    assert events[0]["lowering_hash"] != events[1]["lowering_hash"]
    # schema v8: the SECOND compile carries the recompile-cause diff
    # (graftlint's HLO stratum names the first divergent op) — the
    # first compile of a name never does.
    assert "recompile_cause" not in events[0]
    assert "first divergent op" in events[1]["recompile_cause"]
    snap = registry.snapshot()
    assert snap["compiles"] == 2
    assert snap["compile_time_ms"]["count"] == 2
    assert snap["compile_time_ms"]["sum"] > 0


def test_recompile_cause_survives_reinstrumentation_same_name():
    """The diff baseline is per NAME on the CostModel, not per wrapper:
    re-instrumenting a name with a fresh fn object (generate() rebuilding
    a closure) shares the compile count, so its first compile is the
    name's SECOND — and must carry the recompile_cause diagnosis
    (review regression)."""
    cm = obs.CostModel()
    f1 = cm.instrument("loop", jax.jit(lambda x: x + 1))
    f1(jnp.ones((4,)))
    f2 = cm.instrument("loop", jax.jit(lambda x: x * 3))   # fresh fn
    assert f2 is not f1
    f2(jnp.ones((4,)))
    assert cm.compile_counts == {"loop": 2}
    events = [e for e in cm.events if e["record"] == "compile_event"]
    assert "recompile_cause" not in events[0]
    assert "first divergent op" in events[1]["recompile_cause"]


def test_weak_type_mismatch_never_escapes_typeerror():
    """A weak/strong dtype mismatch must not crash through a cached
    executable.  Depending on how tolerant the backend's arg check is,
    either the sole-executable fast path reuses the one program (1
    compile) or the keyed path recompiles (2 compiles — an honest
    compile_event); the contract is that every call SUCCEEDS with the
    right result and no TypeError escapes observation."""
    import numpy as np
    cm = obs.CostModel()
    f = cm.instrument("w", jax.jit(lambda x: x + 1))
    strong = jnp.asarray(np.float32(1.0))            # strong f32 scalar
    weak = jnp.asarray(1.0)                          # weak-typed f32
    assert float(f(strong)) == 2.0
    assert float(f(weak)) == 2.0
    assert float(f(strong)) == 2.0
    assert cm.compile_counts["w"] in (1, 2)


def test_instrument_degrades_on_unlowerable_callable():
    """Observation must never break the run: a plain python callable
    (no AOT surface) falls back to direct calls and emits nothing."""
    cm = obs.CostModel()
    g = cm.instrument("g", lambda x: x + 1)
    assert g(1) == 2 and g(2) == 3
    assert cm.compile_counts == {}


def test_instrument_is_identity_without_default():
    assert costmodel.get_default() is None
    fn = jax.jit(lambda x: x)
    assert costmodel.instrument("anything", fn) is fn
    assert costmodel.instrument("anything", None) is None


def test_instrument_caches_per_name_and_fn():
    """generate() re-fetches the same lru-cached loop per call; the
    wrapper (and with it the compiled executable) must be reused."""
    cm = obs.CostModel()
    fn = jax.jit(lambda x: x)
    w1 = cm.instrument("loop", fn)
    w2 = cm.instrument("loop", fn)
    assert w1 is w2
    assert cm.instrument("loop", w1) is w1           # idempotent on wrap


def test_cost_model_requires_metrics_jsonl():
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "gpt_tiny", "--cost-model"])
