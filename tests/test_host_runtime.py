"""Native host runtime (csrc/apex_tpu_host.cpp via ctypes): the apex_C
flatten/unflatten analog and the fast_collate/prefetcher analog
(SURVEY.md §2.1, §3.5).  Skips cleanly when no C++ toolchain is present."""

import numpy as np
import pytest

from apex_example_tpu import host_runtime as hr

pytestmark = pytest.mark.skipif(not hr.available(),
                                reason="native host runtime not buildable")


class TestFlattenUnflatten:
    def test_roundtrip(self):
        rng = np.random.RandomState(0)
        arrs = [rng.randn(3, 4).astype(np.float32),
                rng.randn(1).astype(np.float32),
                rng.randn(5, 2, 2).astype(np.float32)]
        flat = hr.flatten_f32(arrs)
        assert flat.shape == (3 * 4 + 1 + 5 * 2 * 2,)
        np.testing.assert_array_equal(
            flat, np.concatenate([a.ravel() for a in arrs]))
        outs = hr.unflatten_f32(flat, [a.shape for a in arrs])
        for a, o in zip(arrs, outs):
            np.testing.assert_array_equal(a, o)

    def test_size_mismatch_raises(self):
        with pytest.raises(AssertionError):
            hr.unflatten_f32(np.zeros(5, np.float32), [(2,), (2,)])


class TestGeneratorAndCollate:
    def test_gen_deterministic_and_spread(self):
        a = hr.gen_u8(seed=7, start_index=0, n=10_000)
        b = hr.gen_u8(seed=7, start_index=0, n=10_000)
        np.testing.assert_array_equal(a, b)
        c = hr.gen_u8(seed=8, start_index=0, n=10_000)
        assert not np.array_equal(a, c)
        # roughly uniform bytes
        hist = np.bincount(a, minlength=256)
        assert hist.min() > 0 and hist.max() < 5 * hist.mean()

    def test_collate_matches_numpy(self):
        rng = np.random.RandomState(1)
        frames = rng.randint(0, 256, (4, 8, 8, 3), dtype=np.uint8)
        mean, std = (0.485, 0.456, 0.406), (0.229, 0.224, 0.225)
        got = hr.collate_f32(frames, mean, std)
        want = ((frames.astype(np.float32) / 255.0
                 - np.asarray(mean, np.float32))
                / np.asarray(std, np.float32))
        np.testing.assert_allclose(got, want, atol=1e-6)


class TestPrefetcher:
    @staticmethod
    def _take(pf, n):
        # next() returns views valid until the following next(); copy.
        return [(img.copy(), lab.copy()) for img, lab in
                (next(pf) for _ in range(n))]

    def test_deterministic_ordered_batches(self):
        mk = lambda: hr.NativePrefetcher(batch=8, image_size=16,
                                         num_classes=10, seed=3)
        p1 = mk()
        run1 = self._take(p1, 4)
        p1.close()
        p2 = mk()
        run2 = self._take(p2, 4)
        p2.close()
        for (i1, l1), (i2, l2) in zip(run1, run2):
            np.testing.assert_array_equal(i1, i2)
            np.testing.assert_array_equal(l1, l2)
        assert not np.array_equal(run1[0][0], run1[1][0])
        for img, lab in run1:
            assert img.shape == (8, 16, 16, 3) and img.dtype == np.float32
            assert lab.shape == (8,) and lab.dtype == np.int32
            assert lab.min() >= 0 and lab.max() < 10
            assert np.isfinite(img).all()

    def test_start_index_resumes_stream(self):
        # Checkpoint-resume contract: a prefetcher started at index k yields
        # exactly the batches a fresh one yields after k next() calls.
        p = hr.NativePrefetcher(batch=4, image_size=16, num_classes=10,
                                seed=5)
        full = self._take(p, 4)
        p.close()
        p2 = hr.NativePrefetcher(batch=4, image_size=16, num_classes=10,
                                 seed=5, start_index=2)
        resumed = self._take(p2, 2)
        p2.close()
        for (fi, fl), (ri, rl) in zip(full[2:], resumed):
            np.testing.assert_array_equal(fi, ri)
            np.testing.assert_array_equal(fl, rl)

    def test_channel_validation(self):
        with pytest.raises(ValueError):
            hr.NativePrefetcher(batch=2, image_size=8, num_classes=4,
                                channels=5, seed=0)

    def test_images_are_class_separable(self):
        # The learnable-signal contract: same-class images correlate more
        # than cross-class ones.
        p = hr.NativePrefetcher(batch=64, image_size=16, num_classes=2,
                                seed=9)
        img, lab = next(p)
        flat = img.reshape(64, -1)
        mean0 = flat[lab == 0].mean(0)
        mean1 = flat[lab == 1].mean(0)
        within = np.linalg.norm(flat[lab == 0] - mean0, axis=1).mean()
        across = np.linalg.norm(flat[lab == 0] - mean1, axis=1).mean()
        p.close()
        assert across > within * 1.02


class TestLMPrefetcher:
    """Native LM/MLM token producer (csrc apex_lm_prefetcher_*; train.py
    --host-pipeline for the LM archs)."""

    def test_mlm_determinism_and_resume(self):
        if not hr.available():
            pytest.skip("native runtime not buildable")
        a = hr.NativeLMPrefetcher(4, 16, 256, mlm=True, mask_token_id=255,
                                  seed=3)
        _, b1 = next(a), next(a)
        a.close()
        # start_index resumes the exact stream (checkpoint-resume contract)
        b = hr.NativeLMPrefetcher(4, 16, 256, mlm=True, mask_token_id=255,
                                  seed=3, start_index=1)
        c1 = next(b)
        b.close()
        for x, y in zip(b1, c1):
            np.testing.assert_array_equal(x, y)

    def test_mlm_masking_contract(self):
        if not hr.available():
            pytest.skip("native runtime not buildable")
        p = hr.NativeLMPrefetcher(8, 64, 256, mlm=True, mask_token_id=255,
                                  seed=0)
        ids, lab, w = next(p)
        p.close()
        assert set(np.unique(w)) <= {0.0, 1.0}
        assert 0.05 < w.mean() < 0.30            # ~15% masked
        # unmasked positions pass through untouched
        np.testing.assert_array_equal(ids[w == 0], lab[w == 0])
        # masked positions are mostly [MASK] (80/10/10)
        masked = ids[w == 1]
        assert (masked == 255).mean() > 0.6
        assert lab.min() >= 0 and lab.max() < 256

    def test_causal_form_is_shifted_bigram_stream(self):
        if not hr.available():
            pytest.skip("native runtime not buildable")
        p = hr.NativeLMPrefetcher(2, 8, 64, mlm=False, seed=1)
        ids, lab, w = next(p)
        p.close()
        assert (w == 1.0).all()
        # targets are inputs shifted by one...
        np.testing.assert_array_equal(ids[:, 1:], lab[:, :-1])
        # ...and follow the learnable affine-bigram map up to noise_p flips
        assert (lab == (31 * ids + 17) % 64).mean() > 0.7

    def test_mlm_rejects_missing_mask_token(self):
        if not hr.available():
            pytest.skip("native runtime not buildable")
        with pytest.raises(ValueError):
            hr.NativeLMPrefetcher(2, 8, 64, mlm=True)


def test_train_py_lm_host_pipeline():
    """CLI end to end: BERT trains from the native token stream."""
    if not hr.available():
        pytest.skip("native runtime not buildable")
    import train as train_mod
    assert train_mod.main(
        ["--arch", "bert_tiny", "--host-pipeline", "--batch-size", "8",
         "--seq-len", "16", "--epochs", "1", "--steps-per-epoch", "3",
         "--opt", "adam", "--opt-level", "O0", "--print-freq", "1"]) == 0
