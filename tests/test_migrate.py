"""Live KV migration + elastic pools (ISSUE 20):

- engine-level mid-flight migration: extract_live -> admit_migrated
  token-identity (fp AND int8 storage dtypes), mid-prefill resume,
  migrating drain (zero ticks, zero evictions), the --speculate
  draft-lane trim satellite, the rebalance ping-pong regression,
- exactly-once under the adversarial ack-crash window on the leased
  FileTransport spool (the destination dies between admit and ack;
  the peer reclaims the expired lease and finishes),
- ProcReplica interrupt() idempotence across the restart window (the
  double-interrupt satellite),
- jax-free router surface: backlog()/retire_replica/add_replica,
  note_autoscale, KV-pressure rebalance targeting, and fleet.py's
  ElasticPool hysteresis — all on scripted fakes, sub-second,
- the three scored scenarios riding the session's SLOTS=4/MAX_LEN=32
  compiled programs (zero new compiles): drain_zero_evictions and
  migrate_under_crash_storm double-run bit-identical on invariant
  scores, autoscale_flap inside its oscillation bound,
- schema v18 validation + the v1-v17 back-compat sweep over every
  checked-in fixture, ci_gate --migrate-stream conservation gate
  (PASS on the checked-in stream, FAIL on tampered variants), and
  the serve_report / fleet_report MIGRATION lines.
"""

import glob
import importlib.util
import json
import os
import signal
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import obs
from apex_example_tpu.fleet import (FleetRouter, ProcReplica,
                                    ThreadReplica, run_scenario,
                                    synthetic_specs)
from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.resilience.faults import SERVE_KINDS, FaultPlan
from apex_example_tpu.serve import (FileTransport, Request, ServeEngine,
                                    synthetic_requests)

pytestmark = pytest.mark.migrate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "migrate",
                       "drain_migrate.jsonl")
SLOTS, MAX_LEN = 4, 32          # the session-shared decode geometry


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_fleet_cli():
    """fleet.py (the CLI) by file path — jax-free at import by the
    graftlint contract, and ElasticPool lives there."""
    spec = importlib.util.spec_from_file_location(
        "apex_fleet_cli_migrate_test", os.path.join(REPO, "fleet.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _engine(model, params, **kw):
    return ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                       rng=jax.random.PRNGKey(0), **kw)


def _reqs(model, n, seed, prompt_len=(3, 8), max_new=(6, 12),
          repetitive=False):
    return synthetic_requests(n, vocab_size=model.vocab_size, seed=seed,
                              prompt_len=prompt_len, max_new=max_new,
                              stagger=0, repetitive=repetitive)


def _slot_of(eng, uid):
    for i in eng.pool.live:
        if eng.pool.slots[i].request.uid == uid:
            return eng.pool.slots[i]
    return None


def _step_until(eng, pred, cap=500):
    steps = 0
    while not pred() and steps < cap:
        eng.step()
        steps += 1
    assert pred(), f"condition not reached within {cap} ticks"


def _mid_decode(eng, uid, n_gen=2):
    def pred():
        s = _slot_of(eng, uid)
        return s is not None and not s.prefilling \
            and s.n_generated >= n_gen
    return pred


def _ref_map(model, params, reqs, kv_quant=False):
    """Unmigrated reference: the SAME prompts served to completion on
    one engine of the same storage dtype, keyed on (prompt, budget) —
    int8 token identity is judged against int8, never against dense
    generate() (the quantized arena legitimately diverges)."""
    eng = _engine(model, params, kv_quant=kv_quant)
    eng.queue.submit_all(reqs)
    eng.queue.close()
    comps = eng.run(max_steps=2000)
    out = {(tuple(c.request.prompt), c.request.max_new_tokens):
           list(c.tokens) for c in comps}
    assert len(out) == len(reqs)        # no (prompt, budget) collision
    return out


# ===================================== engine-level migration (jax)


@pytest.mark.parametrize("kv_quant", [False, True], ids=["fp", "int8"])
def test_mid_flight_migration_token_identity(model_and_params, kv_quant):
    """THE tentpole contract: a request snapshotted MID-DECODE
    (extract_live) and resumed elsewhere (admit_migrated) finishes
    with tokens identical to never having moved — for the fp arena
    and the int8+scales arena alike."""
    model, params = model_and_params
    ref = _ref_map(model, params, _reqs(model, 4, seed=11),
                   kv_quant=kv_quant)

    reqs = _reqs(model, 4, seed=11)
    src = _engine(model, params, kv_quant=kv_quant)
    dst = _engine(model, params, kv_quant=kv_quant)
    src.queue.submit_all(reqs)
    src.queue.close()
    uid = reqs[0].uid
    _step_until(src, _mid_decode(src, uid))
    h = src.extract_live(uid)
    assert h is not None and h.kind == "migration"
    assert h.fill >= len(reqs[0].prompt)        # really mid-decode
    assert src.extract_live(uid) is None        # slot already gone
    assert src.counts["migrated"] == 1
    src_comps = src.run(max_steps=2000)
    assert dst.admit_migrated(h) is True
    dst.queue.close()
    dst_comps = dst.run(max_steps=2000)

    moved = [c for c in dst_comps if c.request.uid == uid]
    assert len(moved) == 1 and moved[0].status == "ok"
    # the source's "migrated" completion is the partial snapshot — the
    # DESTINATION owns the request's real terminal
    assert [c.status for c in src_comps if c.request.uid == uid] \
        == ["migrated"]
    finished = [c for c in src_comps + dst_comps
                if c.status != "migrated"]
    assert len(finished) == len(reqs)
    for c in finished:
        key = (tuple(c.request.prompt), c.request.max_new_tokens)
        assert list(c.tokens) == ref[key], c.request.uid
    if not kv_quant:
        # fp additionally matches dense one-shot generate()
        c = moved[0]
        P = len(c.request.prompt)
        full = generate(model, params,
                        jnp.asarray([c.request.prompt], jnp.int32),
                        max_len=MAX_LEN)
        np.testing.assert_array_equal(
            np.asarray(full)[0, P:P + len(c.tokens)],
            np.asarray(c.tokens, np.int32))
    # the source's availability never dips: "migrated" sits outside
    # the denominator (the destination owns the terminal)
    summ = src.summary_record()
    assert summ["availability"] == 1.0
    assert summ["migrations_out"] == 1
    assert dst.summary_record()["migrations_in"] == 1


def test_mid_prefill_migration_resumes(model_and_params):
    """extract_live works at ANY lifecycle point: a long prompt caught
    between prefill chunks (fill < prompt length, zero generated
    tokens) resumes its chunked prefill on the destination."""
    model, params = model_and_params
    rs = np.random.RandomState(5)
    req = Request(prompt=[int(t) for t in rs.randint(0, 256, 22)],
                  max_new_tokens=6)
    src = _engine(model, params)
    dst = _engine(model, params)
    src.queue.submit_all([req])
    src.queue.close()
    src.step()                          # one 8-token prefill chunk
    s = _slot_of(src, req.uid)
    assert s is not None and s.prefilling and s.cursor < len(req.prompt)
    h = src.extract_live(req.uid)
    assert h is not None and h.fill < len(req.prompt)
    assert dst.admit_migrated(h) is True
    dst.queue.close()
    comps = dst.run(max_steps=2000)
    assert len(comps) == 1 and comps[0].status == "ok"
    P = len(req.prompt)
    full = generate(model, params, jnp.asarray([req.prompt], jnp.int32),
                    max_len=MAX_LEN)
    np.testing.assert_array_equal(
        np.asarray(full)[0, P:P + len(comps[0].tokens)],
        np.asarray(comps[0].tokens, np.int32))


def test_classic_drain_record_unchanged(model_and_params):
    """v18 gating: a classic (non-migrating) drain's serve_drain record
    carries NO "migrated" key — pre-v18 consumers see byte-identical
    output."""
    model, params = model_and_params
    eng = _engine(model, params)
    eng.queue.close()
    rec = eng.drain()
    assert rec["record"] == "serve_drain"
    assert "migrated" not in rec


@pytest.mark.parametrize("kv_quant", [False, True], ids=["fp", "int8"])
def test_migration_exactly_once_under_ack_crash(model_and_params,
                                                tmp_path, kv_quant):
    """The satellite acceptance: payloads shipped by a migrating drain
    survive the adversarial ack-crash window EXACTLY once.  Worker B
    claims the spool, admits, and "dies" before the ack; after the
    lease expires worker C reclaims the payloads (redelivered
    provenance), finishes them token-identically, and a re-admission
    of the same payload is suppressed as a duplicate."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    reqs = _reqs(model, 3, seed=13)
    ref = _ref_map(model, params, _reqs(model, 3, seed=13),
                   kv_quant=kv_quant)

    src = _engine(model, params, kv_quant=kv_quant)
    src.queue.submit_all(reqs)
    src.queue.close()
    _step_until(src, _mid_decode(src, reqs[0].uid, n_gen=1))
    n_live = len(src.pool.live)
    assert n_live >= 2                  # the drain really ships work
    tx_src = FileTransport(spool, worker="src")
    rec = src.drain(migrate=tx_src.send)
    assert rec["migrated"] == n_live and rec["evicted"] == 0

    # worker B: claim + admit, then crash before ack (engine abandoned)
    lease_s = 0.3
    tx_b = FileTransport(spool, worker="b", lease_s=lease_s)
    claimed = tx_b.poll()
    assert len(claimed) == n_live
    eng_b = _engine(model, params, kv_quant=kv_quant)
    assert eng_b.admit_migrated(claimed[0]) is True
    del eng_b                           # died holding unacked claims

    # worker C: wait out the lease, reclaim, finish, ack
    time.sleep(lease_s * 1.5)
    tx_c = FileTransport(spool, worker="c", lease_s=lease_s)
    eng_c = _engine(model, params, kv_quant=kv_quant)
    redelivered = []
    deadline = time.time() + 10.0
    while len(redelivered) < n_live and time.time() < deadline:
        for h in tx_c.poll():
            assert h.redelivered >= 1   # provably reclaimed work
            assert eng_c.admit_migrated(h) is True
            tx_c.ack(h)
            redelivered.append(h)
        time.sleep(0.05)
    assert len(redelivered) == n_live
    assert len(eng_c.migration_redelivered) == n_live
    eng_c.queue.close()
    comps = eng_c.run(max_steps=2000)
    assert len(comps) == n_live
    for c in comps:
        assert c.status == "ok"
        key = (tuple(c.request.prompt), c.request.max_new_tokens)
        assert list(c.tokens) == ref[key], c.request.uid
    assert tx_c.poll() == []            # spool fully drained
    # duplicate suppression: the same payload again is consumed
    # (acked) WITHOUT a second scatter or a second terminal
    assert eng_c.admit_migrated(redelivered[0]) is True
    assert eng_c.migration_duplicates == 1
    assert len(eng_c.completions) == n_live


def test_spec_drain_ships_only_committed_blocks(model_and_params):
    """The --speculate satellite: stage_writes maps arena blocks for
    draft lanes the accept decision then rejects — unverified garbage
    past the committed cursor.  A migration payload must ship exactly
    ceil(fill/BS) blocks and at most fill+1 tokens, and the resumed
    request stays token-identical to plain greedy decoding."""
    from apex_example_tpu.spec import DraftProposer
    model, params = model_and_params

    class WrongProposer(DraftProposer):
        # Always-rejected drafts: every tick stage_writes maps blocks
        # for lanes the accept decision throws away, so slots sit in
        # the overmapped state (n_mapped > ceil(fill/BS)) the trim
        # exists for — deterministically, not at the mercy of ngram
        # acceptance luck.
        name = "wrong"

        def propose(self, uid, prompt_tokens, generated_tokens, k):
            last = (generated_tokens[-1] if generated_tokens
                    else prompt_tokens[-1])
            return [(int(last) + 1 + j) % model.vocab_size
                    for j in range(k)]

    reqs = _reqs(model, 2, seed=3, prompt_len=(6, 12),
                 max_new=(12, 16), repetitive=True)
    eng = _engine(model, params, speculate=3,   # test_spec's K=3 program
                  proposer=WrongProposer())
    eng.queue.submit_all(reqs)
    eng.queue.close()
    BS = eng.pool.block_size
    found = {}

    def overmapped_slot():
        for i in list(eng.pool.live):
            s = eng.pool.slots[i]
            if s.prefilling or s.n_generated < 1:
                continue
            fill, n_mapped, _ = eng.pool.extract_blocks(i)
            if n_mapped > (fill + BS - 1) // BS:
                found["uid"] = s.request.uid
                found["n_mapped"] = n_mapped
                return True
        return False

    _step_until(eng, overmapped_slot)
    uid = found["uid"]
    h = eng.extract_live(uid)
    assert h is not None
    n_ship = (h.fill + BS - 1) // BS
    assert n_ship < found["n_mapped"]       # the trim really fired
    for arr in h.payload.values():
        assert arr.shape[0] == n_ship       # draft-lane blocks trimmed
    assert len(h.tokens) <= h.fill + 1      # pending feed token only

    dst = _engine(model, params)            # plain engine resumes it
    assert dst.admit_migrated(h) is True
    dst.queue.close()
    comps = dst.run(max_steps=2000)
    c = next(c for c in comps if c.request.uid == uid)
    assert c.status == "ok"
    P = len(c.request.prompt)
    full = generate(model, params,
                    jnp.asarray([c.request.prompt], jnp.int32),
                    max_len=MAX_LEN)
    np.testing.assert_array_equal(
        np.asarray(full)[0, P:P + len(c.tokens)],
        np.asarray(c.tokens, np.int32))


def test_migration_ping_pong_not_suppressed(model_and_params):
    """THE rebalance ping-pong regression (A -> B -> A -> B): an engine
    that once admitted a uid and later migrated it OUT must forget its
    duplicate suppression — the uid's return is a new incarnation, and
    swallowing it as a duplicate would lose the request."""
    model, params = model_and_params
    reqs = _reqs(model, 1, seed=17, max_new=(10, 12))
    uid = reqs[0].uid
    a = _engine(model, params)
    b = _engine(model, params)
    a.queue.submit_all(reqs)
    a.queue.close()
    _step_until(a, _mid_decode(a, uid, n_gen=1))
    hop = a.extract_live(uid)
    for eng in (b, a, b):               # B admits, then A, then B again
        assert eng.admit_migrated(hop) is True, eng
        s = _slot_of(eng, uid)
        assert s is not None
        if eng is not b or b.migrations_in < 2:
            eng.step()
            eng.step()
            hop = eng.extract_live(uid)
            assert hop is not None
    assert b.migration_duplicates == 0  # the second visit was admitted
    b.queue.close()
    comps = b.run(max_steps=2000)
    # each engine holds "migrated" partials from its earlier visits;
    # exactly ONE real terminal exists, on b, after the final hop
    finished = [c for c in a.completions + comps
                if c.request.uid == uid and c.status != "migrated"]
    assert len(finished) == 1
    c = finished[0]
    assert c.status == "ok"
    P = len(c.request.prompt)
    full = generate(model, params,
                    jnp.asarray([c.request.prompt], jnp.int32),
                    max_len=MAX_LEN)
    np.testing.assert_array_equal(
        np.asarray(full)[0, P:P + len(c.tokens)],
        np.asarray(c.tokens, np.int32))


# ======================== jax-free router + pool unit tests (fakes)


class FakeMigReplica:
    """The replica contract with a kv_bytes_live gauge and a recording
    migrate() — the rebalance/autoscale surface without an engine."""

    def __init__(self, name, kv_bytes_live=None, pending=0,
                 migrate_raises=False):
        self.name = name
        self.specs = []
        self.events = []
        self.migrate_asks = []
        self._migrate_raises = migrate_raises
        self._state = {"state": "healthy", "pending": pending,
                       "blocks_live": 0, "progress_age_s": 0.0,
                       "pid": None, "restarts": 0}
        if kv_bytes_live is not None:
            self._state["kv_bytes_live"] = kv_bytes_live

    def submit(self, spec):
        self.specs.append(spec)
        return True

    def poll(self):
        out, self.events = self.events, []
        return out

    def state(self):
        return dict(self._state, name=self.name)

    def set_state(self, **kw):
        self._state.update(kw)

    def migrate(self, n=1):
        if self._migrate_raises:
            raise ValueError("no migration spool")
        self.migrate_asks.append(n)

    def start(self):
        return self

    def stop(self, *a, **k):
        pass


def test_router_backlog_retire_and_add():
    a = FakeMigReplica("a", pending=2)
    b = FakeMigReplica("b", pending=3)
    router = FleetRouter([a, b], log=None)
    router.poll()                       # absorb the pending gauges
    assert router.backlog() == 5
    router.retire_replica("a")          # unroutable, still polled
    assert router.backlog() == 3
    for i in range(4):
        router.submit({"uid": f"u{i}", "prompt": [1], "max_new_tokens": 1})
    assert a.specs == [] and len(b.specs) == 4
    with pytest.raises(ValueError):
        router.add_replica(FakeMigReplica("b"))     # duplicate name
    c = FakeMigReplica("c")
    router.add_replica(c)
    router.submit({"uid": "u9", "prompt": [1], "max_new_tokens": 1})
    assert len(c.specs) + len(b.specs) == 5         # c is routable
    assert router.ttft_p50_ms() is None             # SLO plane unarmed


def test_router_note_autoscale_ledger():
    router = FleetRouter([FakeMigReplica("a")], log=None)
    with pytest.raises(ValueError):
        router.note_autoscale("sideways", "a")
    router.note_autoscale("up", "e0", "backlog 5 > 4")
    router.note_autoscale("up", "e1")
    router.note_autoscale("down", "e1")
    summ = router.summary_record()
    assert summ["scale_up_events"] == 2
    assert summ["scale_down_events"] == 1


def test_router_rebalance_targets_hottest():
    a = FakeMigReplica("a", kv_bytes_live=100)
    b = FakeMigReplica("b", kv_bytes_live=900)
    router = FleetRouter([a, b], rebalance_kv_ratio=1.5,
                         rebalance_cooldown_s=0.0, log=None)
    router.poll()
    router.poll()
    assert a.migrate_asks == []
    assert b.migrate_asks and all(n == 1 for n in b.migrate_asks)
    # the asks are ledgered (the summary field itself is gated on a
    # migration actually landing, which these fakes never report)
    assert router._rebalance_migrations == len(b.migrate_asks)


def test_router_rebalance_respects_ratio_and_failures():
    # balanced fleet: nobody clears the ratio, no asks
    a = FakeMigReplica("a", kv_bytes_live=500)
    b = FakeMigReplica("b", kv_bytes_live=510)
    router = FleetRouter([a, b], rebalance_kv_ratio=1.5,
                         rebalance_cooldown_s=0.0, log=None)
    router.poll()
    router.poll()
    assert a.migrate_asks == [] and b.migrate_asks == []
    # a hot replica WITHOUT a migration spool: the ask degrades to a
    # no-op instead of crashing the poll loop, and is not ledgered
    c = FakeMigReplica("c", kv_bytes_live=100)
    d = FakeMigReplica("d", kv_bytes_live=900, migrate_raises=True)
    router2 = FleetRouter([c, d], rebalance_kv_ratio=1.5,
                          rebalance_cooldown_s=0.0, log=None)
    router2.poll()
    router2.poll()
    assert router2._rebalance_migrations == 0
    # a retired replica is exempt however hot it runs
    e = FakeMigReplica("e", kv_bytes_live=100)
    f = FakeMigReplica("f", kv_bytes_live=120)
    g = FakeMigReplica("g", kv_bytes_live=900)
    router3 = FleetRouter([e, f, g], rebalance_kv_ratio=1.5,
                          rebalance_cooldown_s=0.0, log=None)
    router3.retire_replica("g")
    router3.poll()
    router3.poll()
    assert g.migrate_asks == []


class _PoolRouter:
    """The four methods ElasticPool duck-types against."""

    def __init__(self):
        self.backlog_v = 0
        self.ttft = None
        self.added = []
        self.retired = []
        self.notes = []

    def backlog(self):
        return self.backlog_v

    def ttft_p50_ms(self):
        return self.ttft

    def add_replica(self, handle):
        self.added.append(handle.name)

    def retire_replica(self, name):
        self.retired.append(name)

    def note_autoscale(self, direction, replica, reason=""):
        self.notes.append((direction, replica))


class _PoolHandle:
    def __init__(self, name, migrate_tx=None):
        self.name = name
        self.migrate_tx = migrate_tx
        self.started = False
        self.stopped = False
        self.interrupts = []

    def start(self):
        self.started = True
        return self

    def stop(self, timeout_s=0.0):
        self.stopped = True

    def interrupt(self, mode="drain"):
        self.interrupts.append(mode)


def test_elastic_pool_validation_and_hysteresis():
    fleet_cli = _load_fleet_cli()
    ElasticPool = fleet_cli.ElasticPool
    router = _PoolRouter()
    spawn = lambda i: _PoolHandle(f"e{i}", migrate_tx=object())
    for bad in (dict(min_replicas=0), dict(min_replicas=3,
                                           max_replicas=2),
                dict(up_backlog=4, down_backlog=4),
                dict(cooldown_s=-1)):
        with pytest.raises(ValueError):
            ElasticPool(router, spawn, **bad)

    r0 = _PoolHandle("r0")
    pool = ElasticPool(router, spawn, min_replicas=1, max_replicas=3,
                       up_backlog=4, down_backlog=0, cooldown_s=0.0,
                       initial=[r0])
    # hot: spawn, start, register, ledger — up to max_replicas
    router.backlog_v = 9
    assert pool.step() == ("up", "e0")
    assert pool.step() == ("up", "e1")
    assert pool.step() is None          # at max, no further spawns
    assert pool.size() == 3 and pool.within_bounds()
    assert router.added == ["e0", "e1"]
    assert all(h.started for h in pool.active if h.name != "r0")
    # inside the band: nothing moves
    router.backlog_v = 2
    assert pool.step() is None
    # idle: LIFO retirement, migrate-drain (the handle has a spool),
    # non-blocking stop, never below min_replicas
    router.backlog_v = 0
    assert pool.step() == ("down", "e1")
    assert pool.step() == ("down", "e0")
    assert pool.step() is None          # r0 is the floor
    assert pool.size() == 1 and pool.active[0] is r0
    assert router.retired == ["e1", "e0"]
    down = [h for h in pool.retired]
    assert all(h.interrupts == ["migrate"] and h.stopped for h in down)
    assert router.notes == [("up", "e0"), ("up", "e1"),
                            ("down", "e1"), ("down", "e0")]


def test_elastic_pool_cooldown_and_ttft_signal():
    fleet_cli = _load_fleet_cli()
    router = _PoolRouter()
    spawn = lambda i: _PoolHandle(f"e{i}")
    pool = fleet_cli.ElasticPool(router, spawn, min_replicas=1,
                                 max_replicas=4, up_backlog=4,
                                 down_backlog=0, cooldown_s=60.0,
                                 ttft_p50_ms=50.0,
                                 initial=[_PoolHandle("r0")])
    # latency signal alone scales up (backlog is quiet)...
    router.ttft = 120.0
    assert pool.step() == ("up", "e0")
    # ...and the cooldown swallows the immediate second decision
    assert pool.step() is None
    assert pool.size() == 2
    # a retired handle WITHOUT a spool gets the graceful stop, no
    # migrate interrupt
    pool.cooldown_s = 0.0
    router.ttft = 10.0
    assert pool.step() == ("down", "e0")
    assert pool.retired[0].interrupts == []
    assert pool.retired[0].stopped


def test_proc_replica_interrupt_idempotent(tmp_path, monkeypatch):
    """The double-interrupt satellite: while a drain/restart is in
    flight the newest heartbeat still advertises the OLD pid —
    re-SIGTERMing it could hit a recycled process.  interrupt() is a
    no-op (None) unless the replica reads healthy."""
    r = ProcReplica("p0", str(tmp_path), REPO)
    kills = []
    monkeypatch.setattr(os, "kill",
                        lambda pid, sig: kills.append((pid, sig)))
    monkeypatch.setattr(
        r, "state", lambda: {"state": "healthy", "pid": 4242})
    assert r.interrupt(mode="migrate") == 4242
    assert kills == [(4242, signal.SIGTERM)]
    for busy in ("draining", "restarting", "crashed", "stopped"):
        monkeypatch.setattr(
            r, "state", lambda b=busy: {"state": b, "pid": 4242})
        assert r.interrupt() is None
        assert r.interrupt(mode="migrate") is None
    assert kills == [(4242, signal.SIGTERM)]    # exactly one SIGTERM
    with pytest.raises(ValueError):
        r.interrupt(mode="rebalance")


# =========================== schema v18 + back-compat + tools gates


def test_schema_v18_migration_records_validate():
    assert obs_schema.SCHEMA_VERSION >= 18
    records = obs.read_jsonl(FIXTURE)
    assert not obs_schema.validate_stream(records)
    migs = [r for r in records if r["record"] == "kv_migration"]
    assert {m["direction"] for m in migs} == {"out", "in"}
    # required-field enforcement on the new table
    bad = dict(migs[0])
    bad.pop("fill")
    assert obs_schema.validate_record(bad)
    # a migrating serve_drain and the v18 fleet_summary ledger are in
    # the checked-in stream (the fixture proves the shape end-to-end)
    drains = [r for r in records if r["record"] == "serve_drain"]
    assert drains and all("migrated" in d for d in drains)
    assert all(d["evicted"] == 0 for d in drains)
    summ = next(r for r in records if r["record"] == "fleet_summary")
    assert summ["migrations"] >= 1
    assert summ["migration_completed"] == summ["migrations"]
    assert summ["in_spool"] == 0 and summ["lost"] == 0


def test_metrics_lint_back_compat_sweep():
    """Every checked-in fixture stream — v10 fleet, v12/v13 disagg,
    v14 SLO, v15 perf, v16 spec, v17 sched, v11 quant, v18 migrate —
    lints clean under the v18 schema: each version's tables stay a
    strict superset of the last."""
    lint = _load_tool("metrics_lint")
    fixtures = sorted(glob.glob(
        os.path.join(REPO, "tests", "fixtures", "**", "*.jsonl"),
        recursive=True))
    assert len(fixtures) >= 11
    for path in fixtures:
        code, errors = lint.lint(path)
        assert code == 0 and not errors, (path, errors)


def test_ci_gate_migrate_stream_and_tampers(tmp_path, capsys):
    ci_gate = _load_tool("ci_gate")
    # ONE full-command run (graftlint + migrate gate); the failure
    # variants exercise the gate function directly
    assert ci_gate.main(["--migrate-stream", FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "migrate gate" in out and "PASS" in out
    assert ci_gate.main(["--migrate-stream",
                         str(tmp_path / "missing.jsonl")]) == 2

    records = obs.read_jsonl(FIXTURE)

    def rewrite(name, mutate):
        recs = mutate([dict(r) for r in records])
        path = str(tmp_path / name)
        with open(path, "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
        return path

    assert ci_gate._migrate_gate(FIXTURE) == 0

    def tampered_counter(recs):
        for r in recs:
            if r["record"] == "fleet_summary":
                r["migration_completed"] += 1
        return recs

    def evicting_drain(recs):
        for r in recs:
            if r["record"] == "serve_drain" and "migrated" in r:
                r["evicted"] = 1
                break
        return recs

    def lost_leg(recs):
        out, dropped = [], False
        for r in recs:
            if (not dropped and r["record"] == "kv_migration"
                    and r.get("direction") == "in"
                    and not r.get("duplicate")):
                dropped = True
                continue
            out.append(r)
        return out

    def unarmed(recs):
        for r in recs:
            if r["record"] == "fleet_summary":
                r.pop("migrations", None)
        return recs

    assert ci_gate._migrate_gate(
        rewrite("tamper.jsonl", tampered_counter)) == 1
    assert ci_gate._migrate_gate(
        rewrite("evict.jsonl", evicting_drain)) == 1
    assert ci_gate._migrate_gate(
        rewrite("lost.jsonl", lost_leg)) == 1
    assert ci_gate._migrate_gate(
        rewrite("unarmed.jsonl", unarmed)) == 1


def test_fleet_report_migration_line(capsys):
    report = _load_tool("fleet_report")
    assert report.main([FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "MIGRATION:" in out
    assert "shipped mid-flight" in out
    assert "scenario verdict: PASS" in out
    assert "MIGRATION LOSS" not in out


def test_serve_report_migration_lines(model_and_params, tmp_path,
                                      capsys):
    """serve_report over a migration-armed single-replica stream: the
    MIGRATION block (out/in, bytes, transit percentiles), the DRAIN
    line's migrated count, and availability that excludes migrated-
    away requests from the denominator."""
    model, params = model_and_params
    path = str(tmp_path / "serve.jsonl")
    spool = str(tmp_path / "spool")
    sink = obs.JsonlSink(path, rank=0)
    src = _engine(model, params, sink=sink, run_id="mig-report")
    reqs = _reqs(model, 3, seed=19)
    src.queue.submit_all(reqs)
    src.queue.close()
    _step_until(src, _mid_decode(src, reqs[0].uid, n_gen=1))
    tx = FileTransport(spool, worker="src")
    drain_rec = src.drain(migrate=tx.send)
    assert drain_rec["migrated"] >= 1

    dst = _engine(model, params, sink=sink, run_id="mig-report")
    rx = FileTransport(spool, worker="dst")
    for h in rx.poll():
        assert dst.admit_migrated(h) is True
        rx.ack(h)
    dst.queue.close()
    dst.run(max_steps=2000)
    sink.write(dst.summary_record())
    sink.close()

    report = _load_tool("serve_report")
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "MIGRATION:" in out
    assert "DRAIN:" in out and "migrated" in out
    assert "availability 1.000" in out


# =========================== scored scenarios (thread fleet, shared
# compiled programs — zero new compiles)


def _make_request(spec):
    return Request(prompt=spec["prompt"],
                   max_new_tokens=int(spec["max_new_tokens"]),
                   temperature=float(spec.get("temperature", 0.0)),
                   top_k=int(spec.get("top_k", 0)),
                   eos_id=spec.get("eos_id"),
                   deadline_s=spec.get("deadline_s"),
                   uid=spec["uid"])


def _mig_replica(model, params, name, spool, lease_s=0.5, fault=None,
                 intake=True):
    def factory():
        return ServeEngine(model, params, num_slots=SLOTS,
                           max_len=MAX_LEN,
                           rng=jax.random.PRNGKey(0))

    def mig_factory(worker=name):
        return FileTransport(spool, worker=worker + ".mig",
                             lease_s=lease_s)

    return ThreadReplica(name, factory, _make_request, fault=fault,
                         migrate_factory=mig_factory,
                         migrate_intake=intake)


def _token_identity(model, params, specs, results):
    for spec in specs:
        ev = results[spec["uid"]]
        assert ev["status"] == "ok", (spec["uid"], ev)
        P = len(spec["prompt"])
        n = len(ev["tokens"])
        ref = generate(model, params,
                       jnp.asarray([spec["prompt"]], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(
            np.asarray(ref)[0, P:P + n],
            np.asarray(ev["tokens"], np.int32), err_msg=spec["uid"])


def _drain_once(model, params, specs, spool):
    replicas = [_mig_replica(model, params, f"r{i}", spool)
                for i in range(2)]
    router = FleetRouter(replicas, log=None)
    summary = run_scenario("drain_zero_evictions", router, replicas,
                           specs, timeout_s=90)
    results = dict(router.results)
    for r in replicas:
        r.stop(timeout_s=5.0)
    # The INVARIANT score (the test_fleet stance): HOW MANY slots were
    # live at each interrupt is thread-timing-dependent, so raw
    # migration counts are scored as identities/booleans — only that
    # migrations flowed, that every one landed as a terminal, and that
    # nothing stayed parked is a pure function of the workload.
    score = {k: summary.get(k, 0) for k in
             ("completed", "failed", "timed_out", "lost",
              "availability", "verdict", "requests")}
    score["migrations_flowed"] = summary.get("migrations", 0) > 0
    score["all_landed"] = (summary.get("migration_completed", 0)
                           == summary.get("migrations", 0))
    score["in_spool"] = summary.get("in_spool", 0)
    return score, summary, results


def test_drain_zero_evictions_deterministic(model_and_params, tmp_path):
    """THE rolling restart that kills no request: both replicas are
    cycled with interrupt(mode="migrate") while holding live work —
    zero evictions (failed == timed_out == 0 at availability 1.0),
    every migrated uid reaches a terminal, the spool drains, outputs
    stay token-identical to one-shot generate(), and the invariant
    score is bit-identical across two runs."""
    model, params = model_and_params
    specs = synthetic_specs(10, vocab_size=model.vocab_size, seed=21,
                            prompt_len=(3, 8), max_new=(4, 10))
    first, summary, results = _drain_once(
        model, params, specs, str(tmp_path / "spool_a"))
    assert first["verdict"] == "pass"
    assert first["completed"] == 10 and first["lost"] == 0
    assert first["failed"] == 0 and first["timed_out"] == 0
    assert first["availability"] == 1.0
    assert first["migrations_flowed"] and first["all_landed"]
    assert first["in_spool"] == 0
    assert len(results) == 10
    _token_identity(model, params, specs, results)
    second, _, _ = _drain_once(model, params, specs,
                               str(tmp_path / "spool_b"))
    assert second == first              # deterministic invariant score


def _crash_once(model, params, specs, spool):
    faults = {"r1": FaultPlan("handoff_crash_preack", 1,
                              kinds=SERVE_KINDS)}
    replicas = [
        _mig_replica(model, params, "r0", spool, lease_s=0.3,
                     intake=False),     # outbound-only source
        _mig_replica(model, params, "r1", spool, lease_s=0.3,
                     fault=faults["r1"]),
        _mig_replica(model, params, "r2", spool, lease_s=0.3),
    ]
    router = FleetRouter(replicas, breaker_backoff_s=0.1, log=None)
    summary = run_scenario("migrate_under_crash_storm", router,
                           replicas, specs, source_name="r0",
                           crashed_name="r1", timeout_s=90)
    results = dict(router.results)
    for r in replicas:
        r.stop(timeout_s=5.0)
    score = {k: summary.get(k, 0) for k in
             ("completed", "failed", "timed_out", "lost",
              "availability", "verdict", "requests")}
    score["migrations_flowed"] = summary.get("migrations", 0) > 0
    score["peer_redelivered"] = \
        summary.get("migration_redelivered", 0) > 0
    score["in_spool"] = summary.get("in_spool", 0)
    return score, summary, results


def test_migrate_under_crash_storm_deterministic(model_and_params,
                                                 tmp_path):
    """THE chaos acceptance: the migration DESTINATION dies in the
    ack-crash window holding claimed payloads; nobody restarts it —
    the peer waits out the lease, reclaims, and finishes the
    redelivered payloads exactly once.  Zero lost at availability 1.0,
    token identity end-to-end, invariant score bit-identical twice."""
    model, params = model_and_params
    specs = synthetic_specs(8, vocab_size=model.vocab_size, seed=22,
                            prompt_len=(3, 8), max_new=(4, 10))
    first, summary, results = _crash_once(
        model, params, specs, str(tmp_path / "spool_a"))
    assert first["verdict"] == "pass"
    assert first["completed"] == 8 and first["lost"] == 0
    assert first["availability"] == 1.0
    assert first["migrations_flowed"] and first["peer_redelivered"]
    assert first["in_spool"] == 0
    assert len(results) == 8
    _token_identity(model, params, specs, results)
    second, _, _ = _crash_once(model, params, specs,
                               str(tmp_path / "spool_b"))
    assert second == first              # deterministic chaos score


def test_autoscale_flap_scenario(model_and_params, tmp_path):
    """The elastic-pool drill on a REAL thread fleet: bursty load with
    idle gaps, ElasticPool interleaved with every router poll — the
    pool must track the bursts (>= 1 scale-up) without oscillating
    past the hysteresis bound, retire via migrate-drain (zero lost at
    availability 1.0), and end inside its [min, max] bounds."""
    model, params = model_and_params
    fleet_cli = _load_fleet_cli()
    spool = str(tmp_path / "spool")
    r0 = _mig_replica(model, params, "r0", spool)
    router = FleetRouter([r0], log=None)
    spawned = []

    def spawn(i):
        rep = _mig_replica(model, params, f"e{i}", spool)
        spawned.append(rep)
        return rep

    pool = fleet_cli.ElasticPool(router, spawn, min_replicas=1,
                                 max_replicas=3, up_backlog=3,
                                 down_backlog=0, cooldown_s=0.25,
                                 initial=[r0])
    specs = synthetic_specs(12, vocab_size=model.vocab_size, seed=23,
                            prompt_len=(3, 8), max_new=(4, 10))
    summary = run_scenario("autoscale_flap", router, [r0], specs,
                           pool=pool, bursts=3, gap_s=0.4,
                           timeout_s=90)
    for r in [r0] + spawned:
        r.stop(timeout_s=5.0)
    assert summary["verdict"] == "pass"
    assert summary["completed"] == 12 and summary["lost"] == 0
    assert summary["availability"] == 1.0
    ups = summary.get("scale_up_events", 0)
    downs = summary.get("scale_down_events", 0)
    assert ups >= 1                     # the bursts were tracked
    assert ups + downs <= 6             # the hysteresis bound held
    assert pool.within_bounds()
    # the router ledger and the pool's own event log agree
    assert ups == sum(1 for e in pool.events if e[0] == "up")
    assert downs == sum(1 for e in pool.events if e[0] == "down")
