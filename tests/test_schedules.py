"""LR schedules (SURVEY.md §3.5 adjust_learning_rate + warmup)."""

import jax.numpy as jnp
import numpy as np
import pytest

from apex_example_tpu.optim import (FusedSGD, build_schedule, constant_lr,
                                    cosine_decay, polynomial_decay,
                                    step_decay)


def _at(f, s):
    return float(f(jnp.asarray(s, jnp.int32)))


def test_warmup_ramp():
    f = constant_lr(1.0, warmup_steps=10)
    assert _at(f, 1) == pytest.approx(0.1)
    assert _at(f, 5) == pytest.approx(0.5)
    assert _at(f, 10) == pytest.approx(1.0)
    assert _at(f, 500) == pytest.approx(1.0)


def test_step_decay_boundaries():
    f = step_decay(1.0, boundaries=[30, 60], gamma=0.1)
    assert _at(f, 29) == pytest.approx(1.0)
    assert _at(f, 30) == pytest.approx(0.1)
    assert _at(f, 59) == pytest.approx(0.1)
    assert _at(f, 60) == pytest.approx(0.01, rel=1e-5)


def test_cosine_endpoints():
    f = cosine_decay(1.0, total_steps=100, warmup_steps=10, min_lr=0.05)
    assert _at(f, 10) == pytest.approx(1.0)
    mid = _at(f, 55)
    assert 0.05 < mid < 1.0
    assert _at(f, 100) == pytest.approx(0.05)
    assert _at(f, 200) == pytest.approx(0.05)   # clamped past the end


def test_poly_linear():
    f = polynomial_decay(1.0, total_steps=110, warmup_steps=10, power=1.0)
    assert _at(f, 10) == pytest.approx(1.0)
    assert _at(f, 60) == pytest.approx(0.5)
    assert _at(f, 110) == pytest.approx(0.0, abs=1e-7)


def test_build_schedule_const_fast_path():
    assert build_schedule("const", 0.3, 100) == pytest.approx(0.3)
    f = build_schedule("step", 1.0, 90)   # default boundaries at 30/60
    assert _at(f, 29) == pytest.approx(1.0)
    assert _at(f, 31) == pytest.approx(0.1)


def test_fused_sgd_consumes_schedule():
    """The optimizer's callable-lr path: updates shrink as the schedule
    decays (SGD no-momentum: Δp = lr·g)."""
    f = step_decay(1.0, boundaries=[2], gamma=0.1)
    opt = FusedSGD(lr=f, momentum=0.0)
    p = {"w": jnp.ones((4,), jnp.float32)}
    g = {"w": jnp.ones((4,), jnp.float32)}
    s = opt.init(p)
    p1, s = opt.apply(g, s, p)     # step 1: lr 1.0
    p2, s = opt.apply(g, s, p1)    # step 2: lr 0.1
    d1 = float(jnp.abs(p1["w"] - p["w"]).mean())
    d2 = float(jnp.abs(p2["w"] - p1["w"]).mean())
    np.testing.assert_allclose(d1, 1.0, rtol=1e-6)
    np.testing.assert_allclose(d2, 0.1, rtol=1e-5)


def test_get_forward_backward_func_decision_table():
    """Reference decision table: pp==1 -> no_pipelining; virtual set ->
    interleaved; else plain 1F1B."""
    from apex_example_tpu.transformer.pipeline_parallel import (
        forward_backward_no_pipelining,
        forward_backward_pipelining_with_interleaving,
        forward_backward_pipelining_without_interleaving,
        get_forward_backward_func)
    assert get_forward_backward_func(None, 1) \
        is forward_backward_no_pipelining
    assert get_forward_backward_func(None, 4) \
        is forward_backward_pipelining_without_interleaving
    assert get_forward_backward_func(2, 4) \
        is forward_backward_pipelining_with_interleaving
