"""Test rig: 8 logical CPU devices + Pallas interpret mode.

SURVEY.md §5: multi-device semantics are tested on real XLA CPU devices via
--xla_force_host_platform_device_count=8 (the actual pjit/psum code path, not
a mock — this exceeds the reference's "need 2 physical GPUs" test gap), and
Pallas kernels run under the interpreter so kernel tests execute on CPU.
Env vars must be set before jax initializes, hence the import-time block.
"""

import os

# Overwrite (not setdefault): the shell may pin JAX_PLATFORMS to the real
# TPU ("axon"); tests always run on the 8-logical-device CPU rig.  Set
# APEX_TPU_TESTS=1 to run on whatever platform the env selects instead.
if not os.environ.get("APEX_TPU_TESTS"):
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "all-reduce-promotion" not in flags:
    # XLA CPU's all-reduce-promotion pass check-fails on the bf16 model-axis
    # all-reduces GSPMD emits inside the TP×PP partially-manual shard_map
    # (__graft_entry__._dryrun_tp_pp_train documents the crash).  Disabling
    # it keeps bf16 all-reduces in bf16 — the TPU backend's semantics (it
    # has no such pass), so the CPU rig matches the real target more
    # closely, not less.
    flags = (flags + " --xla_disable_hlo_passes=all-reduce-promotion").strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402
import pytest  # noqa: E402

if not os.environ.get("APEX_TPU_TESTS"):
    # The axon TPU plugin pins jax_platforms at import time; the env var
    # alone does not win.  Force CPU before the backend initializes.
    jax.config.update("jax_platforms", "cpu")

from apex_example_tpu import ops  # noqa: E402

ops.set_interpret_mode(True)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("need 8 logical devices")
    return devs[:8]


@pytest.fixture
def compile_events():
    """Recompile-regression guard (ISSUE 7): a callable mapping a
    telemetry JSONL path (or already-parsed records) to the per-function
    ``compile_event`` counts via obs.costmodel.compile_counts — tier-1
    tests assert every instrumented function's count is exactly 1, so a
    silent recompile regression (which would multiply compile time into
    the 870 s suite budget) fails loudly.

    ``counts.gate(path)`` additionally runs the CI gate itself —
    ``tools/cost_report.py PATH --fail-on-recompile`` — over the stream
    (ISSUE 8: the serve path rides the same gate as the train path), so
    the tests police the exact command CI scripts key on, not just the
    underlying counter."""
    import importlib.util

    from apex_example_tpu.obs import costmodel
    from apex_example_tpu.obs.metrics import read_jsonl

    def counts(path_or_records):
        records = path_or_records
        if isinstance(path_or_records, str):
            records = read_jsonl(path_or_records)
        return costmodel.compile_counts(records)

    def gate(path):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "cost_report", os.path.join(repo, "tools", "cost_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main([path, "--fail-on-recompile"])

    counts.gate = gate
    return counts
