"""Auxiliary-subsystem tests (SURVEY.md §6):

- psum determinism: the race-detection analog.  XLA/jit is data-race-free
  by construction; the observable contract is bitwise-identical results for
  identical (seed, data, devices) — which the reference's
  ddp_race_condition_test can only probe stochastically.
- fault injection: kill a training process mid-run (SIGKILL, no cleanup),
  resume from its checkpoint, assert step continuity — the reference
  family's recovery contract is exactly relaunch+resume (no elastic).
"""

import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_example_tpu import amp
from apex_example_tpu.data import image_batch
from apex_example_tpu.engine import (create_train_state,
                                     make_sharded_train_step)
from apex_example_tpu.models import resnet18
from apex_example_tpu.optim import FusedSGD
from apex_example_tpu.parallel import make_data_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_steps(devices, n_steps=5, seed=0):
    policy, scaler = amp.initialize("O2")
    model = resnet18(num_classes=8, small_stem=True, num_filters=8,
                     bn_axis_name="data")
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    mesh = make_data_mesh(devices=devices)
    x, y = image_batch(jnp.asarray(0), batch_size=16, image_size=16,
                       channels=3, num_classes=8, seed=seed)
    state = create_train_state(jax.random.PRNGKey(seed), model, opt, x[:1],
                               policy, scaler)
    step = make_sharded_train_step(mesh, model, opt, policy, donate=False)
    losses = []
    for i in range(n_steps):
        batch = image_batch(jnp.asarray(i), batch_size=16, image_size=16,
                            channels=3, num_classes=8, seed=seed)
        state, metrics = step(state, batch)
        losses.append(np.asarray(metrics["loss"]))
    return np.stack(losses), state


def test_psum_determinism_bitwise(devices8):
    """Same seed, same 8-device mesh, two runs → bitwise-equal losses and
    params (SURVEY.md §6 race-detection row)."""
    l1, s1 = _run_steps(devices8)
    l2, s2 = _run_steps(devices8)
    np.testing.assert_array_equal(l1, l2)      # bitwise, not allclose
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s1.params, s2.params)


def _spawn_trainer(ckpt, extra, env):
    # bert_tiny, not resnet18: the kill/resume contract under test is
    # arch-agnostic (checkpoint step continuity + AMP O2 state survival),
    # and the tiny-LM step compiles several times faster — this test is
    # two cold subprocess trainers, the suite's single largest cost.
    return subprocess.Popen(
        [sys.executable, "train.py", "--arch", "bert_tiny", "--seq-len",
         "16", "--opt", "adam", "--opt-level", "O2", "--epochs", "3",
         "--steps-per-epoch", "3", "--batch-size", "8", "--print-freq",
         "1", "--checkpoint-dir", ckpt] + extra,
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, bufsize=1)


def test_fault_injection_kill_and_resume(tmp_path):
    """SIGKILL mid-run, then resume: training continues from the saved
    step with loss continuity (SURVEY.md §6 failure-detection row)."""
    ckpt = str(tmp_path / "ck")
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and not k.startswith("TPU_")}
    env.update({"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})

    # Phase 1: run until the first checkpoint lands, then SIGKILL (the
    # harshest failure mode: no atexit, no finally blocks).
    p = _spawn_trainer(ckpt, [], env)
    saw_save, out1 = False, []
    deadline = time.time() + 540
    for line in p.stdout:
        out1.append(line)
        if "saved checkpoint at step" in line:
            saw_save = True
            break
        if time.time() > deadline:
            break
    assert saw_save, "".join(out1)
    os.kill(p.pid, signal.SIGKILL)
    p.wait(timeout=60)

    # Phase 2: resume from the murdered run's checkpoint.
    p2 = _spawn_trainer(ckpt, ["--resume", ckpt], env)
    out2, _ = p2.communicate(timeout=540)
    assert p2.returncode == 0, out2
    assert "resumed from step 3 (epoch 1)" in out2, out2
    # It continued (epoch 1 and 2 ran, a later checkpoint was written).
    assert "saved checkpoint at step 9" in out2, out2


def test_async_checkpoint_save_restore(tmp_path):
    """--async-checkpoint semantics: save(wait=False) returns immediately,
    wait_until_finished joins the background write, restore round-trips."""
    import jax
    import jax.numpy as jnp
    from apex_example_tpu import amp
    from apex_example_tpu.engine import create_train_state, make_train_step
    from apex_example_tpu.models.resnet import BasicBlock, ResNet
    from apex_example_tpu.optim import FusedSGD
    from apex_example_tpu.utils.checkpoint import CheckpointManager

    policy, scaler = amp.initialize("O0")
    model = ResNet(stage_sizes=[1], block_cls=BasicBlock, num_classes=4,
                   num_filters=8, small_stem=True)
    opt = FusedSGD(lr=0.1)
    x = jnp.ones((4, 16, 16, 3))
    y = jnp.zeros((4,), jnp.int32)
    state = create_train_state(jax.random.PRNGKey(0), model, opt, x[:1],
                               policy, scaler)
    step = jax.jit(make_train_step(model, opt, policy))
    state, _ = step(state, (x, y))

    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(state, wait=False)          # async: returns before IO lands
    state, _ = step(state, (x, y))       # training continues meanwhile
    mgr.wait_until_finished()
    assert mgr.latest_step() == 1

    fresh = create_train_state(jax.random.PRNGKey(1), model, opt, x[:1],
                               policy, scaler)
    restored = mgr.restore(fresh)
    assert int(restored.step) == 1
    mgr.close()


def test_ddp_resume_through_train_cli(tmp_path, devices8):
    """Resume under a mesh: orbax restores INTO the template's shardings, so
    a single-device-committed template used to make the sharded step raise
    'incompatible devices' on the first post-resume step (found by driving
    train.py end to end; utils.checkpoint.restore_under_mesh is the fix)."""
    import train as train_mod
    ck = str(tmp_path / "ck")
    base = ["--arch", "resnet18", "--opt-level", "O2", "--sync_bn",
            "--steps-per-epoch", "2", "--batch-size", "16",
            "--print-freq", "1"]
    assert train_mod.main(base + ["--epochs", "1",
                                  "--checkpoint-dir", ck]) == 0
    assert train_mod.main(base + ["--epochs", "2", "--resume", ck]) == 0


def test_zero_resume_through_train_cli(tmp_path, devices8):
    """ZeRO resume: restore_under_mesh places the optimizer state per the
    ZeRO optimizer's own state_spec (data-sharded), so the restored shards
    land where the sharded step expects them."""
    import train as train_mod
    ck = str(tmp_path / "ck")
    base = ["--arch", "bert_tiny", "--zero", "--opt", "adam",
            "--opt-level", "O0", "--steps-per-epoch", "2",
            "--batch-size", "8", "--seq-len", "16", "--print-freq", "1"]
    assert train_mod.main(base + ["--epochs", "1",
                                  "--checkpoint-dir", ck]) == 0
    assert train_mod.main(base + ["--epochs", "2", "--resume", ck]) == 0


def test_cp_resume_through_train_cli(tmp_path, devices8):
    """Context-parallel resume: CP state is replicated, so the replicated
    restore_under_mesh template is its restore target too."""
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    ck = str(tmp_path / "ck")
    base = ["--arch", "bert_tiny", "--context-parallel", "4",
            "--opt", "adam", "--opt-level", "O0", "--steps-per-epoch", "2",
            "--batch-size", "8", "--seq-len", "16", "--print-freq", "1"]
    try:
        assert train_mod.main(base + ["--epochs", "1",
                                      "--checkpoint-dir", ck]) == 0
        assert train_mod.main(base + ["--epochs", "2", "--resume", ck]) == 0
    finally:
        parallel_state.set_mesh(None)


# ---------------------------------------------------------------------------
# MFU accounting (utils/flops.py, VERDICT r4 item 3): the analytic FLOPs
# models bench.py's mfu_pct field is computed from.
# ---------------------------------------------------------------------------

def test_resnet50_flops_matches_literature():
    """torchvision ResNet-50 @224 is 4.09 GMACs forward — the per-conv
    enumeration must land on 2x that (±2% for fc/stem conventions)."""
    from apex_example_tpu.utils.flops import resnet_train_flops_per_image
    train = resnet_train_flops_per_image("resnet50", 224, 1000)
    fwd = train / 3.0
    assert abs(fwd - 8.2e9) / 8.2e9 < 0.02
    # resnet18 @224: 1.82 GMACs forward
    fwd18 = resnet_train_flops_per_image("resnet18", 224, 1000) / 3.0
    assert abs(fwd18 - 3.64e9) / 3.64e9 < 0.02


def test_transformer_flops_model():
    from apex_example_tpu.models.bert import bert_base
    from apex_example_tpu.models.gpt import gpt_base
    from apex_example_tpu.models.transformer_xl import transformer_xl_base
    from apex_example_tpu.utils.flops import model_train_flops_per_token

    # BERT-base: 6*N_matmul dominates; N_matmul = 12*(4*768^2 + 2*768*3072)
    # + 768*30522 head = 108.4M -> ~650 MFLOPs/token + attention term.
    bert = model_train_flops_per_token(bert_base(), 128)
    assert 6.3e8 < bert < 7.0e8
    # GPT-base shares the geometry; same ballpark.
    gpt = model_train_flops_per_token(gpt_base(), 128)
    assert abs(gpt - bert) / bert < 0.05
    # attention quadratic: span doubles => flops strictly increase
    assert model_train_flops_per_token(bert_base(), 512) > bert
    # TXL: recurrence widens the attention span by mem_len
    txl = model_train_flops_per_token(transformer_xl_base(), 192)
    assert txl > 0
    # MoE top-2 routes each token through two expert FFNs
    m1 = model_train_flops_per_token(
        bert_base(moe_experts=8, moe_top_k=1), 128)
    m2 = model_train_flops_per_token(
        bert_base(moe_experts=8, moe_top_k=2), 128)
    assert m2 > m1


def test_mfu_pct_and_bench_emit():
    import io
    import json
    from contextlib import redirect_stdout

    from apex_example_tpu.utils.flops import V5E_BF16_PEAK_FLOPS, mfu_pct
    # rate * flops == peak => 100%
    assert mfu_pct(1000.0, V5E_BF16_PEAK_FLOPS / 1000.0) == 100.0

    import bench
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._emit("m", 2000.0, "images/sec/chip", 0.5,
                    flops_per_item=24.5e9)
    rec = json.loads(buf.getvalue())
    assert rec["mfu_pct"] == round(100.0 * 2000 * 24.5e9 / 197e12, 2)
    # without a flops model the field is absent, not null
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._emit("m", 1.0, "u", None)
    assert "mfu_pct" not in json.loads(buf.getvalue())


def test_bench_matrix_rows_carry_mfu():
    """The recorded acceptance-matrix artifact carries the MFU field on
    every row (VERDICT r4 item 3 'Done' criterion)."""
    import json
    import os
    p = os.path.join(os.path.dirname(__file__), "..", "BENCH_MATRIX.json")
    rows = json.load(open(p))["rows"]
    assert rows and all("mfu_pct" in r for r in rows)
    c2 = next(r for r in rows if r["config"] == "c2")
    # 2554.8 img/s x 24.54 GFLOPs/img / 197 TFLOPs ~= 31.8%
    assert 30.0 < c2["mfu_pct"] < 34.0
