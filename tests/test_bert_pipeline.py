"""Pipeline-parallel BERT training (transformer/bert_pipeline.py; train.py
--pipeline-parallel): the SPMD ring schedule driving a REAL workload must
reproduce the dense single-device trajectory exactly — embedding/head
replicated-compute gradients (including the tied decoder's psum-stitched
table grad) and the global masked-position loss normalization are the parts
worth pinning."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from apex_example_tpu import amp
from apex_example_tpu.data import mlm_batch
from apex_example_tpu.engine import (TrainState, create_train_state,
                                     make_train_step)
from apex_example_tpu.models.bert import bert_tiny
from apex_example_tpu.optim import FusedAdam, FusedSGD
from apex_example_tpu.transformer.bert_pipeline import (
    bert_pp_state_shardings, make_bert_pp_train_step, pack_params,
    unpack_params)
from apex_example_tpu.workloads import mlm_loss

BATCH, SEQ = 8, 16


def _batch(i, vocab):
    ids, lab, w = mlm_batch(jnp.asarray(i, jnp.int32), batch_size=BATCH,
                            seq_len=SEQ, vocab_size=vocab,
                            mask_token_id=vocab - 1, seed=0)
    return ids, (lab, w)


def _pp_state(dense_state, model, opt):
    packed = pack_params(dense_state.params, model.num_layers)
    return TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                      batch_stats={}, opt_state=opt.init(packed),
                      scaler=dense_state.scaler)


def test_pp_train_matches_dense(devices8):
    """3 steps on a (pipe=2, data=4) mesh == 3 dense single-device steps,
    loss and end params."""
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, scaler = amp.initialize("O0")
    model = bert_tiny()
    V = model.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)

    state_d = create_train_state(jax.random.PRNGKey(0), model, opt(),
                                 _batch(0, V)[0][:1], policy, scaler)
    step_d = jax.jit(make_train_step(model, opt(), policy, loss_fn=mlm_loss,
                                     compute_accuracy=False))
    zopt = opt()
    state_p = _pp_state(state_d, model, zopt)
    step_p = make_bert_pp_train_step(mesh, model, zopt, policy,
                                     microbatches=2, donate=False)
    for i in range(3):
        b = _batch(i, V)
        state_d, m_d = step_d(state_d, b)
        state_p, m_p = step_p(state_p, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_p["loss"]),
                                   rtol=3e-5)
    un = unpack_params(state_p.params, model.num_layers)
    for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                    jax.tree_util.tree_leaves(un)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pp_state_actually_shards(devices8):
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, scaler = amp.initialize("O0")
    model = bert_tiny()
    opt = FusedAdam(lr=1e-3)
    state_d = create_train_state(jax.random.PRNGKey(0), model, opt,
                                 _batch(0, model.vocab_size)[0][:1],
                                 policy, scaler)
    state = _pp_state(state_d, model, opt)
    state = jax.device_put(state, bert_pp_state_shardings(mesh, state, opt))
    k = state.params["layers"]["attention"]["query"]["kernel"]
    assert k.shape[0] == model.num_layers
    # each pipe stage holds num_layers/2 stacked layers
    assert k.addressable_shards[0].data.shape[0] == model.num_layers // 2
    mu = state.opt_state.mu["layers"]["attention"]["query"]["kernel"]
    assert mu.addressable_shards[0].data.shape[0] == model.num_layers // 2
    # embedding/head replicate
    emb = state.params["rest"]["word_embeddings"]["embedding"]
    assert emb.addressable_shards[0].data.shape == emb.shape


def test_pp_o2_bf16_trains(devices8):
    """amp-O2 under PP: loss falls over a few steps (bf16 compute, fp32
    masters, static scale)."""
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, scaler = amp.initialize("O2")
    md = amp.module_dtypes(policy)
    model = bert_tiny(dtype=md.compute, param_dtype=md.param,
                      ln_dtype=md.ln_io, softmax_dtype=md.softmax)
    opt = FusedAdam(lr=3e-3)
    state_d = create_train_state(jax.random.PRNGKey(0), model, opt,
                                 _batch(0, model.vocab_size)[0][:1],
                                 policy, scaler)
    state = _pp_state(state_d, model, opt)
    step = make_bert_pp_train_step(mesh, model, opt, policy,
                                   microbatches=2, donate=False)
    losses = []
    for i in range(6):
        state, m = step(state, _batch(i, model.vocab_size))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_tp_pp_train_matches_dense(devices8, sequence_parallel):
    """TP×PP composition (VERDICT r3 item 2): 3 steps on a (pipe=2, data=2,
    model=2) mesh — GSPMD TP layers inside the ring-schedule stages, layer
    params sharded over BOTH pipe and model — match 3 dense single-device
    steps, loss and end params."""
    from apex_example_tpu.transformer import parallel_state
    mesh = Mesh(np.asarray(devices8).reshape(2, 2, 2),
                ("pipe", "data", "model"))
    parallel_state.set_mesh(mesh)
    try:
        policy, scaler = amp.initialize("O0")
        dense = bert_tiny()
        model_tp = bert_tiny(tensor_parallel=True,
                             sequence_parallel=sequence_parallel)
        V = dense.vocab_size
        opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
        state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                     _batch(0, V)[0][:1], policy, scaler)
        step_d = jax.jit(make_train_step(dense, opt(), policy,
                                         loss_fn=mlm_loss,
                                         compute_accuracy=False))
        zopt = opt()
        state_p = _pp_state(state_d, dense, zopt)
        state_p = jax.device_put(
            state_p, bert_pp_state_shardings(mesh, state_p, zopt,
                                             model=model_tp))
        step_p = make_bert_pp_train_step(mesh, model_tp, zopt, policy,
                                         microbatches=2, donate=False)
        for i in range(3):
            b = _batch(i, V)
            state_d, m_d = step_d(state_d, b)
            state_p, m_p = step_p(state_p, b)
            np.testing.assert_allclose(float(m_d["loss"]),
                                       float(m_p["loss"]), rtol=3e-5)
        un = unpack_params(state_p.params, dense.num_layers)
        for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                        jax.tree_util.tree_leaves(un)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        # Jointly sharded, and still so after the step: the stacked dim
        # splits over pipe AND the column dim over model.
        qk = state_p.params["layers"]["attention"]["query"]["kernel"]
        assert qk.shape == (dense.num_layers, 64, 64)
        assert qk.addressable_shards[0].data.shape == \
            (dense.num_layers // 2, 64, 32)
        mu = state_p.opt_state.momentum["layers"]["attention"]["query"][
            "kernel"]
        assert mu.addressable_shards[0].data.shape == \
            (dense.num_layers // 2, 64, 32)
    finally:
        parallel_state.set_mesh(None)


@pytest.mark.parametrize("sched,chunks,layers", [("1f1b", 1, 2),
                                                 ("interleaved", 2, 4)])
def test_pp_1f1b_matches_dense(devices8, sched, chunks, layers):
    """True-1F1B (and interleaved-virtual-stage) BERT == dense: the value-
    program schedule with its externally-assembled embedding/head backward
    (head grads + input cotangents through the loss cell) reproduces the
    autodiff trajectory exactly."""
    from apex_example_tpu.transformer.bert_pipeline import (
        pack_params_1f1b, unpack_params_1f1b)
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, scaler = amp.initialize("O0")
    model = bert_tiny(num_layers=layers)
    V = model.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
    state_d = create_train_state(jax.random.PRNGKey(0), model, opt(),
                                 _batch(0, V)[0][:1], policy, scaler)
    step_d = jax.jit(make_train_step(model, opt(), policy, loss_fn=mlm_loss,
                                     compute_accuracy=False))
    zopt = opt()
    packed = pack_params_1f1b(state_d.params, layers, 2, chunks)
    state_p = TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                         batch_stats={}, opt_state=zopt.init(packed),
                         scaler=state_d.scaler)
    state_p = jax.device_put(
        state_p, bert_pp_state_shardings(mesh, state_p, zopt))
    step_p = make_bert_pp_train_step(mesh, model, zopt, policy,
                                     microbatches=2, donate=False,
                                     schedule=sched, num_chunks=chunks)
    for i in range(3):
        b = _batch(i, V)
        state_d, m_d = step_d(state_d, b)
        state_p, m_p = step_p(state_p, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_p["loss"]),
                                   rtol=3e-5)
    un = unpack_params_1f1b(state_p.params, layers, 2, chunks)
    key = lambda kv: str(kv[0])
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(state_d.params),
                   key=key),
            sorted(jax.tree_util.tree_leaves_with_path(un), key=key)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=str(ka))


def test_train_py_cli_pp_1f1b(devices8):
    """--pipeline-schedule 1f1b from the CLI (with LAMB: the arranged pack
    keeps per-layer trust ratios through the extra leading dims)."""
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--pipeline-parallel", "2",
            "--microbatches", "2", "--pipeline-schedule", "1f1b",
            "--batch-size", str(BATCH), "--seq-len", str(SEQ),
            "--epochs", "1", "--steps-per-epoch", "2", "--opt", "lamb",
            "--opt-level", "O0", "--print-freq", "1",
            "--eval", "--eval-batches", "2"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        parallel_state.set_mesh(None)


def test_pp_lamb_matches_dense(devices8):
    """PP + PipelineFusedLAMB == dense FusedLAMB (VERDICT r3 item 5): the
    per-LAYER trust ratios and the GLOBAL clip norm survive the stacked/
    pipelined layout — end params match the dense trajectory, which they
    could not if any layer's ratio or the clip scale differed."""
    from apex_example_tpu.optim import FusedLAMB
    from apex_example_tpu.transformer.bert_pipeline import PipelineFusedLAMB
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, scaler = amp.initialize("O0")
    model = bert_tiny()
    V = model.vocab_size
    mk = lambda: FusedLAMB(lr=2e-3)   # defaults: wd 0.01, max_grad_norm 1.0
    state_d = create_train_state(jax.random.PRNGKey(0), model, mk(),
                                 _batch(0, V)[0][:1], policy, scaler)
    step_d = jax.jit(make_train_step(model, mk(), policy, loss_fn=mlm_loss,
                                     compute_accuracy=False))
    popt = PipelineFusedLAMB(mk())
    state_p = _pp_state(state_d, model, popt)
    state_p = jax.device_put(
        state_p, bert_pp_state_shardings(mesh, state_p, popt))
    step_p = make_bert_pp_train_step(mesh, model, popt, policy,
                                     microbatches=2, donate=False)
    for i in range(3):
        b = _batch(i, V)
        state_d, m_d = step_d(state_d, b)
        state_p, m_p = step_p(state_p, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_p["loss"]),
                                   rtol=3e-5)
    un = unpack_params(state_p.params, model.num_layers)
    for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                    jax.tree_util.tree_leaves(un)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pp_bare_lamb_rejected(devices8):
    """Bare FusedLAMB on the packed tree would silently collapse per-layer
    trust ratios — the factory must refuse it."""
    from apex_example_tpu.optim import FusedLAMB
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, _ = amp.initialize("O0")
    with pytest.raises(ValueError, match="PipelineFusedLAMB"):
        make_bert_pp_train_step(mesh, bert_tiny(), FusedLAMB(lr=1e-3),
                                policy, microbatches=2)


def test_pp_factory_layout_rejections(devices8):
    """The factory rejects (rather than silently ignores/mistrains):
    num_chunks on a non-interleaved schedule, and a PipelineFusedLAMB
    whose stacked_dims does not match the schedule's param layout."""
    from apex_example_tpu.optim import FusedLAMB
    from apex_example_tpu.transformer.bert_pipeline import PipelineFusedLAMB
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, _ = amp.initialize("O0")
    with pytest.raises(ValueError, match="interleaved"):
        make_bert_pp_train_step(mesh, bert_tiny(), None, policy,
                                microbatches=2, schedule="1f1b",
                                num_chunks=4)
    # ring layout is [num_layers, ...]: stacked_dims must be 1
    with pytest.raises(ValueError, match="stacked_dims"):
        make_bert_pp_train_step(
            mesh, bert_tiny(),
            PipelineFusedLAMB(FusedLAMB(lr=1e-3), stacked_dims=3),
            policy, microbatches=2, schedule="ring")
    # 1F1B arranged layout is [S, V, per, ...]: stacked_dims must be 3
    with pytest.raises(ValueError, match="stacked_dims"):
        make_bert_pp_train_step(
            mesh, bert_tiny(),
            PipelineFusedLAMB(FusedLAMB(lr=1e-3), stacked_dims=1),
            policy, microbatches=2, schedule="1f1b")


def test_train_py_cli_pp_lamb(devices8):
    """C4's FusedLAMB rides the pipeline from the CLI."""
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--pipeline-parallel", "2",
            "--microbatches", "2", "--batch-size", str(BATCH),
            "--seq-len", str(SEQ), "--epochs", "1", "--steps-per-epoch",
            "2", "--opt", "lamb", "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        parallel_state.set_mesh(None)


def test_train_py_cli_tp_pp(devices8, capsys):
    """train.py --tensor-parallel 2 --pipeline-parallel 2 trains AND evals
    (the jointly-composed stack from the CLI; eval runs the GSPMD TP model
    on unpack_params of the pipe+model-sharded packed tree)."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--tensor-parallel", "2",
            "--pipeline-parallel", "2", "--microbatches", "2",
            "--batch-size", str(BATCH), "--seq-len", str(SEQ),
            "--epochs", "1", "--steps-per-epoch", "3", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1",
            "--eval", "--eval-batches", "2"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)
    assert "masked_acc" in capsys.readouterr().out


@pytest.mark.parametrize("sched,chunks,layers",
                         [("ring", 1, 2), ("1f1b", 1, 2),
                          ("interleaved", 2, 4)])
def test_pp_fp16_dynamic_scaling_skips_globally(devices8, sched, chunks,
                                                layers):
    """fp16 dynamic scaling under PP: an overflow anywhere in the schedule
    poisons the accumulated grads, the pipe-pmean'd finite flag is mesh-
    invariant, and every stage takes the same all-or-none skip — scale
    halves, the sharded state rolls back bit-exactly, and the next clean
    step trains (mirror of test_tp_fp16_dynamic_scaling_skips_globally).
    Parametrized over the autodiff ring schedule AND the value-program
    1F1B/interleaved schedules: the latter assemble their backward
    externally (head grads + input cotangents), so their overflow/
    unscale path is distinct code."""
    from apex_example_tpu.transformer.bert_pipeline import pack_params_1f1b
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, scaler = amp.initialize("O2", loss_scale="dynamic",
                                    half_dtype=jnp.float16,
                                    init_scale=2.0 ** 4)
    model = bert_tiny(dtype=jnp.float16, num_layers=layers)
    V = model.vocab_size
    opt = FusedAdam(lr=1e-3)
    state_d = create_train_state(jax.random.PRNGKey(0), model, opt,
                                 _batch(0, V)[0][:1], policy, scaler)
    if sched == "ring":
        state = _pp_state(state_d, model, opt)
    else:
        packed = pack_params_1f1b(state_d.params, model.num_layers, 2,
                                  chunks)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                           batch_stats={}, opt_state=opt.init(packed),
                           scaler=state_d.scaler)
    state = jax.device_put(state, bert_pp_state_shardings(mesh, state, opt))
    step = make_bert_pp_train_step(mesh, model, opt, policy,
                                   microbatches=2, donate=False,
                                   schedule=sched, num_chunks=chunks)

    ids, (labels, w) = _batch(0, V)
    w_bad = w.at[0, 0].set(jnp.inf)
    p_before = jax.tree_util.tree_map(lambda p: np.asarray(p), state.params)
    o_before = jax.tree_util.tree_map(lambda p: np.asarray(p),
                                      state.opt_state)
    state, m = step(state, (ids, (labels, w_bad)))
    assert float(m["grads_finite"]) == 0.0
    assert float(state.scaler.scale) == 2.0 ** 3
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # The skip must also roll back the optimizer state — a missed rollback
    # leaves nan in mu/nu that the next step's grads cannot reveal.
    for a, b in zip(jax.tree_util.tree_leaves(o_before),
                    jax.tree_util.tree_leaves(state.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    state, m = step(state, (ids, (labels, w)))
    assert float(m["grads_finite"]) == 1.0
    moved = False
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(state.params)):
        assert np.isfinite(np.asarray(b)).all()
        moved = moved or not np.array_equal(np.asarray(a), np.asarray(b))
    assert moved


def test_train_py_cli_pipeline_parallel(devices8):
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--pipeline-parallel", "2",
            "--microbatches", "2", "--batch-size", str(BATCH),
            "--seq-len", str(SEQ), "--epochs", "1", "--steps-per-epoch",
            "3", "--opt", "adam", "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        parallel_state.set_mesh(None)


def test_train_py_pp_rejections():
    import train as train_mod
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "resnet18", "--pipeline-parallel", "2"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "transformer_xl_tiny",
                        "--pipeline-parallel", "2"])
    with pytest.raises(SystemExit):
        # (ZeRO x PP composes since round 5; ZeRO stays adam-only)
        train_mod.main(["--arch", "bert_tiny", "--pipeline-parallel", "2",
                        "--zero", "--opt", "lamb"])


@pytest.mark.parametrize("sched,chunks,layers", [("1f1b", 1, 2),
                                                 ("interleaved", 2, 4)])
def test_tp_pp_1f1b_interleaved_matches_dense(devices8, sched, chunks,
                                              layers):
    """TP under the 1F1B AND interleaved schedules (VERDICT r4 item 8 —
    previously rejected): the branch-free uniform-collectives cell form
    keeps one collective order on every device, so the GSPMD model-axis
    collectives ride inside the schedule without the cond deadlock.  3
    lockstep steps on a (pipe=2, data=2, model=2) mesh == dense, params
    jointly sharded over pipe AND model."""
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    from apex_example_tpu.transformer.bert_pipeline import (
        pack_params_1f1b, unpack_params_1f1b)
    mesh = Mesh(np.asarray(devices8).reshape(2, 2, 2),
                ("pipe", "data", "model"))
    parallel_state.set_mesh(mesh)
    ops_config.set_force_xla(True)
    try:
        policy, scaler = amp.initialize("O0")
        dense = bert_tiny(num_layers=layers)
        model_tp = bert_tiny(tensor_parallel=True, num_layers=layers)
        V = dense.vocab_size
        opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
        state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                     _batch(0, V)[0][:1], policy, scaler)
        step_d = jax.jit(make_train_step(dense, opt(), policy,
                                         loss_fn=mlm_loss,
                                         compute_accuracy=False))
        zopt = opt()
        packed = pack_params_1f1b(state_d.params, layers, 2, chunks)
        state_p = TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                             batch_stats={}, opt_state=zopt.init(packed),
                             scaler=state_d.scaler)
        state_p = jax.device_put(
            state_p, bert_pp_state_shardings(mesh, state_p, zopt,
                                             model=model_tp))
        step_p = make_bert_pp_train_step(mesh, model_tp, zopt, policy,
                                         microbatches=2, donate=False,
                                         schedule=sched, num_chunks=chunks)
        for i in range(3):
            b = _batch(i, V)
            state_d, m_d = step_d(state_d, b)
            state_p, m_p = step_p(state_p, b)
            np.testing.assert_allclose(float(m_d["loss"]),
                                       float(m_p["loss"]), rtol=3e-5)
        un = unpack_params_1f1b(state_p.params, layers, 2, chunks)
        key = lambda kv: str(kv[0])
        for (ka, a), (kb, b) in zip(
                sorted(jax.tree_util.tree_leaves_with_path(state_d.params),
                       key=key),
                sorted(jax.tree_util.tree_leaves_with_path(un), key=key)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=str(ka))
        # jointly sharded: stacked [S, V, per] dims over pipe, column dim
        # over model — and still so after the steps.
        qk = state_p.params["layers"]["attention"]["query"]["kernel"]
        assert qk.addressable_shards[0].data.shape[-1] == \
            qk.shape[-1] // 2
        assert qk.addressable_shards[0].data.shape[0] == qk.shape[0] // 2
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_train_py_cli_tp_pp_1f1b(devices8):
    """--tensor-parallel now rides the 1F1B schedule from the CLI (the
    interleaved×TP cell is pinned at the library level in
    test_tp_pp_1f1b_interleaved_matches_dense — bert_tiny's 2 layers
    cannot divide stages × virtual chunks for a CLI interleaved smoke)."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--pipeline-parallel", "2",
            "--tensor-parallel", "2", "--microbatches", "2",
            "--pipeline-schedule", "1f1b",
            "--batch-size", str(BATCH), "--seq-len", str(SEQ),
            "--epochs", "1", "--steps-per-epoch", "2", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


@pytest.mark.parametrize("arch,sched,mode", [("gpt", "ring", "ring"),
                                             ("gpt", "1f1b", "ring"),
                                             ("gpt", "ring", "ulysses"),
                                             ("gpt", "ring", "zigzag"),
                                             ("gpt", "1f1b", "zigzag"),
                                             ("bert", "ring", "ring")])
def test_cp_pp_matches_dense(devices8, arch, sched, mode):
    """CP x PP (round 5; previously rejected): the KV ring rides the
    'context' axis INSIDE the schedule's stage cells — long context and
    deep pipelines jointly.  3 lockstep steps on a (pipe=2, data=2,
    context=2) mesh == dense; position embeddings offset per context
    shard in the schedule's embed; losses psum over (data, context).
    1F1B requires the branch-free uniform-collectives cells (the manual
    KV-ring ppermutes inside a cond diverge the collective order exactly
    like the TP case)."""
    from apex_example_tpu.models.gpt import gpt_tiny
    from apex_example_tpu.transformer.bert_pipeline import (
        pack_params_1f1b, unpack_params_1f1b)
    from apex_example_tpu.workloads import lm_loss
    is_gpt = arch == "gpt"
    mk = gpt_tiny if is_gpt else bert_tiny
    mesh = Mesh(np.asarray(devices8).reshape(2, 2, 2),
                ("pipe", "data", "context"))
    policy, scaler = amp.initialize("O0")
    dense = mk()
    cp_model = mk(context_parallel=True, cp_mode=mode)
    V = dense.vocab_size

    def batch(i):
        if is_gpt:
            from apex_example_tpu.data import lm_batch
            toks = lm_batch(jnp.asarray(i, jnp.int32), batch_size=BATCH,
                            seq_len=SEQ, vocab_size=V, seed=0)
            return toks[:, :-1], toks[:, 1:]
        return _batch(i, V)

    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
    state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 batch(0)[0][:1], policy, scaler)
    step_d = jax.jit(make_train_step(dense, opt(), policy,
                                     loss_fn=lm_loss if is_gpt
                                     else mlm_loss,
                                     compute_accuracy=False))
    zopt = opt()
    if sched == "ring":
        packed = pack_params(state_d.params, dense.num_layers)
        unp = lambda p: unpack_params(p, dense.num_layers)
    else:
        packed = pack_params_1f1b(state_d.params, dense.num_layers, 2, 1)
        unp = lambda p: unpack_params_1f1b(p, dense.num_layers, 2, 1)
    state_p = TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                         batch_stats={}, opt_state=zopt.init(packed),
                         scaler=state_d.scaler)
    state_p = jax.device_put(
        state_p, bert_pp_state_shardings(mesh, state_p, zopt))
    step_p = make_bert_pp_train_step(mesh, cp_model, zopt, policy,
                                     microbatches=2, donate=False,
                                     schedule=sched)
    for i in range(3):
        b = batch(i)
        state_d, m_d = step_d(state_d, b)
        state_p, m_p = step_p(state_p, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_p["loss"]),
                                   rtol=3e-5)
    key = lambda kv: str(kv[0])
    for (ka, a), (kb, b2) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(state_d.params),
                   key=key),
            sorted(jax.tree_util.tree_leaves_with_path(
                unp(state_p.params)), key=key)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=1e-4, atol=1e-5, err_msg=str(ka))


def test_cp_pp_zigzag_rejected():
    """zigzag under PP is causal-only (BERT rejected); the general cp
    block fires first at the CLI."""
    import train as train_mod
    mesh_args = ["--arch", "bert_tiny", "--pipeline-parallel", "2",
                 "--context-parallel", "2", "--cp-mode", "zigzag",
                 "--microbatches", "2", "--batch-size", "8",
                 "--seq-len", "16", "--opt", "adam"]
    with pytest.raises(SystemExit):
        train_mod.main(mesh_args)
    with pytest.raises(SystemExit):      # no ZeRO x PP x TP triple
        train_mod.main(["--arch", "gpt_tiny", "--pipeline-parallel", "2",
                        "--zero", "--tensor-parallel", "2",
                        "--microbatches", "2", "--batch-size",
                        "8", "--seq-len", "16", "--opt", "adam"])


def test_train_py_cli_cp_pp(devices8):
    """--context-parallel composes with --pipeline-parallel from the CLI
    (GPT ring schedule + BERT 1f1b)."""
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    base = ["--microbatches", "2", "--batch-size", "8", "--seq-len", "16",
            "--epochs", "1", "--steps-per-epoch", "2", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(
            ["--arch", "gpt_tiny", "--pipeline-parallel", "2",
             "--context-parallel", "2", "--eval", "--eval-batches", "2"]
            + base) == 0
        assert train_mod.main(
            ["--arch", "bert_tiny", "--pipeline-parallel", "2",
             "--context-parallel", "2", "--pipeline-schedule", "1f1b"]
            + base) == 0
    finally:
        parallel_state.set_mesh(None)


@pytest.mark.parametrize("sched", ["ring", "1f1b"])
def test_cp_pp_tp_triple_matches_dense(devices8, sched):
    """The CP x PP x TP TRIPLE (round 5): manual (pipe, data, context) +
    automatic 'model' in one schedule body — KV ring inside the stage
    cells, GSPMD TP inside the attention/FFN, layer params jointly
    sharded over pipe AND model, sequence over context.  3 lockstep
    steps == dense on a (2, 1, 2, 2) mesh."""
    from apex_example_tpu.models.gpt import gpt_tiny
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    from apex_example_tpu.transformer.bert_pipeline import (
        pack_params_1f1b, unpack_params_1f1b)
    from apex_example_tpu.data import lm_batch
    from apex_example_tpu.workloads import lm_loss

    mesh = Mesh(np.asarray(devices8).reshape(2, 1, 2, 2),
                ("pipe", "data", "context", "model"))
    parallel_state.set_mesh(mesh)
    ops_config.set_force_xla(True)
    try:
        policy, scaler = amp.initialize("O0")
        dense = gpt_tiny()
        triple = gpt_tiny(tensor_parallel=True, context_parallel=True,
                          cp_mode="ring")
        V = dense.vocab_size

        def batch(i):
            toks = lm_batch(jnp.asarray(i, jnp.int32), batch_size=BATCH,
                            seq_len=SEQ, vocab_size=V, seed=0)
            return toks[:, :-1], toks[:, 1:]

        opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
        state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                     batch(0)[0][:1], policy, scaler)
        step_d = jax.jit(make_train_step(dense, opt(), policy,
                                         loss_fn=lm_loss,
                                         compute_accuracy=False))
        zopt = opt()
        if sched == "ring":
            packed = pack_params(state_d.params, dense.num_layers)
            unp = lambda p: unpack_params(p, dense.num_layers)
        else:
            packed = pack_params_1f1b(state_d.params, dense.num_layers,
                                      2, 1)
            unp = lambda p: unpack_params_1f1b(p, dense.num_layers, 2, 1)
        state_p = TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                             batch_stats={}, opt_state=zopt.init(packed),
                             scaler=state_d.scaler)
        state_p = jax.device_put(
            state_p, bert_pp_state_shardings(mesh, state_p, zopt,
                                             model=triple))
        step_p = make_bert_pp_train_step(mesh, triple, zopt, policy,
                                         microbatches=2, donate=False,
                                         schedule=sched)
        for i in range(3):
            b = batch(i)
            state_d, m_d = step_d(state_d, b)
            state_p, m_p = step_p(state_p, b)
            np.testing.assert_allclose(float(m_d["loss"]),
                                       float(m_p["loss"]), rtol=3e-5)
        key = lambda kv: str(kv[0])
        for (ka, a), (kb, b2) in zip(
                sorted(jax.tree_util.tree_leaves_with_path(state_d.params),
                       key=key),
                sorted(jax.tree_util.tree_leaves_with_path(
                    unp(state_p.params)), key=key)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=str(ka))
        # params jointly pipe x model sharded
        qk = state_p.params["layers"]["attention"]["query"]["kernel"]
        assert qk.addressable_shards[0].data.shape[0] == qk.shape[0] // 2
        assert qk.addressable_shards[0].data.shape[-1] == qk.shape[-1] // 2
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_train_py_cli_cp_pp_tp(devices8):
    """The triple from the CLI: --pipeline-parallel 2 --context-parallel 2
    --tensor-parallel 2 on 8 devices."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "gpt_tiny", "--pipeline-parallel", "2",
            "--context-parallel", "2", "--tensor-parallel", "2",
            "--microbatches", "2", "--batch-size", "8", "--seq-len", "16",
            "--epochs", "1", "--steps-per-epoch", "2", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_zero_pp_matches_pp_adam(devices8):
    """ZeRO x PP (round 5): PipelineZeroAdam — stage-local flat (m, v)
    buffers sharded over 'data' within the pipe sharding — follows the
    plain-FusedAdam PP trajectory (Adam tolerances), and the buffers'
    LAYOUT and SCALE match the adam tree exactly (rest buffer ==
    flatten(rest mu); stage-s layer buffer == flatten(stage-s layer mu)
    — the check Adam's scale invariance cannot fool)."""
    from apex_example_tpu.optim.distributed import (DistributedFusedAdam,
                                                    _flatten)
    from apex_example_tpu.transformer.bert_pipeline import PipelineZeroAdam

    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, scaler = amp.initialize("O0")
    model = bert_tiny()
    V = model.vocab_size
    hp = dict(lr=1e-3, weight_decay=1e-2)

    state0 = create_train_state(jax.random.PRNGKey(0), model,
                                FusedAdam(**hp), _batch(0, V)[0][:1],
                                policy, scaler)
    packed = pack_params(state0.params, model.num_layers)

    def mk(opt):
        st = TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                        batch_stats={}, opt_state=opt.init(packed),
                        scaler=state0.scaler)
        return jax.device_put(st,
                              bert_pp_state_shardings(mesh, st, opt))

    aopt = FusedAdam(**hp)
    state_a = mk(aopt)
    step_a = make_bert_pp_train_step(mesh, model, aopt, policy,
                                     microbatches=2, donate=False)
    zopt = PipelineZeroAdam(
        DistributedFusedAdam(**hp, world=4, grads_global_mean=True),
        stages=2)
    state_z = mk(zopt)
    step_z = make_bert_pp_train_step(mesh, model, zopt, policy,
                                     microbatches=2, donate=False)

    for i in range(5):
        b = _batch(i, V)
        state_a, m_a = step_a(state_a, b)
        state_z, m_z = step_z(state_z, b)
        np.testing.assert_allclose(float(m_a["loss"]), float(m_z["loss"]),
                                   rtol=1e-4)
    diffs = np.concatenate([
        np.abs(np.asarray(a) - np.asarray(b)).ravel()
        for a, b in zip(jax.tree_util.tree_leaves(state_a.params),
                        jax.tree_util.tree_leaves(state_z.params))])
    assert float((diffs < 5e-3).mean()) > 0.999

    mu_a = state_a.opt_state.mu
    rest_mu = np.asarray(state_z.opt_state.rest_mu)
    np.testing.assert_allclose(
        np.asarray(_flatten(mu_a["rest"], rest_mu.shape[0])), rest_mu,
        rtol=2e-2, atol=2e-4)
    lay_mu = np.asarray(state_z.opt_state.layer_mu)
    L = model.num_layers
    for s in range(2):
        local = jax.tree_util.tree_map(
            lambda x: x[s * (L // 2):(s + 1) * (L // 2)], mu_a["layers"])
        np.testing.assert_allclose(
            np.asarray(_flatten(local, lay_mu.shape[1])), lay_mu[s],
            rtol=2e-2, atol=2e-4)
    # 1/(S*dp) optimizer state per device
    mu = state_z.opt_state.layer_mu
    assert mu.addressable_shards[0].data.size * 8 == mu.size


def test_train_py_cli_zero_pp(devices8):
    """--zero --pipeline-parallel from the CLI (ring + 1f1b)."""
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    base = ["--microbatches", "2", "--batch-size", "8", "--seq-len", "16",
            "--epochs", "1", "--steps-per-epoch", "2", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(
            ["--arch", "bert_tiny", "--zero", "--pipeline-parallel", "2"]
            + base) == 0
        assert train_mod.main(
            ["--arch", "gpt_tiny", "--zero", "--pipeline-parallel", "2",
             "--pipeline-schedule", "1f1b"] + base) == 0
    finally:
        parallel_state.set_mesh(None)
