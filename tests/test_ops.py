"""Kernel tests: Pallas (interpret mode) + XLA reference vs torch goldens.

The reference's L0 pattern (SURVEY.md §5): FusedLayerNorm vs nn.LayerNorm,
fused optimizers vs torch.optim on identical data.  torch here is CPU-only
and used solely as the golden.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_example_tpu import ops
from apex_example_tpu.ops import layer_norm as ln_mod


def _rand(*shape, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return rng.randn(*shape).astype(dtype)


class TestLayerNorm:
    @pytest.mark.parametrize("shape", [(4, 128), (3, 5, 256), (16, 384)])
    def test_forward_vs_torch(self, shape):
        x = _rand(*shape, seed=1)
        g = _rand(shape[-1], seed=2) * 0.1 + 1.0
        b = _rand(shape[-1], seed=3) * 0.1
        y = ops.layer_norm(jnp.asarray(x), jnp.asarray(g), jnp.asarray(b))
        tln = torch.nn.LayerNorm(shape[-1], eps=1e-5)
        with torch.no_grad():
            tln.weight.copy_(torch.from_numpy(g))
            tln.bias.copy_(torch.from_numpy(b))
        want = tln(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(np.asarray(y), want, atol=2e-5, rtol=2e-5)

    def test_backward_vs_torch(self):
        shape = (8, 256)
        x = _rand(*shape, seed=4)
        g = _rand(shape[-1], seed=5) * 0.1 + 1.0
        b = _rand(shape[-1], seed=6) * 0.1

        def f(x_, g_, b_):
            return jnp.sum(ops.layer_norm(x_, g_, b_) ** 2)

        dx, dg, db = jax.grad(f, argnums=(0, 1, 2))(
            jnp.asarray(x), jnp.asarray(g), jnp.asarray(b))

        tx = torch.from_numpy(x).requires_grad_(True)
        tln = torch.nn.LayerNorm(shape[-1], eps=1e-5)
        with torch.no_grad():
            tln.weight.copy_(torch.from_numpy(g))
            tln.bias.copy_(torch.from_numpy(b))
        (tln(tx) ** 2).sum().backward()
        np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(dg), tln.weight.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(db), tln.bias.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)

    def test_bf16_io_fp32_stats(self):
        x = jnp.asarray(_rand(4, 128, seed=7), jnp.bfloat16)
        g = jnp.ones((128,)); b = jnp.zeros((128,))
        y = ops.layer_norm(x, g, b)
        assert y.dtype == jnp.bfloat16
        ref = ops.layer_norm_reference(x, g, b)
        np.testing.assert_allclose(
            np.asarray(y, np.float32), np.asarray(ref, np.float32),
            atol=0.05)

    def test_pallas_matches_reference_path(self):
        # Same inputs through the kernel (interpret) and pure-XLA path.
        x = jnp.asarray(_rand(6, 384, seed=8))
        g = jnp.asarray(_rand(384, seed=9))
        b = jnp.asarray(_rand(384, seed=10))
        y_kernel = ops.layer_norm(x, g, b)
        y_ref = ops.layer_norm_reference(x, g, b)
        np.testing.assert_allclose(np.asarray(y_kernel), np.asarray(y_ref),
                                   atol=2e-5, rtol=2e-5)


class TestRMSNorm:
    @pytest.mark.parametrize("shape", [(4, 128), (3, 5, 256), (16, 384)])
    def test_forward_vs_torch(self, shape):
        x = _rand(*shape, seed=21)
        g = _rand(shape[-1], seed=22) * 0.1 + 1.0
        y = ops.rms_norm(jnp.asarray(x), jnp.asarray(g))
        tx = torch.from_numpy(x)
        want = torch.nn.functional.rms_norm(
            tx, (shape[-1],), torch.from_numpy(g), eps=1e-5).numpy()
        np.testing.assert_allclose(np.asarray(y), want, atol=2e-5, rtol=2e-5)

    def test_backward_vs_torch(self):
        shape = (8, 256)
        x = _rand(*shape, seed=23)
        g = _rand(shape[-1], seed=24) * 0.1 + 1.0

        def f(x_, g_):
            return jnp.sum(ops.rms_norm(x_, g_) ** 2)

        dx, dg = jax.grad(f, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(g))

        tx = torch.from_numpy(x).requires_grad_(True)
        tg = torch.from_numpy(g).requires_grad_(True)
        (torch.nn.functional.rms_norm(tx, (shape[-1],), tg, eps=1e-5)
         ** 2).sum().backward()
        np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(dg), tg.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)

    def test_bf16_io_and_module(self):
        from apex_example_tpu.normalization import FusedRMSNorm
        x = jnp.asarray(_rand(4, 128, seed=25), jnp.bfloat16)
        m = FusedRMSNorm()
        variables = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(variables, x)
        assert y.dtype == jnp.bfloat16
        ref = ops.rms_norm_reference(x, jnp.ones((128,)))
        np.testing.assert_allclose(
            np.asarray(y, np.float32), np.asarray(ref, np.float32),
            atol=0.05)

    def test_pallas_matches_reference_path(self):
        x = jnp.asarray(_rand(6, 384, seed=26))
        g = jnp.asarray(_rand(384, seed=27))
        y_kernel = ops.rms_norm(x, g)
        y_ref = ops.rms_norm_reference(x, g)
        np.testing.assert_allclose(np.asarray(y_kernel), np.asarray(y_ref),
                                   atol=2e-5, rtol=2e-5)


class TestMultiTensor:
    def _tree(self, seed=0):
        return {"a": jnp.asarray(_rand(3, 7, seed=seed)),
                "b": jnp.asarray(_rand(130, seed=seed + 1)),
                "c": jnp.asarray(_rand(2, 2, 2, seed=seed + 2))}

    def test_scale(self):
        t = self._tree()
        out, finite = ops.multi_tensor_scale(t, 0.5)
        assert bool(finite)
        for k in t:
            np.testing.assert_allclose(np.asarray(out[k]),
                                       np.asarray(t[k]) * 0.5, rtol=1e-6)

    def test_scale_detects_inf_nan(self):
        t = self._tree()
        t["b"] = t["b"].at[7].set(jnp.inf)
        _, finite = ops.multi_tensor_scale(t, 1.0)
        assert not bool(finite)
        t["b"] = t["b"].at[7].set(jnp.nan)
        _, finite = ops.multi_tensor_scale(t, 1.0)
        assert not bool(finite)

    def test_axpby(self):
        x, y = self._tree(1), self._tree(5)
        out = ops.multi_tensor_axpby(2.0, x, -0.5, y)
        for k in x:
            np.testing.assert_allclose(
                np.asarray(out[k]),
                2.0 * np.asarray(x[k]) - 0.5 * np.asarray(y[k]), rtol=1e-5,
                atol=1e-6)

    def test_l2norm_global_and_per_tensor(self):
        t = self._tree(3)
        total, per = ops.multi_tensor_l2norm(t, per_tensor=True)
        flat = np.concatenate([np.asarray(v).ravel() for v in
                               jax.tree_util.tree_leaves(t)])
        np.testing.assert_allclose(float(total), np.linalg.norm(flat),
                                   rtol=1e-5)
        leaves = jax.tree_util.tree_leaves(t)
        for n, leaf in zip(per, leaves):
            np.testing.assert_allclose(float(n),
                                       np.linalg.norm(np.asarray(leaf)),
                                       rtol=1e-5)

    def test_clip_grad_norm(self):
        t = {"w": jnp.asarray(_rand(64, seed=11)) * 100.0}
        clipped, norm = ops.clip_grad_norm(t, max_norm=1.0)
        new_norm = ops.multi_tensor_l2norm(clipped)
        assert float(norm) > 1.0
        np.testing.assert_allclose(float(new_norm), 1.0, rtol=1e-3)


class TestFusedOptimKernels:
    def test_adamw_step_vs_torch(self):
        p = _rand(33, seed=20); g = _rand(33, seed=21)
        m = np.zeros_like(p); v = np.zeros_like(p)
        kw = dict(lr=1e-2, beta1=0.9, beta2=0.999, eps=1e-8,
                  weight_decay=0.01)
        # two steps
        jp, jm, jv = jnp.asarray(p), jnp.asarray(m), jnp.asarray(v)
        tp = torch.from_numpy(p.copy()).requires_grad_(True)
        topt = torch.optim.AdamW([tp], lr=1e-2, betas=(0.9, 0.999),
                                 eps=1e-8, weight_decay=0.01)
        for t in (1, 2):
            c1 = 1.0 / (1.0 - 0.9 ** t)
            c2 = 1.0 / (1.0 - 0.999 ** t)
            jp, jm, jv = ops.adam_update_leaf(
                jp, jnp.asarray(g), jm, jv, bias_c1=c1, bias_c2=c2,
                adam_w_mode=True, **kw)
            tp.grad = torch.from_numpy(g.copy())
            topt.step()
        np.testing.assert_allclose(np.asarray(jp), tp.detach().numpy(),
                                   atol=1e-6, rtol=1e-5)

    def test_adam_l2_mode_vs_torch(self):
        p = _rand(40, seed=22); g = _rand(40, seed=23)
        jp = jnp.asarray(p)
        jm = jnp.zeros(40); jv = jnp.zeros(40)
        tp = torch.from_numpy(p.copy()).requires_grad_(True)
        topt = torch.optim.Adam([tp], lr=3e-3, betas=(0.9, 0.999),
                                eps=1e-8, weight_decay=0.1)
        for t in (1, 2, 3):
            c1 = 1.0 / (1.0 - 0.9 ** t)
            c2 = 1.0 / (1.0 - 0.999 ** t)
            jp, jm, jv = ops.adam_update_leaf(
                jp, jnp.asarray(g), jm, jv, lr=3e-3, beta1=0.9, beta2=0.999,
                eps=1e-8, weight_decay=0.1, bias_c1=c1, bias_c2=c2,
                adam_w_mode=False)
            tp.grad = torch.from_numpy(g.copy())
            topt.step()
        np.testing.assert_allclose(np.asarray(jp), tp.detach().numpy(),
                                   atol=1e-6, rtol=1e-5)

    def test_sgd_momentum_vs_torch(self):
        p = _rand(50, seed=24); g = _rand(50, seed=25)
        jp = jnp.asarray(p); jb = jnp.zeros(50)
        tp = torch.from_numpy(p.copy()).requires_grad_(True)
        topt = torch.optim.SGD([tp], lr=0.1, momentum=0.9,
                               weight_decay=1e-4)
        for _ in range(3):
            jp, jb = ops.sgd_update_leaf(jp, jnp.asarray(g), jb, lr=0.1,
                                         momentum=0.9, weight_decay=1e-4)
            tp.grad = torch.from_numpy(g.copy())
            topt.step()
        np.testing.assert_allclose(np.asarray(jp), tp.detach().numpy(),
                                   atol=1e-6, rtol=1e-5)

    def test_lamb_stages_consistency(self):
        # Kernel path vs pure-numpy restatement of the two-stage math.
        p = _rand(70, seed=26); g = _rand(70, seed=27)
        u, m, v, psq, usq = ops.lamb_stage1_leaf(
            jnp.asarray(p), jnp.asarray(g), jnp.zeros(70), jnp.zeros(70),
            beta1=0.9, beta2=0.999, eps=1e-6, weight_decay=0.01,
            bias_c1=10.0, bias_c2=1000.0, grad_scale=1.0)
        m_ref = 0.1 * g
        v_ref = 0.001 * g * g
        u_ref = (m_ref * 10.0) / (np.sqrt(v_ref * 1000.0) + 1e-6) + 0.01 * p
        np.testing.assert_allclose(np.asarray(u), u_ref, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(float(psq), np.sum(p * p), rtol=1e-5)
        np.testing.assert_allclose(float(usq), np.sum(u_ref * u_ref),
                                   rtol=1e-4)
        pn = ops.lamb_stage2_leaf(jnp.asarray(p), u, 0.37)
        np.testing.assert_allclose(np.asarray(pn), p - 0.37 * u_ref,
                                   rtol=1e-4, atol=1e-5)


class TestReviewRegressions:
    def test_sgd_nesterov_first_step_vs_torch(self):
        # Review finding: wd must fold into the grad before the nesterov
        # direction on the first step too.
        p = _rand(33, seed=30); g = _rand(33, seed=31)
        po, bo = ops.sgd_update_leaf(
            jnp.asarray(p), jnp.asarray(g), jnp.zeros(33), lr=0.1,
            momentum=0.9, weight_decay=0.1, nesterov=True, first_step=True)
        tp = torch.from_numpy(p.copy()).requires_grad_(True)
        topt = torch.optim.SGD([tp], lr=0.1, momentum=0.9, weight_decay=0.1,
                               nesterov=True)
        tp.grad = torch.from_numpy(g.copy())
        topt.step()
        np.testing.assert_allclose(np.asarray(po), tp.detach().numpy(),
                                   atol=1e-6, rtol=1e-5)

    def test_grid_rows_padding_bounded(self):
        from apex_example_tpu.ops.multi_tensor import _grid_rows
        for rows in (1, 7, 8, 127, 128, 513, 520, 1000, 4096):
            block, pad = _grid_rows(rows)
            assert pad <= 7, (rows, block, pad)
            assert (rows + pad) % block == 0


def test_larc_clip_matches_apex_semantics():
    import optax
    from apex_example_tpu.parallel import larc as larc_fn
    lr = 0.1
    params = {"w": jnp.ones(4) * 2.0}          # ||p|| = 4
    grads = {"w": jnp.ones(4) * 0.01}          # ||g|| = 0.02
    tx = optax.chain(larc_fn(trust_coefficient=0.02, clip=True, lr=lr),
                     optax.sgd(lr))
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    # adaptive_lr = 0.02*4/0.02 = 4.0 > lr -> ratio clamps at 1 ->
    # effective step = lr * g.
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               -lr * np.asarray(grads["w"]), rtol=1e-5)
    # adaptive_lr below lr scales the step down by adaptive/lr.
    grads2 = {"w": jnp.ones(4) * 10.0}         # ||g||=20, adaptive=0.004
    updates2, _ = tx.update(grads2, state, params)
    np.testing.assert_allclose(
        np.asarray(updates2["w"]),
        -lr * (0.02 * 4.0 / 20.0 / lr) * np.asarray(grads2["w"]), rtol=1e-4)


class TestNovoGrad:
    """FusedNovoGrad vs a pure-numpy restatement of the reference semantics
    (multi_tensor_novograd.cu / apex.optimizers.FusedNovoGrad, SURVEY.md
    §3.4): per-tensor second moment = EMA of ||g||², first-step v = ||g₁||²,
    grad_averaging, L2 on the normalized gradient, Adam-style bias
    correction."""

    @staticmethod
    def _numpy_novograd(p, grads, lr=1e-2, b1=0.95, b2=0.98, eps=1e-8,
                        wd=0.01, grad_averaging=True, bias_correction=True):
        p = p.astype(np.float64).copy()
        m = np.zeros_like(p)
        v = 0.0
        ga = (1.0 - b1) if grad_averaging else 1.0
        for t, g in enumerate(grads, start=1):
            g = g.astype(np.float64)
            gsq = float(np.sum(g * g))
            v = gsq if t == 1 else b2 * v + (1.0 - b2) * gsq
            c1 = 1.0 / (1.0 - b1 ** t) if bias_correction else 1.0
            c2 = 1.0 / (1.0 - b2 ** t) if bias_correction else 1.0
            g_hat = g / (np.sqrt(v * c2) + eps) + wd * p
            m = b1 * m + ga * g_hat
            p = p - lr * c1 * m
        return p

    def test_three_steps_vs_numpy(self):
        from apex_example_tpu.optim import FusedNovoGrad
        p0 = _rand(37, seed=40)
        grads = [_rand(37, seed=41 + i) for i in range(3)]
        opt = FusedNovoGrad(lr=1e-2, betas=(0.95, 0.98), eps=1e-8,
                            weight_decay=0.01)
        params = {"w": jnp.asarray(p0)}
        state = opt.init(params)
        for g in grads:
            params, state = opt.apply({"w": jnp.asarray(g)}, state, params)
        want = self._numpy_novograd(p0, grads)
        np.testing.assert_allclose(np.asarray(params["w"]), want,
                                   atol=1e-5, rtol=1e-4)
        assert state.nu["w"].shape == ()          # per-TENSOR scalar state

    def test_no_bias_correction_no_averaging(self):
        from apex_example_tpu.optim import FusedNovoGrad
        p0 = _rand(20, seed=50)
        grads = [_rand(20, seed=51 + i) for i in range(2)]
        opt = FusedNovoGrad(lr=5e-3, weight_decay=0.0, grad_averaging=False,
                            bias_correction=False)
        params = {"w": jnp.asarray(p0)}
        state = opt.init(params)
        for g in grads:
            params, state = opt.apply({"w": jnp.asarray(g)}, state, params)
        want = self._numpy_novograd(p0, grads, lr=5e-3, wd=0.0,
                                    grad_averaging=False,
                                    bias_correction=False)
        np.testing.assert_allclose(np.asarray(params["w"]), want,
                                   atol=1e-5, rtol=1e-4)

    def test_kernel_matches_reference_path(self):
        # Pallas (interpret) vs the XLA reference branch of the leaf update.
        from apex_example_tpu.ops import _config
        p = _rand(300, seed=60); g = _rand(300, seed=61)
        m = _rand(300, seed=62) * 0.1
        kw = dict(inv_denom=0.37, lr_c1=0.02, beta1=0.95,
                  weight_decay=0.01, grad_avg_coeff=0.05)
        po_k, mo_k = ops.novograd_update_leaf(
            jnp.asarray(p), jnp.asarray(g), jnp.asarray(m), **kw)
        saved = _config.INTERPRET
        _config.INTERPRET = False     # on CPU this selects the XLA reference
        try:
            po_r, mo_r = ops.novograd_update_leaf(
                jnp.asarray(p), jnp.asarray(g), jnp.asarray(m), **kw)
        finally:
            _config.INTERPRET = saved
        np.testing.assert_allclose(np.asarray(po_k), np.asarray(po_r),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(mo_k), np.asarray(mo_r),
                                   atol=1e-6, rtol=1e-6)


class TestFusedAdagrad:
    """FusedAdagrad vs torch.optim.Adagrad (apex's fused_adagrad drops
    lr_decay; with lr_decay=0 the recurrences are identical)."""

    def test_adagrad_vs_torch(self):
        p = _rand(37, seed=40); g = _rand(37, seed=41)
        jp = jnp.asarray(p); jh = jnp.zeros(37)
        tp = torch.from_numpy(p.copy()).requires_grad_(True)
        topt = torch.optim.Adagrad([tp], lr=0.05, eps=1e-10,
                                   weight_decay=0.1)
        for _ in range(3):
            jp, jh = ops.adagrad_update_leaf(
                jp, jnp.asarray(g), jh, lr=0.05, eps=1e-10,
                weight_decay=0.1)
            tp.grad = torch.from_numpy(g.copy())
            topt.step()
        np.testing.assert_allclose(np.asarray(jp), tp.detach().numpy(),
                                   atol=1e-6, rtol=1e-5)

    def test_kernel_matches_reference(self):
        p = _rand(300, seed=42); g = _rand(300, seed=43)
        h = np.abs(_rand(300, seed=44))
        kw = dict(lr=0.01, eps=1e-10, weight_decay=0.01, adagrad_w_mode=True)
        kp, kh = ops.adagrad_update_leaf(
            jnp.asarray(p), jnp.asarray(g), jnp.asarray(h), **kw)
        rp, rh = ops.adagrad_update_leaf_reference(
            jnp.asarray(p), jnp.asarray(g), jnp.asarray(h), **kw)
        np.testing.assert_allclose(np.asarray(kp), np.asarray(rp),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(kh), np.asarray(rh),
                                   atol=1e-6, rtol=1e-6)

    def test_frontend_runs(self):
        from apex_example_tpu.optim import FusedAdagrad
        opt = FusedAdagrad(lr=0.1)
        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        grads = jax.tree.map(jnp.ones_like, params)
        state = opt.init(params)
        new_p, state = opt.apply(grads, state, params)
        assert int(state.step) == 1
        assert float(new_p["w"][0, 0]) < 1.0


class TestXentropy:
    """Fused softmax-CE (contrib xentropy analog) vs torch cross_entropy:
    values and gradients, with and without label smoothing."""

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_vs_torch(self, smoothing):
        rng = np.random.RandomState(50)
        logits = rng.randn(6, 17).astype(np.float32)
        labels = rng.randint(0, 17, (6,))
        jl = jnp.asarray(logits)
        jy = jnp.asarray(labels)

        loss = ops.softmax_cross_entropy(jl, jy, smoothing)
        tl = torch.from_numpy(logits.copy()).requires_grad_(True)
        tloss = torch.nn.functional.cross_entropy(
            tl, torch.from_numpy(labels), reduction="none",
            label_smoothing=smoothing)
        np.testing.assert_allclose(np.asarray(loss),
                                   tloss.detach().numpy(),
                                   atol=1e-5, rtol=1e-5)

        # Gradients of the mean loss.
        gj = jax.grad(lambda l: ops.softmax_cross_entropy(
            l, jy, smoothing).mean())(jl)
        tloss.mean().backward()
        np.testing.assert_allclose(np.asarray(gj), tl.grad.numpy(),
                                   atol=1e-6, rtol=1e-5)

    def test_matches_reference_and_optax(self):
        import optax
        rng = np.random.RandomState(51)
        logits = jnp.asarray(rng.randn(4, 9, 31).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, 31, (4, 9)))
        a = ops.softmax_cross_entropy(logits, labels)
        b = ops.softmax_cross_entropy_reference(logits, labels)
        c = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   atol=1e-5, rtol=1e-5)

    def test_no_probs_residual(self):
        """The op's point: the saved residuals exclude the (N, V) probability
        tensor — only logits (an input), labels, and the O(N) lse."""
        logits = jnp.ones((8, 128))
        labels = jnp.zeros((8,), jnp.int32)
        _, vjp = jax.vjp(
            lambda l: ops.softmax_cross_entropy(l, labels), logits)
        # Residual arrays reachable from the vjp closure: anything with
        # logits' (N, V) shape must BE logits itself (no extra V-sized
        # tensor saved).
        big = [x for x in jax.tree_util.tree_leaves(vjp)
               if hasattr(x, "shape") and x.shape == logits.shape]
        assert all(x is logits or (x == logits).all() for x in big)
