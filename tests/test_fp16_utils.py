"""Legacy fp16_utils facade (SURVEY.md:129): FP16_Optimizer master-weight
flow, overflow skip, network_to_half, param-list helpers."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_example_tpu import fp16_utils as fu
from apex_example_tpu.models import resnet18
from apex_example_tpu.optim import FusedSGD


def _half_params(key, shapes, dtype=jnp.bfloat16):
    ks = jax.random.split(key, len(shapes))
    return {f"p{i}": jax.random.normal(k, s, dtype)
            for i, (k, s) in enumerate(zip(ks, shapes))}


def test_fp16_optimizer_matches_fp32_sgd():
    """Master-weight SGD through the facade == plain fp32 SGD on the same
    data (up to the half-precision grad cast)."""
    key = jax.random.PRNGKey(0)
    params = _half_params(key, [(8, 4), (4,)])
    grads = jax.tree_util.tree_map(
        lambda p: jnp.ones_like(p) * 0.1, params)

    opt = fu.FP16_Optimizer(FusedSGD(lr=0.5, momentum=0.0),
                            static_loss_scale=1.0)
    state = opt.init(params)
    half, state = opt.step(grads, state)

    # reference: fp32 masters - lr * grad
    for k in params:
        want = (params[k].astype(jnp.float32)
                - 0.5 * grads[k].astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(state.masters[k]),
                                   np.asarray(want), rtol=1e-6)
        assert half[k].dtype == params[k].dtype


def test_fp16_optimizer_overflow_skips_step():
    key = jax.random.PRNGKey(1)
    params = _half_params(key, [(4, 4)])
    opt = fu.FP16_Optimizer(FusedSGD(lr=0.5), dynamic_loss_scale=True)
    state = opt.init(params)
    s0 = float(state.scaler.scale)

    bad = {"p0": jnp.full((4, 4), jnp.inf, jnp.bfloat16)}
    half, state = opt.step(bad, state)
    np.testing.assert_allclose(np.asarray(half["p0"], np.float32),
                               np.asarray(params["p0"], np.float32))
    assert float(state.scaler.scale) == s0 * state.scaler.backoff_factor

    good = {"p0": jnp.ones((4, 4), jnp.bfloat16)}
    masters_before = np.asarray(state.masters["p0"])
    _, state = opt.step(good, state)
    # grads unscale to 1/scale ~ 3e-5: visible on the fp32 masters even
    # though it is below bf16 resolution on the half params.
    assert not np.allclose(np.asarray(state.masters["p0"]), masters_before)


def test_scale_loss_and_state_dict_roundtrip():
    opt = fu.FP16_Optimizer(FusedSGD(lr=0.1), static_loss_scale=128.0)
    state = opt.init({"w": jnp.ones((2, 2), jnp.bfloat16)})
    assert float(opt.scale_loss(jnp.asarray(2.0), state)) == 256.0
    d = opt.state_dict(state)
    state2 = opt.load_state_dict(state, d)
    assert float(state2.scaler.scale) == 128.0


def test_network_to_half_model_and_tree():
    m = resnet18(num_classes=10)
    mh = fu.network_to_half(m)
    assert mh.dtype == jnp.bfloat16 and mh.bn_dtype == jnp.float32

    tree = {"a": jnp.ones((3,), jnp.float32), "n": jnp.arange(3)}
    th = fu.network_to_half(tree)
    assert th["a"].dtype == jnp.bfloat16 and th["n"].dtype == jnp.int32


def test_prep_and_sync_param_lists():
    params = _half_params(jax.random.PRNGKey(2), [(3, 3)])
    model_p, masters = fu.prep_param_lists(params)
    assert masters["p0"].dtype == jnp.float32
    back = fu.master_to_model(masters, model_p)
    assert back["p0"].dtype == jnp.bfloat16
    g = fu.grads_to_master({"p0": jnp.ones((3, 3), jnp.bfloat16)})
    assert g["p0"].dtype == jnp.float32
