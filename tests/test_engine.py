"""End-to-end train-step tests: the C1 slice (SURVEY.md §8 phase 2) plus
amp/DDP composition — loss decreases, skip-step fires, DDP equals big-batch
single-device training, checkpoint round-trips."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_example_tpu import amp
from apex_example_tpu.data import image_batch
from apex_example_tpu.engine import (
    TrainState, create_train_state, make_eval_step, make_sharded_train_step,
    make_train_step)
from apex_example_tpu.models import resnet18
from apex_example_tpu.optim import FusedSGD
from apex_example_tpu.parallel import make_data_mesh


def tiny_model(**kw):
    # ResNet-18 topology at tiny width/stem so CPU tests stay fast.
    from apex_example_tpu.models.resnet import BasicBlock, ResNet
    return ResNet(stage_sizes=[1, 1], block_cls=BasicBlock, num_classes=4,
                  num_filters=8, small_stem=True, **kw)


def tiny_batch(step=0, bs=16):
    x, y = image_batch(jnp.asarray(step), batch_size=bs, image_size=8,
                       channels=3, num_classes=4, seed=7, noise=0.3)
    return x, y


class TestC1SingleDevice:
    def test_loss_decreases_fp32(self):
        policy, scaler = amp.initialize("O0")
        model = tiny_model()
        opt = FusedSGD(lr=0.05, momentum=0.9)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   tiny_batch()[0], policy, scaler)
        step = jax.jit(make_train_step(model, opt, policy))
        first = last = None
        for i in range(12):
            state, metrics = step(state, tiny_batch(i))
            if i == 0:
                first = float(metrics["loss"])
            last = float(metrics["loss"])
        assert int(state.step) == 12
        assert last < first, (first, last)

    def test_o2_bf16_params_stay_fp32(self):
        policy, scaler = amp.initialize("O2")
        model = tiny_model(dtype=jnp.bfloat16, bn_dtype=jnp.float32)
        opt = FusedSGD(lr=0.05, momentum=0.9)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   tiny_batch()[0], policy, scaler)
        # fp32 master params (apex O2: master weights).
        for leaf in jax.tree_util.tree_leaves(state.params):
            assert leaf.dtype == jnp.float32
        step = jax.jit(make_train_step(model, opt, policy))
        losses = []
        for i in range(10):
            state, metrics = step(state, tiny_batch(i))
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    def test_eval_step(self):
        policy, scaler = amp.initialize("O0")
        model = tiny_model()
        opt = FusedSGD(lr=0.05)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   tiny_batch()[0], policy, scaler)
        ev = jax.jit(make_eval_step(model))
        m = ev(state, tiny_batch(99))
        assert np.isfinite(float(m["loss"]))
        assert 0.0 <= float(m["top1"]) <= 100.0


class TestDynamicScalingSkipStep:
    def test_inf_grad_skips_update_and_halves_scale(self):
        policy, scaler = amp.initialize("O2", loss_scale="dynamic",
                                        init_scale=2.0 ** 10)
        model = tiny_model(dtype=jnp.bfloat16)
        opt = FusedSGD(lr=1e10)   # absurd LR: any real update visibly moves

        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   tiny_batch()[0], policy, scaler)

        # Poison batch: inf input produces nonfinite grads.
        x, y = tiny_batch(0)
        x_bad = x.at[0, 0, 0, 0].set(jnp.inf)
        step = jax.jit(make_train_step(model, opt, policy))
        p_before = jax.tree_util.tree_leaves(state.params)[0].copy()
        state, metrics = step(state, (x_bad, y))
        assert float(metrics["grads_finite"]) == 0.0
        # step skipped: params unchanged
        p_after = jax.tree_util.tree_leaves(state.params)[0]
        np.testing.assert_array_equal(np.asarray(p_before),
                                      np.asarray(p_after))
        assert float(state.scaler.scale) == 2.0 ** 9

    def test_growth_after_interval(self):
        policy, _ = amp.initialize("O2", loss_scale="dynamic")
        scaler = amp.make_scaler(policy, init_scale=8.0, growth_interval=2)
        model = tiny_model(dtype=jnp.bfloat16)
        opt = FusedSGD(lr=0.01)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   tiny_batch()[0], policy, scaler)
        step = jax.jit(make_train_step(model, opt, policy))
        for i in range(2):
            state, _ = step(state, tiny_batch(i))
        assert float(state.scaler.scale) == 16.0


class TestDDPEightDevices:
    def test_ddp_matches_single_device_bigbatch(self, devices8):
        """DDP over 8 shards × B/8 == single device × B (SyncBN on):
        identical params after each step (the DDP contract)."""
        policy, scaler = amp.initialize("O0")
        mesh = make_data_mesh(devices=devices8)
        model_sync = tiny_model(bn_axis_name="data")
        model_local = tiny_model()
        opt = FusedSGD(lr=0.05, momentum=0.9)

        state = create_train_state(jax.random.PRNGKey(0), model_local, opt,
                                   tiny_batch()[0], policy, scaler)
        state2 = jax.tree_util.tree_map(lambda x: x.copy(), state)

        sharded = make_sharded_train_step(mesh, model_sync, opt, policy,
                                          donate=False)
        single = jax.jit(make_train_step(model_local, opt, policy))

        for i in range(3):
            batch = tiny_batch(i, bs=16)
            state, m_ddp = sharded(state, batch)
            state2, m_one = single(state2, batch)

        np.testing.assert_allclose(float(m_ddp["loss"]),
                                   float(m_one["loss"]), rtol=1e-4)
        for a, b in zip(jax.tree_util.tree_leaves(state.params),
                        jax.tree_util.tree_leaves(state2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=1e-4)
        for a, b in zip(jax.tree_util.tree_leaves(state.batch_stats),
                        jax.tree_util.tree_leaves(state2.batch_stats)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4)

    def test_ddp_o2_runs(self, devices8):
        policy, scaler = amp.initialize("O2")
        mesh = make_data_mesh(devices=devices8)
        model = tiny_model(dtype=jnp.bfloat16, bn_axis_name="data")
        opt = FusedSGD(lr=0.05, momentum=0.9)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   tiny_batch()[0], policy, scaler)
        sharded = make_sharded_train_step(mesh, model, opt, policy,
                                          donate=False)
        losses = []
        for i in range(6):
            state, m = sharded(state, tiny_batch(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]


class TestCheckpoint:
    def test_roundtrip_including_scaler(self, tmp_path):
        from apex_example_tpu.utils.checkpoint import CheckpointManager
        policy, scaler = amp.initialize("O2", loss_scale="dynamic",
                                        init_scale=512.0)
        model = tiny_model(dtype=jnp.bfloat16)
        opt = FusedSGD(lr=0.05, momentum=0.9)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   tiny_batch()[0], policy, scaler)
        step = jax.jit(make_train_step(model, opt, policy))
        for i in range(3):
            state, _ = step(state, tiny_batch(i))

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(state)

        template = create_train_state(jax.random.PRNGKey(1), model, opt,
                                      tiny_batch()[0], policy,
                                      amp.make_scaler(policy))
        restored = mgr.restore(template)
        assert int(restored.step) == 3
        # scaler state survives resume (apex test_checkpointing behavior)
        assert float(restored.scaler.scale) == float(state.scaler.scale)
        for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                        jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        mgr.close()


class TestGradAccum:
    """--grad-accum: K microbatches accumulate into one optimizer step
    (reference DDP grad-accumulation semantics, SURVEY.md §3.2)."""

    def _bert_step(self, grad_accum):
        from apex_example_tpu.models.bert import bert_tiny
        from apex_example_tpu.workloads import mlm_loss
        policy, scaler = amp.initialize("O0")
        model = bert_tiny()
        opt = FusedSGD(lr=0.1, momentum=0.9)
        ids = jnp.asarray(
            np.random.RandomState(3).randint(0, 256, (8, 16)), jnp.int32)
        labels = ids
        w = jnp.ones(ids.shape, jnp.float32)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   ids[:1], policy, scaler, train_kwargs={})
        step = jax.jit(make_train_step(model, opt, policy, loss_fn=mlm_loss,
                                       compute_accuracy=False,
                                       grad_accum=grad_accum))
        return step(state, (ids, (labels, w)))

    def test_accum_matches_full_batch(self):
        """BERT has no batch-dependent state, so K-microbatch accumulation
        must reproduce the full-batch step exactly (grads are averaged)."""
        s1, m1 = self._bert_step(1)
        s4, m4 = self._bert_step(4)
        np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]),
                                   rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
            s1.params, s4.params)

    def test_resnet_accum_runs_and_learns(self):
        """BN models: stats thread through microbatches (per-forward
        update, apex semantics); loss falls over a few accum steps."""
        policy, scaler = amp.initialize("O0")
        model = resnet18(num_classes=4, small_stem=True, num_filters=8)
        opt = FusedSGD(lr=0.05, momentum=0.9)
        x, y = image_batch(jnp.asarray(0), batch_size=16, image_size=16,
                           channels=3, num_classes=4, seed=0)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   x[:1], policy, scaler)
        step = jax.jit(make_train_step(model, opt, policy, grad_accum=4))
        first = None
        for i in range(6):
            x, y = image_batch(jnp.asarray(i), batch_size=16, image_size=16,
                               channels=3, num_classes=4, seed=0)
            state, metrics = step(state, (x, y))
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first
        assert int(state.step) == 6


class TestShardedGradAccum:
    """Regression: the scan carry inside a shard_map'd grad-accum step must
    take its per-leaf shard-variance types from a real microbatch (the
    prologue in make_train_step) — a zeros init is mesh-invariant and
    rejected by shard_map's vma check, and blanket-casting the carry
    varying instead erases the invariant typing of implicitly-psummed
    grads that allreduce_grads keys on, which produced 8x-scaled gradients
    on this exact config.  Do NOT 'fix' a vma mismatch here with pcast."""

    def test_ddp_accum_matches_no_accum(self, devices8):
        """BERT (no batch-dependent state): K-microbatch accumulation under
        shard_map reproduces the plain sharded step.  (BN models legitimately
        differ — per-forward stats see the microbatch, apex semantics — so
        the exactness check uses a stateless model; the BN path is covered
        by test_resnet_accum_runs_and_learns and the smoke below.)"""
        from apex_example_tpu.models.bert import bert_tiny
        from apex_example_tpu.workloads import mlm_loss
        policy, scaler = amp.initialize("O0")
        mesh = make_data_mesh(devices=devices8)
        model = bert_tiny()
        opt = FusedSGD(lr=0.05, momentum=0.0)
        ids = jnp.asarray(
            np.random.RandomState(5).randint(0, 256, (16, 16)), jnp.int32)
        batch = (ids, (ids, jnp.ones(ids.shape, jnp.float32)))
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   ids[:1], policy, scaler, train_kwargs={})
        state2 = jax.tree_util.tree_map(lambda x: x.copy(), state)

        mk = lambda k: make_sharded_train_step(
            mesh, model, opt, policy, loss_fn=mlm_loss,
            compute_accuracy=False, donate=False, grad_accum=k)
        state, m1 = mk(1)(state, batch)
        state2, m2 = mk(2)(state2, batch)  # 2 per shard → 2 microbatches
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(state.params),
                        jax.tree_util.tree_leaves(state2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=1e-4)

    def test_ddp_bn_accum_smoke(self, devices8):
        policy, scaler = amp.initialize("O0")
        mesh = make_data_mesh(devices=devices8)
        model = tiny_model(bn_axis_name="data")
        opt = FusedSGD(lr=0.05, momentum=0.0)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   tiny_batch()[0], policy, scaler)
        step = make_sharded_train_step(mesh, model, opt, policy,
                                       donate=False, grad_accum=2)
        state, m = step(state, tiny_batch(0, bs=16))
        assert np.isfinite(float(m["loss"]))

    def test_txl_ddp_accum_runs(self, devices8):
        from apex_example_tpu.data import lm_batch
        from apex_example_tpu.models.transformer_xl import transformer_xl_tiny
        from apex_example_tpu.workloads import make_sharded_txl_train_step
        policy, scaler = amp.initialize("O0")
        mesh = make_data_mesh(devices=devices8)
        model = transformer_xl_tiny()
        opt = FusedSGD(lr=0.05, momentum=0.0)
        toks = lm_batch(jnp.asarray(0), batch_size=16, seq_len=9,
                        vocab_size=256, seed=3)
        state = create_train_state(jax.random.PRNGKey(0), model, opt,
                                   toks[:1, :8], policy, scaler,
                                   train_kwargs={})
        mems = model.init_mems(16)
        step = make_sharded_txl_train_step(mesh, model, opt, policy,
                                           donate=False, grad_accum=2)
        state, mems, m = step(state, mems, (toks[:, :8], toks[:, 1:9]))
        assert np.isfinite(float(m["loss"]))
