// graftlint HLO fixture (ISSUE 13): the SEEDED f32 dequant pin.
// Identical program to int8_clean.mlir except the second weight's
// dequant: the i8 kernel is converted UP to f32, the relu output
// follows it, and the dot_general runs wide — the exact signature of
// a dequant placed outside the scale-fused path (or an f32 scale
// joining the matmul uncast).  The HBM bytes the int8 storage saved
// are spent right back on the widened matmul operands.  The
// claimed-int8 upcast-leak mode (--policy int8) must FIRE on the f32
// dot_general, and diff_lowerings(clean, leak) must name it.
module @jit_qmlp attributes {mhlo.num_partitions = 1 : i32, mhlo.num_replicas = 1 : i32} {
  func.func public @main(%arg0: tensor<16x32xi8>, %arg1: tensor<1x32xf32>, %arg2: tensor<32x8xi8>, %arg3: tensor<1x8xf32>, %arg4: tensor<8x16xbf16>) -> (tensor<8x8xf32> {jax.result_info = ""}) {
    %0 = stablehlo.convert %arg0 : (tensor<16x32xi8>) -> tensor<16x32xbf16>
    %1 = stablehlo.convert %arg1 : (tensor<1x32xf32>) -> tensor<1x32xbf16>
    %2 = stablehlo.broadcast_in_dim %1, dims = [0, 1] : (tensor<1x32xbf16>) -> tensor<16x32xbf16>
    %3 = stablehlo.multiply %0, %2 : tensor<16x32xbf16>
    %4 = stablehlo.dot_general %arg4, %3, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<8x16xbf16>, tensor<16x32xbf16>) -> tensor<8x32xbf16>
    %cst = stablehlo.constant dense<0.000000e+00> : tensor<bf16>
    %5 = stablehlo.broadcast_in_dim %cst, dims = [] : (tensor<bf16>) -> tensor<8x32xbf16>
    %6 = stablehlo.maximum %4, %5 : tensor<8x32xbf16>
    %7 = stablehlo.convert %arg2 : (tensor<32x8xi8>) -> tensor<32x8xf32>
    %8 = stablehlo.broadcast_in_dim %arg3, dims = [0, 1] : (tensor<1x8xf32>) -> tensor<32x8xf32>
    %9 = stablehlo.multiply %7, %8 : tensor<32x8xf32>
    %10 = stablehlo.convert %6 : (tensor<8x32xbf16>) -> tensor<8x32xf32>
    %11 = stablehlo.dot_general %10, %9, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<8x32xf32>, tensor<32x8xf32>) -> tensor<8x8xf32>
    return %11 : tensor<8x8xf32>
  }
}
