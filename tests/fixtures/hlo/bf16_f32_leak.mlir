// graftlint HLO fixture (ISSUE 9): the SEEDED f32 leak.
// Identical program to bf16_clean.mlir except the second matmul: the
// relu output is converted UP to f32 and the dot_general runs wide —
// the exact signature of an AMP policy miss (an op class left out of
// the cast tables, or an fp32 residual joining the MXU path).  The
// upcast-leak rule must FIRE on the f32 dot_general, and
// diff_lowerings(clean, leak) must name it (first divergent op).
module @jit_mlp attributes {mhlo.num_partitions = 1 : i32, mhlo.num_replicas = 1 : i32} {
  func.func public @main(%arg0: tensor<16x32xf32>, %arg1: tensor<32x8xf32>, %arg2: tensor<8x16xbf16>) -> (tensor<8x8xf32> {jax.result_info = ""}) {
    %0 = stablehlo.convert %arg0 : (tensor<16x32xf32>) -> tensor<16x32xbf16>
    %1 = stablehlo.dot_general %arg2, %0, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<8x16xbf16>, tensor<16x32xbf16>) -> tensor<8x32xbf16>
    %cst = stablehlo.constant dense<0.000000e+00> : tensor<bf16>
    %2 = stablehlo.broadcast_in_dim %cst, dims = [] : (tensor<bf16>) -> tensor<8x32xbf16>
    %3 = stablehlo.maximum %1, %2 : tensor<8x32xbf16>
    %4 = stablehlo.convert %3 : (tensor<8x32xbf16>) -> tensor<8x32xf32>
    %5 = stablehlo.dot_general %4, %arg1, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<8x32xf32>, tensor<32x8xf32>) -> tensor<8x8xf32>
    return %5 : tensor<8x8xf32>
  }
}
