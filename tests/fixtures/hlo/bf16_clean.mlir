// graftlint HLO fixture (ISSUE 9): a bf16-clean two-matmul forward.
// Recorded shape: jax.jit(mlp).lower(...) .as_text() for a toy
// [8,16] @ [16,32] @ [32,8] MLP under an AMP-O2 (bf16 compute)
// policy — params arrive f32 (master weights) and are converted DOWN
// to bf16 before every dot; only the loss-side convert goes back up.
// The upcast-leak rule must stay QUIET here; bf16_f32_leak.mlir is the
// same program with the second matmul leaked to f32, and the
// recompile-cause diff between the two names that dot_general.
module @jit_mlp attributes {mhlo.num_partitions = 1 : i32, mhlo.num_replicas = 1 : i32} {
  func.func public @main(%arg0: tensor<16x32xf32>, %arg1: tensor<32x8xf32>, %arg2: tensor<8x16xbf16>) -> (tensor<8x8xf32> {jax.result_info = ""}) {
    %0 = stablehlo.convert %arg0 : (tensor<16x32xf32>) -> tensor<16x32xbf16>
    %1 = stablehlo.dot_general %arg2, %0, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<8x16xbf16>, tensor<16x32xbf16>) -> tensor<8x32xbf16>
    %cst = stablehlo.constant dense<0.000000e+00> : tensor<bf16>
    %2 = stablehlo.broadcast_in_dim %cst, dims = [] : (tensor<bf16>) -> tensor<8x32xbf16>
    %3 = stablehlo.maximum %1, %2 : tensor<8x32xbf16>
    %4 = stablehlo.convert %arg1 : (tensor<32x8xf32>) -> tensor<32x8xbf16>
    %5 = stablehlo.dot_general %3, %4, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<8x32xbf16>, tensor<32x8xbf16>) -> tensor<8x8xbf16>
    %6 = stablehlo.convert %5 : (tensor<8x8xbf16>) -> tensor<8x8xf32>
    return %6 : tensor<8x8xf32>
  }
}
