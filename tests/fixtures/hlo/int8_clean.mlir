// graftlint HLO fixture (ISSUE 13): the int8-clean quantized forward.
// Recorded shape: the serve decode step's weight path under a
// --weight-quant int8 policy — kernels arrive as i8 {qvalue} plus a
// per-output-channel f32 scale, are dequantized DOWN onto the bf16
// compute grid (convert i8 -> bf16, multiply by the bf16-cast scale),
// and every dot_general runs bf16.  The claimed-int8 upcast-leak mode
// (--policy int8) must stay QUIET here: i8 tensors present, no wide
// heavy op.  int8_f32_leak.mlir is the same program with the second
// dequant converted UP to f32 — the silent whole-matmul pin the rule
// exists to catch.
module @jit_qmlp attributes {mhlo.num_partitions = 1 : i32, mhlo.num_replicas = 1 : i32} {
  func.func public @main(%arg0: tensor<16x32xi8>, %arg1: tensor<1x32xf32>, %arg2: tensor<32x8xi8>, %arg3: tensor<1x8xf32>, %arg4: tensor<8x16xbf16>) -> (tensor<8x8xf32> {jax.result_info = ""}) {
    %0 = stablehlo.convert %arg0 : (tensor<16x32xi8>) -> tensor<16x32xbf16>
    %1 = stablehlo.convert %arg1 : (tensor<1x32xf32>) -> tensor<1x32xbf16>
    %2 = stablehlo.broadcast_in_dim %1, dims = [0, 1] : (tensor<1x32xbf16>) -> tensor<16x32xbf16>
    %3 = stablehlo.multiply %0, %2 : tensor<16x32xbf16>
    %4 = stablehlo.dot_general %arg4, %3, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<8x16xbf16>, tensor<16x32xbf16>) -> tensor<8x32xbf16>
    %cst = stablehlo.constant dense<0.000000e+00> : tensor<bf16>
    %5 = stablehlo.broadcast_in_dim %cst, dims = [] : (tensor<bf16>) -> tensor<8x32xbf16>
    %6 = stablehlo.maximum %4, %5 : tensor<8x32xbf16>
    %7 = stablehlo.convert %arg2 : (tensor<32x8xi8>) -> tensor<32x8xbf16>
    %8 = stablehlo.convert %arg3 : (tensor<1x8xf32>) -> tensor<1x8xbf16>
    %9 = stablehlo.broadcast_in_dim %8, dims = [0, 1] : (tensor<1x8xbf16>) -> tensor<32x8xbf16>
    %10 = stablehlo.multiply %7, %9 : tensor<32x8xbf16>
    %11 = stablehlo.dot_general %6, %10, contracting_dims = [1] x [0], precision = [DEFAULT, DEFAULT] : (tensor<8x32xbf16>, tensor<32x8xbf16>) -> tensor<8x8xbf16>
    %12 = stablehlo.convert %11 : (tensor<8x8xbf16>) -> tensor<8x8xf32>
    return %12 : tensor<8x8xf32>
  }
}
