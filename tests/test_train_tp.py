"""train.py-level tensor parallelism (SURVEY.md §3.2 rebuild stance,
VERDICT r2 item 4): the GSPMD TP path must (a) be numerically identical to
the dense single-device model given the same params, (b) train end-to-end
through the CLI on a (data, model) CPU mesh.

The param trees of the TP and dense BERT variants are structurally identical
(same names/shapes — column/row/vocab layers only attach partitioning
metadata), which is what lets (a) literally feed one's params to the other.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import amp
from apex_example_tpu.data import mlm_batch
from apex_example_tpu.engine import (create_gspmd_train_state,
                                     create_train_state,
                                     make_gspmd_train_step, make_train_step)
from apex_example_tpu.models.bert import bert_tiny
from apex_example_tpu.optim import FusedAdam
from apex_example_tpu.transformer import parallel_state
from apex_example_tpu.workloads import mlm_loss

TP, SEQ, BATCH = 4, 16, 8


def _batch(i, vocab):
    ids, labels, w = mlm_batch(jnp.asarray(i, jnp.int32), batch_size=BATCH,
                               seq_len=SEQ, vocab_size=vocab,
                               mask_token_id=vocab - 1, seed=0)
    return ids, (labels, w)


@pytest.fixture()
def tp_mesh(devices8):
    mesh = parallel_state.initialize_model_parallel(tensor_parallel=TP,
                                                    devices=devices8)
    yield mesh
    parallel_state.set_mesh(None)


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_tp_train_matches_dense(tp_mesh, sequence_parallel):
    """3 train steps on the (data=2, model=4) mesh == 3 single-device dense
    steps, fed the same initial params and batches."""
    from apex_example_tpu.optim import FusedSGD
    policy, scaler = amp.initialize("O0")
    dense = bert_tiny()
    tp_model = bert_tiny(tensor_parallel=True,
                         sequence_parallel=sequence_parallel)
    V = dense.vocab_size
    # SGD, not Adam: Adam's near-zero-grad updates behave like sign(g)·lr,
    # so fp32 reduction-order noise flips individual elements by ±lr (the
    # ZeRO suite documents the same) — SGD keeps the update linear in g and
    # the end states comparable elementwise.
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)

    sample = _batch(0, V)[0][:1]
    state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_d = jax.jit(make_train_step(dense, opt(), policy, loss_fn=mlm_loss,
                                     compute_accuracy=False))

    state_t, shardings = create_gspmd_train_state(
        jax.random.PRNGKey(0), tp_mesh, tp_model, opt(), sample, policy,
        scaler)
    # Same starting point: the dense params ARE a valid TP state (identical
    # tree); placed onto the mesh per the TP shardings.
    state_t = state_t.replace(
        params=jax.device_put(state_d.params, shardings.params))
    step_t = make_gspmd_train_step(tp_mesh, tp_model, opt(), policy,
                                   shardings, loss_fn=mlm_loss,
                                   compute_accuracy=False, donate=False)

    for i in range(3):
        b = _batch(i, V)
        state_d, m_d = step_d(state_d, b)
        state_t, m_t = step_t(state_t, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_t["loss"]),
                                   rtol=2e-5)

    # End state agrees too (reduction-order noise only).
    for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                    jax.tree_util.tree_leaves(state_t.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_tp_params_actually_shard(tp_mesh):
    policy, scaler = amp.initialize("O0")
    model = bert_tiny(tensor_parallel=True)
    sample = _batch(0, model.vocab_size)[0][:1]
    state, _ = create_gspmd_train_state(
        jax.random.PRNGKey(0), tp_mesh, model, FusedAdam(lr=1e-3), sample,
        policy, scaler)
    emb = state.params["word_embeddings"]["embedding"]
    k1 = state.params["layer_0"]["intermediate"]["kernel"]
    # vocab rows / FFN output features sharded TP-ways
    assert emb.addressable_shards[0].data.shape[0] == emb.shape[0] // TP
    assert k1.addressable_shards[0].data.shape[1] == k1.shape[1] // TP
    # optimizer state shards along with its param
    mu1 = state.opt_state.mu["layer_0"]["intermediate"]["kernel"]
    assert mu1.addressable_shards[0].data.shape[1] == k1.shape[1] // TP


def test_train_py_cli_tensor_parallel(devices8):
    """The VERDICT contract: ``train.py --arch bert_* --tensor-parallel N``
    trains on the CPU mesh (CLI path end to end)."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    argv = ["--arch", "bert_tiny", "--tensor-parallel", "2",
            "--batch-size", str(BATCH), "--seq-len", str(SEQ),
            "--epochs", "1", "--steps-per-epoch", "3", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_txl_tp_train_matches_dense(tp_mesh):
    """Transformer-XL under GSPMD TP: 3 recurrent steps (mems carried) match
    the dense single-device trajectory given the same params."""
    from apex_example_tpu.data import lm_batch
    from apex_example_tpu.models.transformer_xl import transformer_xl_tiny
    from apex_example_tpu.optim import FusedSGD
    from apex_example_tpu.workloads import (make_gspmd_txl_train_step,
                                            make_txl_train_step)
    policy, scaler = amp.initialize("O0")
    dense = transformer_xl_tiny()
    tp_model = transformer_xl_tiny(tensor_parallel=True)
    V = dense.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)

    def batch(i):
        toks = lm_batch(jnp.asarray(i, jnp.int32), batch_size=BATCH,
                        seq_len=SEQ, vocab_size=V, seed=0)
        return toks[:, :-1], toks[:, 1:]

    sample = batch(0)[0][:1]
    state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_d = jax.jit(make_txl_train_step(dense, opt(), policy))
    mems_d = dense.init_mems(BATCH)

    state_t, shardings = create_gspmd_train_state(
        jax.random.PRNGKey(0), tp_mesh, tp_model, opt(), sample, policy,
        scaler)
    state_t = state_t.replace(
        params=jax.device_put(state_d.params, shardings.params))
    step_t = make_gspmd_txl_train_step(tp_mesh, tp_model, opt(), policy,
                                       shardings, donate=False)
    mems_t = tp_model.init_mems(BATCH)

    for i in range(3):
        b = batch(i)
        state_d, mems_d, m_d = step_d(state_d, mems_d, b)
        state_t, mems_t, m_t = step_t(state_t, mems_t, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_t["loss"]),
                                   rtol=3e-5)
    np.testing.assert_allclose(np.asarray(mems_d), np.asarray(mems_t),
                               rtol=1e-4, atol=1e-5)


def test_train_py_cli_txl_tensor_parallel(devices8):
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    argv = ["--arch", "transformer_xl_tiny", "--tensor-parallel", "2",
            "--batch-size", str(BATCH), "--seq-len", str(SEQ),
            "--epochs", "1", "--steps-per-epoch", "3", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_train_py_tp_rejections():
    import train as train_mod
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "resnet18", "--tensor-parallel", "2"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "transformer_xl_tiny",
                        "--tensor-parallel", "2", "--sequence-parallel"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "bert_tiny", "--tensor-parallel", "2",
                        "--fused-attention"])


def test_train_py_cli_tp_with_grad_accum(devices8):
    """--grad-accum composes with --tensor-parallel under GSPMD (plain-jit
    microbatching; no shard_map carry constraints)."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    argv = ["--arch", "bert_tiny", "--tensor-parallel", "2",
            "--grad-accum", "2", "--batch-size", str(BATCH),
            "--seq-len", str(SEQ), "--epochs", "1", "--steps-per-epoch",
            "2", "--opt", "adam", "--opt-level", "O0", "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_tp_fp16_dynamic_scaling_skips_globally(tp_mesh):
    """fp16 dynamic scaling under GSPMD TP: the program is one logical jit,
    so the finite flag and skip decision are global by construction — a
    poisoned batch rolls the whole (TP-sharded) state back and halves the
    scale, and a clean step then trains."""
    policy, scaler = amp.initialize("O2", loss_scale="dynamic",
                                    half_dtype=jnp.float16,
                                    init_scale=2.0 ** 4)
    model = bert_tiny(tensor_parallel=True, dtype=jnp.float16)
    V = model.vocab_size
    opt = FusedAdam(lr=1e-3)
    sample = _batch(0, V)[0][:1]
    state, shardings = create_gspmd_train_state(
        jax.random.PRNGKey(0), tp_mesh, model, opt, sample, policy, scaler)
    step = make_gspmd_train_step(tp_mesh, model, opt, policy, shardings,
                                 loss_fn=mlm_loss, compute_accuracy=False,
                                 donate=False)

    ids, (labels, w) = _batch(0, V)
    # Poison the loss via a weight spike: inf weight -> nonfinite loss/grads
    w_bad = w.at[0, 0].set(jnp.inf)
    p_before = jax.tree_util.tree_map(lambda p: np.asarray(p), state.params)
    o_before = jax.tree_util.tree_map(lambda p: np.asarray(p),
                                      state.opt_state)
    state, m = step(state, (ids, (labels, w_bad)))
    assert float(m["grads_finite"]) == 0.0
    assert float(state.scaler.scale) == 2.0 ** 3
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # The skip must also roll back the optimizer state — a missed rollback
    # leaves nan in mu/nu that the next step's grads cannot reveal.
    for a, b in zip(jax.tree_util.tree_leaves(o_before),
                    jax.tree_util.tree_leaves(state.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    state, m = step(state, (ids, (labels, w)))
    assert float(m["grads_finite"]) == 1.0
    moved = False
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(state.params)):
        assert np.isfinite(np.asarray(b)).all()
        moved = moved or not np.array_equal(np.asarray(a), np.asarray(b))
    assert moved
