"""Cross-product short-convergence matrix (SURVEY.md §5 test plan:
{O0, O2} × {1, 8 devices} must converge into a common loss band).

Round-1 covered the individual cells; this is the explicit matrix: same
model/init/data/LR across all four cells, loss must fall in every cell, and
the final losses must agree across opt levels and device counts (bf16-O2's
loss curve tracks fp32 on the synthetic set, sharded == single-device).
"""

import jax
import jax.numpy as jnp
import pytest

from apex_example_tpu import amp
from apex_example_tpu.data import image_batch
from apex_example_tpu.engine import (create_train_state, make_train_step,
                                     make_sharded_train_step)
from apex_example_tpu.models.resnet import BasicBlock, ResNet
from apex_example_tpu.optim import FusedSGD
from apex_example_tpu.parallel.mesh import make_data_mesh

STEPS = 40
BATCH = 32


def _run_cell(opt_level: str, n_dev: int, devices8):
    policy, scaler = amp.initialize(opt_level)
    md = amp.module_dtypes(policy)
    # tiny ResNet (the dryrun's): the matrix premise — every (opt level,
    # device count) cell trains — doesn't need ResNet-18's compile cost.
    model = ResNet(stage_sizes=[1, 1], block_cls=BasicBlock, num_filters=16,
                   small_stem=True, num_classes=10, dtype=md.compute,
                   param_dtype=md.param, bn_dtype=md.bn_stats,
                   bn_io_dtype=md.bn_io,
                   bn_axis_name="data" if n_dev > 1 else None)
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
    state = create_train_state(jax.random.PRNGKey(0), model, opt, sample,
                               policy, scaler)
    if n_dev > 1:
        mesh = make_data_mesh(devices=devices8[:n_dev])
        step = make_sharded_train_step(mesh, model, opt, policy)
    else:
        step = jax.jit(make_train_step(model, opt, policy),
                       donate_argnums=(0,))

    first = None
    for i in range(STEPS):
        batch = image_batch(jnp.asarray(i, jnp.int32), batch_size=BATCH,
                            image_size=32, channels=3, num_classes=10,
                            seed=0)
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    return first, float(metrics["loss"])


def test_convergence_matrix(devices8):
    finals = {}
    for opt_level in ("O0", "O2"):
        for n_dev in (1, 8):
            first, final = _run_cell(opt_level, n_dev, devices8)
            # every cell must actually learn
            assert final < 0.6 * first, (opt_level, n_dev, first, final)
            finals[(opt_level, n_dev)] = final

    # Every cell must land deep below the 10-class chance level (ln 10 ≈
    # 2.30).  The cells saturate at different RATES on the easy synthetic
    # task (plain-BN vs SyncBN trajectories legitimately diverge once loss
    # approaches zero — measured finals span 5e-4..0.7 at 24 steps), so the
    # matrix asserts convergence per cell rather than a tight common band;
    # exact cross-device equivalence is covered by the DDP==big-batch and
    # SyncBN invariance tests (tests/test_engine.py, test_parallel.py).
    assert all(v < 1.0 for v in finals.values()), finals
