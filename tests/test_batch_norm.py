"""Fused custom-VJP BatchNorm kernels (ops/batch_norm.py) vs the XLA
composite path — value, gradient, and running-stat equivalence, single-device
and cross-replica (SURVEY.md §5 syncbn test strategy)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_example_tpu.ops.batch_norm import _pick_block
from apex_example_tpu.parallel.mesh import make_data_mesh
from apex_example_tpu.parallel.sync_batchnorm import SyncBatchNorm

try:
    from jax import shard_map as shard_map_fn
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as shard_map_fn
from jax.sharding import PartitionSpec as P


def _run(fused, x, key, axis_name=None):
    bn = SyncBatchNorm(use_running_average=False, axis_name=axis_name,
                       stats_dtype=jnp.float32, fused_kernel=fused)
    variables = bn.init(key, x)

    def loss_fn(params, stats, x):
        y, mut = bn.apply({"params": params, "batch_stats": stats}, x,
                          mutable=["batch_stats"])
        return jnp.sum(y.astype(jnp.float32) ** 2), (y, mut["batch_stats"])

    (val, (y, new_stats)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(variables["params"], variables["batch_stats"],
                               x)
    dx = jax.grad(lambda x: loss_fn(variables["params"],
                                    variables["batch_stats"], x)[0])(x)
    return y, new_stats, grads, dx


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_kernel_matches_xla(dtype):
    key = jax.random.PRNGKey(0)
    x = (jax.random.normal(key, (16, 8, 8, 64)) * 2.0 + 1.5).astype(dtype)
    y0, st0, g0, dx0 = _run(False, x, key)
    y1, st1, g1, dx1 = _run(True, x, key)

    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(y0, np.float32),
                               np.asarray(y1, np.float32), atol=tol, rtol=tol)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=tol, rtol=tol), st0, st1)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=5e-2, rtol=5e-2), g0, g1)
    np.testing.assert_allclose(np.asarray(dx0, np.float32),
                               np.asarray(dx1, np.float32),
                               atol=tol * 10, rtol=tol * 10)


def test_fused_kernel_sync_matches_full_batch(devices8):
    """N-shard fused-kernel SyncBN == full-batch XLA BN (values + dx)."""
    mesh = make_data_mesh(devices=devices8)
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (32, 4, 4, 16), jnp.float32) * 3.0 - 0.7

    y_full, _, _, dx_full = _run(False, x, key)

    bn = SyncBatchNorm(use_running_average=False, axis_name="data",
                       stats_dtype=jnp.float32, fused_kernel=True)
    variables = bn.init(key, x[:4])

    def shard_fn(params, stats, xs):
        def loss_fn(xs):
            y, _ = bn.apply({"params": params, "batch_stats": stats}, xs,
                            mutable=["batch_stats"])
            # global sum so the cotangent matches the full-batch loss
            return jax.lax.psum(jnp.sum(y.astype(jnp.float32) ** 2), "data")
        dx = jax.grad(loss_fn)(xs)
        y, mut = bn.apply({"params": params, "batch_stats": stats}, xs,
                          mutable=["batch_stats"])
        return y, dx, mut["batch_stats"]

    sharded = shard_map_fn(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(), P("data")),
        out_specs=(P("data"), P("data"), P()))
    y_sh, dx_sh, stats_sh = jax.jit(sharded)(
        variables["params"], variables["batch_stats"], x)

    np.testing.assert_allclose(np.asarray(y_full, np.float32),
                               np.asarray(y_sh, np.float32),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dx_full, np.float32),
                               np.asarray(dx_sh, np.float32),
                               atol=1e-3, rtol=1e-3)


def test_pick_block_divides():
    for rows in (802816, 200704, 50176, 12544, 256 * 32 * 32, 8, 16):
        for C in (64, 256, 1024, 2048):
            blk = _pick_block(rows, C)
            assert blk is not None and rows % blk == 0 and blk % 8 == 0
            assert blk * C <= (1 << 19) or blk == 8
    assert _pick_block(12, 64) is None   # not a multiple of 8
