"""Multi-host launch wiring (SURVEY.md §3.3/§4.1: the L6 layer).

The env-contract parser is unit-tested directly; the actual
``jax.distributed.initialize`` path is exercised by a REAL two-process CPU
rendezvous (subprocesses, TCP coordinator on localhost) — the same
"test the real collective path, not a mock" strategy the 8-device rig uses.
"""

import os
import socket
import subprocess
import sys

import pytest

from apex_example_tpu.parallel.launch import _parse_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestParseEnv:
    def test_no_env_is_single_process(self):
        assert _parse_env({}) is None

    def test_jax_native_address_only(self):
        kw = _parse_env({"JAX_COORDINATOR_ADDRESS": "10.0.0.1:1234"})
        assert kw == {"coordinator_address": "10.0.0.1:1234"}

    def test_jax_native_full(self):
        kw = _parse_env({"JAX_COORDINATOR_ADDRESS": "h:1",
                         "JAX_NUM_PROCESSES": "4",
                         "JAX_PROCESS_ID": "2"})
        assert kw == {"coordinator_address": "h:1", "num_processes": 4,
                      "process_id": 2}

    def test_torch_names_carry_over(self):
        kw = _parse_env({"MASTER_ADDR": "host0", "MASTER_PORT": "29500",
                         "WORLD_SIZE": "2", "RANK": "1"})
        assert kw == {"coordinator_address": "host0:29500",
                      "num_processes": 2, "process_id": 1}

    def test_torch_world_size_one_collapses(self):
        assert _parse_env({"MASTER_ADDR": "h", "WORLD_SIZE": "1",
                           "RANK": "0"}) is None

    def test_torch_default_port(self):
        kw = _parse_env({"MASTER_ADDR": "h", "WORLD_SIZE": "2", "RANK": "0"})
        assert kw["coordinator_address"].endswith(":12355")


_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
from apex_example_tpu.parallel import (is_main_process,
                                       maybe_initialize_distributed)
pid, n = maybe_initialize_distributed()
assert n == 2, n
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
# one global psum across the two processes' devices: the real multi-host
# collective path (global devices > local devices).
devs = jax.devices()
assert len(devs) == 2 and len(jax.local_devices()) == 1
mesh = Mesh(devs, ("data",))
x = jax.make_array_from_callback(
    (2,), NamedSharding(mesh, P("data")),
    lambda idx: jnp.asarray([float(pid + 1)]))
total = jax.jit(lambda a: jnp.sum(a))(x)
assert float(total) == 3.0, float(total)
print(f"proc{pid} main={is_main_process()} OK")
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cpu_rendezvous():
    port = _free_port()
    procs = []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if "AXON" not in k and not k.startswith("TPU_")}
        env.update({
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            # torch-style names: the reference-parity contract end to end
            "MASTER_ADDR": "127.0.0.1", "MASTER_PORT": str(port),
            "WORLD_SIZE": "2", "RANK": str(rank),
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=300) for p in procs]
    for i, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {i} failed:\n{out}\n{err}"
    assert "proc0 main=True OK" in outs[0][0]
    assert "proc1 main=False OK" in outs[1][0]
