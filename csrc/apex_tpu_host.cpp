// Host-side native runtime for apex_example_tpu.
//
// The reference keeps its host-side native code in csrc/ (SURVEY.md §2.1):
//   - csrc/flatten_unflatten.cpp ("apex_C"): flatten a list of tensors into
//     one contiguous buffer (bucketed-NCCL staging) and scatter it back.
//   - the fast_collate + pinned-memory prefetcher in the harness (SURVEY.md
//     §3.5): uint8 HWC frames -> normalized float batch on a side thread,
//     overlapping host work with device compute.
//
// TPU-native restatement, same division of labor: device math belongs to
// XLA/Pallas; the *host* runtime around it — contiguous staging buffers for
// checkpoint/broadcast, the synthetic-data generator, uint8->float collate,
// and a double-buffered background producer — is plain C++ driven through
// ctypes (no pybind11 in this image).  Single compilation unit, no deps.
//
// All functions use C linkage and raw pointers + explicit sizes so the
// ctypes layer stays declarative.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// apex_C analog: flatten / unflatten over a list of float32 spans.
// ---------------------------------------------------------------------------

// Copy n_tensors source spans (srcs[i], sizes[i] floats) back-to-back into
// dst.  Returns total floats copied.
int64_t apex_flatten_f32(const float** srcs, const int64_t* sizes,
                         int64_t n_tensors, float* dst) {
  int64_t off = 0;
  for (int64_t i = 0; i < n_tensors; ++i) {
    std::memcpy(dst + off, srcs[i], sizeof(float) * (size_t)sizes[i]);
    off += sizes[i];
  }
  return off;
}

// Scatter the contiguous src back into n_tensors destination spans.
int64_t apex_unflatten_f32(const float* src, float** dsts,
                           const int64_t* sizes, int64_t n_tensors) {
  int64_t off = 0;
  for (int64_t i = 0; i < n_tensors; ++i) {
    std::memcpy(dsts[i], src + off, sizeof(float) * (size_t)sizes[i]);
    off += sizes[i];
  }
  return off;
}

// ---------------------------------------------------------------------------
// Synthetic data generator (the "dataset"): splitmix64 -> uint8 pixels /
// int32 labels.  Deterministic in (seed, index) exactly like the Python
// generators in apex_example_tpu/data/synthetic.py, so epochs are
// reproducible without any dataset on disk (SURVEY.md §5 env facts).
// ---------------------------------------------------------------------------

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// Fill `out` with n uint8 values derived from (seed, start_index).
void apex_gen_u8(uint64_t seed, uint64_t start_index, uint8_t* out,
                 int64_t n) {
  int64_t i = 0;
  uint64_t ctr = start_index;
  while (i < n) {
    uint64_t r = splitmix64(seed ^ (0xA5A5A5A5u + ctr * 0x100000001B3ULL));
    ++ctr;
    for (int b = 0; b < 8 && i < n; ++b, ++i) {
      out[i] = (uint8_t)(r >> (8 * b));
    }
  }
}

// Labels in [0, num_classes).
void apex_gen_labels_i32(uint64_t seed, uint64_t start_index, int32_t* out,
                         int64_t n, int32_t num_classes) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = (int32_t)(splitmix64(seed ^ (start_index + (uint64_t)i)) %
                       (uint64_t)num_classes);
  }
}

// ---------------------------------------------------------------------------
// fast_collate analog: uint8 HWC frames -> normalized float32 NHWC batch.
// mean/std are per-channel (length c), matching the reference harness's
// normalize-in-prefetcher (SURVEY.md §3.5).
// ---------------------------------------------------------------------------

void apex_collate_f32(const uint8_t* src, int64_t n, int64_t hw, int64_t c,
                      const float* mean, const float* std_, float* dst) {
  // Precompute 256-entry LUT per channel: (v/255 - mean) / std.
  std::vector<float> lut((size_t)c * 256);
  for (int64_t ch = 0; ch < c; ++ch) {
    const float inv = 1.0f / std_[ch];
    for (int v = 0; v < 256; ++v) {
      lut[(size_t)ch * 256 + v] = ((float)v * (1.0f / 255.0f) - mean[ch]) *
                                  inv;
    }
  }
  const int64_t total = n * hw * c;
  for (int64_t i = 0; i < total; ++i) {
    dst[i] = lut[(size_t)(i % c) * 256 + src[i]];
  }
}

// ---------------------------------------------------------------------------
// Double-buffered background producer (prefetcher).  One worker thread fills
// image+label buffers for batch index `next`, the consumer swaps and
// continues — host generation overlaps device compute exactly like the
// reference's CUDA-stream prefetcher overlapped H2D with the step.
// ---------------------------------------------------------------------------

// Cheap standard-normal-ish noise: Irwin–Hall sum of 4 uniforms.
static inline float approx_gauss(uint64_t r) {
  float s = 0.0f;
  for (int i = 0; i < 4; ++i) {
    s += (float)((r >> (16 * i)) & 0xFFFF) * (1.0f / 65535.0f);
  }
  return (s - 2.0f) * 1.732f;  // var of IH(4) is 4/12 → scale to ~unit
}

struct Prefetcher {
  int64_t batch, hw, c, num_classes;
  int64_t side;                   // image_size (hw == side*side)
  uint64_t seed;
  std::vector<float> mean, std_;
  // Learnable signal, as in data/synthetic.py: a fixed low-res (8×8×C)
  // per-class pattern, bilinearly upsampled, plus noise — so models
  // genuinely train from this pipeline (loss falls, top-1 rises).
  static const int64_t PAT = 8;
  std::vector<float> patterns;    // [num_classes, 8, 8, c]
  std::vector<int> y0s, x0s;      // bilinear taps per output row/col
  std::vector<float> wys, wxs;
  // two slots
  std::vector<uint8_t> raw[2];
  std::vector<float> img[2];
  std::vector<int32_t> lab[2];
  int64_t slot_index[2];          // which batch index each slot holds
  int filled[2];
  int64_t next_index;             // next batch index to produce
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv;
  std::atomic<bool> stop;

  void init_patterns() {
    patterns.resize((size_t)(num_classes * PAT * PAT * c));
    for (size_t i = 0; i < patterns.size(); ++i) {
      patterns[i] = approx_gauss(splitmix64(seed ^ (0xbeefULL + i)));
    }
    // Half-pixel-center bilinear taps (jax.image.resize "bilinear" style).
    y0s.resize((size_t)side); wys.resize((size_t)side);
    x0s.resize((size_t)side); wxs.resize((size_t)side);
    for (int64_t i = 0; i < side; ++i) {
      float srcf = ((float)i + 0.5f) * (float)PAT / (float)side - 0.5f;
      if (srcf < 0.0f) srcf = 0.0f;
      if (srcf > (float)(PAT - 1)) srcf = (float)(PAT - 1);
      int lo = (int)srcf;
      if (lo > PAT - 2) lo = PAT - 2;
      y0s[i] = x0s[i] = lo;
      wys[i] = wxs[i] = srcf - (float)lo;
    }
  }

  // Pure computation: fills slot s for batch index bi.  No shared flags are
  // touched here; run() publishes the slot under the lock.
  void produce(int s, int64_t bi) {
    const int64_t npix = batch * hw * c;
    apex_gen_labels_i32(seed ^ 0x51ab5eedULL, (uint64_t)bi * (uint64_t)batch,
                        lab[s].data(), batch, (int32_t)num_classes);
    apex_gen_u8(seed, (uint64_t)bi * (uint64_t)npix, raw[s].data(), npix);
    // uint8 frame = clip(128 + 40·pattern + 20·noise): the class signal
    // dominates, collate re-centers it around zero.
    uint8_t* dst = raw[s].data();
    for (int64_t b = 0; b < batch; ++b) {
      const float* pat =
          &patterns[(size_t)lab[s][b] * PAT * PAT * c];
      for (int64_t y = 0; y < side; ++y) {
        const int y0 = y0s[y];
        const float wy = wys[y];
        for (int64_t x = 0; x < side; ++x) {
          const int x0 = x0s[x];
          const float wx = wxs[x];
          for (int64_t ch = 0; ch < c; ++ch) {
            const float p00 = pat[(y0 * PAT + x0) * c + ch];
            const float p01 = pat[(y0 * PAT + x0 + 1) * c + ch];
            const float p10 = pat[((y0 + 1) * PAT + x0) * c + ch];
            const float p11 = pat[((y0 + 1) * PAT + x0 + 1) * c + ch];
            const float v = (1 - wy) * ((1 - wx) * p00 + wx * p01) +
                            wy * ((1 - wx) * p10 + wx * p11);
            // raw[] currently holds uniform bytes — reuse as noise source.
            const float noise = ((float)(*dst) * (1.0f / 255.0f) - 0.5f);
            float px = 128.0f + 40.0f * v + 40.0f * noise;
            if (px < 0.0f) px = 0.0f;
            if (px > 255.0f) px = 255.0f;
            *dst++ = (uint8_t)px;
          }
        }
      }
    }
    apex_collate_f32(raw[s].data(), batch, hw, c, mean.data(), std_.data(),
                     img[s].data());
  }

  void run() {
    std::unique_lock<std::mutex> lk(mu);
    while (!stop.load()) {
      int s = -1;
      if (!filled[0]) s = 0;
      else if (!filled[1]) s = 1;
      if (s < 0) {
        cv.wait(lk);
        continue;
      }
      const int64_t bi = next_index++;
      lk.unlock();
      produce(s, bi);
      lk.lock();
      slot_index[s] = bi;
      filled[s] = 1;
      cv.notify_all();
    }
  }
};

void* apex_prefetcher_new(int64_t batch, int64_t hw, int64_t c,
                          int64_t num_classes, uint64_t seed,
                          const float* mean, const float* std_,
                          int64_t start_index) {
  auto* p = new Prefetcher();
  p->batch = batch; p->hw = hw; p->c = c; p->num_classes = num_classes;
  p->seed = seed;
  p->side = 1;
  while (p->side * p->side < hw) ++p->side;   // hw is image_size²
  p->mean.assign(mean, mean + c);
  p->std_.assign(std_, std_ + c);
  p->init_patterns();
  for (int s = 0; s < 2; ++s) {
    p->raw[s].resize((size_t)(batch * hw * c));
    p->img[s].resize((size_t)(batch * hw * c));
    p->lab[s].resize((size_t)batch);
    p->filled[s] = 0;
    p->slot_index[s] = -1;
  }
  p->next_index = start_index;   // checkpoint-resume: continue the stream
  p->stop.store(false);
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Blocks until the slot holding the OLDEST ready batch is available, copies
// it out, marks the slot refillable, and returns the batch index.
int64_t apex_prefetcher_next(void* handle, float* img_out, int32_t* lab_out) {
  auto* p = (Prefetcher*)handle;
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv.wait(lk, [p] { return p->filled[0] || p->filled[1]; });
  int s;
  if (p->filled[0] && p->filled[1])
    s = p->slot_index[0] < p->slot_index[1] ? 0 : 1;
  else
    s = p->filled[0] ? 0 : 1;
  const int64_t bi = p->slot_index[s];
  std::memcpy(img_out, p->img[s].data(), p->img[s].size() * sizeof(float));
  std::memcpy(lab_out, p->lab[s].data(), p->lab[s].size() * sizeof(int32_t));
  p->filled[s] = 0;
  p->cv.notify_all();
  return bi;
}

void apex_prefetcher_free(void* handle) {
  auto* p = (Prefetcher*)handle;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop.store(true);
    p->cv.notify_all();
  }
  p->worker.join();
  delete p;
}

// ---------------------------------------------------------------------------
// LM / MLM token prefetcher: the language-model counterpart of the image
// producer above (train.py --host-pipeline for bert_*/transformer_xl).
// Streams have the same learnable affine-bigram structure as
// data/synthetic.py lm_batch — t_{k+1} = (31·t_k + 17) mod V with
// noise_p random flips — so models genuinely train from this pipeline.
// mlm=1 additionally applies BERT's 15% / 80-10-10 masking and emits
// (input_ids, labels=original, weights=mask); mlm=0 emits next-token
// (inputs, targets) with weights all-ones.  Deterministic in (seed, batch
// index); start_index resumes mid-stream exactly like the image form.
// ---------------------------------------------------------------------------

struct LmPrefetcher {
  int64_t batch, seq, vocab;
  uint64_t seed;
  int mlm;
  int32_t mask_token;
  float mask_prob, noise_p;
  std::vector<int32_t> ids[2], lab[2];
  std::vector<float> w[2];
  int64_t slot_index[2];
  int filled[2];
  int64_t next_index;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv;
  std::atomic<bool> stop;

  static inline float u01(uint64_t r) {
    return (float)(r >> 40) * (1.0f / 16777216.0f);
  }

  void produce(int s, int64_t bi) {
    const uint64_t bseed = splitmix64(seed ^ (0x11abcdefULL + (uint64_t)bi));
    for (int64_t b = 0; b < batch; ++b) {
      const uint64_t rseed = splitmix64(bseed + (uint64_t)b);
      // affine-bigram stream with noise flips, one extra token so both the
      // causal (inputs/targets offset by one) and the MLM form fit in seq.
      int64_t t = (int64_t)(splitmix64(rseed) % (uint64_t)vocab);
      int32_t* row_i = &ids[s][(size_t)(b * seq)];
      int32_t* row_l = &lab[s][(size_t)(b * seq)];
      float* row_w = &w[s][(size_t)(b * seq)];
      for (int64_t k = 0; k < seq + 1; ++k) {
        const uint64_t r = splitmix64(rseed ^ (0x5eedULL + (uint64_t)k * 2));
        int64_t nxt = (31 * t + 17) % vocab;
        if (u01(r) < noise_p) {
          nxt = (int64_t)(splitmix64(r) % (uint64_t)vocab);
        }
        if (mlm) {
          if (k >= seq) break;
          const uint64_t m = splitmix64(rseed ^ (0xa11ULL + (uint64_t)k));
          row_l[k] = (int32_t)t;
          row_w[k] = 0.0f;
          row_i[k] = (int32_t)t;
          if (u01(m) < mask_prob) {                  // masked position
            row_w[k] = 1.0f;
            const float u = u01(splitmix64(m));
            if (u < 0.8f) {
              row_i[k] = mask_token;                 // 80% [MASK]
            } else if (u < 0.9f) {                   // 10% random token
              row_i[k] = (int32_t)(splitmix64(m ^ 0x77ULL) %
                                   (uint64_t)vocab);
            }                                        // 10% unchanged
          }
        } else {
          if (k < seq) row_i[k] = (int32_t)t;        // inputs  = t_0..t_{L-1}
          if (k >= 1) {                              // targets = t_1..t_L
            row_l[k - 1] = (int32_t)t;
            row_w[k - 1] = 1.0f;
          }
        }
        t = nxt;
      }
    }
  }

  void run() {
    std::unique_lock<std::mutex> lk(mu);
    while (!stop.load()) {
      int s = -1;
      if (!filled[0]) s = 0;
      else if (!filled[1]) s = 1;
      if (s < 0) {
        cv.wait(lk);
        continue;
      }
      const int64_t bi = next_index++;
      lk.unlock();
      produce(s, bi);
      lk.lock();
      slot_index[s] = bi;
      filled[s] = 1;
      cv.notify_all();
    }
  }
};

void* apex_lm_prefetcher_new(int64_t batch, int64_t seq_len, int64_t vocab,
                             uint64_t seed, int64_t start_index, int32_t mlm,
                             int32_t mask_token, float mask_prob,
                             float noise_p) {
  auto* p = new LmPrefetcher();
  p->batch = batch; p->seq = seq_len; p->vocab = vocab; p->seed = seed;
  p->mlm = mlm; p->mask_token = mask_token;
  p->mask_prob = mask_prob; p->noise_p = noise_p;
  for (int s = 0; s < 2; ++s) {
    p->ids[s].resize((size_t)(batch * seq_len));
    p->lab[s].resize((size_t)(batch * seq_len));
    p->w[s].resize((size_t)(batch * seq_len));
    p->filled[s] = 0;
    p->slot_index[s] = -1;
  }
  p->next_index = start_index;
  p->stop.store(false);
  p->worker = std::thread([p] { p->run(); });
  return p;
}

int64_t apex_lm_prefetcher_next(void* handle, int32_t* ids_out,
                                int32_t* lab_out, float* w_out) {
  auto* p = (LmPrefetcher*)handle;
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv.wait(lk, [p] { return p->filled[0] || p->filled[1]; });
  int s;
  if (p->filled[0] && p->filled[1])
    s = p->slot_index[0] < p->slot_index[1] ? 0 : 1;
  else
    s = p->filled[0] ? 0 : 1;
  const int64_t bi = p->slot_index[s];
  std::memcpy(ids_out, p->ids[s].data(),
              p->ids[s].size() * sizeof(int32_t));
  std::memcpy(lab_out, p->lab[s].data(),
              p->lab[s].size() * sizeof(int32_t));
  std::memcpy(w_out, p->w[s].data(), p->w[s].size() * sizeof(float));
  p->filled[s] = 0;
  p->cv.notify_all();
  return bi;
}

void apex_lm_prefetcher_free(void* handle) {
  auto* p = (LmPrefetcher*)handle;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop.store(true);
    p->cv.notify_all();
  }
  p->worker.join();
  delete p;
}

}  // extern "C"
