"""Learning-rate schedules for the harness (SURVEY.md §3.5: step-decay
``adjust_learning_rate`` with warmup; §7 item C4: BERT/LAMB warmup).

The reference adjusts ``param_group['lr']`` host-side per epoch; here a
schedule is a pure function ``f(step) -> lr`` fed to the fused optimizers'
callable-lr path (optim/fused.py ``_lr_at``), so the learning rate is a
traced scalar and one compiled step serves the whole run.

All schedules compose linear warmup (0 → base over ``warmup_steps``) with a
decay phase and are exact ``jnp`` expressions of the step counter — no
Python control flow, jit-safe.

Deliberately not thin wrappers over optax's schedule zoo: the fused
optimizers call schedules with a **1-based** post-increment step (the apex
``state.step`` convention their bias corrections use), while optax
schedules are 0-based — wrapping would hide an off-by-one at every
boundary.  These ~60 lines keep the convention explicit and are pinned by
tests/test_schedules.py at the exact boundary steps.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def _warmup_factor(step: jnp.ndarray, warmup_steps: int) -> jnp.ndarray:
    """Linear 0→1 over warmup_steps; 1 afterwards.  ``step`` is 1-based (the
    fused optimizers call the schedule with the post-increment count)."""
    if warmup_steps <= 0:
        return jnp.asarray(1.0, jnp.float32)
    s = step.astype(jnp.float32)
    return jnp.minimum(s / float(warmup_steps), 1.0)


def constant_lr(base_lr: float, warmup_steps: int = 0) -> Schedule:
    def f(step):
        return base_lr * _warmup_factor(step, warmup_steps)
    return f


def step_decay(base_lr: float, boundaries: Sequence[int],
               gamma: float = 0.1, warmup_steps: int = 0) -> Schedule:
    """lr = base · gamma^(#boundaries passed) — the reference harness's
    ``adjust_learning_rate`` (epoch//30 decades), expressed in steps."""
    bounds = jnp.asarray(sorted(int(b) for b in boundaries), jnp.int32)

    def f(step):
        passed = jnp.sum((step >= bounds).astype(jnp.int32))
        return (base_lr * jnp.power(gamma, passed.astype(jnp.float32))
                * _warmup_factor(step, warmup_steps))
    return f


def cosine_decay(base_lr: float, total_steps: int, warmup_steps: int = 0,
                 min_lr: float = 0.0) -> Schedule:
    """Cosine from base to min_lr over [warmup_steps, total_steps]."""
    span = max(total_steps - warmup_steps, 1)

    def f(step):
        s = jnp.clip(step.astype(jnp.float32) - warmup_steps, 0.0,
                     float(span))
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * s / span))
        return ((min_lr + (base_lr - min_lr) * cos)
                * _warmup_factor(step, warmup_steps))
    return f


def polynomial_decay(base_lr: float, total_steps: int, warmup_steps: int = 0,
                     power: float = 1.0, min_lr: float = 0.0) -> Schedule:
    """Linear (power=1) / polynomial decay — the BERT/LAMB pretraining
    schedule (warmup then linear to 0)."""
    span = max(total_steps - warmup_steps, 1)

    def f(step):
        s = jnp.clip(step.astype(jnp.float32) - warmup_steps, 0.0,
                     float(span))
        frac = jnp.power(1.0 - s / span, power)
        return ((min_lr + (base_lr - min_lr) * frac)
                * _warmup_factor(step, warmup_steps))
    return f


def build_schedule(name: str, base_lr: float, total_steps: int,
                   warmup_steps: int = 0,
                   boundaries: Sequence[int] = (),
                   gamma: float = 0.1, power: float = 1.0,
                   min_lr: float = 0.0):
    """CLI-facing factory.  ``name`` in {const, step, cosine, poly}.
    Returns a float (not a closure) for warmup-free const so optimizers
    keep their static-lr fast path."""
    if name == "const":
        if warmup_steps <= 0:
            return base_lr
        return constant_lr(base_lr, warmup_steps)
    if name == "step":
        if not boundaries:
            # Reference default: decade drops at 1/3 and 2/3 of the run
            # (the epoch//30-of-90 recipe, expressed fractionally).
            boundaries = [total_steps // 3, 2 * total_steps // 3]
        return step_decay(base_lr, boundaries, gamma, warmup_steps)
    if name == "cosine":
        return cosine_decay(base_lr, total_steps, warmup_steps, min_lr)
    if name == "poly":
        return polynomial_decay(base_lr, total_steps, warmup_steps, power,
                                min_lr)
    raise ValueError(f"unknown schedule {name!r}")
