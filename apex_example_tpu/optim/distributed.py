"""DistributedFusedAdam: ZeRO-1 optimizer-state sharding over the data axis.

Reference: apex/contrib/optimizers/distributed_fused_adam.py (SURVEY.md §3.4
contrib row) — Adam whose optimizer state and parameter update are sharded
across the data-parallel group: gradients reduce-scatter instead of
all-reduce, each rank updates only its 1/N shard of the flattened parameter
space, and the new parameters all-gather back.  SURVEY.md §3.3 notes the same
idea for TPU as "cross-replica weight-update sharding".

TPU-native design: the flattened parameter space is ONE fp32 buffer padded to
``world × 128`` lanes.  Optimizer state (m, v) lives as global (padded,)
arrays that shard over the mesh's data axis — inside ``shard_map`` each
replica holds exactly its (padded/world,) slice, so per-device state memory
is 1/N of FusedAdam's.  One step, inside the same jitted program as
forward/backward:

    flat_g   = flatten(grads)                      # per-replica, shard-varying
    g_shard  = psum_scatter(flat_g, 'data')        # the reduce-scatter
    p_shard  = dynamic_slice(flatten(params), axis_index * shard)
    p_shard' = fused adam kernel (p, g, m, v shards — ops/fused_optim.py)
    flat_p'  = all_gather(p_shard', 'data', tiled)  # replicated again
    params'  = unflatten(flat_p')

reduce_scatter + all_gather move the same bytes as the plain psum, so the
step trades nothing on the wire for an N-fold cut in optimizer-state memory
and update FLOPs — the ZeRO-1 contract.

``make_zero_train_step`` wires this into the engine's DDP step: the only
difference from ``make_sharded_train_step`` is that the optimizer-state
in/out specs shard over the data axis (P("data")) instead of replicating.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_example_tpu.ops.fused_optim import adam_update_leaf
from apex_example_tpu.optim.fused import Schedule, _lr_at

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

_LANES = 128


class ZeroAdamState(NamedTuple):
    step: jnp.ndarray
    mu: jnp.ndarray        # (padded,) fp32 — shards over the data axis
    nu: jnp.ndarray        # (padded,) fp32 — shards over the data axis


def _flat_size(params) -> int:
    return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))


def _padded_size(n: int, world: int) -> int:
    quantum = world * _LANES
    return n + (-n) % quantum


def _flatten(tree, padded: int, dtype=jnp.float32) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    flat = jnp.concatenate([l.astype(dtype).reshape(-1) for l in leaves])
    return jnp.pad(flat, (0, padded - flat.shape[0]))


def _unflatten(flat: jnp.ndarray, like) -> Any:
    leaves, treedef = jax.tree_util.tree_flatten(like)
    out, off = [], 0
    for l in leaves:
        out.append(flat[off:off + l.size].reshape(l.shape).astype(l.dtype))
        off += l.size
    return treedef.unflatten(out)


class DistributedFusedAdam:
    """ZeRO-1 Adam/AdamW over a data-parallel mesh axis.

    Ctor mirrors FusedAdam plus the sharding contract: ``world`` (the data-
    axis size, static) and ``axis_name``.  ``apply`` must run inside
    ``shard_map`` with ``axis_name`` bound and state sharded P(axis) (see
    ``make_zero_train_step``); ``init`` runs anywhere and returns the
    global-shaped state.
    """

    def __init__(self, lr: Schedule = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adam_w_mode: bool = True, *, world: int,
                 axis_name: str = "data",
                 grads_global_mean: bool = False):
        self.lr, self.betas, self.eps = lr, betas, eps
        self.weight_decay, self.adam_w_mode = weight_decay, adam_w_mode
        self.world, self.axis_name = world, axis_name
        # Reduction contract: False (the DDP engine path) = incoming
        # grads are per-shard LOCAL means whose implicit psum sums to
        # world x the global mean — apply() divides by world.  True (the
        # CP path, whose losses are psum-normalized GLOBALLY) = grads
        # arrive as the true global mean already — dividing again would
        # hand Adam g/world and silently inflate the effective epsilon.
        self.grads_global_mean = grads_global_mean

    def init(self, params) -> ZeroAdamState:
        padded = _padded_size(_flat_size(params), self.world)
        return ZeroAdamState(step=jnp.zeros((), jnp.int32),
                             mu=jnp.zeros((padded,), jnp.float32),
                             nu=jnp.zeros((padded,), jnp.float32))

    def state_spec(self) -> ZeroAdamState:
        """shard_map PartitionSpecs for the state (m/v shard over data)."""
        return ZeroAdamState(step=P(), mu=P(self.axis_name),
                             nu=P(self.axis_name))

    def apply(self, grads, state: ZeroAdamState, params
              ) -> Tuple[Any, ZeroAdamState]:
        """Sharded update; inside shard_map state.mu/nu are the LOCAL shard.

        ``grads`` are the per-replica (unreduced) gradients — the reduce
        happens here, as a reduce-scatter, so the engine must NOT have
        psum-ed them already (make_zero_train_step passes ddp-less grads).
        """
        step = state.step + 1
        b1, b2 = self.betas
        t = step.astype(jnp.float32)
        c1 = 1.0 / (1.0 - jnp.power(b1, t))
        c2 = 1.0 / (1.0 - jnp.power(b2, t))
        lr = _lr_at(self.lr, step)

        world = lax.axis_size(self.axis_name)
        padded = _padded_size(_flat_size(params), world)
        shard = padded // world
        idx = lax.axis_index(self.axis_name)

        flat_g = _flatten(grads, padded)
        if not self.grads_global_mean:
            flat_g = flat_g / world                  # mean-reduction contract
        vma = getattr(jax.typeof(flat_g), "vma", None)
        if vma is None:
            # Without vma typing (pre-vma JAX / check_vma=False) we cannot
            # tell already-psummed engine grads from raw per-replica grads;
            # guessing wrong silently trains each shard on 1/N of the data.
            raise RuntimeError(
                "DistributedFusedAdam requires vma-typed shard_map "
                "(jax.shard_map with check_vma=True, the default) so the "
                "gradient-reduction state is visible; got an aval without "
                "vma typing")
        if self.axis_name in vma:
            if self.grads_global_mean:
                raise RuntimeError(
                    "grads_global_mean=True expects implicitly psum-ed "
                    "(shard-invariant) grads — the CP-loss contract; got "
                    "shard-varying grads, whose reduce-scatter would need "
                    "the /world mean the flag disables")
            # Raw per-replica grads: the reduction IS the reduce-scatter.
            g_shard = lax.psum_scatter(flat_g, self.axis_name,
                                       scatter_dimension=0, tiled=True)
        else:
            # Engine-path grads: jax.grad w.r.t. replicated params already
            # psum-ed them inside backward (see parallel/distributed.py) —
            # XLA owns that collective's schedule; only the slice remains.
            # The ZeRO-1 memory contract (1/N optimizer state + update) is
            # unchanged; the reduce-scatter wire saving applies only to the
            # varying-grads path.
            g_shard = lax.dynamic_slice(flat_g, (idx * shard,), (shard,))
        p_shard = lax.dynamic_slice(_flatten(params, padded),
                                    (idx * shard,), (shard,))

        # Finite check AFTER the reduce — the fp16 dynamic-scaling contract.
        # A nonfinite grad element lands in exactly one replica's shard after
        # psum_scatter, so the per-shard flag alone would diverge across
        # replicas (each skipping or stepping on its own) and de-synchronize
        # the gathered params.  psum-ing the flag makes the skip decision
        # identical everywhere: every replica steps, or none does.  The psum
        # output is mesh-invariant, so the select below provably keeps the
        # replicated-params out-spec.
        shard_ok = jnp.all(jnp.isfinite(g_shard)).astype(jnp.float32)
        finite = lax.psum(shard_ok, self.axis_name) == world

        po, mo, vo = adam_update_leaf(
            p_shard, g_shard, state.mu, state.nu, lr=lr, beta1=b1, beta2=b2,
            eps=self.eps, weight_decay=self.weight_decay, bias_c1=c1,
            bias_c2=c2, adam_w_mode=self.adam_w_mode)

        # Overflow ⇒ the whole sharded update is dropped (params, m, v and
        # the bias-correction step all keep their old values) — the same
        # "skip optimizer.step()" select the engine applies for replicated
        # optimizers, enforced here where the shard structure is known.
        po = jnp.where(finite, po, p_shard)
        mo = jnp.where(finite, mo, state.mu)
        vo = jnp.where(finite, vo, state.nu)
        step = jnp.where(finite, step, state.step)

        # Gather the updated shards back to replicated parameters.  The psum
        # of per-replica scattered writes is the vma-typed form of the
        # all_gather (shard_map's replication checker can prove psum outputs
        # invariant; lax.all_gather stays 'varying' and would be rejected at
        # the P() out_spec) — XLA lowers this select-free sum-of-disjoint
        # slices to the same collective traffic class.
        contrib = lax.dynamic_update_slice(
            jnp.zeros((padded,), jnp.float32), po, (idx * shard,))
        flat_p = lax.psum(contrib, self.axis_name)
        return _unflatten(flat_p, params), ZeroAdamState(step, mo, vo)


def make_zero_train_step(mesh: Mesh, model, optimizer: DistributedFusedAdam,
                         policy, loss_fn=None, compute_accuracy: bool = True,
                         donate: bool = True):
    """DDP train step with ZeRO-1 state sharding.

    Identical contract to ``engine.make_sharded_train_step`` except the
    optimizer-state leaves shard over the data axis (P("data")) and gradient
    reduction happens inside the optimizer (reduce-scatter), not as a psum.
    """
    from apex_example_tpu import amp as amp_lib
    from apex_example_tpu.engine import (TrainState, cross_entropy_loss,
                                         make_train_step, _replicate_mean)

    axis = optimizer.axis_name
    loss_fn = loss_fn or cross_entropy_loss
    # Dynamic loss scaling composes safely here on two grounds:
    #  - On this engine path grads reach the optimizer already implicitly
    #    psum-ed (jax.grad w.r.t. replicated params inside shard_map), so the
    #    engine's unscale/finite flag is mesh-invariant — every replica makes
    #    the same skip decision and updates the scaler identically.
    #  - Independently, DistributedFusedAdam.apply re-checks finiteness on
    #    the post-reduce shard and psums the flag, so even the raw
    #    reduce-scatter path (varying grads) skips in lockstep.  A skipped
    #    step is therefore a no-op on params AND on the sharded (m, v, step).
    # axis_name=None: the inner step must NOT psum grads (the optimizer's
    # reduce-scatter is the reduction); loss/metrics get pmean-ed below.
    per_shard = make_train_step(model, optimizer, policy, axis_name=None,
                                loss_fn=loss_fn,
                                compute_accuracy=compute_accuracy)

    def step_and_sync(state, batch):
        new_state, metrics = per_shard(state, batch)
        metrics = {k: lax.pmean(v, axis) for k, v in metrics.items()}
        synced = _replicate_mean(new_state.batch_stats, axis)
        return new_state.replace(batch_stats=synced), metrics

    # Prefix specs: a single P() stands for a whole replicated subtree.
    spec = TrainState(step=P(), params=P(), batch_stats=P(),
                      opt_state=optimizer.state_spec(), scaler=P())
    sharded = _shard_map(
        step_and_sync, mesh=mesh,
        in_specs=(spec, (P(axis), P(axis))),
        out_specs=(spec, P()))
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())
