"""FusedAdam / FusedLAMB / FusedSGD frontends over the Pallas kernels.

Reference (apex/optimizers/fused_{adam,lamb,sgd}.py; SURVEY.md §3.4): torch
optimizers whose ``step()`` is one multi-tensor kernel sweep over all params.

TPU-native shape: a torch optimizer mutates params; JAX optimizers are pure.
Each fused optimizer here exposes

    state = opt.init(params)
    new_params, new_state = opt.apply(grads, state, params)

where ``apply`` runs the fused Pallas kernels leaf-by-leaf (p/m/v read once,
written once, buffers donated — the HBM-traffic shape of the CUDA kernels).
An ``as_optax()`` adapter provides the optax GradientTransformation calling
convention (updates = new_p − p) for interop with optax schedules/chains; the
train step uses ``apply`` directly so the fused path stays fused.

The learning rate may be a float or an optax-style schedule ``f(step)``; the
step counter lives in the optimizer state, so bias corrections are traced
scalars and one compiled step serves the whole run.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from apex_example_tpu.ops.fused_optim import (
    adagrad_update_leaf, adam_update_leaf, lamb_stage1_leaf,
    lamb_stage2_leaf, novograd_update_leaf, sgd_update_leaf)
from apex_example_tpu.ops.multi_tensor import (multi_tensor_l2norm,
                                               sqsum_leaf)

Schedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


def _lr_at(lr: Schedule, step: jnp.ndarray) -> jnp.ndarray:
    return jnp.asarray(lr(step) if callable(lr) else lr, jnp.float32)


class AdamState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any


class FusedAdam:
    """Adam/AdamW with a fused per-leaf update kernel.

    Ctor surface mirrors apex.optimizers.FusedAdam: ``adam_w_mode=True`` gives
    AdamW (decoupled decay), False gives classic Adam with L2-in-gradient.
    """

    def __init__(self, lr: Schedule = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adam_w_mode: bool = True, amsgrad: bool = False):
        if amsgrad:
            raise ValueError("FusedAdam does not support amsgrad "
                             "(parity with the reference)")
        self.lr, self.betas, self.eps = lr, betas, eps
        self.weight_decay, self.adam_w_mode = weight_decay, adam_w_mode

    def init(self, params) -> AdamState:
        zeros = lambda t: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), t)
        return AdamState(step=jnp.zeros((), jnp.int32),
                         mu=zeros(params), nu=zeros(params))

    def apply(self, grads, state: AdamState, params
              ) -> Tuple[Any, AdamState]:
        step = state.step + 1
        b1, b2 = self.betas
        t = step.astype(jnp.float32)
        c1 = 1.0 / (1.0 - jnp.power(b1, t))
        c2 = 1.0 / (1.0 - jnp.power(b2, t))
        lr = _lr_at(self.lr, step)

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            po, mo, vo = adam_update_leaf(
                p, g, m, v, lr=lr, beta1=b1, beta2=b2, eps=self.eps,
                weight_decay=self.weight_decay, bias_c1=c1, bias_c2=c2,
                adam_w_mode=self.adam_w_mode)
            new_p.append(po), new_m.append(mo), new_v.append(vo)
        unflat = treedef.unflatten
        return unflat(new_p), AdamState(step, unflat(new_m), unflat(new_v))

    def as_optax(self) -> optax.GradientTransformation:
        return _as_optax(self)


class LambState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any


def lamb_step_scalars(lamb: "FusedLAMB", step):
    """(bias_c1, bias_c2, lr) at ``step`` for a FusedLAMB config — shared by
    :meth:`FusedLAMB.apply` and the pipeline form
    (transformer.bert_pipeline.PipelineFusedLAMB), whose contract is that
    per-layer updates match this module's bitwise."""
    b1, b2 = lamb.betas
    t = step.astype(jnp.float32)
    if lamb.bias_correction:
        c1 = 1.0 / (1.0 - jnp.power(b1, t))
        c2 = 1.0 / (1.0 - jnp.power(b2, t))
    else:
        c1 = c2 = jnp.asarray(1.0, jnp.float32)
    return c1, c2, _lr_at(lamb.lr, step)


def lamb_clip_scale(lamb: "FusedLAMB", gnorm):
    """Gradient scale implementing LAMB's global-norm clip, given the
    (caller-assembled) global grad norm."""
    return jnp.where(gnorm > lamb.max_grad_norm,
                     lamb.max_grad_norm / (gnorm + 1e-6), 1.0)


def lamb_update_leaf(lamb: "FusedLAMB", p, g, m, v, c1, c2, lr, gscale):
    """stage1 → per-TENSOR trust ratio → stage2 for one leaf; returns
    (p', m', v').  Trust ratio: ||p|| / ||u|| when both positive else 1
    (apex lamb_stage_2 semantics)."""
    u, mo, vo, p_sq, u_sq = lamb_stage1_leaf(
        p, g, m, v, beta1=lamb.betas[0], beta2=lamb.betas[1], eps=lamb.eps,
        weight_decay=lamb.weight_decay, bias_c1=c1, bias_c2=c2,
        grad_scale=gscale)
    w_norm, u_norm = jnp.sqrt(p_sq), jnp.sqrt(u_sq)
    ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
    return lamb_stage2_leaf(p, u, lr * ratio), mo, vo


class FusedLAMB:
    """LAMB with the reference's two-stage fused structure.

    Stage 1 (kernel): Adam-style update + per-tensor ||p||², ||u||².
    Between: optional global grad-norm clip (``max_grad_norm``, default 1.0 in
    the reference) folded into stage 1 as a gradient scale; per-tensor trust
    ratios computed as scalars.
    Stage 2 (kernel): p ← p − lr · trust_ratio · u.
    """

    def __init__(self, lr: Schedule = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 max_grad_norm: float = 1.0, bias_correction: bool = True):
        self.lr, self.betas, self.eps = lr, betas, eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.bias_correction = bias_correction

    def init(self, params) -> LambState:
        zeros = lambda t: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), t)
        return LambState(step=jnp.zeros((), jnp.int32),
                         mu=zeros(params), nu=zeros(params))

    def apply(self, grads, state: LambState, params
              ) -> Tuple[Any, LambState]:
        step = state.step + 1
        c1, c2, lr = lamb_step_scalars(self, step)

        # Global grad clip on the multi_tensor_l2norm path (SURVEY.md §3.4).
        if self.max_grad_norm and self.max_grad_norm > 0:
            gscale = lamb_clip_scale(self, multi_tensor_l2norm(grads))
        else:
            gscale = jnp.asarray(1.0, jnp.float32)

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)

        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            po, mo, vo = lamb_update_leaf(self, p, g, m, v, c1, c2, lr,
                                          gscale)
            new_p.append(po), new_m.append(mo), new_v.append(vo)
        unflat = treedef.unflatten
        return unflat(new_p), LambState(step, unflat(new_m), unflat(new_v))

    def as_optax(self) -> optax.GradientTransformation:
        return _as_optax(self)


class NovoGradState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any          # per-TENSOR scalars: EMA of the squared grad L2-norm


class FusedNovoGrad:
    """NovoGrad: layer-wise normalized momentum SGD.

    Reference surface: apex.optimizers.FusedNovoGrad backed by
    multi_tensor_novograd.cu (SURVEY.md §3.4).  The second moment is a
    *scalar per tensor* — the EMA of ||g||₂² — so the state is a pytree of
    scalars; the elementwise apply is one fused kernel per leaf.

    Defaults mirror the reference: betas=(0.95, 0.98), grad_averaging=True,
    bias_correction=True, ``init_zero=False`` (first-step v = ||g₁||²),
    L2 applied to the *normalized* gradient (reg_inside_moment=False).
    norm_type is fixed at 2, amsgrad unsupported — both as in the reference's
    kernel path.
    """

    def __init__(self, lr: Schedule = 1e-3, betas=(0.95, 0.98),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 grad_averaging: bool = True, bias_correction: bool = True,
                 init_zero: bool = False, amsgrad: bool = False):
        if amsgrad:
            raise ValueError("FusedNovoGrad does not support amsgrad "
                             "(parity with the reference)")
        self.lr, self.betas, self.eps = lr, betas, eps
        self.weight_decay = weight_decay
        self.grad_averaging = grad_averaging
        self.bias_correction = bias_correction
        self.init_zero = init_zero

    def init(self, params) -> NovoGradState:
        zeros = lambda t: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), t)
        return NovoGradState(
            step=jnp.zeros((), jnp.int32), mu=zeros(params),
            nu=jax.tree_util.tree_map(
                lambda p: jnp.zeros((), jnp.float32), params))

    def apply(self, grads, state: NovoGradState, params
              ) -> Tuple[Any, NovoGradState]:
        step = state.step + 1
        b1, b2 = self.betas
        t = step.astype(jnp.float32)
        if self.bias_correction:
            c1 = 1.0 / (1.0 - jnp.power(b1, t))
            c2 = 1.0 / (1.0 - jnp.power(b2, t))
        else:
            c1 = c2 = jnp.asarray(1.0, jnp.float32)
        lr = _lr_at(self.lr, step)
        ga = (1.0 - b1) if self.grad_averaging else 1.0

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            gsq = sqsum_leaf(g)
            if self.init_zero:
                vo = b2 * v + (1.0 - b2) * gsq
            else:        # reference default: first-step v is the raw norm²
                vo = jnp.where(step == 1, gsq, b2 * v + (1.0 - b2) * gsq)
            inv_denom = 1.0 / (jnp.sqrt(vo * c2) + self.eps)
            po, mo = novograd_update_leaf(
                p, g, m, inv_denom=inv_denom, lr_c1=lr * c1, beta1=b1,
                weight_decay=self.weight_decay, grad_avg_coeff=ga)
            new_p.append(po), new_m.append(mo), new_v.append(vo)
        unflat = treedef.unflatten
        return unflat(new_p), NovoGradState(step, unflat(new_m),
                                            unflat(new_v))

    def as_optax(self) -> optax.GradientTransformation:
        return _as_optax(self)


class SGDState(NamedTuple):
    step: jnp.ndarray
    momentum: Any


class FusedSGD:
    """Momentum SGD with a fused update kernel.

    First-step semantics: torch initializes the momentum buffer to the first
    gradient.  With zero-initialized buffers and ``dampening=0`` the fused
    update reproduces that exactly; for nonzero dampening the first step
    differs by the (1−dampening) factor — documented delta, as apex's
    own kernel path has the same property.
    """

    def __init__(self, lr: Schedule = 1e-2, momentum: float = 0.0,
                 weight_decay: float = 0.0, dampening: float = 0.0,
                 nesterov: bool = False):
        self.lr, self.momentum = lr, momentum
        self.weight_decay, self.dampening = weight_decay, dampening
        self.nesterov = nesterov

    def init(self, params) -> SGDState:
        return SGDState(
            step=jnp.zeros((), jnp.int32),
            momentum=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), params))

    def apply(self, grads, state: SGDState, params) -> Tuple[Any, SGDState]:
        step = state.step + 1
        lr = _lr_at(self.lr, step)
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_b = treedef.flatten_up_to(state.momentum)
        new_p, new_b = [], []
        for p, g, b in zip(flat_p, flat_g, flat_b):
            po, bo = sgd_update_leaf(
                p, g, b, lr=lr, momentum=self.momentum,
                weight_decay=self.weight_decay, dampening=self.dampening,
                nesterov=self.nesterov)
            new_p.append(po), new_b.append(bo)
        unflat = treedef.unflatten
        return unflat(new_p), SGDState(step, unflat(new_b))

    def as_optax(self) -> optax.GradientTransformation:
        return _as_optax(self)


class AdagradState(NamedTuple):
    step: jnp.ndarray
    sum_sq: Any


class FusedAdagrad:
    """Adagrad with a fused update kernel.

    Reference: apex/optimizers/fused_adagrad.py (multi_tensor_adagrad.cu) —
    apex's surface drops torch.optim.Adagrad's ``lr_decay``/
    ``initial_accumulator_value`` and adds ``adagrad_w_mode`` (decoupled
    weight decay); this frontend matches apex.
    """

    def __init__(self, lr: Schedule = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, adagrad_w_mode: bool = False):
        self.lr, self.eps = lr, eps
        self.weight_decay, self.adagrad_w_mode = weight_decay, adagrad_w_mode

    def init(self, params) -> AdagradState:
        return AdagradState(
            step=jnp.zeros((), jnp.int32),
            sum_sq=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), params))

    def apply(self, grads, state: AdagradState, params
              ) -> Tuple[Any, AdagradState]:
        step = state.step + 1
        lr = _lr_at(self.lr, step)
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_h = treedef.flatten_up_to(state.sum_sq)
        new_p, new_h = [], []
        for p, g, h in zip(flat_p, flat_g, flat_h):
            po, ho = adagrad_update_leaf(
                p, g, h, lr=lr, eps=self.eps,
                weight_decay=self.weight_decay,
                adagrad_w_mode=self.adagrad_w_mode)
            new_p.append(po), new_h.append(ho)
        unflat = treedef.unflatten
        return unflat(new_p), AdagradState(step, unflat(new_h))

    def as_optax(self) -> optax.GradientTransformation:
        return _as_optax(self)


def _as_optax(opt) -> optax.GradientTransformation:
    """optax adapter: updates = fused_new_params − params."""

    def init_fn(params):
        return opt.init(params)

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused optimizers require params")
        new_params, new_state = opt.apply(grads, state, params)
        updates = jax.tree_util.tree_map(
            lambda n, p: n.astype(jnp.float32) - p.astype(jnp.float32),
            new_params, params)
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)
