"""apex.optimizers-shaped surface (SURVEY.md §3.4) + LR schedules."""

from apex_example_tpu.optim.distributed import (DistributedFusedAdam,
                                                ZeroAdamState,
                                                make_zero_train_step)
from apex_example_tpu.optim.fused import (
    AdagradState, AdamState, FusedAdagrad, FusedAdam, FusedLAMB,
    FusedNovoGrad, FusedSGD, LambState, NovoGradState, SGDState)
from apex_example_tpu.optim.schedules import (
    build_schedule, constant_lr, cosine_decay, polynomial_decay, step_decay)

__all__ = ["AdagradState", "AdamState", "DistributedFusedAdam",
           "FusedAdagrad", "FusedAdam", "FusedLAMB", "FusedNovoGrad",
           "FusedSGD", "LambState", "NovoGradState", "SGDState",
           "ZeroAdamState", "build_schedule", "constant_lr", "cosine_decay",
           "make_zero_train_step", "polynomial_decay", "step_decay"]
