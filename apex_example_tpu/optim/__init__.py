"""apex.optimizers-shaped surface (SURVEY.md §3.4)."""

from apex_example_tpu.optim.fused import (
    AdamState, FusedAdam, FusedLAMB, FusedSGD, LambState, SGDState)

__all__ = ["AdamState", "FusedAdam", "FusedLAMB", "FusedSGD", "LambState",
           "SGDState"]
