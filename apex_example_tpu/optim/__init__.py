"""apex.optimizers-shaped surface (SURVEY.md §3.4)."""

from apex_example_tpu.optim.fused import (
    AdamState, FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD, LambState,
    NovoGradState, SGDState)

__all__ = ["AdamState", "FusedAdam", "FusedLAMB", "FusedNovoGrad",
           "FusedSGD", "LambState", "NovoGradState", "SGDState"]
