"""apex.optimizers-shaped surface (SURVEY.md §3.4) + LR schedules."""

from apex_example_tpu.optim.fused import (
    AdamState, FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD, LambState,
    NovoGradState, SGDState)
from apex_example_tpu.optim.schedules import (
    build_schedule, constant_lr, cosine_decay, polynomial_decay, step_decay)

__all__ = ["AdamState", "FusedAdam", "FusedLAMB", "FusedNovoGrad",
           "FusedSGD", "LambState", "NovoGradState", "SGDState",
           "build_schedule", "constant_lr", "cosine_decay",
           "polynomial_decay", "step_decay"]
