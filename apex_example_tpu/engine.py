"""The train-step engine: one jitted function per workload.

This is the TPU-native restatement of the reference's hot loop (SURVEY.md
§4.2–4.3): forward, scaled backward, gradient allreduce, unscale + finite
check, (possibly skipped) optimizer step, scaler update — all of it a single
traced program.  What the reference spreads across autograd hooks, patched
optimizers and host-side scaler logic collapses here into data flow:

    loss → grad → psum('data') → unscale/finite → fused update → where-select

XLA overlaps the psum with backward computation (the bucketed-NCCL overlap,
compiler-scheduled) and the where-select realizes apex's "overflow ⇒ skip
optimizer.step()" without a host sync.

Data parallelism wraps the same step in ``shard_map`` over the ``data`` mesh
axis — the per-device function IS the single-device step plus collectives,
which is how DDP semantics (identical replicated params, summed grads, synced
BN stats) are preserved by construction.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_example_tpu import amp as amp_lib
from apex_example_tpu._compat import axis_size, pcast, vma_of
from apex_example_tpu.amp.policy import Policy
from apex_example_tpu.amp.scaler import ScalerState
from apex_example_tpu.obs import numerics as numerics_lib
from apex_example_tpu.obs.spans import device_span
from apex_example_tpu.parallel.distributed import DDPConfig, allreduce_grads
from apex_example_tpu.parallel.mesh import DATA_AXIS

try:
    from jax import shard_map as _shard_map  # jax >= 0.7 spelling
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


@struct.dataclass
class TrainState:
    """Everything the step carries; a pure pytree (donatable)."""
    step: jnp.ndarray
    params: Any                 # fp32 masters (or half under O3)
    batch_stats: Any            # BN running stats, {} for stat-free models
    opt_state: Any
    scaler: ScalerState


def create_train_state(rng, model, optimizer, sample_batch, policy: Policy,
                       scaler: Optional[ScalerState] = None,
                       train_kwargs: Optional[dict] = None) -> TrainState:
    """Initialize params/stats/optimizer for a model + policy.

    Params are stored in ``policy.param_dtype`` — fp32 for O0–O2 (they double
    as apex's "master weights"), half for O3.
    """
    from flax.core import meta
    variables = meta.unbox(model.init(rng, sample_batch, **(train_kwargs or
                                                            {"train": False})))
    # unbox: TP layers wrap params in flax Partitioned boxes (metadata for
    # gspmd_state_shardings); the train state carries plain arrays — a no-op
    # for non-partitioned models.
    params = variables["params"]
    if policy.param_dtype != jnp.float32:
        params = jax.tree_util.tree_map(
            lambda p: p.astype(policy.param_dtype), params)
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=optimizer.init(params),
        scaler=scaler if scaler is not None else amp_lib.make_scaler(policy))


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray
                       ) -> jnp.ndarray:
    """Mean softmax-CE in fp32 (the reference computes criterion on
    ``output.float()``)."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels).mean()


def _apply_model(model, params, batch_stats, x, train: bool):
    variables = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
        if train:
            out, mut = model.apply(variables, x, train=True,
                                   mutable=["batch_stats"])
            return out, mut["batch_stats"]
        return model.apply(variables, x, train=False), batch_stats
    if train:
        return model.apply(variables, x, train=True), batch_stats
    return model.apply(variables, x, train=False), batch_stats


def make_train_step(model, optimizer, policy: Policy,
                    ddp: Optional[DDPConfig] = None,
                    axis_name: Optional[str] = None,
                    loss_fn: Callable = cross_entropy_loss,
                    compute_accuracy: bool = True,
                    grad_accum: int = 1,
                    finite_reduce_axes=None,
                    numerics: bool = False):
    """Build the single-device (or per-shard) train step.

    ``optimizer`` is a fused optimizer (init/apply) from
    ``apex_example_tpu.optim``; optax GradientTransformations are adapted
    automatically.  When ``axis_name`` is set the step must run inside
    shard_map/pmap with that axis bound (see :func:`make_sharded_train_step`).

    ``grad_accum=K`` splits the batch into K microbatches and accumulates
    fp32 grads across them before the (single) optimizer step — the
    reference's DDP grad-accumulation hook semantics (SURVEY.md §3.2
    ``message_size``/accumulation): BN running stats update per forward,
    grads average over microbatches, the allreduce happens once on the
    accumulated grads (delay_allreduce-style).

    ``finite_reduce_axes``: mesh axis name(s) to AND the dynamic-scaling
    finite flag over.  Needed whenever some PARAM grads are legitimately
    shard-varying inside a shard_map (e.g. expert-parallel MoE weights,
    where each shard owns its expert): a local overflow must skip the
    update and halve the scale on EVERY shard, or the replicated scaler
    state diverges across the mesh.  Replicated-param-only steps (DDP,
    CP) don't need it — their grads arrive psum-ed, so the flag is
    already mesh-invariant.

    ``numerics=True`` adds overflow provenance to the metrics: per-top-
    level-module non-finite counts + grad norms (``metrics["numerics"]``,
    obs/numerics.module_grad_stats), computed right next to the finite
    check that already reads every grad element so XLA fuses the
    reductions into the same pass.  Like ``grad_norm`` it is skipped
    under ``finite_reduce_axes`` (shard-varying expert grads would make
    the per-module stats mesh-variant).
    """
    opt = _wrap_optimizer(optimizer)
    ddp = ddp or DDPConfig()
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

    # Non-default reduction options (fp16 overflow-headroom pre-divide, fp32
    # upcast) need the *explicit* psum path: differentiating wrt replicated
    # params would psum implicitly inside backward, before those options
    # could apply.  Casting params to shard-varying first keeps the grads
    # per-shard so allreduce_grads controls the reduction.
    explicit_reduce = (axis_name is not None and
                       (ddp.gradient_predivide_factor != 1.0 or
                        ddp.allreduce_always_fp32 or
                        ddp.quantized_allreduce))

    def train_step(state: TrainState, batch) -> Tuple[TrainState, Dict]:
        x, y = batch

        diff_params = state.params
        if explicit_reduce:
            diff_params = jax.tree_util.tree_map(
                lambda p: pcast(p, axis_name, to="varying"),
                diff_params)

        def scaled_loss_for(stats, x_mb, y_mb):
            def scaled_loss_fn(params):
                logits, new_stats = _apply_model(
                    model, params, stats, x_mb, train=True)
                loss = loss_fn(logits, y_mb)
                # amp.scale_loss: multiply before backward (§4.3).
                return amp_lib.scale_loss(loss, state.scaler), (
                    loss, logits, new_stats)
            return scaled_loss_fn

        # device_span (jax.named_scope): phase labels in xprof/tensorboard
        # traces (SURVEY.md §6 tracing row — the reference's nvtx range
        # annotations).  The labels come from obs.spans.PHASES so host-side
        # spans and the device timeline share one vocabulary.
        if grad_accum == 1:
            with device_span("fwd_bwd"):
                grads, (loss, logits, new_stats) = jax.grad(
                    scaled_loss_for(state.batch_stats, x, y),
                    has_aux=True)(diff_params)
            top1 = _batch_top1(logits, y) if (
                compute_accuracy and isinstance(y, jnp.ndarray)) else None
        else:
            k = grad_accum
            split = lambda a: a.reshape(k, a.shape[0] // k, *a.shape[1:])
            xk = jax.tree_util.tree_map(split, x)
            yk = jax.tree_util.tree_map(split, y)
            head = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            tail = lambda t: jax.tree_util.tree_map(lambda a: a[1:], t)

            def micro(stats, x_mb, y_mb):
                grads_mb, (loss_mb, logits_mb, stats) = jax.grad(
                    scaled_loss_for(stats, x_mb, y_mb),
                    has_aux=True)(diff_params)
                gf = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads_mb)
                t = (_batch_top1(logits_mb, y_mb)
                     if compute_accuracy and isinstance(y, jnp.ndarray)
                     else jnp.zeros((), jnp.float32))
                return stats, gf, loss_mb, t

            def body(carry, mb):
                stats, gsum, lsum, tsum = carry
                stats, gf, loss_mb, t = micro(stats, *mb)
                gsum = jax.tree_util.tree_map(jnp.add, gsum, gf)
                return (stats, gsum, lsum + loss_mb, tsum + t), None

            # Prologue: microbatch 0 runs outside the scan so the carry's
            # per-leaf shard-variance (vma) types are exactly those the body
            # produces — a zeros-init carry would be mesh-invariant while
            # grads/losses vary per shard (shard_map rejects the mismatch),
            # and blanket-casting it varying would erase the invariant typing
            # of implicitly-psummed grads that allreduce_grads relies on to
            # skip the double reduction.
            (new_stats, gsum, lsum, tsum), _ = jax.lax.scan(
                body, micro(state.batch_stats, *head((xk, yk))),
                tail((xk, yk)))
            grads = jax.tree_util.tree_map(
                lambda a, p: (a / k).astype(p.dtype), gsum, diff_params)
            loss = lsum / k
            top1 = tsum / k if (compute_accuracy and
                                isinstance(y, jnp.ndarray)) else None

        # DDP: reduce *scaled* grads, like the reference's backward-hook
        # allreduce; then unscale + finite-check (scale_loss __exit__).
        if axis_name is not None:
            with device_span("grad_allreduce"):
                grads = allreduce_grads(grads, ddp, axis_name)
                loss = jax.lax.pmean(loss, axis_name)
        with device_span("unscale_check"):
            grads, grads_finite = amp_lib.unscale_grads(grads, state.scaler)
            if finite_reduce_axes is not None:
                # all-or-none across shards: pmean == 1.0 is an AND, and
                # the collective makes the flag (and with it the scaler
                # update and skip decision) mesh-invariant.
                grads_finite = jax.lax.pmean(
                    grads_finite.astype(jnp.float32),
                    finite_reduce_axes) == 1.0

        with device_span("optimizer"):
            new_params, new_opt_state = opt.apply(grads, state.opt_state,
                                                  state.params)
        if policy.uses_dynamic_scaling:
            # Overflow ⇒ the whole update is skipped (params and optimizer
            # state keep their old values; BN stats are NOT rolled back —
            # apex updates them during forward regardless).
            new_params = amp_lib.select_tree(grads_finite, new_params,
                                            state.params)
            new_opt_state = amp_lib.select_tree(grads_finite, new_opt_state,
                                                state.opt_state)
        scaler = amp_lib.update_scaler(state.scaler, grads_finite)

        metrics = {"loss": loss, "scale": scaler.scale,
                   "grads_finite": grads_finite.astype(jnp.float32)}
        if finite_reduce_axes is None:
            # Post-unscale global grad norm, for the telemetry record (the
            # TXL step computes its own for clipping; this covers the image
            # and BERT/GPT steps).  Computed unconditionally, like the TXL
            # step's: the finite check above already reads every grad
            # element, so XLA fuses the square-sum into that same pass — no
            # extra HBM traffic.  Skipped under finite_reduce_axes: there
            # some grads are legitimately shard-varying (per-expert MoE
            # weights) and a naive global norm would be mesh-variant,
            # violating the replicated metrics out_spec.
            metrics["grad_norm"] = optax.global_norm(grads)
            if numerics:
                # Per-module overflow provenance, fused into the same
                # every-grad-element pass as the finite check above
                # (obs/numerics.py; host side reads it via the
                # NumericsMonitor when --numerics-check is on).
                metrics["numerics"] = numerics_lib.module_grad_stats(grads)
        # top1 only makes sense for integer-class labels; structured label
        # pytrees (e.g. BERT's (labels, weights)) must not silently broadcast
        # into a garbage metric.
        if top1 is not None:
            if axis_name is not None:
                top1 = jax.lax.pmean(top1, axis_name)
            metrics["top1"] = top1

        return TrainState(step=state.step + 1, params=new_params,
                          batch_stats=new_stats, opt_state=new_opt_state,
                          scaler=scaler), metrics

    return train_step


def _batch_top1(logits, y):
    return jnp.mean((jnp.argmax(logits, -1) == y)
                    .astype(jnp.float32)) * 100.0


def make_eval_step(model, loss_fn: Callable = cross_entropy_loss,
                   axis_name: Optional[str] = None):
    """Eval step with the reference harness's top-1/top-5 metrics
    (utils.meters.accuracy; SURVEY.md §3.5)."""
    from apex_example_tpu.utils.meters import accuracy

    def eval_step(state: TrainState, batch) -> Dict:
        x, y = batch
        logits, _ = _apply_model(model, state.params, state.batch_stats, x,
                                 train=False)
        loss = loss_fn(logits, y)
        k5 = min(5, logits.shape[-1])
        top1, top5 = accuracy(logits, y, topk=(1, k5))
        if axis_name is not None:
            loss = jax.lax.pmean(loss, axis_name)
            top1 = jax.lax.pmean(top1, axis_name)
            top5 = jax.lax.pmean(top5, axis_name)
        return {"loss": loss, "top1": top1, "top5": top5}
    return eval_step


def make_sharded_train_step(mesh: Mesh, model, optimizer, policy: Policy,
                            ddp: Optional[DDPConfig] = None,
                            loss_fn: Callable = cross_entropy_loss,
                            compute_accuracy: bool = True,
                            axis_name: str = DATA_AXIS,
                            donate: bool = True,
                            grad_accum: int = 1,
                            numerics: bool = False):
    """DDP train step: shard_map over the data axis, jitted, state donated.

    State is replicated (P()), the batch is split on axis 0.  Inside the
    shard, grads cross replicas via psum (allreduce_grads) so every replica
    computes the identical update — exactly DDP's contract.
    """
    per_shard = make_train_step(model, optimizer, policy, ddp=ddp,
                                axis_name=axis_name, loss_fn=loss_fn,
                                compute_accuracy=compute_accuracy,
                                grad_accum=grad_accum, numerics=numerics)

    def step_and_sync(state, batch):
        new_state, metrics = per_shard(state, batch)
        # BN running stats: SyncBatchNorm already produced identical stats on
        # every replica; plain (local) BatchNorm under DDP produces per-shard
        # stats, which must not silently diverge on replicated state — average
        # them (apex keeps rank-0's; the mean is the symmetric equivalent).
        synced = _replicate_mean(new_state.batch_stats, axis_name)
        return new_state.replace(batch_stats=synced), metrics

    # NOTE: vma checking stays ON (default).  With check_vma=False, psum's
    # transpose drops cross-replica cotangents and SyncBatchNorm's backward
    # silently loses the terms the reference all-reduces (sum_dy/sum_dy_xmu,
    # SURVEY.md §4.4) — verified by tests/test_parallel.py.
    sharded = _shard_map(
        step_and_sync, mesh=mesh,
        in_specs=(P(), (P(axis_name), P(axis_name))),
        out_specs=(P(), P()))
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def _zero_leaf_spec(spec: P, shape, axis_name: str, axis_size: int) -> P:
    """ZeRO-1 spec upgrade for one optimizer-state leaf: add ``axis_name``
    (the data axis) on the largest dim that is currently unsharded and
    divisible by the axis size, keeping whatever model-parallel sharding the
    param already carries on its other dims.  Leaves with no eligible dim
    (odd-sized biases) stay on the param's spec — they are the tail of the
    byte count, and correctness never depends on which leaves shard.
    """
    entries = list(spec) + [None] * (len(shape) - len(spec))
    best = -1
    for d, (e, n) in enumerate(zip(entries, shape)):
        if e is None and n > 0 and n % axis_size == 0 \
                and (best < 0 or n > shape[best]):
            best = d
    if best < 0:
        return spec
    entries[best] = axis_name
    return P(*entries)


def _opt_state_specs(optimizer, abs_params, param_specs, zero_spec_fn=None):
    """PartitionSpec tree for an optimizer state.

    The fused-optimizer states (AdamState etc.) are NamedTuples whose fields
    are either scalars or whole subtrees mirroring the params tree (mu/nu/
    momentum buffers): any node with the params' tree structure AND leaf
    shapes inherits the params' specs elementwise, everything else
    replicates.  The shape check matters: NovoGrad's ``nu`` mirrors the
    params TREE but holds per-tensor scalars — structure alone would hand
    its scalars the params' (possibly sharded) specs.  Recursion covers
    optax-style nested tuples of such states.

    ``zero_spec_fn(spec, shape) -> spec``, when given, rewrites each
    params-shaped leaf's spec — the ZeRO-1 hook that shards mu/nu over the
    data axis while the params themselves stay on their TP specs.
    """
    params_def = jax.tree_util.tree_structure(abs_params)
    param_leaves = jax.tree_util.tree_leaves(abs_params)
    abs_state = jax.eval_shape(optimizer.init, abs_params)

    def params_shaped(node):
        if jax.tree_util.tree_structure(node) != params_def:
            return False
        return all(getattr(l, "shape", None) == p.shape
                   for l, p in zip(jax.tree_util.tree_leaves(node),
                                   param_leaves))

    def walk(node):
        if params_shaped(node):
            if zero_spec_fn is None:
                return param_specs
            return jax.tree_util.tree_map(
                lambda sp, p: zero_spec_fn(sp, p.shape),
                param_specs, abs_params,
                is_leaf=lambda v: isinstance(v, P))
        if isinstance(node, tuple):
            sub = [walk(c) for c in node]
            # NamedTuple ctors take fields positionally; plain tuples take
            # one iterable.
            return type(node)(*sub) if hasattr(node, "_fields") \
                else tuple(sub)
        if isinstance(node, (list,)):
            return [walk(c) for c in node]
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return P()                           # scalar / unrecognized leaf
    return walk(abs_state)


def gspmd_state_shardings(mesh: Mesh, model, optimizer, sample_batch,
                          policy: Policy, scaler=None,
                          train_kwargs: Optional[dict] = None,
                          zero_axis: Optional[str] = None) -> TrainState:
    """NamedSharding pytree for this model's TrainState under GSPMD.

    Param specs come from the flax partitioning metadata the TP layers
    attach (``nn.with_partitioning``); optimizer-state subtrees mirror
    them; step/scaler/batch_stats replicate.  Feed the result to
    jit ``in_shardings``/``out_shardings`` (prefix semantics: a bare P()
    stands for a replicated subtree).

    ``zero_axis``: ZeRO-1 under GSPMD — the *annotate, don't orchestrate*
    form of the reference's distributed_fused_adam (SURVEY.md §3.4 contrib
    row, §3.3 weight-update sharding).  Optimizer-state leaves additionally
    shard over this (data) axis on a free dim while params keep their TP
    specs: the partitioner then stores mu/nu distributed (1/N bytes per
    device), slices the Adam update over ``data``, and all-gathers the new
    params back to their param sharding — reduce-scatter(grads) + sharded
    update + all-gather(params), derived from the sharding lattice instead
    of hand-written collectives, and composing with tensor parallelism
    because ``data`` and ``model`` are independent mesh axes.
    """
    import flax.linen as nn
    from flax.core import meta

    init = lambda r: model.init(r, sample_batch,
                                **(train_kwargs or {"train": False}))
    abs_vars = jax.eval_shape(init, jax.random.PRNGKey(0))
    specs = nn.get_partition_spec(abs_vars)
    param_specs = specs["params"]
    abs_params = meta.unbox(abs_vars)["params"]
    zfn = None
    if zero_axis is not None:
        axis_size = mesh.shape[zero_axis]
        zfn = lambda sp, shape: _zero_leaf_spec(sp, shape, zero_axis,
                                                axis_size)
    spec_state = TrainState(
        step=P(), params=param_specs, batch_stats=P(),
        opt_state=_opt_state_specs(optimizer, abs_params, param_specs,
                                   zero_spec_fn=zfn),
        scaler=P())
    to_sharding = lambda s: NamedSharding(mesh, s)
    return jax.tree_util.tree_map(to_sharding, spec_state,
                                  is_leaf=lambda v: isinstance(v, P))


def create_gspmd_train_state(rng, mesh: Mesh, model, optimizer, sample_batch,
                             policy: Policy, scaler=None,
                             train_kwargs: Optional[dict] = None,
                             zero_axis: Optional[str] = None):
    """(state, state_shardings): TrainState initialized directly into its
    GSPMD placement — params/optimizer state land sharded (no host-side
    full materialization beyond tracing).  ``zero_axis``: see
    :func:`gspmd_state_shardings` (ZeRO-1 optimizer-state sharding)."""
    shardings = gspmd_state_shardings(mesh, model, optimizer, sample_batch,
                                      policy, scaler, train_kwargs,
                                      zero_axis=zero_axis)
    init = jax.jit(
        lambda r: create_train_state(r, model, optimizer, sample_batch,
                                     policy, scaler, train_kwargs),
        out_shardings=shardings)
    return init(rng), shardings


def make_gspmd_train_step(mesh: Mesh, model, optimizer, policy: Policy,
                          state_shardings: TrainState,
                          loss_fn: Callable = cross_entropy_loss,
                          compute_accuracy: bool = True,
                          donate: bool = True,
                          grad_accum: int = 1,
                          numerics: bool = False):
    """Tensor/sequence-parallel train step — the *annotate, don't
    orchestrate* counterpart of :func:`make_sharded_train_step`.

    The per-example program is the plain single-device step; parallelism
    comes entirely from shardings: params carry the TP layers' partitioning
    metadata (column/row/vocab over ``model``), the batch shards over
    ``data``, and GSPMD inserts the Megatron collectives (all-gather /
    reduce-scatter / all-reduce on ICI) at the layers' constraint points.
    Reference: apex.transformer's explicit f/g autograd functions
    (SURVEY.md §3.2) — here they are compiler-derived from the sharding
    lattice.  Gradient reduction over ``data`` needs no collective in the
    program: under jit the batch is one logical array, so the grads ARE the
    global grads.

    Requires the mesh registered via ``parallel_state.set_mesh`` (or
    ``initialize_model_parallel``) at trace time, so the models'
    ``constrain`` points bind to it.  On multi-chip TPU runs combine with
    ``ops._config.set_force_xla(True)``: pallas custom calls are opaque to
    the SPMD partitioner, the XLA reference forms partition cleanly.
    """
    step = make_train_step(model, optimizer, policy, axis_name=None,
                           loss_fn=loss_fn,
                           compute_accuracy=compute_accuracy,
                           grad_accum=grad_accum, numerics=numerics)
    batch_sh = NamedSharding(mesh, P(DATA_AXIS))
    metrics_sh = NamedSharding(mesh, P())
    return jax.jit(step,
                   in_shardings=(state_shardings, batch_sh),
                   out_shardings=(state_shardings, metrics_sh),
                   donate_argnums=(0,) if donate else ())


def _replicate_mean(tree, axis_name: str):
    """pmean that accepts both replicated and shard-varying leaves."""
    if not jax.tree_util.tree_leaves(tree):
        return tree
    world = axis_size(axis_name)

    def f(x):
        if axis_name not in vma_of(x):  # replicated leaf (SyncBN stats)
            x = pcast(x, axis_name, to="varying")
        return jax.lax.psum(x, axis_name) / world

    return jax.tree_util.tree_map(f, tree)


def _wrap_optimizer(optimizer):
    """Accept fused optimizers (init/apply) or optax transforms."""
    if hasattr(optimizer, "apply") and hasattr(optimizer, "init"):
        return optimizer

    class _OptaxAdapter:
        def __init__(self, tx):
            self.tx = tx

        def init(self, params):
            return self.tx.init(params)

        def apply(self, grads, opt_state, params):
            updates, new_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_state

    return _OptaxAdapter(optimizer)
