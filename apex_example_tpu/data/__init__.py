from apex_example_tpu.data.synthetic import (
    CIFAR10, IMAGENET, SyntheticLoader, image_batch, lm_batch, mlm_batch)

__all__ = ["CIFAR10", "IMAGENET", "SyntheticLoader", "image_batch",
           "lm_batch", "mlm_batch"]
