"""Synthetic, procedurally generated datasets.

This environment has no datasets on disk and no network (SURVEY.md §5 item 6),
so every workload runs on deterministic synthetic data shaped like its real
counterpart:

- :func:`image_batch` — CIFAR-/ImageNet-shaped classification batches.  Images
  are a class-dependent low-frequency pattern plus noise, so models genuinely
  learn (loss curves fall, accuracy rises) and convergence tests are
  meaningful, while generation stays cheap enough for 1 CPU core.
- :func:`lm_batch` — token streams with affine bigram structure
  (``t+1 = (a·t + b) mod V`` with noise) for Transformer-XL style causal LM.
- :func:`mlm_batch` — BERT-style masked-LM batches (15% masking: 80/10/10)
  over the same learnable streams.

All generators are pure ``jax`` functions of ``(seed, step)`` — they can run
jitted *on device*, which is how the benchmark harness isolates device
throughput from the (single-core) host input pipeline, mirroring the
reference's CUDA-stream prefetcher intent (SURVEY.md §3.5) the TPU way.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Shapes for the reference's workload matrix (BASELINE.json configs).
CIFAR10 = dict(image_size=32, channels=3, num_classes=10)
IMAGENET = dict(image_size=224, channels=3, num_classes=1000)


def _class_patterns(num_classes: int, image_size: int, channels: int,
                    seed: int) -> jnp.ndarray:
    """Fixed low-res per-class patterns, upsampled — the learnable signal."""
    key = jax.random.PRNGKey(seed)
    low = jax.random.normal(key, (num_classes, 8, 8, channels), jnp.float32)
    return jax.image.resize(
        low, (num_classes, image_size, image_size, channels), "bilinear")


@functools.partial(jax.jit, static_argnames=(
    "batch_size", "image_size", "channels", "num_classes", "seed",
    "label_noise"))
def image_batch(step: jnp.ndarray, *, batch_size: int, image_size: int = 32,
                channels: int = 3, num_classes: int = 10, seed: int = 0,
                noise: float = 0.5, label_noise: float = 0.0
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (images NHWC f32 ~N(0,1)-ish, labels i32).

    ``label_noise=p`` replaces each label with a uniform class with
    probability p (images keep their clean-class pattern), imposing an
    irreducible error: best-achievable top-1 is (1−p)+p/C.  The accuracy
    harness uses it to keep the task un-saturated, so an fp32-vs-amp gap is
    measured mid-range instead of trivially at 100% (SURVEY.md §7
    acceptance).
    """
    pats = _class_patterns(num_classes, image_size, channels, seed)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    if label_noise == 0.0:
        # Static arg, resolved at trace time — and the split stays 2-way so
        # the label_noise=0 stream is bit-identical to earlier rounds'
        # recorded artifacts (threefry split(key, n) depends on n).
        k1, k2 = jax.random.split(key)
        labels = jax.random.randint(k1, (batch_size,), 0, num_classes)
        imgs = pats[labels] + noise * jax.random.normal(
            k2, (batch_size, image_size, image_size, channels), jnp.float32)
        return imgs, labels
    k1, k2, k3, k4 = jax.random.split(key, 4)
    labels = jax.random.randint(k1, (batch_size,), 0, num_classes)
    imgs = pats[labels] + noise * jax.random.normal(
        k2, (batch_size, image_size, image_size, channels), jnp.float32)
    flip = jax.random.bernoulli(k3, label_noise, (batch_size,))
    rand = jax.random.randint(k4, (batch_size,), 0, num_classes)
    return imgs, jnp.where(flip, rand, labels)


@functools.partial(jax.jit, static_argnames=(
    "batch_size", "seq_len", "vocab_size", "seed"))
def lm_batch(step: jnp.ndarray, *, batch_size: int, seq_len: int,
             vocab_size: int, seed: int = 0,
             noise_p: float = 0.1) -> jnp.ndarray:
    """Token sequences (B, L+1) with affine-bigram structure; callers slice
    inputs = [:, :-1], targets = [:, 1:]."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed ^ 0x5eed), step)
    k0, kn, kr = jax.random.split(key, 3)
    a, b = 31, 17  # coprime with typical vocab sizes → full-cycle bigram map
    t0 = jax.random.randint(k0, (batch_size,), 0, vocab_size)

    def next_tok(t, k):
        clean = (a * t + b) % vocab_size
        rand = jax.random.randint(k, t.shape, 0, vocab_size)
        flip = jax.random.bernoulli(jax.random.fold_in(k, 1), noise_p,
                                    t.shape)
        nxt = jnp.where(flip, rand, clean)
        return nxt, nxt

    keys = jax.random.split(kn, seq_len)
    _, toks = jax.lax.scan(next_tok, t0, keys)
    del kr
    return jnp.concatenate([t0[:, None], toks.T], axis=1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "batch_size", "seq_len", "vocab_size", "seed", "mask_token_id"))
def mlm_batch(step: jnp.ndarray, *, batch_size: int, seq_len: int,
              vocab_size: int, mask_token_id: int, seed: int = 0,
              mask_prob: float = 0.15):
    """BERT-style MLM batch: (input_ids, labels, weights).

    labels hold the original token everywhere; weights are 1.0 at masked
    positions (the only positions that contribute to the loss).  Masked
    positions get [MASK] 80% / random 10% / unchanged 10%.
    """
    toks = lm_batch(step, batch_size=batch_size, seq_len=seq_len - 1,
                    vocab_size=vocab_size, seed=seed)
    key = jax.random.fold_in(jax.random.PRNGKey(seed + 101), step)
    k1, k2, k3 = jax.random.split(key, 3)
    is_masked = jax.random.bernoulli(k1, mask_prob, toks.shape)
    u = jax.random.uniform(k2, toks.shape)
    rand_tok = jax.random.randint(k3, toks.shape, 0, vocab_size)
    inputs = jnp.where(is_masked & (u < 0.8), mask_token_id, toks)
    inputs = jnp.where(is_masked & (u >= 0.8) & (u < 0.9), rand_tok, inputs)
    return (inputs.astype(jnp.int32), toks.astype(jnp.int32),
            is_masked.astype(jnp.float32))


class SyntheticLoader:
    """Host-side iterator facade (DataLoader+DistributedSampler analog).

    ``shard``/``num_shards`` reproduce DistributedSampler semantics: each
    shard folds its index into the seed so replicas see disjoint streams.
    Iteration yields device arrays; for peak throughput use the jitted batch
    functions directly inside the step (see harness/bench).
    """

    def __init__(self, kind: str = "image", steps_per_epoch: int = 100,
                 shard: int = 0, num_shards: int = 1, **kw):
        self.kind, self.steps = kind, steps_per_epoch
        self.kw = dict(kw)
        self.kw["seed"] = self.kw.get("seed", 0) * num_shards + shard

    def __iter__(self):
        for i in range(self.steps):
            step = jnp.asarray(i, jnp.int32)
            if self.kind == "image":
                yield image_batch(step, **self.kw)
            elif self.kind == "lm":
                yield lm_batch(step, **self.kw)
            elif self.kind == "mlm":
                yield mlm_batch(step, **self.kw)
            else:
                raise ValueError(self.kind)

    def __len__(self):
        return self.steps
