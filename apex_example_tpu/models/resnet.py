"""ResNet-18/50 in Flax (torchvision-architecture parity).

The reference imports ``torchvision.models.resnet{18,50}`` rather than
implementing them (SURVEY.md §3.5), so the parity target is the torchvision
architecture: 7×7/2 stem + 3×3 maxpool, BasicBlock (18) / Bottleneck (50)
stages [2,2,2,2] / [3,4,6,3], stride-2 downsample convs, final FC.

TPU-native specifics:
- NHWC layout throughout (TPU conv layout; torch is NCHW — the harness's data
  generators produce NHWC directly).
- ``dtype``/``param_dtype`` thread the amp policy: convs/dense run in
  ``dtype`` (bf16 under O2), params stored in ``param_dtype`` (fp32 masters).
- Normalization is :class:`SyncBatchNorm` with torch momentum/eps semantics;
  ``bn_axis_name`` switches on cross-replica stats (the
  ``convert_syncbn_model`` hook), and ``bn_dtype`` realizes
  ``keep_batchnorm_fp32``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from apex_example_tpu.parallel.sync_batchnorm import SyncBatchNorm

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 (self.strides, self.strides),
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 (self.strides, self.strides),
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32          # compute dtype (policy)
    param_dtype: jnp.dtype = jnp.float32
    bn_dtype: Optional[jnp.dtype] = None    # None: follow dtype (O3)
    bn_axis_name: Optional[str] = None      # "data" => SyncBatchNorm
    bn_momentum: float = 0.1
    small_stem: bool = False                # CIFAR-style 3x3 stem (optional)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, padding="SAME",
                                 dtype=self.dtype,
                                 param_dtype=self.param_dtype,
                                 kernel_init=nn.initializers.he_normal())
        norm = functools.partial(
            SyncBatchNorm,
            use_running_average=not train,
            axis_name=self.bn_axis_name,
            momentum=self.bn_momentum,
            epsilon=1e-5,
            dtype=self.bn_dtype or self.dtype,
            param_dtype=jnp.float32)

        x = x.astype(self.dtype)
        if self.small_stem:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i, strides,
                                   conv=conv, norm=norm)(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="fc")(x)
        # Classifier output in fp32 (loss is computed fp32 under every opt
        # level; reference computes criterion on .float() output).
        return x.astype(jnp.float32)


def resnet18(**kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck, **kw)


ARCHS = {"resnet18": resnet18, "resnet50": resnet50}
