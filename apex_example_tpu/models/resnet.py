"""ResNet-18/50 in Flax (torchvision-architecture parity).

The reference imports ``torchvision.models.resnet{18,50}`` rather than
implementing them (SURVEY.md §3.5), so the parity target is the torchvision
architecture: 7×7/2 stem + 3×3 maxpool, BasicBlock (18) / Bottleneck (50)
stages [2,2,2,2] / [3,4,6,3], stride-2 downsample convs, final FC.

TPU-native specifics:
- NHWC layout throughout (TPU conv layout; torch is NCHW — the harness's data
  generators produce NHWC directly).
- ``dtype``/``param_dtype`` thread the amp policy: convs/dense run in
  ``dtype`` (bf16 under O2), params stored in ``param_dtype`` (fp32 masters).
- Normalization is :class:`SyncBatchNorm` with torch momentum/eps semantics;
  ``bn_axis_name`` switches on cross-replica stats (the
  ``convert_syncbn_model`` hook), and ``bn_dtype`` realizes
  ``keep_batchnorm_fp32``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from flax import linen as nn

from apex_example_tpu.parallel.sync_batchnorm import SyncBatchNorm

ModuleDef = Any

# Residual-selection experiments for the memory-bound backward (PERF.md):
# tag conv outputs so a checkpoint policy can pin exactly them as the saved
# set — BN normalize + ReLU are then REMATERIALIZED in backward instead of
# their outputs being stored/reloaded through HBM.  checkpoint_name is an
# identity outside a remat region.
_CONV_OUT = "conv_out"


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = checkpoint_name(y, _CONV_OUT)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = checkpoint_name(y, _CONV_OUT)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 (self.strides, self.strides),
                                 name="downsample_conv")(residual)
            residual = checkpoint_name(residual, _CONV_OUT)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = checkpoint_name(y, _CONV_OUT)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = checkpoint_name(y, _CONV_OUT)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = checkpoint_name(y, _CONV_OUT)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 (self.strides, self.strides),
                                 name="downsample_conv")(residual)
            residual = checkpoint_name(residual, _CONV_OUT)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class SpaceToDepthStem(nn.Module):
    """The 7×7/2 stem conv computed via space-to-depth (MLPerf TPU trick).

    A 3-channel 224×224 input wastes the MXU's 128-wide lane dimension
    (3 of 128 lanes) and runs the stem at <450 GiB/s (profiled).  Rearranged
    as 2×2 blocks → a 112×112×12 input, the same convolution becomes a 4×4/1
    conv over 12 channels.  The parameter is *still* the (7,7,C,F) kernel —
    padded to 8×8 and rearranged in-graph (free: it's a tiny tensor) — so the
    param tree, init order, and checkpoints are identical to the plain stem,
    and the output is mathematically equal (tested in test_models.py).
    """

    features: int = 64
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        import jax.lax as lax
        c = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.he_normal(),
                            (7, 7, c, self.features), self.param_dtype)
        # 7×7 stride-2 SAME on even H needs pad (2,3); one extra zero row/col
        # of both image and kernel makes the footprint 8×8, which tiles
        # exactly into 2×2 space-to-depth blocks.
        xp = jnp.pad(x, ((0, 0), (2, 4), (2, 4), (0, 0)))
        b, h, w, _ = xp.shape
        s = xp.reshape(b, h // 2, 2, w // 2, 2, c)
        s = s.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
        k8 = jnp.pad(kernel.astype(jnp.float32), ((0, 1), (0, 1), (0, 0),
                                                  (0, 0)))
        k4 = k8.reshape(4, 2, 4, 2, c, self.features)
        k4 = k4.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                    self.features)
        return lax.conv_general_dilated(
            s.astype(self.dtype), k4.astype(self.dtype), (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32          # compute dtype (policy)
    param_dtype: jnp.dtype = jnp.float32
    bn_dtype: Optional[jnp.dtype] = None    # None: follow dtype (O3)
    # BN input/output dtype; None follows ``dtype``.  O1 (op-classification:
    # batch_norm is blacklisted) sets this to fp32 so the norm runs wholly in
    # fp32 while convs stay half — see amp/autocast.module_dtypes.
    bn_io_dtype: Optional[jnp.dtype] = None
    bn_axis_name: Optional[str] = None      # "data" => SyncBatchNorm
    bn_momentum: float = 0.1
    small_stem: bool = False                # CIFAR-style 3x3 stem (optional)
    # Equivalent 4×4×12 stem (MLPerf space-to-depth).  Measured on v5e-1 it
    # LOST ~3.5 ms/step (the rearrangement's backward outweighs the stem-conv
    # gain at this batch), so the default stays the plain 7×7 stem; the
    # option (and its equivalence proof in test_models.py) remain available.
    stem_space_to_depth: bool = False
    # Rematerialization experiments for the HBM-bound backward (PERF.md
    # byte accounting; jax.checkpoint — the reference has no analog, its
    # equivalent is torch.utils.checkpoint which apex never integrates):
    #   "none"  — XLA chooses the saved set (default).
    #   "conv"  — save ONLY conv outputs per block; BN normalize + ReLU are
    #             recomputed in backward (drops the stored x̂/ReLU
    #             activations the BN-backward fusions otherwise reload).
    #   "block" — save only block inputs; the whole block forward is
    #             recomputed in backward (max traffic cut, max recompute).
    remat: str = "none"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, padding="SAME",
                                 dtype=self.dtype,
                                 param_dtype=self.param_dtype,
                                 kernel_init=nn.initializers.he_normal())
        norm = functools.partial(
            SyncBatchNorm,
            use_running_average=not train,
            axis_name=self.bn_axis_name,
            momentum=self.bn_momentum,
            epsilon=1e-5,
            # I/O in the compute dtype (fuses with the bf16 conv chain);
            # moments/normalization in bn_dtype — keep_batchnorm_fp32 the
            # way the reference's cuDNN path actually does it.  Under O1
            # bn_io_dtype=fp32 blacklists the whole op instead.
            dtype=self.bn_io_dtype or self.dtype,
            stats_dtype=self.bn_dtype or self.dtype,
            param_dtype=jnp.float32)

        x = x.astype(self.dtype)
        if self.small_stem:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
        else:
            if (self.stem_space_to_depth and x.shape[1] % 2 == 0
                    and x.shape[2] % 2 == 0):
                x = SpaceToDepthStem(self.num_filters, dtype=self.dtype,
                                     param_dtype=self.param_dtype,
                                     name="conv_init")(x)
            else:
                x = conv(self.num_filters, (7, 7), (2, 2),
                         name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        block_cls = self.block_cls
        if self.remat == "block":
            block_cls = nn.remat(block_cls, prevent_cse=False)
        elif self.remat == "conv":
            block_cls = nn.remat(
                block_cls, prevent_cse=False,
                policy=jax.checkpoint_policies.save_only_these_names(
                    _CONV_OUT))
        elif self.remat != "none":
            raise ValueError(f"remat must be none|conv|block, got "
                             f"{self.remat!r}")
        n = 0
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                # Explicit name: nn.remat's wrapper class would otherwise
                # auto-name modules "CheckpointBottleneck_i", changing param
                # paths (and so init RNG streams / checkpoint layout) vs the
                # non-remat model.  Pinning the default-style name keeps
                # remat a pure backward-schedule choice.
                x = block_cls(self.num_filters * 2 ** i, strides,
                              conv=conv, norm=norm,
                              name=f"{self.block_cls.__name__}_{n}")(x)
                n += 1

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="fc")(x)
        # Classifier output in fp32 (loss is computed fp32 under every opt
        # level; reference computes criterion on .float() output).
        return x.astype(jnp.float32)


def resnet18(**kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock, **kw)


def resnet34(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck, **kw)


def resnet101(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck, **kw)


def resnet152(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 8, 36, 3], block_cls=Bottleneck, **kw)


# The torchvision family surface the reference's --arch flag can name
# (SURVEY.md §3.5: models are imported from torchvision in the reference;
# stage sizes follow the He et al. table).
ARCHS = {"resnet18": resnet18, "resnet34": resnet34, "resnet50": resnet50,
         "resnet101": resnet101, "resnet152": resnet152}
