"""Transformer-XL for causal LM (workload C5, SURVEY.md §1/§6).

The reference's long-sequence capability is *algorithmic*: Transformer-XL's
segment-level recurrence (cached, stop-gradient hidden states as extended
context) plus relative positional encodings — not sequence-dim communication
(SURVEY.md §3.2: CP/ring absent in the reference family).  This module
implements that algorithm natively in Flax:

- The memory is a fixed-shape carry ``(num_layers, B, mem_len, d)`` threaded
  through the step function — jit-stable, donatable, and it composes with the
  DDP shard_map (memory is per-replica activation state, sharded on batch).
- Relative attention uses the content/position bias decomposition with the
  standard rel-shift realized via gather-free slicing (static shapes only).
- FusedLayerNorm (Pallas) everywhere; softmax in fp32 per amp op rules.

Architecture follows the canonical Transformer-XL base: pre-LN off (post-norm
like the original), learnable per-head content/position biases shared across
layers is a variant choice — we keep them per-layer (original paper setup).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from apex_example_tpu.normalization import FusedLayerNorm


def rel_shift(x: jnp.ndarray) -> jnp.ndarray:
    """Relative-position shift: (..., qlen, klen) scores indexed by distance.

    Standard TXL trick: pad one column, reshape, drop — converts position-
    indexed logits into distance-indexed alignment with static shapes.
    """
    *lead, q, k = x.shape
    x = jnp.pad(x, [(0, 0)] * len(lead) + [(0, 0), (1, 0)])
    x = x.reshape(*lead, k + 1, q)
    x = x[..., 1:, :]
    return x.reshape(*lead, q, k)


class RelMultiHeadAttn(nn.Module):
    d_model: int
    num_heads: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    softmax_dtype: jnp.dtype = jnp.float32   # blacklist op; O3 runs it half
    # Megatron TP (GSPMD form, same contract as models/bert.py): q/k/v/r
    # column-parallel (heads shard over 'model'), o row-parallel, the
    # (h, hd) rel-position biases sharded on h.  Param names/shapes match
    # the dense path — checkpoints interchange.
    tensor_parallel: bool = False

    @nn.compact
    def __call__(self, x, mem, pos_emb):
        """x: (B, q, d); mem: (B, m, d); pos_emb: (q+m, d) for distances
        [q+m-1 ... 0]."""
        b, qlen, d = x.shape
        mlen = mem.shape[1]
        klen = qlen + mlen
        h, hd = self.num_heads, self.d_model // self.num_heads

        if self.tensor_parallel:
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                ColumnParallelLinear, batch_axis, constrain)
            ba = batch_axis()
            dense_in = lambda name: ColumnParallelLinear(
                d, use_bias=False, gather_output=False, dtype=self.dtype,
                param_dtype=self.param_dtype, name=name)
            # heads shard over 'model' after the (…, d)->(…, h, hd) reshape
            hspec = lambda t: constrain(
                t, *(([ba] if t.ndim == 4 else []) + [None, "model", None]))
            bias_init = nn.with_partitioning(nn.initializers.zeros,
                                             ("model", None))
        else:
            dense_in = lambda name: nn.Dense(
                d, use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype, name=name)
            hspec = lambda t: t
            bias_init = nn.initializers.zeros

        cat = jnp.concatenate([mem.astype(x.dtype), x], axis=1)
        q = hspec(dense_in("q")(x).reshape(b, qlen, h, hd))
        k = hspec(dense_in("k")(cat).reshape(b, klen, h, hd))
        v = hspec(dense_in("v")(cat).reshape(b, klen, h, hd))
        r = hspec(dense_in("r")(pos_emb.astype(self.dtype))
                  .reshape(klen, h, hd))

        u = self.param("u_bias", bias_init, (h, hd),
                       self.param_dtype).astype(self.dtype)
        w = self.param("v_bias", bias_init, (h, hd),
                       self.param_dtype).astype(self.dtype)

        # content score AC: (q + u) · k ; position score BD: (q + v) · r
        ac = jnp.einsum("bqhd,bkhd->bhqk", q + u, k)
        bd = jnp.einsum("bqhd,khd->bhqk", q + w, r)
        bd = rel_shift(bd)
        sd = self.softmax_dtype
        logits = (ac + bd).astype(sd) / jnp.asarray(jnp.sqrt(hd), sd)

        # causal mask with memory: query i attends keys [0 .. mlen+i]
        qi = jnp.arange(qlen)[:, None]
        kj = jnp.arange(klen)[None, :]
        causal = kj <= (qi + mlen)
        neg = jnp.asarray(-1e30 if sd == jnp.float32 else -1e4, sd)
        logits = jnp.where(causal[None, None], logits, neg)

        probs = jax.nn.softmax(logits, axis=-1).astype(self.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, qlen, d)
        if self.tensor_parallel:
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                RowParallelLinear)
            return RowParallelLinear(d, use_bias=False,
                                     input_is_parallel=True,
                                     dtype=self.dtype,
                                     param_dtype=self.param_dtype,
                                     name="o")(ctx)
        return nn.Dense(d, use_bias=False, dtype=self.dtype,
                        param_dtype=self.param_dtype, name="o")(ctx)


class TXLLayer(nn.Module):
    d_model: int
    num_heads: int
    d_inner: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    ln_dtype: Optional[jnp.dtype] = None     # LN I/O; None follows dtype
    softmax_dtype: jnp.dtype = jnp.float32
    tensor_parallel: bool = False

    @nn.compact
    def __call__(self, x, mem, pos_emb):
        ln_io = self.ln_dtype or self.dtype
        a = RelMultiHeadAttn(self.d_model, self.num_heads, self.dtype,
                             self.param_dtype, self.softmax_dtype,
                             tensor_parallel=self.tensor_parallel,
                             name="attn")(x, mem, pos_emb)
        x = FusedLayerNorm(dtype=ln_io, name="attn_ln")(
            (x + a).astype(ln_io)).astype(self.dtype)
        if self.tensor_parallel:
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                ColumnParallelLinear, RowParallelLinear)
            y = ColumnParallelLinear(self.d_inner, gather_output=False,
                                     dtype=self.dtype,
                                     param_dtype=self.param_dtype,
                                     name="ff1")(x)
            y = nn.relu(y)
            y = RowParallelLinear(self.d_model, input_is_parallel=True,
                                  dtype=self.dtype,
                                  param_dtype=self.param_dtype,
                                  name="ff2")(y)
        else:
            y = nn.Dense(self.d_inner, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ff1")(x)
            y = nn.relu(y)
            y = nn.Dense(self.d_model, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ff2")(y)
        x = FusedLayerNorm(dtype=ln_io, name="ff_ln")(
            (x + y).astype(ln_io)).astype(self.dtype)
        return x


class TransformerXL(nn.Module):
    """Returns (logits, new_mems); mems: (num_layers, B, mem_len, d_model).

    Call with ``mems=None`` to start a document (zeros); thread the returned
    mems through subsequent segments.  New memories are stop-gradient (the
    reference behavior: cached states receive no gradient).
    """

    vocab_size: int = 267735        # WikiText-103 vocab (synthetic runs use less)
    d_model: int = 410
    num_layers: int = 16
    num_heads: int = 10
    d_inner: int = 2100
    mem_len: int = 150
    clamp_len: int = 1000
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    ln_dtype: Optional[jnp.dtype] = None
    softmax_dtype: jnp.dtype = jnp.float32
    # Megatron TP over the GSPMD 'model' mesh axis (same contract as
    # models/bert.py): vocab-sharded embedding + tied parallel LM head,
    # column/row attention (incl. the r projection and u/v biases) and FFN.
    tensor_parallel: bool = False

    def init_mems(self, batch_size: int) -> jnp.ndarray:
        return jnp.zeros((self.num_layers, batch_size, self.mem_len,
                          self.d_model), self.dtype)

    @nn.compact
    def __call__(self, input_ids, mems: Optional[jnp.ndarray] = None,
                 train: bool = True
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        del train
        b, qlen = input_ids.shape
        if mems is None:
            mems = self.init_mems(b)
        mlen = mems.shape[2]
        klen = qlen + mlen

        if self.tensor_parallel:
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                VocabParallelEmbedding)
            emb = VocabParallelEmbedding(self.vocab_size, self.d_model,
                                         dtype=self.dtype,
                                         param_dtype=self.param_dtype,
                                         name="word_emb")
        else:
            emb = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                           param_dtype=self.param_dtype, name="word_emb")
        x = emb(input_ids) * jnp.sqrt(self.d_model).astype(self.dtype)

        # Sinusoidal relative position encodings for distances klen-1 .. 0.
        pos_seq = jnp.arange(klen - 1, -1, -1.0)
        if self.clamp_len > 0:
            pos_seq = jnp.minimum(pos_seq, self.clamp_len)
        inv_freq = 1.0 / (10000.0 ** (jnp.arange(0, self.d_model, 2.0)
                                      / self.d_model))
        sinusoid = pos_seq[:, None] * inv_freq[None, :]
        pos_emb = jnp.concatenate([jnp.sin(sinusoid), jnp.cos(sinusoid)],
                                  axis=-1)

        new_mems = []
        for i in range(self.num_layers):
            # Cache the layer INPUT (reference behavior), truncated to
            # mem_len, gradient-stopped.
            cat = jnp.concatenate([mems[i], x], axis=1)
            new_mems.append(jax.lax.stop_gradient(cat[:, -self.mem_len:]))
            x = TXLLayer(self.d_model, self.num_heads, self.d_inner,
                         self.dtype, self.param_dtype, self.ln_dtype,
                         self.softmax_dtype,
                         tensor_parallel=self.tensor_parallel,
                         name=f"layer_{i}")(x, mems[i], pos_emb)

        logits = emb.attend(x).astype(jnp.float32)
        return logits, jnp.stack(new_mems)


def transformer_xl_base(**kw) -> TransformerXL:
    return TransformerXL(**kw)


def transformer_xl_tiny(**kw) -> TransformerXL:
    kw.setdefault("vocab_size", 256)
    kw.setdefault("d_model", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("d_inner", 128)
    kw.setdefault("mem_len", 16)
    return TransformerXL(**kw)
