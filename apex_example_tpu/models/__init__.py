"""Model zoo (the reference imports these from torchvision/external repos;
SURVEY.md §3.5 — here they are implemented natively in Flax)."""

from apex_example_tpu.models.gpt import (GPTForCausalLM, generate,
                                         gpt_base, gpt_tiny)
from apex_example_tpu.models.resnet import (ARCHS, ResNet, resnet18,
                                            resnet34, resnet50, resnet101,
                                            resnet152)

__all__ = ["ARCHS", "GPTForCausalLM", "ResNet", "generate", "gpt_base",
           "gpt_tiny",
           "resnet18", "resnet34", "resnet50", "resnet101", "resnet152"]
