"""BERT-base for masked-LM pretraining (workload C4, SURVEY.md §1).

The reference imports BERT from an external repo and exercises apex on it
(amp-O2 + FusedLAMB, BASELINE.json config 4); the parity target is the
standard BERT-base architecture: learned word+position+type embeddings with
post-embedding LayerNorm, 12 post-norm encoder layers (self-attention + GELU
FFN, hidden 768, heads 12, FFN 3072), and an MLM head whose decoder is tied
to the word embeddings.

TPU-native specifics:
- All LayerNorms are :class:`FusedLayerNorm` (the Pallas kernel — fp32 stats
  regardless of compute dtype, the MixedFusedLayerNorm contract).
- ``dtype``/``param_dtype`` thread the amp policy; attention logits and
  softmax run in fp32 (the op-classification "blacklist" of amp O1/O2:
  softmax is fp32; SURVEY.md §3.1).
- Static shapes throughout; the attention mask is an additive bias, so the
  whole step stays jit-compatible.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
from flax import linen as nn

from apex_example_tpu.normalization import FusedLayerNorm

# Measured fused-vs-XLA crossover on the v5e rig (PERF.md attention table):
# the flash kernel loses below ~2k tokens (XLA's fusions keep the small
# score tensor cheap; the kernel adds launch/blocking overhead) and wins
# above (O(S·D) HBM vs the naive path's O(S²) probability tensor).
FLASH_AUTO_MIN_SEQ = 2048


def _resolve_fused_attention(setting: Union[bool, str], seq_len: int,
                             softmax_dtype) -> bool:
    """The fused_attention policy: explicit bool wins; "auto" keys on the
    measured crossover.  The kernel's softmax is always fp32, so any
    half-softmax contract (O3) forces the naive path."""
    if softmax_dtype != jnp.float32:
        return False
    if isinstance(setting, bool):
        return setting
    if setting == "auto":
        return seq_len >= FLASH_AUTO_MIN_SEQ
    raise ValueError(f"fused_attention must be bool or 'auto', "
                     f"got {setting!r}")


def _softmax_attention(q, k, v, softmax_dtype, out_dtype,
                       bool_mask=None, add_bias=None):
    """The einsum attention core shared by the standard and KV-cache-decode
    paths: scaled QK^T (+boolean mask as a where, +additive bias), softmax
    in ``softmax_dtype``, context product.  ``bool_mask`` broadcasts
    against [B, H, Sq, Sk]; the -1e9/-1e4 "minus infinity enough" constant
    follows the half-dtype clamp rationale (fp16 overflows -1e9 to -inf
    and a fully-masked row would softmax to NaN)."""
    hd = q.shape[-1]
    sd = softmax_dtype
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(sd)
    logits = logits / jnp.sqrt(hd).astype(sd)
    neg = -1e9 if sd == jnp.float32 else -1e4
    if bool_mask is not None:
        logits = jnp.where(bool_mask, logits, jnp.asarray(neg, sd))
    if add_bias is not None:
        logits = logits + jnp.maximum(add_bias, neg).astype(sd)
    probs = nn.softmax(logits, axis=-1).astype(out_dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class BertSelfAttention(nn.Module):
    hidden_size: int
    num_heads: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # softmax is blacklisted under O0–O2 (fp32); O3 runs it half.  Resolved
    # by amp/autocast.module_dtypes and threaded in by the builder.
    softmax_dtype: jnp.dtype = jnp.float32
    # Blockwise flash-attention kernel (ops/attention.py).  Only taken when
    # the softmax contract is fp32 — the kernel always computes fp32 softmax,
    # so routing O3's half-softmax through it would silently upgrade
    # precision.  The op itself falls back to the XLA reference off-TPU.
    # "auto" (default) applies the measured crossover: kernel at seq >=
    # FLASH_AUTO_MIN_SEQ, XLA einsum path below.
    fused_attention: Union[bool, str] = "auto"
    # Megatron-style tensor parallelism (GSPMD form): q/k/v are column-
    # parallel (heads shard over the ``model`` axis), the output projection
    # is row-parallel.  Param names/shapes are identical to the dense path —
    # checkpoints interchange.  sequence_parallel additionally keeps the
    # activations outside the TP block sequence-sharded (Megatron-SP).
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    # Ring context parallelism (shard_map form): the sequence is sharded
    # over the 'context' mesh axis; q/k/v projections are per-token local,
    # attention runs as a ppermute KV ring whose per-chunk scores stay in
    # VMEM (parallel/context_parallel.ring_attention, flash-composed) —
    # the long-context training path (no reference analog).
    context_parallel: bool = False
    # Causal (decoder-only) masking: position t attends to keys <= t.  On
    # the einsum path a triangular bias; the flash kernel and the KV ring
    # take it natively (their blockwise/chunkwise skip logic).  Consumed
    # by models/gpt.py.
    causal: bool = False
    # Context-parallel attention program (with context_parallel):
    #   "ring"    — ppermute KV ring, contiguous chunks (flash-composed);
    #   "zigzag"  — load-balanced CAUSAL ring: local shards hold zigzag
    #               chunk pairs (i, 2n-1-i), identical live work per ring
    #               step (the caller reorders the batch with zigzag_shard);
    #   "ulysses" — all-to-all head sharding: full sequence per device,
    #               H/N heads per device, exact attention (DeepSpeed-
    #               Ulysses form; needs heads % axis size == 0).
    cp_mode: str = "ring"
    # Autoregressive KV-cache decoding (flax 'cache' collection, the
    # canonical single-token pattern): init with a [B, max_len] dummy
    # allocates cached_key/cached_value/cache_index; each subsequent call
    # takes ONE token, writes its k/v at the running index, and attends
    # against the filled prefix.  models/gpt.generate drives it.
    decode: bool = False
    # Block-paged slot decode (with decode=True): instead of a dense
    # [B, max_len, H, D] page per row, K/V live in one shared arena of
    # shape [kv_num_blocks, kv_block_size, H, D] per layer.  Each batch
    # row is an independent request slot whose logical sequence is
    # scattered across arena blocks named by a per-slot block table —
    # the ``paged`` call argument carries the table plus per-slot fill
    # levels, new-token counts and copy-on-write pairs, all host-owned
    # (serve/slots.py is the allocator; there is no device-side index
    # state).  One compiled step advances every live slot by up to
    # kv_block_size tokens (chunked prefill) or one token (decode) —
    # the geometry is static, so the program compiles exactly once.
    slot_decode: bool = False
    kv_num_blocks: int = 0
    kv_block_size: int = 0
    # Quantized paged KV (ISSUE 13, with slot_decode): the arenas store
    # int8 K/V with bf16 PER-TOKEN BLOCK SCALES ([NB, BS] per arena) —
    # quantized on the scatter write, dequantized (scale-fused) in the
    # gathered attention, scale rows copied with their payload rows on
    # COW so prefix-sharing semantics carry over unchanged.  Geometry
    # stays static; the program still compiles exactly once.  The
    # attention math itself (softmax included) runs at full precision
    # on the dequantized values — the amp/lists sensitivity contract.
    kv_quant: bool = False

    @nn.compact
    def __call__(self, x, mask_bias, paged=None):
        d = self.hidden_size
        h = self.num_heads
        hd = d // h
        if self.decode and (self.context_parallel or self.sequence_parallel
                            or mask_bias is not None or not self.causal):
            raise ValueError(
                "decode (KV-cache) is the causal inference path: no "
                "CP/SP/mask composition (tensor_parallel composes: the "
                "cache shards over heads like training attention; SP's "
                "sequence-dim constraints cannot partition a length-1 "
                "decode step)")
        use_kernel = (not self.decode) and _resolve_fused_attention(
            self.fused_attention, x.shape[1], self.softmax_dtype)
        if self.tensor_parallel:
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                ColumnParallelLinear, RowParallelLinear, batch_axis,
                constrain)
            dense_in = lambda name: ColumnParallelLinear(
                d, gather_output=False,
                sequence_parallel=self.sequence_parallel,
                dtype=self.dtype, param_dtype=self.param_dtype, name=name)
            dense_out = RowParallelLinear(
                d, input_is_parallel=True,
                sequence_parallel=self.sequence_parallel,
                dtype=self.dtype, param_dtype=self.param_dtype,
                name="output")
            # Heads shard over 'model': the (…, d)->(…, h, hd) reshape keeps
            # h outer, so the column-sharded feature dim becomes a sharded
            # head dim (hd stays whole — it is the MXU lane dim).
            head_spec = lambda t: constrain(t, batch_axis(), None, "model",
                                            None)
        else:
            dense_in = lambda name: nn.Dense(d, dtype=self.dtype,
                                             param_dtype=self.param_dtype,
                                             name=name)
            dense_out = nn.Dense(d, dtype=self.dtype,
                                 param_dtype=self.param_dtype, name="output")
            head_spec = lambda t: t
        q = head_spec(dense_in("query")(x).reshape(*x.shape[:-1], h, hd))
        k = head_spec(dense_in("key")(x).reshape(*x.shape[:-1], h, hd))
        v = head_spec(dense_in("value")(x).reshape(*x.shape[:-1], h, hd))
        if self.decode:
            from jax import lax as _lax
            cache_ready = self.has_variable("cache", "cached_key")
            if self.slot_decode:
                # Block-paged arena: one [NB, BS, H, D] K and V buffer
                # per layer, shared by every slot through per-slot block
                # tables.  Allocation/refcounts/COW policy are host-side
                # (serve/slots.py); the compiled step only executes the
                # table the host hands it.
                NB, BS = self.kv_num_blocks, self.kv_block_size
                if NB < 1 or BS < 1:
                    raise ValueError(
                        "slot_decode is block-paged: clone the model "
                        "with kv_num_blocks/kv_block_size >= 1 "
                        f"(got {NB}/{BS})")
                kv_store = jnp.int8 if self.kv_quant else k.dtype
                ck = self.variable("cache", "cached_key", jnp.zeros,
                                   (NB, BS, h, hd), kv_store)
                cv = self.variable("cache", "cached_value", jnp.zeros,
                                   (NB, BS, h, hd), kv_store)
                if self.kv_quant:
                    from apex_example_tpu.quant import kv as kv_quant
                    cks = self.variable("cache", "cached_key_scale",
                                        jnp.zeros, (NB, BS),
                                        kv_quant.KV_SCALE_DTYPE)
                    cvs = self.variable("cache", "cached_value_scale",
                                        jnp.zeros, (NB, BS),
                                        kv_quant.KV_SCALE_DTYPE)
            else:
                if self.kv_quant:
                    raise ValueError("kv_quant quantizes the block-"
                                     "paged arena; it requires "
                                     "slot_decode=True")
                ck = self.variable("cache", "cached_key", jnp.zeros,
                                   k.shape, k.dtype)
                cv = self.variable("cache", "cached_value", jnp.zeros,
                                   v.shape, v.dtype)
                ci = self.variable("cache", "cache_index",
                                   lambda: jnp.zeros((), jnp.int32))
            if cache_ready and self.slot_decode:
                if paged is None:
                    raise ValueError(
                        "paged slot decode needs the host state: pass "
                        "paged={'block_table', 'fill', 'n_new', "
                        "'cow_src', 'cow_dst'} (serve/engine.py builds "
                        "it each tick)")
                NB, BS = self.kv_num_blocks, self.kv_block_size
                S, C = x.shape[0], x.shape[1]
                if self.tensor_parallel:
                    # Under TP the [NB, BS, h, hd] arenas shard over
                    # heads on 'model' exactly like the dense decode
                    # cache; re-constraining after every in-place
                    # update keeps GSPMD from gathering the arena
                    # through the COW/scatter chain (the block tables,
                    # fills and scale tables stay replicated — they
                    # are host policy, not sharded state).
                    arena = lambda t: constrain(t, None, None, "model",
                                                None)
                else:
                    arena = lambda t: t
                table = paged["block_table"]          # [S, max_blocks]
                fill = paged["fill"]                  # [S] tokens cached
                n_new = paged["n_new"]                # [S] fed this tick
                # 1. Copy-on-write: slots whose next write lands in a
                # shared (immutable) block copy it first — dst -1 means
                # no COW this tick and the scatter drops out of range.
                src = jnp.clip(paged["cow_src"], 0, NB - 1)
                dst = jnp.where(paged["cow_dst"] >= 0, paged["cow_dst"],
                                NB)
                ck.value = arena(ck.value.at[dst].set(ck.value[src],
                                                      mode="drop"))
                cv.value = arena(cv.value.at[dst].set(cv.value[src],
                                                      mode="drop"))
                if self.kv_quant:
                    # Scales are block-resident state: a COW must carry
                    # them with the payload, or the copy dequantizes
                    # under the zero scales of a fresh block.
                    cks.value = cks.value.at[dst].set(cks.value[src],
                                                      mode="drop")
                    cvs.value = cvs.value.at[dst].set(cvs.value[src],
                                                      mode="drop")
                # 2. Scatter this tick's K/V through the block table:
                # token j of slot s lands at logical position fill[s]+j,
                # physical arena row table[s, pos//BS]*BS + pos%BS.
                # Lanes past n_new[s] scatter out of range and drop —
                # the host only maps exclusively-owned blocks for the
                # write span, so no two slots write one block.
                pos = fill[:, None] + jnp.arange(C)[None, :]
                blk = jnp.take_along_axis(
                    table, jnp.clip(pos // BS, 0, table.shape[1] - 1),
                    axis=1)
                flat = blk * BS + pos % BS
                valid = jnp.arange(C)[None, :] < n_new[:, None]
                flat = jnp.where(valid, flat, NB * BS).reshape(-1)
                if self.kv_quant:
                    # Quantize on the write: one symmetric max-abs
                    # scale per token over its [h, hd] vector, scale
                    # rows scattered through the SAME flat indices as
                    # the int8 payload (quant/kv.py).
                    k, k_sc = kv_quant.quantize_write(k)
                    v, v_sc = kv_quant.quantize_write(v)
                    cks.value = cks.value.reshape(NB * BS).at[flat].set(
                        k_sc.reshape(S * C),
                        mode="drop").reshape(NB, BS)
                    cvs.value = cvs.value.reshape(NB * BS).at[flat].set(
                        v_sc.reshape(S * C),
                        mode="drop").reshape(NB, BS)
                ck.value = arena(
                    ck.value.reshape(NB * BS, h, hd).at[flat].set(
                        k.reshape(S * C, h, hd),
                        mode="drop").reshape(NB, BS, h, hd))
                cv.value = arena(
                    cv.value.reshape(NB * BS, h, hd).at[flat].set(
                        v.reshape(S * C, h, hd),
                        mode="drop").reshape(NB, BS, h, hd))
                # 3. Gather each slot's logical K/V view back out of the
                # arena ([S, max_blocks*BS, H, D], logical order) and
                # attend under the per-slot causal live mask: query j
                # (position fill+j) sees keys at positions <= fill+j —
                # unwritten/stale arena rows sit beyond it and garbage
                # lanes of dead slots are discarded by the host.
                tbl = jnp.clip(table, 0, NB - 1)
                keys = ck.value[tbl].reshape(S, -1, h, hd)
                vals = cv.value[tbl].reshape(S, -1, h, hd)
                if self.kv_quant:
                    # Scale-fused dequant of the gathered logical view:
                    # attention (softmax included) runs at full
                    # precision on the dequantized values.
                    keys = kv_quant.dequantize_gather(
                        keys, cks.value[tbl].reshape(S, -1), self.dtype)
                    vals = kv_quant.dequantize_gather(
                        vals, cvs.value[tbl].reshape(S, -1), self.dtype)
                L = keys.shape[1]
                live = jnp.arange(L)[None, None, :] <= pos[:, :, None]
                # head_spec: under TP the arena shards over heads
                # ('model') exactly like training attention.
                ctx = _softmax_attention(q, head_spec(keys),
                                         head_spec(vals),
                                         self.softmax_dtype, self.dtype,
                                         bool_mask=live[:, None])
                return dense_out(ctx.reshape(*x.shape[:-1], d))
            if cache_ready:      # per-token decode step (cache exists)
                if x.shape[1] != 1:
                    raise ValueError("decode takes ONE token per call "
                                     f"(got seq {x.shape[1]}); the "
                                     "[B, max_len] shape is for cache "
                                     "allocation at init only")
                idx = ci.value
                ck.value = _lax.dynamic_update_slice(ck.value, k,
                                                     (0, idx, 0, 0))
                cv.value = _lax.dynamic_update_slice(cv.value, v,
                                                     (0, idx, 0, 0))
                ci.value = idx + 1
                # keys beyond the running index are unwritten slots
                live = jnp.arange(ck.value.shape[1]) <= idx
                mask = live[None, None, None]
                # head_spec: under TP the cache shards over heads ('model')
                # exactly like training attention — the constraint keeps
                # GSPMD from gathering the [B, max_len, h, hd] cache.
                ctx = _softmax_attention(q, head_spec(ck.value),
                                         head_spec(cv.value),
                                         self.softmax_dtype, self.dtype,
                                         bool_mask=mask)
                return dense_out(ctx.reshape(*x.shape[:-1], d))
            # init trace on the [B, max_len] dummy: cache allocated above;
            # fall through to the standard causal path so params/shapes
            # initialize.
        if self.context_parallel:
            # Same projections as the dense path (identical param tree);
            # only the attention computation changes: a ppermute KV ring
            # over the 'context'-sharded sequence.
            if self.softmax_dtype != jnp.float32:
                # ring_attention always computes its online softmax in fp32;
                # silently upgrading O3's half-softmax contract would make
                # CP runs incomparable with the dense O3 model (mirror of
                # _resolve_fused_attention's fp32-softmax gate).
                raise ValueError(
                    "context_parallel attention computes fp32 softmax; "
                    f"softmax_dtype={self.softmax_dtype} (O3 half-softmax) "
                    "does not compose with it")
            from apex_example_tpu.parallel.context_parallel import (
                ring_attention)
            if mask_bias is not None:
                raise ValueError("context_parallel BERT does not support an "
                                 "attention mask (the benchmark MLM path "
                                 "uses none); masking would need per-chunk "
                                 "key-bias rotation in the ring")
            if self.cp_mode == "zigzag":
                if not self.causal:
                    raise ValueError(
                        "cp_mode='zigzag' is the load-BALANCED CAUSAL "
                        "layout; non-causal CP has uniform work already — "
                        "use the plain ring")
                from apex_example_tpu.parallel.context_parallel import (
                    ring_attention_zigzag)
                ctx = ring_attention_zigzag(q, k, v,
                                            scale=1.0 / float(hd) ** 0.5)
            elif self.cp_mode == "ulysses":
                from apex_example_tpu.parallel.context_parallel import (
                    ulysses_attention)
                ctx = ulysses_attention(q, k, v, causal=self.causal,
                                        scale=1.0 / float(hd) ** 0.5)
            elif self.cp_mode == "ring":
                # causal=True: contiguous sequence chunks; blocks entirely
                # in the future are skipped, the diagonal chunk masks
                # blockwise (zigzag is the load-balanced causal variant).
                ctx = ring_attention(q, k, v, causal=self.causal,
                                     scale=1.0 / float(hd) ** 0.5)
            else:
                raise ValueError(f"unknown cp_mode {self.cp_mode!r} "
                                 "(ring | zigzag | ulysses)")
            return dense_out(ctx.reshape(*x.shape[:-1], d))
        if use_kernel and not self.tensor_parallel:
            # (TP runs the einsum path: pallas_call is opaque to the SPMD
            # partitioner, while the einsums partition over the head dim.)
            from apex_example_tpu.ops.attention import flash_attention
            key_bias = None if mask_bias is None \
                else mask_bias[:, 0, 0, :].astype(jnp.float32)
            ctx = flash_attention(q, k, v, key_bias, causal=self.causal,
                                  scale=1.0 / float(hd) ** 0.5)
            return dense_out(ctx.reshape(*x.shape[:-1], d))
        tri = None
        if self.causal:
            S = x.shape[1]
            tri = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None]
        ctx = _softmax_attention(q, k, v, self.softmax_dtype, self.dtype,
                                 bool_mask=tri, add_bias=mask_bias)
        ctx = ctx.reshape(*x.shape[:-1], d)
        return dense_out(ctx)


class BertLayer(nn.Module):
    hidden_size: int
    num_heads: int
    intermediate_size: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    ln_dtype: Optional[jnp.dtype] = None     # LN I/O; None follows dtype
    softmax_dtype: jnp.dtype = jnp.float32
    fused_attention: Union[bool, str] = "auto"
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    context_parallel: bool = False
    # Switch-MoE FFN: >0 replaces the dense MLP with moe_experts experts
    # (transformer/expert_parallel.MoEMLP).  When >0 the layer returns
    # (x, aux_loss) — the load-balancing term belongs in the objective.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_axis_name: str = "expert"
    moe_top_k: int = 1
    causal: bool = False
    cp_mode: str = "ring"
    decode: bool = False
    slot_decode: bool = False
    kv_num_blocks: int = 0
    kv_block_size: int = 0
    kv_quant: bool = False

    @nn.compact
    def __call__(self, x, mask_bias, paged=None):
        # LN I/O dtype per the op classification (O1: fp32; O2/O3: half
        # I/O).  The Pallas kernel computes its statistics in fp32
        # regardless, so half I/O loses no precision in the moments — the
        # MixedFusedLayerNorm contract.
        ln_io = self.ln_dtype or self.dtype
        attn = BertSelfAttention(self.hidden_size, self.num_heads,
                                 self.dtype, self.param_dtype,
                                 self.softmax_dtype,
                                 fused_attention=self.fused_attention,
                                 tensor_parallel=self.tensor_parallel,
                                 sequence_parallel=self.sequence_parallel,
                                 context_parallel=self.context_parallel,
                                 causal=self.causal,
                                 cp_mode=self.cp_mode,
                                 decode=self.decode,
                                 slot_decode=self.slot_decode,
                                 kv_num_blocks=self.kv_num_blocks,
                                 kv_block_size=self.kv_block_size,
                                 kv_quant=self.kv_quant,
                                 name="attention")(x, mask_bias,
                                                   paged=paged)
        x = FusedLayerNorm(dtype=ln_io, name="attention_ln")(
            (x + attn).astype(ln_io))
        x = x.astype(self.dtype)
        if self.moe_experts:
            from apex_example_tpu.transformer.expert_parallel import MoEMLP
            y, aux = MoEMLP(self.hidden_size, self.intermediate_size,
                            self.moe_experts,
                            capacity_factor=self.moe_capacity_factor,
                            dtype=self.dtype, param_dtype=self.param_dtype,
                            axis_name=self.moe_axis_name,
                            top_k=self.moe_top_k, name="moe")(x)
        elif self.tensor_parallel:
            # Megatron MLP: column (sharded GELU features) -> row (the
            # all-reduce — or, under sequence_parallel, the reduce-scatter
            # onto sequence shards — lands at the row output constraint).
            # (checked after moe_experts: under the MoE x TP composition
            # the FFN is the expert block and TP applies to attention/head)
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                ColumnParallelLinear, RowParallelLinear)
            y = ColumnParallelLinear(
                self.intermediate_size, gather_output=False,
                sequence_parallel=self.sequence_parallel, dtype=self.dtype,
                param_dtype=self.param_dtype, name="intermediate")(x)
            y = nn.gelu(y, approximate=False)
            y = RowParallelLinear(
                self.hidden_size, input_is_parallel=True,
                sequence_parallel=self.sequence_parallel, dtype=self.dtype,
                param_dtype=self.param_dtype, name="output")(y)
        else:
            y = nn.Dense(self.intermediate_size, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="intermediate")(x)
            y = nn.gelu(y, approximate=False)
            y = nn.Dense(self.hidden_size, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="output")(y)
        x = FusedLayerNorm(dtype=ln_io, name="output_ln")(
            (x + y).astype(ln_io))
        x = x.astype(self.dtype)
        return (x, aux) if self.moe_experts else x


class BertForMaskedLM(nn.Module):
    """BERT encoder + tied-decoder MLM head; returns vocab logits (fp32)."""

    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    ln_dtype: Optional[jnp.dtype] = None
    softmax_dtype: jnp.dtype = jnp.float32
    fused_attention: Union[bool, str] = "auto"
    # Megatron TP over the GSPMD 'model' mesh axis: vocab-sharded embeddings
    # + tied parallel LM head, column/row attention and MLP.  Consumed by
    # engine.make_gspmd_train_step / train.py --tensor-parallel.
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    # Ring context parallelism: __call__ runs inside shard_map with the
    # 'context' axis bound, input_ids holding THIS shard's sequence slice;
    # position ids offset by the shard index, attention rides the KV ring.
    # Consumed by workloads.make_bert_cp_train_step / --context-parallel.
    context_parallel: bool = False
    # Switch-MoE encoder FFNs (expert parallelism over moe_axis_name —
    # train.py --moe-experts binds it to the 'data' axis, DeepSpeed-MoE
    # style).  When >0 __call__ returns (logits, aux): the load-balancing
    # loss is part of the objective and rides the output contract.
    # Consumed by workloads.make_bert_moe_train_step.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_axis_name: str = "expert"
    moe_top_k: int = 1
    # context-parallel attention program: "ring" (default) or "ulysses"
    # (all-to-all head sharding; "zigzag" is causal-only -> GPT)
    cp_mode: str = "ring"

    @nn.compact
    def __call__(self, input_ids, attention_mask: Optional[jnp.ndarray] = None,
                 train: bool = True):
        del train  # no dropout in the pretraining benchmark path
        if self.moe_experts and self.sequence_parallel:
            # SP re-shards the sequence dim the dispatch indexes.  (TP
            # composes: the FFN is the expert block and the Megatron
            # sharding applies to attention/embeddings/head on the
            # automatic model axis.  CP composes: every local token still
            # routes over the full expert set via the all_to_all on
            # 'data', independent of the KV ring on 'context' — per-shard
            # routing/capacity, the pure-EP per-device contract.)
            raise ValueError("moe_experts does not compose with "
                             "sequence parallelism yet")
        if self.sequence_parallel and self.context_parallel:
            raise ValueError("sequence_parallel shards activations along "
                             "the sequence dim the context axis already "
                             "owns; CP composes with plain tensor_parallel")
        if self.context_parallel and attention_mask is not None:
            raise ValueError("context_parallel BERT does not support an "
                             "attention mask")
        ln_io = self.ln_dtype or self.dtype
        b, L = input_ids.shape
        if self.tensor_parallel:
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                VocabParallelEmbedding)
            word_emb = VocabParallelEmbedding(
                self.vocab_size, self.hidden_size, dtype=self.dtype,
                param_dtype=self.param_dtype, name="word_embeddings")
        else:
            word_emb = nn.Embed(self.vocab_size, self.hidden_size,
                                dtype=self.dtype,
                                param_dtype=self.param_dtype,
                                name="word_embeddings")
        x = word_emb(input_ids)
        pos = jnp.arange(L)[None, :]
        if self.context_parallel:
            # input_ids hold this context shard's slice; global positions
            # offset by the shard index (bound by the enclosing shard_map).
            from jax import lax as _lax
            from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
            pos = pos + _lax.axis_index(CONTEXT_AXIS) * L
        x = x + nn.Embed(self.max_position, self.hidden_size,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         name="position_embeddings")(pos)
        x = FusedLayerNorm(dtype=ln_io, name="embeddings_ln")(
            x.astype(ln_io)).astype(self.dtype)

        if attention_mask is not None:
            mask_bias = jnp.where(attention_mask[:, None, None, :] > 0,
                                  0.0, -1e9).astype(jnp.float32)
        else:
            mask_bias = None

        aux_total = jnp.zeros((), jnp.float32)
        for i in range(self.num_layers):
            x = BertLayer(self.hidden_size, self.num_heads,
                          self.intermediate_size, self.dtype,
                          self.param_dtype, self.ln_dtype,
                          self.softmax_dtype,
                          fused_attention=self.fused_attention,
                          tensor_parallel=self.tensor_parallel,
                          sequence_parallel=self.sequence_parallel,
                          context_parallel=self.context_parallel,
                          moe_experts=self.moe_experts,
                          moe_capacity_factor=self.moe_capacity_factor,
                          moe_axis_name=self.moe_axis_name,
                          moe_top_k=self.moe_top_k,
                          cp_mode=self.cp_mode,
                          name=f"layer_{i}")(x, mask_bias)
            if self.moe_experts:
                x, aux = x
                aux_total = aux_total + aux

        # MLM head: dense+gelu+LN, then tied decoder.  Under TP the decoder
        # is the parallel LM head (vocab-sharded logits — the CE's logsumexp
        # reduction over vocab becomes a psum, GSPMD's lowering of
        # Megatron's vocab_parallel_cross_entropy).
        x = nn.Dense(self.hidden_size, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="mlm_dense")(x)
        x = nn.gelu(x, approximate=False)
        x = FusedLayerNorm(dtype=ln_io, name="mlm_ln")(
            x.astype(ln_io)).astype(self.dtype)
        logits = word_emb.attend(x)
        bias_init = nn.initializers.zeros
        if self.tensor_parallel:
            bias_init = nn.with_partitioning(bias_init, ("model",))
        logits = logits + self.param("mlm_bias", bias_init,
                                     (self.vocab_size,), jnp.float32)
        logits = logits.astype(jnp.float32)
        if self.moe_experts:
            return logits, aux_total / self.num_layers
        return logits


def bert_base(**kw) -> BertForMaskedLM:
    return BertForMaskedLM(**kw)


def bert_tiny(**kw) -> BertForMaskedLM:
    """Test-scale configuration (same code path, CPU-friendly)."""
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position", 128)
    return BertForMaskedLM(**kw)
