"""BERT-base for masked-LM pretraining (workload C4, SURVEY.md §1).

The reference imports BERT from an external repo and exercises apex on it
(amp-O2 + FusedLAMB, BASELINE.json config 4); the parity target is the
standard BERT-base architecture: learned word+position+type embeddings with
post-embedding LayerNorm, 12 post-norm encoder layers (self-attention + GELU
FFN, hidden 768, heads 12, FFN 3072), and an MLM head whose decoder is tied
to the word embeddings.

TPU-native specifics:
- All LayerNorms are :class:`FusedLayerNorm` (the Pallas kernel — fp32 stats
  regardless of compute dtype, the MixedFusedLayerNorm contract).
- ``dtype``/``param_dtype`` thread the amp policy; attention logits and
  softmax run in fp32 (the op-classification "blacklist" of amp O1/O2:
  softmax is fp32; SURVEY.md §3.1).
- Static shapes throughout; the attention mask is an additive bias, so the
  whole step stays jit-compatible.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from apex_example_tpu.normalization import FusedLayerNorm


class BertSelfAttention(nn.Module):
    hidden_size: int
    num_heads: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # softmax is blacklisted under O0–O2 (fp32); O3 runs it half.  Resolved
    # by amp/autocast.module_dtypes and threaded in by the builder.
    softmax_dtype: jnp.dtype = jnp.float32
    # Blockwise flash-attention kernel (ops/attention.py).  Only taken when
    # the softmax contract is fp32 — the kernel always computes fp32 softmax,
    # so routing O3's half-softmax through it would silently upgrade
    # precision.  The op itself falls back to the XLA reference off-TPU.
    fused_attention: bool = False

    @nn.compact
    def __call__(self, x, mask_bias):
        d = self.hidden_size
        h = self.num_heads
        hd = d // h
        dense = lambda name: nn.Dense(d, dtype=self.dtype,
                                      param_dtype=self.param_dtype,
                                      name=name)
        q = dense("query")(x).reshape(*x.shape[:-1], h, hd)
        k = dense("key")(x).reshape(*x.shape[:-1], h, hd)
        v = dense("value")(x).reshape(*x.shape[:-1], h, hd)
        if self.fused_attention and self.softmax_dtype == jnp.float32:
            from apex_example_tpu.ops.attention import flash_attention
            key_bias = None if mask_bias is None \
                else mask_bias[:, 0, 0, :].astype(jnp.float32)
            ctx = flash_attention(q, k, v, key_bias,
                                  scale=1.0 / float(hd) ** 0.5)
            return dense("output")(ctx.reshape(*x.shape[:-1], d))
        sd = self.softmax_dtype
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(sd)
        logits = logits / jnp.sqrt(hd).astype(sd)
        if mask_bias is not None:
            # Clamp before the cast: -1e9 overflows to -inf in fp16 and a
            # fully-masked row would softmax to NaN (cf. transformer_xl's
            # mask fill).  -1e4 is "minus infinity enough" for half dtypes.
            neg = -1e9 if sd == jnp.float32 else -1e4
            logits = logits + jnp.maximum(mask_bias, neg).astype(sd)
        probs = nn.softmax(logits, axis=-1).astype(self.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        ctx = ctx.reshape(*x.shape[:-1], d)
        return dense("output")(ctx)


class BertLayer(nn.Module):
    hidden_size: int
    num_heads: int
    intermediate_size: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    ln_dtype: Optional[jnp.dtype] = None     # LN I/O; None follows dtype
    softmax_dtype: jnp.dtype = jnp.float32
    fused_attention: bool = False

    @nn.compact
    def __call__(self, x, mask_bias):
        # LN I/O dtype per the op classification (O1: fp32; O2/O3: half
        # I/O).  The Pallas kernel computes its statistics in fp32
        # regardless, so half I/O loses no precision in the moments — the
        # MixedFusedLayerNorm contract.
        ln_io = self.ln_dtype or self.dtype
        attn = BertSelfAttention(self.hidden_size, self.num_heads,
                                 self.dtype, self.param_dtype,
                                 self.softmax_dtype,
                                 fused_attention=self.fused_attention,
                                 name="attention")(x, mask_bias)
        x = FusedLayerNorm(dtype=ln_io, name="attention_ln")(
            (x + attn).astype(ln_io))
        x = x.astype(self.dtype)
        y = nn.Dense(self.intermediate_size, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="intermediate")(x)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(self.hidden_size, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="output")(y)
        x = FusedLayerNorm(dtype=ln_io, name="output_ln")(
            (x + y).astype(ln_io))
        return x.astype(self.dtype)


class BertForMaskedLM(nn.Module):
    """BERT encoder + tied-decoder MLM head; returns vocab logits (fp32)."""

    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    ln_dtype: Optional[jnp.dtype] = None
    softmax_dtype: jnp.dtype = jnp.float32
    fused_attention: bool = False

    @nn.compact
    def __call__(self, input_ids, attention_mask: Optional[jnp.ndarray] = None,
                 train: bool = True):
        del train  # no dropout in the pretraining benchmark path
        ln_io = self.ln_dtype or self.dtype
        b, L = input_ids.shape
        word_emb = nn.Embed(self.vocab_size, self.hidden_size,
                            dtype=self.dtype, param_dtype=self.param_dtype,
                            name="word_embeddings")
        x = word_emb(input_ids)
        pos = jnp.arange(L)[None, :]
        x = x + nn.Embed(self.max_position, self.hidden_size,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         name="position_embeddings")(pos)
        x = FusedLayerNorm(dtype=ln_io, name="embeddings_ln")(
            x.astype(ln_io)).astype(self.dtype)

        if attention_mask is not None:
            mask_bias = jnp.where(attention_mask[:, None, None, :] > 0,
                                  0.0, -1e9).astype(jnp.float32)
        else:
            mask_bias = None

        for i in range(self.num_layers):
            x = BertLayer(self.hidden_size, self.num_heads,
                          self.intermediate_size, self.dtype,
                          self.param_dtype, self.ln_dtype,
                          self.softmax_dtype,
                          fused_attention=self.fused_attention,
                          name=f"layer_{i}")(x, mask_bias)

        # MLM head: dense+gelu+LN, then tied decoder.
        x = nn.Dense(self.hidden_size, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="mlm_dense")(x)
        x = nn.gelu(x, approximate=False)
        x = FusedLayerNorm(dtype=ln_io, name="mlm_ln")(
            x.astype(ln_io)).astype(self.dtype)
        logits = word_emb.attend(x)
        logits = logits + self.param("mlm_bias", nn.initializers.zeros,
                                     (self.vocab_size,), jnp.float32)
        return logits.astype(jnp.float32)


def bert_base(**kw) -> BertForMaskedLM:
    return BertForMaskedLM(**kw)


def bert_tiny(**kw) -> BertForMaskedLM:
    """Test-scale configuration (same code path, CPU-friendly)."""
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position", 128)
    return BertForMaskedLM(**kw)
