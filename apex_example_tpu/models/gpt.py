"""GPT-style decoder-only causal LM.

Reference status: the reference family's LM workloads are BERT (bidirectional
MLM) and Transformer-XL (causal via segment recurrence); a plain decoder-only
GPT is ABSENT there.  It is added here because it is the natural flagship for
the framework's long-context machinery: causal flash attention
(ops/attention.py), the causal ppermute KV ring (parallel/context_parallel),
Megatron TP/SP (transformer/tensor_parallel), ZeRO, and switch-MoE FFNs all
compose with it through the same module flags BERT uses — the model is the
composition demo, not new machinery.

Architecture: learned token+position embeddings -> N post-LN transformer
layers (models/bert.BertLayer with causal=True) -> final LayerNorm ->
tied decoder head (vocab logits, fp32).  The objective is next-token CE
(workloads.lm_loss) on an input/target pair shifted by one token — train.py
generates seq_len+1 tokens per example so the model always sees exactly
seq_len positions.
"""

from __future__ import annotations

from typing import Optional, Union

import flax.linen as nn
import jax.numpy as jnp

from apex_example_tpu.models.bert import BertLayer
from apex_example_tpu.normalization import FusedLayerNorm


class GPTForCausalLM(nn.Module):
    """Decoder-only transformer; returns (B, S, vocab) fp32 logits (plus the
    MoE aux loss when moe_experts > 0, mirroring BertForMaskedLM's
    contract)."""

    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 1024
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    ln_dtype: Optional[jnp.dtype] = None
    softmax_dtype: jnp.dtype = jnp.float32
    fused_attention: Union[bool, str] = "auto"
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    context_parallel: bool = False
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_axis_name: str = "expert"
    # Load-balanced causal ring (with context_parallel): local shards hold
    # zigzag chunk pairs (i, 2n-1-i); position ids follow the same order.
    # The step factory (workloads.make_gpt_cp_train_step(zigzag=True))
    # reorders the batch with parallel.context_parallel.zigzag_shard.
    cp_zigzag: bool = False

    @nn.compact
    def __call__(self, input_ids, train: bool = True):
        del train  # no dropout in the pretraining benchmark path
        if self.moe_experts and (self.tensor_parallel
                                 or self.sequence_parallel
                                 or self.context_parallel):
            raise ValueError("moe_experts does not compose with "
                             "tensor/sequence/context parallelism yet")
        if self.sequence_parallel and self.context_parallel:
            raise ValueError("sequence_parallel shards activations along "
                             "the sequence dim the context axis already "
                             "owns; CP composes with plain tensor_parallel")
        ln_io = self.ln_dtype or self.dtype
        b, L = input_ids.shape
        if self.tensor_parallel:
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                VocabParallelEmbedding)
            word_emb = VocabParallelEmbedding(
                self.vocab_size, self.hidden_size, dtype=self.dtype,
                param_dtype=self.param_dtype, name="word_embeddings")
        else:
            word_emb = nn.Embed(self.vocab_size, self.hidden_size,
                                dtype=self.dtype,
                                param_dtype=self.param_dtype,
                                name="word_embeddings")
        x = word_emb(input_ids)
        pos = jnp.arange(L)[None, :]
        if self.context_parallel:
            from jax import lax as _lax
            from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
            i = _lax.axis_index(CONTEXT_AXIS)
            if self.cp_zigzag:
                # zigzag layout: this shard's halves are global chunks i
                # and 2n-1-i (each of length L/2)
                n = _lax.axis_size(CONTEXT_AXIS)
                c = L // 2
                pos = jnp.concatenate(
                    [jnp.arange(c) + i * c,
                     jnp.arange(c) + (2 * n - 1 - i) * c])[None, :]
            else:
                # contiguous chunks: global positions offset by the shard
                # index (the causal ring keys on the same order)
                pos = pos + i * L
        x = x + nn.Embed(self.max_position, self.hidden_size,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         name="position_embeddings")(pos)
        x = FusedLayerNorm(dtype=ln_io, name="embeddings_ln")(
            x.astype(ln_io)).astype(self.dtype)

        aux_total = jnp.zeros((), jnp.float32)
        for i in range(self.num_layers):
            x = BertLayer(self.hidden_size, self.num_heads,
                          self.intermediate_size, self.dtype,
                          self.param_dtype, self.ln_dtype,
                          self.softmax_dtype,
                          fused_attention=self.fused_attention,
                          tensor_parallel=self.tensor_parallel,
                          sequence_parallel=self.sequence_parallel,
                          context_parallel=self.context_parallel,
                          moe_experts=self.moe_experts,
                          moe_capacity_factor=self.moe_capacity_factor,
                          moe_axis_name=self.moe_axis_name,
                          causal=True, cp_zigzag=self.cp_zigzag,
                          name=f"layer_{i}")(x, None)
            if self.moe_experts:
                x, aux = x
                aux_total = aux_total + aux

        x = FusedLayerNorm(dtype=ln_io, name="final_ln")(
            x.astype(ln_io)).astype(self.dtype)
        logits = word_emb.attend(x)
        bias_init = nn.initializers.zeros
        if self.tensor_parallel:
            bias_init = nn.with_partitioning(bias_init, ("model",))
        logits = logits + self.param("lm_bias", bias_init,
                                     (self.vocab_size,), jnp.float32)
        logits = logits.astype(jnp.float32)
        if self.moe_experts:
            return logits, aux_total / self.num_layers
        return logits


def gpt_base(**kw) -> GPTForCausalLM:
    return GPTForCausalLM(**kw)


def gpt_tiny(**kw) -> GPTForCausalLM:
    """Test-scale configuration (same code path, CPU-friendly)."""
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position", 128)
    return GPTForCausalLM(**kw)
