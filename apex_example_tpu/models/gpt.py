"""GPT-style decoder-only causal LM.

Reference status: the reference family's LM workloads are BERT (bidirectional
MLM) and Transformer-XL (causal via segment recurrence); a plain decoder-only
GPT is ABSENT there.  It is added here because it is the natural flagship for
the framework's long-context machinery: causal flash attention
(ops/attention.py), the causal ppermute KV ring (parallel/context_parallel),
Megatron TP/SP (transformer/tensor_parallel), ZeRO, and switch-MoE FFNs all
compose with it through the same module flags BERT uses — the model is the
composition demo, not new machinery.

Architecture: learned token+position embeddings -> N post-LN transformer
layers (models/bert.BertLayer with causal=True) -> final LayerNorm ->
tied decoder head (vocab logits, fp32).  The objective is next-token CE
(workloads.lm_loss) on an input/target pair shifted by one token — train.py
generates seq_len+1 tokens per example so the model always sees exactly
seq_len positions.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu.models.bert import BertLayer
from apex_example_tpu.normalization import FusedLayerNorm


class GPTForCausalLM(nn.Module):
    """Decoder-only transformer; returns (B, S, vocab) fp32 logits (plus the
    MoE aux loss when moe_experts > 0, mirroring BertForMaskedLM's
    contract)."""

    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 1024
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    ln_dtype: Optional[jnp.dtype] = None
    softmax_dtype: jnp.dtype = jnp.float32
    fused_attention: Union[bool, str] = "auto"
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    context_parallel: bool = False
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_axis_name: str = "expert"
    moe_top_k: int = 1
    # Context-parallel attention program: "ring" (contiguous causal KV
    # ring), "zigzag" (load-balanced causal ring — the step factory
    # reorders the batch with zigzag_shard and position ids follow), or
    # "ulysses" (all-to-all head sharding, full sequence per device).
    cp_mode: str = "ring"
    # Autoregressive KV-cache inference (see :func:`generate`): init with
    # a [B, max_len] dummy to allocate per-layer caches, then apply one
    # token at a time with mutable=["cache"].
    decode: bool = False
    # Block-paged slot decode (with decode=True): K/V live in one
    # [kv_num_blocks, kv_block_size, H, D] arena per layer, addressed
    # through per-slot block tables, and there is NO device-side index
    # state at all — the host (serve/slots.py BlockPool) owns fill
    # levels, allocation, refcounts and copy-on-write, and passes the
    # per-tick state in through the ``paged`` call argument.  Each batch
    # row is an independent request slot fed up to kv_block_size tokens
    # per step (chunked prefill) or one (decode); the geometry is
    # static, so one compiled step serves every slot mix.  This is the
    # substrate the continuous-batching engine (serve/) schedules on.
    slot_decode: bool = False
    kv_num_blocks: int = 0
    kv_block_size: int = 0
    # Quantized paged KV (ISSUE 13, with slot_decode): int8 arenas with
    # bf16 per-token block scales — quantize on the scatter write,
    # scale-fused dequant in the gathered attention, scales copied with
    # their blocks on COW (models/bert.py holds the mechanics).
    kv_quant: bool = False

    @nn.compact
    def __call__(self, input_ids, train: bool = True, paged=None):
        del train  # no dropout in the pretraining benchmark path
        if self.moe_experts and self.sequence_parallel:
            # (TP composes: the expert block replaces the FFN; Megatron
            # sharding applies to attention/embeddings/head.  CP composes
            # too: the expert all_to_all over 'data' and the KV ring over
            # 'context' are independent collectives — routing/capacity are
            # per-(data, context) shard, the pure-EP per-device contract.)
            raise ValueError("moe_experts does not compose with "
                             "sequence parallelism yet")
        if self.sequence_parallel and self.context_parallel:
            raise ValueError("sequence_parallel shards activations along "
                             "the sequence dim the context axis already "
                             "owns; CP composes with plain tensor_parallel")
        ln_io = self.ln_dtype or self.dtype
        b, L = input_ids.shape
        if self.tensor_parallel:
            from apex_example_tpu.transformer.tensor_parallel.layers import (
                VocabParallelEmbedding)
            word_emb = VocabParallelEmbedding(
                self.vocab_size, self.hidden_size, dtype=self.dtype,
                param_dtype=self.param_dtype, name="word_embeddings")
        else:
            word_emb = nn.Embed(self.vocab_size, self.hidden_size,
                                dtype=self.dtype,
                                param_dtype=self.param_dtype,
                                name="word_embeddings")
        if self.decode and (self.moe_experts or self.context_parallel
                            or self.sequence_parallel):
            # SP shards activations along the sequence dim, which is 1 in
            # per-token decode — its scatter/gather constraints cannot
            # partition it; rejecting here beats an opaque GSPMD
            # divisibility error deep in the trace.
            raise ValueError("decode (KV-cache) is the dense/TP inference "
                             "path: no CP/MoE/sequence-parallel "
                             "composition")
        if self.slot_decode and not self.decode:
            raise ValueError("slot_decode modifies the KV-cache indices; "
                             "it requires decode=True")
        x = word_emb(input_ids)
        pos = jnp.arange(L)[None, :]
        if self.decode and self.slot_decode:
            # Paged slot decode: positions come from the HOST's per-slot
            # fill levels (paged["fill"]), not a device counter — the
            # block pool is the single source of truth for how far each
            # slot has filled.  paged is None only on the init trace
            # (cache allocation), where plain arange positions serve the
            # [B, max_len] dummy.  The clip keeps garbage lanes of dead
            # slots inside the position table; real lanes never bind
            # (fill + j <= max_len - 1 <= max_position - 1).
            if paged is not None:
                pos = jnp.clip(paged["fill"][:, None] + pos,
                               0, self.max_position - 1)
        elif self.decode:
            # position = running cache index (checked BEFORE .variable
            # creates it: at allocation time the dummy covers 0..L-1)
            cache_ready = self.has_variable("cache", "cache_position")
            pi = self.variable("cache", "cache_position",
                               lambda: jnp.zeros((), jnp.int32))
            if cache_ready:      # per-token decode step
                pos = pos + pi.value
                pi.value = pi.value + L
        if self.context_parallel:
            from jax import lax as _lax
            from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
            i = _lax.axis_index(CONTEXT_AXIS)
            if self.cp_mode == "zigzag":
                # zigzag layout: this shard's halves are global chunks i
                # and 2n-1-i (each of length L/2)
                n = _lax.axis_size(CONTEXT_AXIS)
                c = L // 2
                pos = jnp.concatenate(
                    [jnp.arange(c) + i * c,
                     jnp.arange(c) + (2 * n - 1 - i) * c])[None, :]
            else:
                # contiguous chunks: global positions offset by the shard
                # index (the causal ring keys on the same order)
                pos = pos + i * L
        x = x + nn.Embed(self.max_position, self.hidden_size,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         name="position_embeddings")(pos)
        x = FusedLayerNorm(dtype=ln_io, name="embeddings_ln")(
            x.astype(ln_io)).astype(self.dtype)

        aux_total = jnp.zeros((), jnp.float32)
        for i in range(self.num_layers):
            x = BertLayer(self.hidden_size, self.num_heads,
                          self.intermediate_size, self.dtype,
                          self.param_dtype, self.ln_dtype,
                          self.softmax_dtype,
                          fused_attention=self.fused_attention,
                          tensor_parallel=self.tensor_parallel,
                          sequence_parallel=self.sequence_parallel,
                          context_parallel=self.context_parallel,
                          moe_experts=self.moe_experts,
                          moe_capacity_factor=self.moe_capacity_factor,
                          moe_axis_name=self.moe_axis_name,
                          moe_top_k=self.moe_top_k,
                          causal=True, cp_mode=self.cp_mode,
                          decode=self.decode,
                          slot_decode=self.slot_decode,
                          kv_num_blocks=self.kv_num_blocks,
                          kv_block_size=self.kv_block_size,
                          kv_quant=self.kv_quant,
                          name=f"layer_{i}")(x, None, paged=paged)
            if self.moe_experts:
                x, aux = x
                aux_total = aux_total + aux

        x = FusedLayerNorm(dtype=ln_io, name="final_ln")(
            x.astype(ln_io)).astype(self.dtype)
        logits = word_emb.attend(x)
        bias_init = nn.initializers.zeros
        if self.tensor_parallel:
            bias_init = nn.with_partitioning(bias_init, ("model",))
        logits = logits + self.param("lm_bias", bias_init,
                                     (self.vocab_size,), jnp.float32)
        logits = logits.astype(jnp.float32)
        if self.moe_experts:
            return logits, aux_total / self.num_layers
        return logits


def gpt_base(**kw) -> GPTForCausalLM:
    return GPTForCausalLM(**kw)


def gpt_tiny(**kw) -> GPTForCausalLM:
    """Test-scale configuration (same code path, CPU-friendly)."""
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position", 128)
    return GPTForCausalLM(**kw)


def sample_tokens(rng, logits: jnp.ndarray, temperature,
                  top_k=0) -> jnp.ndarray:
    """Next-token selection over [B, V] logits with RUNTIME temperature and
    top-k — both enter as traced values (scalars or per-row [B] vectors),
    so ONE compiled decode program serves every sampling configuration.
    Per-row vectors are how the continuous-batching engine
    (serve/engine.py) mixes greedy and sampled requests in one batch.

    temperature == 0 selects argmax (greedy); top_k == 0 samples the full
    softmax; top_k > 0 restricts sampling to the k highest logits (a tie
    at the threshold keeps >= k candidates).

    The expensive lanes are fenced by runtime ``lax.cond``s, so a batch
    that is entirely greedy executes only the argmax, and the full-vocab
    sort runs only when some row actually wants top-k — the hot decode
    path does not pay for sampling features it isn't using.
    """
    B, V = logits.shape
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def topk_filter(lg):
        # Runtime k rules out lax.top_k (static k): the per-row cutoff
        # is the k-th largest logit via a descending sort, k clamped
        # into [1, V]; rows with k == 0 skip the filter.
        kk = jnp.clip(k, 1, V)
        desc = -jnp.sort(-lg, axis=-1)
        thresh = jnp.take_along_axis(desc, (kk - 1)[:, None], axis=-1)
        return jnp.where((k[:, None] > 0) & (lg < thresh), -jnp.inf, lg)

    def sample(lg):
        filtered = lax.cond(jnp.any(k > 0), topk_filter, lambda x: x, lg)
        # max() keeps the t == 0 lanes finite; their sample is discarded
        # by the where below (greedy wins), so their distribution is moot.
        return jax.random.categorical(
            rng, filtered / jnp.maximum(t, 1e-6)[:, None]).astype(jnp.int32)

    sampled = lax.cond(jnp.any(t > 0), sample, lambda lg: greedy, logits)
    return jnp.where(t > 0, sampled, greedy)


def generate(model: GPTForCausalLM, params, prompt: jnp.ndarray,
             max_len: int, temperature: float = 0.0, rng=None,
             top_k: int = 0) -> jnp.ndarray:
    """Autoregressive generation with a KV cache (greedy at temperature 0,
    categorical sampling otherwise).

    ``prompt`` is [B, P] int32; returns [B, max_len] — the prompt followed
    by max_len - P generated tokens.  TPU-idiomatic decode: ONE jitted
    ``lax.scan`` over single-token steps with static shapes throughout —
    per-layer K/V caches ([B, max_len, H, D], allocated by a one-time init
    trace) are scan carries, each step costs O(max_len·D) attention
    against the filled prefix instead of re-running the O(S²) forward on
    a growing sequence.  Prompt positions are fed through the same loop
    (their logits are discarded), so prefill and decode share one
    compiled program.

    Beyond-reference: the reference family is training-only; this makes
    the GPT family usable end-to-end (models/gpt.py docstring).

    Composes with tensor parallelism: for a ``tensor_parallel=True`` model
    under a registered ``parallel_state`` mesh, the per-layer KV caches
    shard over heads on the ``model`` axis exactly like training attention
    (pass TP-sharded ``params``; the constraint points in the layers do the
    rest).  The XLA reference ops are pinned for the trace — pallas custom
    calls are opaque to the SPMD partitioner (same as train.py's TP path).
    """
    B, P = prompt.shape
    if not 0 < P < max_len:
        raise ValueError(f"need 0 < prompt len {P} < max_len {max_len}")
    if model.max_position < max_len:
        raise ValueError(f"max_len {max_len} exceeds the model's position "
                         f"table ({model.max_position})")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 samples; pass rng=PRNGKey")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0 (0 = full softmax), "
                         f"got {top_k}")
    dec = model.clone(decode=True, fused_attention=False)
    # cache ALLOCATION without compute: eval_shape traces the init only
    # abstractly (no training-scale dummy forward actually runs), then the
    # zeroed pytree is built from the shapes.
    shapes = jax.eval_shape(
        dec.init, jax.random.PRNGKey(0),
        jnp.zeros((B, max_len), jnp.int32))["cache"]
    cache = jax.tree_util.tree_map(
        lambda t: jnp.zeros(t.shape, t.dtype), shapes)
    tokens = jnp.zeros((B, max_len), jnp.int32).at[:, :P].set(prompt)
    if rng is None:
        rng = jax.random.PRNGKey(0)          # carried but unused (greedy)
    run = _decode_loop(dec, max_len)
    # Cost observability (obs/costmodel.py, --cost-model): with a
    # default instance installed the loop compiles through the AOT path
    # and the compilation is harvested; instrument() caches per
    # (name, fn), so repeated generate() calls at one config keep
    # reusing ONE compiled program — identity when no instance is set.
    # Lazy import: generate() is also used from contexts that never
    # touch the obs package.
    from apex_example_tpu.obs import costmodel as _costmodel
    run = _costmodel.instrument("gpt_decode_loop", run)
    args = (params, tokens, cache, rng, jnp.asarray(P, jnp.int32),
            jnp.asarray(float(temperature), jnp.float32),
            jnp.asarray(int(top_k), jnp.int32))
    if model.tensor_parallel:
        from apex_example_tpu.ops import _config as ops_config
        with ops_config.force_xla():
            return run(*args)
    return run(*args)


@functools.lru_cache(maxsize=32)
def _decode_loop(dec: GPTForCausalLM, max_len: int):
    """Jitted scan for :func:`generate`, cached on the static
    configuration (the module is a frozen dataclass, so it keys the
    cache): repeated generate() calls reuse one compiled program, and
    params enter as an ARGUMENT — baked-as-constants weights would bloat
    the executable and defeat the cache.  temperature and top_k ride as
    TRACED scalars through :func:`sample_tokens`, so one compiled program
    serves every sampling configuration — temperature used to be part of
    this cache key and recompiled the loop per distinct value."""

    def step(params, P, temperature, top_k, carry, t):
        tokens, cache, rng = carry
        B = tokens.shape[0]
        tok = lax.dynamic_slice(tokens, (0, t), (B, 1))
        logits, mut = dec.apply({"params": params, "cache": cache}, tok,
                                train=False, mutable=["cache"])
        cache = mut["cache"]
        rng, key = jax.random.split(rng)
        nxt = sample_tokens(key, logits[:, -1], temperature, top_k)
        # inside the prompt, keep the given token (prefill); past it,
        # write the model's choice
        cur = lax.dynamic_slice(tokens, (0, t + 1), (B, 1))[:, 0]
        nxt = jnp.where(t + 1 < P, cur, nxt)
        tokens = lax.dynamic_update_slice(tokens, nxt[:, None], (0, t + 1))
        return (tokens, cache, rng), None

    @jax.jit
    def run(params, tokens, cache, rng, P, temperature, top_k):
        # P rides as a TRACED scalar (only `t + 1 < P` consumes it), so
        # one compiled program serves every prompt length at this shape.
        (tokens, _, _), _ = lax.scan(
            functools.partial(step, params, P, temperature, top_k),
            (tokens, cache, rng), jnp.arange(max_len - 1))
        return tokens

    return run
