"""Draft proposers: host-side token drafting for speculative decoding.

Contract
--------
A proposer implements::

    propose(uid, prompt_tokens, generated_tokens, k) -> list[int]

where ``uid`` identifies the request (so stateful proposers can keep
per-request scratch), ``prompt_tokens`` is the request's prompt,
``generated_tokens`` is everything sampled so far, and ``k`` is the
maximum draft length for this tick.  The return value is a list of at
most ``k`` candidate token ids for sequence positions immediately after
the last generated token.  Returning ``[]`` is always legal and means
"no draft this tick" — the engine then behaves exactly like the plain
one-token-per-tick path for that slot.

Proposers are jax-free by contract: they run on the host between engine
dispatches and must be pure Python (stdlib only).  They must also be
deterministic — the engine's lossless-greedy guarantee does not depend
on draft quality, but test reproducibility depends on draft stability.
"""

from __future__ import annotations

from typing import List, Sequence


class DraftProposer:
    """Base class for draft proposers.  Subclasses override propose()."""

    name = "base"

    def propose(
        self,
        uid: str,
        prompt_tokens: Sequence[int],
        generated_tokens: Sequence[int],
        k: int,
    ) -> List[int]:
        raise NotImplementedError


class NullProposer(DraftProposer):
    """The off-switch: never drafts.

    With this proposer armed, every speculative tick degenerates to the
    single-lane decode step (the engine feeds only the last sampled
    token), so throughput and outputs match the non-speculative path
    token for token.
    """

    name = "none"

    def propose(self, uid, prompt_tokens, generated_tokens, k):
        return []


class NgramProposer(DraftProposer):
    """Prompt-lookup / n-gram drafter — no second model.

    Matches the last ``n`` tokens of the running sequence (prompt +
    generated) against earlier occurrences in that same sequence and
    proposes the continuation that followed the most recent match.  This
    exploits self-repetition: templated prompts, copy-through spans, and
    the short cycles greedy decoding tends to fall into.  Shorter match
    windows are tried as fallback (n, n-1, …, 1) so a draft is produced
    whenever *any* suffix of the context has appeared before.

    The proposer is stateless across requests (the context is rebuilt
    from the arguments each call), so eviction/retry never leaks drafts
    between requests.
    """

    name = "ngram"

    def __init__(self, n: int = 3):
        if n < 1:
            raise ValueError(f"ngram window must be >= 1, got {n}")
        self.n = int(n)

    def propose(self, uid, prompt_tokens, generated_tokens, k):
        if k <= 0:
            return []
        ctx = list(prompt_tokens) + list(generated_tokens)
        if len(ctx) < 2:
            return []
        for n in range(min(self.n, len(ctx) - 1), 0, -1):
            suffix = ctx[-n:]
            # Most recent earlier occurrence of the suffix (rfind over
            # windows ending strictly before the end of the context).
            for start in range(len(ctx) - n - 1, -1, -1):
                if ctx[start : start + n] == suffix:
                    cont = ctx[start + n : start + n + k]
                    if cont:
                        return [int(t) for t in cont]
                    break
        return []


_PROPOSERS = {
    "ngram": NgramProposer,
    "none": NullProposer,
}


def get_proposer(kind: str, *, ngram: int = 3) -> DraftProposer:
    """Build a proposer by CLI name (``--draft ngram|none``)."""
    if kind == "ngram":
        return NgramProposer(n=ngram)
    if kind == "none":
        return NullProposer()
    raise ValueError(
        f"unknown draft proposer {kind!r} (expected one of {sorted(_PROPOSERS)})"
    )
