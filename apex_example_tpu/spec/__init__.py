"""Speculative decoding: host-side draft proposers for the serve engine.

The serve engine's decode hot path is one token per tick per slot.  The
speculation subsystem breaks that wall losslessly: a host-side *proposer*
drafts up to K candidate tokens per slot per tick, the engine verifies all
K+1 lanes in a single compiled dispatch (reusing the chunked-prefill
multi-lane machinery), and accepts the longest prefix of the draft that
matches the model's own greedy continuation.  Rejected lanes roll back for
free because paged-KV fill levels are host-side — the cursor simply does
not advance past the accepted prefix.

Everything in this package is jax-free by contract: proposers run on the
host between dispatches and must never touch device state.
"""

from apex_example_tpu.spec.proposers import (
    DraftProposer,
    NgramProposer,
    NullProposer,
    get_proposer,
)

__all__ = [
    "DraftProposer",
    "NgramProposer",
    "NullProposer",
    "get_proposer",
]
