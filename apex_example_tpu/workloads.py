"""Workload-specific losses and step builders (C4 BERT-MLM, C5 TXL-LM).

The classification engine (engine.py) covers C1–C3.  BERT reuses it with an
MLM loss (the label pytree is (labels, weights)); Transformer-XL needs its
own step because segment recurrence threads a memory carry alongside the
train state — the memory is per-replica activation state (batch-sharded under
DDP, P(None, "data") on its (layers, B, mem, d) layout), unlike the
replicated TrainState.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from apex_example_tpu import amp as amp_lib
from apex_example_tpu.amp.policy import Policy
from apex_example_tpu.engine import TrainState, _wrap_optimizer
from apex_example_tpu.parallel.distributed import DDPConfig, allreduce_grads
from apex_example_tpu.parallel.mesh import DATA_AXIS

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def mlm_loss(logits: jnp.ndarray, target: Tuple[jnp.ndarray, jnp.ndarray]
             ) -> jnp.ndarray:
    """Masked-LM loss: mean CE over masked positions only (weights mark
    them).  target = (labels, weights)."""
    labels, weights = target
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels)
    denom = jnp.maximum(weights.sum(), 1.0)
    return (ce * weights).sum() / denom


def lm_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Next-token CE, mean over all positions (Transformer-XL objective)."""
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels)
    return ce.mean()


def make_txl_train_step(model, optimizer, policy: Policy,
                        ddp: Optional[DDPConfig] = None,
                        axis_name: Optional[str] = None,
                        max_grad_norm: float = 0.25):
    """Transformer-XL step: (state, mems, (inp, tgt)) → (state, mems', metrics).

    Mirrors the reference C5 recipe (SURVEY.md §1): FusedLayerNorm inside the
    model, global-norm grad clipping (the multi_tensor_l2norm path) before the
    update, segment recurrence via the mems carry.
    """
    from apex_example_tpu.ops import clip_grad_norm

    opt = _wrap_optimizer(optimizer)
    ddp = ddp or DDPConfig()

    def train_step(state: TrainState, mems, batch):
        inp, tgt = batch

        def scaled_loss_fn(params):
            logits, new_mems = model.apply({"params": params}, inp,
                                           mems=mems)
            loss = lm_loss(logits, tgt)
            return amp_lib.scale_loss(loss, state.scaler), (loss, new_mems)

        grads, (loss, new_mems) = jax.grad(
            scaled_loss_fn, has_aux=True)(state.params)
        if axis_name is not None:
            grads = allreduce_grads(grads, ddp, axis_name)
            loss = jax.lax.pmean(loss, axis_name)
        grads, grads_finite = amp_lib.unscale_grads(grads, state.scaler)
        grads, gnorm = clip_grad_norm(grads, max_grad_norm)

        new_params, new_opt_state = opt.apply(grads, state.opt_state,
                                              state.params)
        if policy.uses_dynamic_scaling:
            new_params = amp_lib.select_tree(grads_finite, new_params,
                                            state.params)
            new_opt_state = amp_lib.select_tree(grads_finite, new_opt_state,
                                                state.opt_state)
        scaler = amp_lib.update_scaler(state.scaler, grads_finite)

        metrics = {"loss": loss, "grad_norm": gnorm,
                   "ppl": jnp.exp(loss), "scale": scaler.scale,
                   "grads_finite": grads_finite.astype(jnp.float32)}
        new_state = TrainState(step=state.step + 1, params=new_params,
                               batch_stats=state.batch_stats,
                               opt_state=new_opt_state, scaler=scaler)
        return new_state, new_mems, metrics

    return train_step


def make_sharded_txl_train_step(mesh: Mesh, model, optimizer, policy: Policy,
                                ddp: Optional[DDPConfig] = None,
                                max_grad_norm: float = 0.25,
                                axis_name: str = DATA_AXIS,
                                donate: bool = True):
    """DDP Transformer-XL step.  mems are sharded on their batch axis
    (dim 1 of (layers, B, mem, d)); state is replicated."""
    per_shard = make_txl_train_step(model, optimizer, policy, ddp=ddp,
                                    axis_name=axis_name,
                                    max_grad_norm=max_grad_norm)
    mem_spec = P(None, axis_name)
    sharded = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), mem_spec, (P(axis_name), P(axis_name))),
        out_specs=(P(), mem_spec, P()))
    return jax.jit(sharded,
                   donate_argnums=(0, 1) if donate else ())
