"""Workload-specific losses and step builders (C4 BERT-MLM, C5 TXL-LM).

The classification engine (engine.py) covers C1–C3.  BERT reuses it with an
MLM loss (the label pytree is (labels, weights)); Transformer-XL needs its
own step because segment recurrence threads a memory carry alongside the
train state — the memory is per-replica activation state (batch-sharded under
DDP, P(None, "data") on its (layers, B, mem, d) layout), unlike the
replicated TrainState.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_example_tpu import amp as amp_lib
from apex_example_tpu.amp.policy import Policy
from apex_example_tpu.engine import TrainState, _wrap_optimizer
from apex_example_tpu.ops.xentropy import softmax_cross_entropy
from apex_example_tpu.parallel.distributed import DDPConfig, allreduce_grads
from apex_example_tpu.parallel.mesh import DATA_AXIS

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def mlm_loss(logits: jnp.ndarray, target: Tuple[jnp.ndarray, jnp.ndarray]
             ) -> jnp.ndarray:
    """Masked-LM loss: mean CE over masked positions only (weights mark
    them).  target = (labels, weights).  Uses the fused-CE op: its backward
    rematerializes the (B, S, V) probability tensor instead of saving it —
    at vocab 30k that residual is the largest activation in the step
    (ops/xentropy.py, the contrib-xentropy analog)."""
    labels, weights = target
    ce = softmax_cross_entropy(logits, labels)
    denom = jnp.maximum(weights.sum(), 1.0)
    return (ce * weights).sum() / denom


def lm_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Next-token CE, mean over all positions (Transformer-XL objective)."""
    return softmax_cross_entropy(logits, labels).mean()


def _global_lm_loss(logits, labels, axes):
    """Next-token CE averaged over the GLOBAL position count: psum-ed sum /
    psum-ed count, so shards (whose local means would misweight) combine
    exactly to lm_loss on the full batch.  One definition shared by the CP
    train/eval and MoE 'lm' train/eval steps."""
    ce = softmax_cross_entropy(logits, labels)
    num = jax.lax.psum(ce.sum(), axes)
    den = jax.lax.psum(jnp.asarray(ce.size, jnp.float32), axes)
    return num / den


def make_txl_train_step(model, optimizer, policy: Policy,
                        ddp: Optional[DDPConfig] = None,
                        axis_name: Optional[str] = None,
                        max_grad_norm: float = 0.25,
                        grad_accum: int = 1):
    """Transformer-XL step: (state, mems, (inp, tgt)) → (state, mems', metrics).

    Mirrors the reference C5 recipe (SURVEY.md §1): FusedLayerNorm inside the
    model, global-norm grad clipping (the multi_tensor_l2norm path) before the
    update, segment recurrence via the mems carry.

    ``grad_accum=K`` splits the batch into K microbatches of independent
    *streams* (recurrence runs along time, not batch, so slicing the batch
    axis — of both the tokens and the (layers, B, mem, d) memory — keeps
    each stream's carry exact).  fp32 grads accumulate across microbatches,
    the clip/allreduce/step run once on the mean — the same convention as
    engine.make_train_step.
    """
    from apex_example_tpu.ops import clip_grad_norm

    opt = _wrap_optimizer(optimizer)
    ddp = ddp or DDPConfig()
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

    def train_step(state: TrainState, mems, batch):
        inp, tgt = batch

        def grads_for(mems_mb, inp_mb, tgt_mb):
            def scaled_loss_fn(params):
                logits, new_mems = model.apply({"params": params}, inp_mb,
                                               mems=mems_mb)
                loss = lm_loss(logits, tgt_mb)
                return amp_lib.scale_loss(loss, state.scaler), (loss,
                                                                new_mems)
            return jax.grad(scaled_loss_fn, has_aux=True)(state.params)

        if grad_accum == 1:
            grads, (loss, new_mems) = grads_for(mems, inp, tgt)
        else:
            k = grad_accum
            if inp.shape[0] % k:
                raise ValueError(f"batch {inp.shape[0]} not divisible by "
                                 f"grad_accum {k}")
            split = lambda a: a.reshape(k, a.shape[0] // k, *a.shape[1:])
            # mems batch axis is dim 1 of (layers, B, mem, d).
            mems_k = jax.tree_util.tree_map(
                lambda m: jnp.moveaxis(
                    m.reshape(m.shape[0], k, m.shape[1] // k, *m.shape[2:]),
                    1, 0), mems)
            def micro(mems_mb, inp_mb, tgt_mb):
                g, (l, nm) = grads_for(mems_mb, inp_mb, tgt_mb)
                return (jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.float32), g), l, nm)

            def body(carry, mb):
                gsum, lsum = carry
                gf, l, nm = micro(*mb)
                gsum = jax.tree_util.tree_map(jnp.add, gsum, gf)
                return (gsum, lsum + l), nm

            # Microbatch 0 runs outside the scan so the carry's per-leaf
            # shard-variance types match what the body produces (see
            # engine.make_train_step for the full rationale — a zeros init
            # is mesh-invariant and shard_map's vma check rejects it).
            xs = (mems_k, split(inp), split(tgt))
            g0, l0, nm0 = micro(*jax.tree_util.tree_map(
                lambda a: a[0], xs))
            (gsum, lsum), new_mems_rest = jax.lax.scan(
                body, (g0, l0),
                jax.tree_util.tree_map(lambda a: a[1:], xs))
            grads = jax.tree_util.tree_map(
                lambda a, p: (a / k).astype(p.dtype), gsum, state.params)
            loss = lsum / k
            new_mems_k = jax.tree_util.tree_map(
                lambda first, rest: jnp.concatenate([first[None], rest]),
                nm0, new_mems_rest)
            new_mems = jax.tree_util.tree_map(
                lambda m: jnp.moveaxis(m, 0, 1).reshape(
                    m.shape[1], -1, *m.shape[3:]), new_mems_k)
        if axis_name is not None:
            grads = allreduce_grads(grads, ddp, axis_name)
            loss = jax.lax.pmean(loss, axis_name)
        grads, grads_finite = amp_lib.unscale_grads(grads, state.scaler)
        grads, gnorm = clip_grad_norm(grads, max_grad_norm)

        new_params, new_opt_state = opt.apply(grads, state.opt_state,
                                              state.params)
        if policy.uses_dynamic_scaling:
            new_params = amp_lib.select_tree(grads_finite, new_params,
                                            state.params)
            new_opt_state = amp_lib.select_tree(grads_finite, new_opt_state,
                                                state.opt_state)
        scaler = amp_lib.update_scaler(state.scaler, grads_finite)

        metrics = {"loss": loss, "grad_norm": gnorm,
                   "ppl": jnp.exp(loss), "scale": scaler.scale,
                   "grads_finite": grads_finite.astype(jnp.float32)}
        new_state = TrainState(step=state.step + 1, params=new_params,
                               batch_stats=state.batch_stats,
                               opt_state=new_opt_state, scaler=scaler)
        return new_state, new_mems, metrics

    return train_step


def make_bert_eval_step(model):
    """(params, (ids, (labels, weights))) -> {loss, masked_acc}: MLM loss
    and accuracy over masked positions only — the LM counterpart of the
    image harness's eval loop (engine.make_eval_step; SURVEY.md §3.5)."""
    def eval_step(params, batch) -> Dict:
        ids, (labels, weights) = batch
        logits = model.apply({"params": params}, ids, train=False)
        hit = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        denom = jnp.maximum(weights.sum(), 1.0)
        return {"loss": mlm_loss(logits, (labels, weights)),
                "masked_acc": (hit * weights).sum() / denom * 100.0}
    return eval_step


def make_gpt_eval_step(model):
    """(params, (x, y)) -> {loss}: next-token CE on a held-out batch; the
    harness reports corpus ppl = exp(mean loss) like the TXL eval loop
    (GPT has no recurrence carry, so the signature is BERT-shaped)."""
    def eval_step(params, batch) -> Dict:
        x, y = batch
        logits = model.apply({"params": params}, x, train=False)
        return {"loss": lm_loss(logits, y)}
    return eval_step


def make_txl_eval_step(model):
    """(params, mems, (inp, tgt)) -> (new_mems, {loss}): held-out next-token
    loss, threading the recurrence memory exactly like training (the
    reference evaluates TXL with mems carried).  Perplexity belongs at the
    AGGREGATE level — exp(mean loss), computed by the caller over all eval
    batches; a per-batch exp would make the averaged number Jensen-biased
    toward outlier batches."""
    def eval_step(params, mems, batch):
        inp, tgt = batch
        logits, new_mems = model.apply({"params": params}, inp,
                                       mems=mems, train=False)
        return new_mems, {"loss": lm_loss(logits, tgt)}
    return eval_step


def make_sharded_txl_train_step(mesh: Mesh, model, optimizer, policy: Policy,
                                ddp: Optional[DDPConfig] = None,
                                max_grad_norm: float = 0.25,
                                axis_name: str = DATA_AXIS,
                                donate: bool = True,
                                grad_accum: int = 1):
    """DDP Transformer-XL step.  mems are sharded on their batch axis
    (dim 1 of (layers, B, mem, d)); state is replicated."""
    per_shard = make_txl_train_step(model, optimizer, policy, ddp=ddp,
                                    axis_name=axis_name,
                                    max_grad_norm=max_grad_norm,
                                    grad_accum=grad_accum)
    mem_spec = P(None, axis_name)
    sharded = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), mem_spec, (P(axis_name), P(axis_name))),
        out_specs=(P(), mem_spec, P()))
    return jax.jit(sharded,
                   donate_argnums=(0, 1) if donate else ())


def make_bert_cp_train_step(mesh: Mesh, model, optimizer, policy: Policy,
                            donate: bool = True, grad_accum: int = 1,
                            state_shardings=None):
    """Ring context-parallel BERT MLM step over a ('data', 'context') mesh
    (train.py --context-parallel) — the long-context training path.

    The global (B, L) batch shards batch-over-'data' and
    sequence-over-'context'; per-token work (embeddings, LN, FFN, head)
    runs on local shards, attention rides the ppermute KV ring
    (parallel/context_parallel.ring_attention, flash-composed so even
    per-chunk score tiles stay in VMEM).  The MLM loss is the globally
    normalized weighted CE (psum-ed sums over both axes — per-shard
    masked counts differ, so a mean-of-means would misweight shards);
    params are replicated over both axes, so their grads arrive
    implicitly psum-ed (incl. the custom-VJP LayerNorm via
    _vma.align_param_grad) and every replica applies the identical
    update.  No reference analog (SURVEY.md §3.2: CP absent there).
    """
    from apex_example_tpu.engine import TrainState, make_train_step
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS

    def cp_mlm_loss(logits, target):
        labels, weights = target
        axes = (DATA_AXIS, CONTEXT_AXIS)
        ce = softmax_cross_entropy(logits, labels)
        num = jax.lax.psum((ce * weights).sum(), axes)
        den = jnp.maximum(jax.lax.psum(weights.sum(), axes), 1.0)
        return num / den

    # grad_accum=K: the engine's microbatch scan splits the LOCAL batch dim;
    # each microbatch's loss is normalized by ITS OWN global (psum-ed)
    # masked count, so K-microbatch CP equals K-microbatch dense exactly
    # (both average per-microbatch globally-normalized losses).
    per_shard = make_train_step(model, optimizer, policy, axis_name=None,
                                loss_fn=cp_mlm_loss, compute_accuracy=False,
                                grad_accum=grad_accum)
    st_spec = _cp_state_spec(optimizer)
    sharded = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(st_spec, (P(DATA_AXIS, CONTEXT_AXIS),
                            (P(DATA_AXIS, CONTEXT_AXIS),
                             P(DATA_AXIS, CONTEXT_AXIS)))),
        out_specs=(st_spec, P()), **_cp_axis_names(mesh, model))
    jkw = {}
    if state_shardings is not None:
        # CP×TP: pin the returned state to its model-axis placement
        # (engine.gspmd_state_shardings) — the shard_map's out_specs only
        # govern the MANUAL axes, and with 'model' automatic the compiler
        # would otherwise be free to hand the updated params back
        # replicated, silently losing the TP sharding after one step.
        from jax.sharding import NamedSharding
        jkw["out_shardings"] = (state_shardings, NamedSharding(mesh, P()))
    return jax.jit(sharded, donate_argnums=(0,) if donate else (), **jkw)


def partial_manual_axis_names(mesh: Mesh, model, manual_axes: frozenset,
                              label: str) -> dict:
    """shard_map kwargs for a TP-composed step: with a nontrivial 'model'
    axis the map goes manual over ``manual_axes`` ONLY, leaving 'model'
    automatic so the GSPMD TP layers (tensor_parallel=True) run inside
    the manual program — the partially-manual composition shared by the
    CP x TP, MoE x TP and TP x PP paths.  Param model-axis shardings ride
    along from the arrays' placement (engine.gspmd_state_shardings)."""
    from apex_example_tpu.parallel.mesh import require_model_axis_match
    tp = require_model_axis_match(mesh, getattr(model, "tensor_parallel",
                                                False))
    if tp > 1 and not hasattr(jax, "shard_map"):  # pragma: no cover
        raise RuntimeError(
            f"the {label} composition needs jax.shard_map's axis_names "
            "(jax >= 0.7); the jax.experimental fallback cannot express "
            "a partially-manual mesh")
    return {"axis_names": set(manual_axes)} if tp > 1 else {}


def _cp_axis_names(mesh: Mesh, model) -> dict:
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
    return partial_manual_axis_names(
        mesh, model, frozenset({DATA_AXIS, CONTEXT_AXIS}), "CP x TP")


def _cp_state_spec(optimizer):
    """shard_map TrainState spec for the CP steps: everything replicated
    EXCEPT a ZeRO optimizer's state (ZeRO x CP, round 5) — the flat
    (mu, nu) buffers shard over 'data' while params stay replicated over
    both axes.  The optimizer's reduce/slice/all-gather collectives run
    over 'data' inside the same shard_map; grads arrive implicitly
    psum-ed over BOTH axes (replicated params), so the update is
    context-invariant by construction."""
    from apex_example_tpu.engine import TrainState
    from apex_example_tpu.optim.distributed import DistributedFusedAdam
    if isinstance(optimizer, DistributedFusedAdam):
        return TrainState(step=P(), params=P(), batch_stats=P(),
                          opt_state=optimizer.state_spec(), scaler=P())
    return P()


def make_bert_cp_eval_step(mesh: Mesh, model):
    """Sequence-sharded held-out eval under the same KV ring as CP training
    (train.py --context-parallel --eval).

    Without this, the CP path could train at a context length the dense
    eval forward cannot touch: a single-device eval materializes the
    (L, L) score tensor CP exists to shard.  Shapes, collectives and the
    globally psum-normalized loss/masked-acc mirror
    :func:`make_bert_cp_train_step`'s forward exactly; the metrics are
    bit-comparable to the dense eval on the same params (tested).
    """
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS

    def per_shard(params, batch):
        ids, (labels, weights) = batch
        logits = model.apply({"params": params}, ids, train=False)
        axes = (DATA_AXIS, CONTEXT_AXIS)
        ce = softmax_cross_entropy(logits, labels)
        den = jnp.maximum(jax.lax.psum(weights.sum(), axes), 1.0)
        hit = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        return {"loss": jax.lax.psum((ce * weights).sum(), axes) / den,
                "masked_acc": jax.lax.psum((hit * weights).sum(), axes)
                / den * 100.0}

    spec = P(DATA_AXIS, CONTEXT_AXIS)
    sharded = _shard_map(per_shard, mesh=mesh,
                         in_specs=(P(), (spec, (spec, spec))),
                         out_specs=P(), **_cp_axis_names(mesh, model))
    return jax.jit(sharded)


def _cp_layout_wrap(fn, mesh, model, mode: str):
    """Shared CP-layout plumbing for the GPT CP train/eval factories:
    enforce that the factory's mode and the model's cp_mode agree (a
    mismatch trains/evals on inconsistently ordered data or the wrong
    attention program with no error), and wrap ``fn`` with the
    zigzag_shard pre-pass when the layout calls for it (ring and ulysses
    both use contiguous chunks — no reorder)."""
    model_mode = getattr(model, "cp_mode", "ring")
    if mode != model_mode:
        raise ValueError(
            f"mode={mode!r} but model.cp_mode={model_mode!r} — the batch "
            "layout and the model's position ids/attention program must "
            "agree or the computation is silently wrong")
    if mode != "zigzag":
        return fn
    from apex_example_tpu.parallel.context_parallel import zigzag_shard
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
    n = mesh.shape[CONTEXT_AXIS]

    def wrapped(carry, batch):
        x, y = batch
        return fn(carry, (zigzag_shard(x, n), zigzag_shard(y, n)))
    return wrapped


def make_gpt_cp_train_step(mesh: Mesh, model, optimizer, policy: Policy,
                           donate: bool = True, grad_accum: int = 1,
                           state_shardings=None, mode: str = "ring"):
    """Ring context-parallel GPT step over a ('data', 'context') mesh
    (train.py --context-parallel with a gpt arch).

    Same shape as :func:`make_bert_cp_train_step` with two causal
    specifics: attention runs the CAUSAL KV ring (future chunks skipped,
    diagonal chunk masked blockwise — models/bert.BertSelfAttention
    causal=True under context_parallel), and the objective is next-token
    CE averaged over the GLOBAL position count (a psum-ed sum / psum-ed
    count, so shard means never misweight).  The (x, y) pair arrives
    pre-shifted from the harness; both shard batch-over-'data' and
    sequence-over-'context' in the same contiguous chunk order the ring
    and the position offsets key on.

    ``mode`` selects the CP attention program and must match the model's
    ``cp_mode``: "ring" (contiguous causal KV ring), "zigzag" (the
    load-BALANCED causal ring — the factory reorders both sequences with
    ``zigzag_shard`` before the shard_map, so P('context') hands device i
    its (i, 2n-1-i) chunk pair and every ring step does identical live
    work), or "ulysses" (all-to-all head sharding: full sequence per
    device, H/N heads per device, exact attention).  Losses/grads are
    order-invariant sums, so every mode's trajectory equals the dense
    model exactly.
    """
    from apex_example_tpu.engine import make_train_step
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS

    def cp_lm_loss(logits, y):
        return _global_lm_loss(logits, y, (DATA_AXIS, CONTEXT_AXIS))

    per_shard = make_train_step(model, optimizer, policy, axis_name=None,
                                loss_fn=cp_lm_loss, compute_accuracy=False,
                                grad_accum=grad_accum)
    spec = P(DATA_AXIS, CONTEXT_AXIS)
    st_spec = _cp_state_spec(optimizer)
    sharded = _shard_map(per_shard, mesh=mesh,
                         in_specs=(st_spec, (spec, spec)),
                         out_specs=(st_spec, P()),
                         **_cp_axis_names(mesh, model))
    sharded = _cp_layout_wrap(sharded, mesh, model, mode)
    jkw = {}
    if state_shardings is not None:
        from jax.sharding import NamedSharding
        jkw["out_shardings"] = (state_shardings, NamedSharding(mesh, P()))
    return jax.jit(sharded, donate_argnums=(0,) if donate else (), **jkw)


def make_gpt_cp_eval_step(mesh: Mesh, model, mode: str = "ring"):
    """Sequence-sharded held-out eval under the same causal KV ring
    (train.py --context-parallel --eval, gpt archs): loss at the training
    context length, psum-normalized globally."""
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS

    def per_shard(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x, train=False)
        return {"loss": _global_lm_loss(logits, y,
                                        (DATA_AXIS, CONTEXT_AXIS))}

    spec = P(DATA_AXIS, CONTEXT_AXIS)
    sharded = _shard_map(per_shard, mesh=mesh,
                         in_specs=(P(), (spec, spec)), out_specs=P(),
                         **_cp_axis_names(mesh, model))
    return jax.jit(_cp_layout_wrap(sharded, mesh, model, mode))


def make_gspmd_txl_train_step(mesh: Mesh, model, optimizer, policy: Policy,
                              state_shardings,
                              max_grad_norm: float = 0.25,
                              donate: bool = True,
                              grad_accum: int = 1):
    """Tensor-parallel Transformer-XL step (the train.py --tensor-parallel
    path): same *annotate, don't orchestrate* contract as
    ``engine.make_gspmd_train_step`` — the plain single-device TXL step
    jitted with the TP layers' param shardings, batch AND the (layers, B,
    mem, d) memory carry sharded on 'data', Megatron collectives inserted
    by GSPMD at the layers' constraint points."""
    from jax.sharding import NamedSharding

    step = make_txl_train_step(model, optimizer, policy, axis_name=None,
                               max_grad_norm=max_grad_norm,
                               grad_accum=grad_accum)
    mems_sh = NamedSharding(mesh, P(None, DATA_AXIS))
    batch_sh = NamedSharding(mesh, P(DATA_AXIS))
    metrics_sh = NamedSharding(mesh, P())
    return jax.jit(step,
                   in_shardings=(state_shardings, mems_sh, batch_sh),
                   out_shardings=(state_shardings, mems_sh, metrics_sh),
                   donate_argnums=(0, 1) if donate else ())


# ------------------- Expert-parallel (MoE) BERT --------------------------
#
# The harness face of transformer/expert_parallel.py (train.py
# --moe-experts): switch-MoE encoder FFNs with E/n experts per device over
# the 'data' axis — EP rides the DP devices the way DeepSpeed-MoE does, so
# no new mesh axis is needed and every token still trains on its home
# shard.  No reference analog (SURVEY.md §3.2: EP documented as absent
# there); this is the same "library feature -> harness-reachable" move the
# CP path made in round 3.

def _is_expert_leaf(path) -> bool:
    """The ONE definition of which param leaves are EP-sharded expert
    stacks (under a 'moe' module, named w_in/w_out): used by both the
    shard_map spec tree and the device-placement overlay — they must
    never disagree or placement and specs silently diverge."""
    keys = {getattr(p, "key", None) for p in path}
    return "moe" in keys and ("w_in" in keys or "w_out" in keys)


def _moe_param_spec_tree(params):
    """P(DATA_AXIS) for the stacked [E, ...] expert weights (one expert
    per data-axis device), P() for everything else (router, attention,
    embeddings, head: replicated, their grads arrive implicitly
    psum-ed)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _leaf: P(DATA_AXIS) if _is_expert_leaf(path) else P(),
        params)


def bert_moe_state_specs(state: TrainState, optimizer) -> TrainState:
    """PartitionSpec TrainState for the EP step: expert stacks shard over
    'data', optimizer state mirrors its params-shaped fields
    (engine._opt_state_specs), all else replicates."""
    from apex_example_tpu.engine import _opt_state_specs
    tmap = jax.tree_util.tree_map
    pspecs = _moe_param_spec_tree(state.params)
    abs_params = tmap(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                      state.params)
    return TrainState(
        step=P(), params=pspecs,
        batch_stats=tmap(lambda _: P(), state.batch_stats),
        opt_state=_opt_state_specs(optimizer, abs_params, pspecs),
        scaler=tmap(lambda _: P(), state.scaler))


def bert_moe_state_shardings(mesh: Mesh, state: TrainState, optimizer,
                             base_shardings=None) -> TrainState:
    """NamedSharding tree for device_put / the orbax restore template.

    ``base_shardings`` (MoE x TP): the GSPMD NamedSharding tree from
    create_gspmd_train_state — non-expert leaves keep their model-axis
    placement, the expert stacks are overridden to P('data') (they are
    model-replicated; each data-axis device owns E/n experts)."""
    from jax.sharding import NamedSharding
    if base_shardings is None:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            bert_moe_state_specs(state, optimizer),
            is_leaf=lambda v: isinstance(v, P))

    # Overlay on the BASE tree by path (its structure may collapse
    # sharding-uniform subtrees like the scaler into one leaf): exactly
    # the expert-stack leaves (_is_expert_leaf, the same predicate the
    # spec tree uses) switch to P('data').
    return jax.tree_util.tree_map_with_path(
        lambda path, base_leaf: NamedSharding(mesh, P(DATA_AXIS))
        if _is_expert_leaf(path) else base_leaf, base_shardings)


def _moe_axis_names(mesh: Mesh, model) -> dict:
    return partial_manual_axis_names(mesh, model, frozenset({DATA_AXIS}),
                                     "MoE x TP")


def _moe_cp_axis_names(mesh: Mesh, model) -> dict:
    """EP x CP: manual over 'data' (expert all_to_all) AND 'context' (KV
    ring) jointly; 'model' would stay automatic but the TP triple
    composition is not wired (train.py rejects it)."""
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
    return partial_manual_axis_names(
        mesh, model, frozenset({DATA_AXIS, CONTEXT_AXIS}), "MoE x CP x TP")


def _moe_batch_plumbing(mesh: Mesh, model, objective: str,
                        context_parallel: bool, mode: str):
    """The EP / EP x CP spec-and-layout epilogue the MoE train AND eval
    factories share: (per-item batch spec, shard_map manual-axes kwargs,
    layout wrapper).  One home so the mode validation and the zigzag
    pre-pass can never drift between the two paths."""
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
    if not context_parallel:
        return P(DATA_AXIS), _moe_axis_names(mesh, model), lambda fn: fn
    if objective == "mlm" and mode == "zigzag":
        # (the model layer rejects zigzag for non-causal attention anyway;
        # this keeps the error at the factory boundary)
        raise ValueError("zigzag is the load-balanced CAUSAL layout; "
                         "MLM BERT uses ring or ulysses")
    # ring/ulysses need no reorder, so for MLM the wrap is just the
    # mode<->model.cp_mode agreement check; the zigzag pre-pass only
    # ever fires on the (x, y) LM pair shape.
    return (P(DATA_AXIS, CONTEXT_AXIS), _moe_cp_axis_names(mesh, model),
            lambda fn: _cp_layout_wrap(fn, mesh, model, mode))


def _check_moe_model(mesh: Mesh, model, optimizer=None):
    E = mesh.shape[DATA_AXIS]
    if not model.moe_experts:
        raise ValueError("model has moe_experts=0; build it with "
                         "moe_experts=<data-axis size>")
    if model.moe_experts % E:
        raise ValueError(
            f"moe_experts={model.moe_experts} must be a multiple of the "
            f"data-axis size {E} (the all_to_all splits the [E, C, d] "
            f"dispatch buffer {E}-ways; each device owns "
            f"moe_experts/{E} experts)")
    if model.moe_axis_name != DATA_AXIS:
        raise ValueError(
            f"model.moe_axis_name={model.moe_axis_name!r} but the EP step "
            f"maps over {DATA_AXIS!r}; build the model with "
            f"moe_axis_name=DATA_AXIS or MoEMLP silently falls back to "
            f"its dense reference path")
    if optimizer is not None:
        from apex_example_tpu.optim.fused import FusedLAMB, FusedNovoGrad
        if isinstance(optimizer, (FusedLAMB, FusedNovoGrad)):
            raise ValueError(
                f"{type(optimizer).__name__} computes per-TENSOR statistics "
                "(trust ratio / ||g||^2 EMA); on the EP-sharded [E, ...] "
                "expert stacks each shard would see only its slice, "
                "silently diverging from the dense-model semantics — use "
                "adam/sgd/adagrad under --moe-experts")


def make_bert_moe_train_step(mesh: Mesh, model, optimizer, policy: Policy,
                             state_template: TrainState,
                             aux_weight: float = 1e-2,
                             donate: bool = True, grad_accum: int = 1,
                             objective: str = "mlm",
                             state_shardings=None,
                             context_parallel: bool = False,
                             mode: str = "ring"):
    """Expert-parallel BERT MLM step over the 'data' axis (train.py
    --moe-experts).

    The model returns (logits, aux); the objective is the globally
    psum-normalized masked CE plus ``aux_weight`` x the Switch
    load-balancing loss (already pmean-ed over the axis inside
    moe_forward).  Replicated-param grads arrive implicitly psum-ed
    through the psum-ed loss (the CP-step mechanism); the expert stacks'
    grads stay shard-local — each device owns its experts.  The dynamic-
    scaling finite flag is pmean-ed over 'data'
    (engine.make_train_step(finite_reduce_axes=...)): a local overflow in
    one expert's grads must skip the step and halve the scale on EVERY
    shard or the replicated scaler state diverges.

    ``context_parallel``: the EP x CP composition (train.py --moe-experts
    --context-parallel, the modern long-context-MoE stack): the batch
    additionally shards sequence-over-'context', attention rides the
    causal/ring KV programs on that axis, and the MoE all_to_all over
    'data' runs independently per context column — two manual axes, two
    independent collectives in one body.  Routing/capacity stay
    per-(data, context)-shard (the same per-device contract the pure EP
    path pins); the aux loss is additionally pmean-ed over 'context' so
    the objective (and the metrics' mesh-invariance) see the mean expert
    balance across sequence shards.  ``mode`` selects the CP attention
    program (ring/zigzag/ulysses; must match the model's cp_mode).
    """
    from apex_example_tpu.engine import make_train_step
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
    _check_moe_model(mesh, model, optimizer)
    if objective not in ("mlm", "lm"):
        raise ValueError(f"objective must be 'mlm' or 'lm', "
                         f"got {objective!r}")
    loss_axes = (DATA_AXIS, CONTEXT_AXIS) if context_parallel else DATA_AXIS

    def moe_loss(out, target):
        logits, aux = out
        if context_parallel:
            # per-context-column aux (moe_forward pmean-ed 'data' only)
            aux = jax.lax.pmean(aux, CONTEXT_AXIS)
        if objective == "mlm":
            labels, weights = target
            ce = softmax_cross_entropy(logits, labels)
            num = jax.lax.psum((ce * weights).sum(), loss_axes)
            den = jnp.maximum(jax.lax.psum(weights.sum(), loss_axes), 1.0)
            return (num / den
                    + jnp.asarray(aux_weight, jnp.float32) * aux)
        # next-token CE (MoE GPT)
        return (_global_lm_loss(logits, target, loss_axes)
                + jnp.asarray(aux_weight, jnp.float32) * aux)

    per_shard = make_train_step(model, optimizer, policy, axis_name=None,
                                loss_fn=moe_loss,
                                compute_accuracy=False,
                                grad_accum=grad_accum,
                                finite_reduce_axes=DATA_AXIS)
    # state_template fixes the spec TREE only (the per-leaf expert-vs-
    # replicated split); shapes/values are irrelevant, so the pre-
    # device_put host state works fine.
    spec_state = bert_moe_state_specs(state_template, optimizer)
    b, manual, wrap = _moe_batch_plumbing(mesh, model, objective,
                                          context_parallel, mode)
    batch_spec = (b, (b, b)) if objective == "mlm" else (b, b)
    sharded = wrap(_shard_map(per_shard, mesh=mesh,
                              in_specs=(spec_state, batch_spec),
                              out_specs=(spec_state, P()), **manual))
    jkw = {}
    if state_shardings is not None:
        # MoE x TP: pin the returned state to its combined placement
        # (expert stacks over 'data', TP leaves over 'model') — with
        # 'model' automatic the compiler would otherwise be free to hand
        # the updated params back replicated on that axis.
        from jax.sharding import NamedSharding
        jkw["out_shardings"] = (state_shardings, NamedSharding(mesh, P()))
    return jax.jit(sharded, donate_argnums=(0,) if donate else (), **jkw)


def make_bert_moe_eval_step(mesh: Mesh, model, params_template,
                            objective: str = "mlm",
                            context_parallel: bool = False,
                            mode: str = "ring"):
    """Expert-parallel held-out eval: same mesh, same all_to_all dispatch,
    metrics psum-normalized globally (mirrors make_bert_cp_eval_step's
    contract; --moe-experts --eval).  objective='lm' evaluates next-token
    CE for MoE GPT ({loss} only — the harness reports ppl).
    ``context_parallel``: sequence-sharded EP x CP eval under the same KV
    ring + per-column expert dispatch as training."""
    from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
    _check_moe_model(mesh, model)
    if objective not in ("mlm", "lm"):
        raise ValueError(f"objective must be 'mlm' or 'lm', "
                         f"got {objective!r}")
    axes = (DATA_AXIS, CONTEXT_AXIS) if context_parallel else DATA_AXIS

    def per_shard(params, batch):
        if objective == "mlm":
            ids, (labels, weights) = batch
            logits, _aux = model.apply({"params": params}, ids, train=False)
            ce = softmax_cross_entropy(logits, labels)
            den = jnp.maximum(jax.lax.psum(weights.sum(), axes), 1.0)
            hit = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
            return {"loss":
                    jax.lax.psum((ce * weights).sum(), axes) / den,
                    "masked_acc":
                    jax.lax.psum((hit * weights).sum(), axes)
                    / den * 100.0}
        x, y = batch
        logits, _aux = model.apply({"params": params}, x, train=False)
        return {"loss": _global_lm_loss(logits, y, axes)}

    b, manual, wrap = _moe_batch_plumbing(mesh, model, objective,
                                          context_parallel, mode)
    batch_spec = (b, (b, b)) if objective == "mlm" else (b, b)
    sharded = wrap(_shard_map(per_shard, mesh=mesh,
                              in_specs=(_moe_param_spec_tree(
                                  params_template), batch_spec),
                              out_specs=P(), **manual))
    return jax.jit(sharded)
