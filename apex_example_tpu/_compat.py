"""Version bridges for pre-vma jax (< 0.7).

The codebase targets the vma-typed shard_map era (``jax.typeof``,
``lax.axis_size``, ``lax.pcast``).  On older jax those APIs are absent but
the semantics have classic spellings; routing the handful of call sites
through this module keeps every path importable — and most of them
runnable — on both.  On vma-era jax each shim is exactly the new API.
"""

from __future__ import annotations

import jax
from jax import lax

_TYPEOF = getattr(jax, "typeof", None)

# True on vma-era jax (>= 0.7): shard-variance is typed and the pallas
# kernels' interpret-mode path can run (ops/_config.py keys off this).
HAS_VMA = _TYPEOF is not None

if not hasattr(lax, "pcast"):
    # Pre-vma jax: the old check_rep shard_map needs an explicit
    # replication rule per primitive, and `name` (ad_checkpoint's
    # checkpoint_name, used by the remat-annotated models) never got one
    # upstream.  It is rep-transparent — the standard rule is exact.
    try:  # pragma: no cover - version-dependent
        from jax._src.ad_checkpoint import name_p
        from jax.experimental import shard_map as _sm
        _sm.register_standard_check(name_p)
        _sm.register_standard_rewrite(name_p)
    except Exception:
        pass


def axis_size(axis_name):
    """``lax.axis_size``, or the classic ``psum(1)`` spelling before it
    existed (a compile-time constant either way)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def pcast(x, axis_name, to="varying"):
    """``lax.pcast`` where it exists; a no-op before variance typing (there
    is no vma to cast — old shard_map tracks replication per-eqn
    instead)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to=to)
    return x


def vma_of(x) -> frozenset:
    """The value's shard-variance set; empty on pre-vma jax (variance is
    untracked there, and every query degrades to 'invariant')."""
    if _TYPEOF is None:
        return frozenset()
    return getattr(_TYPEOF(x), "vma", frozenset())


class _NoAbstractMesh:
    """Stand-in for ``jax.sharding.get_abstract_mesh()``'s result on jax
    versions that predate abstract meshes: no axes are trace-manual (the
    partially-manual shard_map compositions that NEED manual-axis
    detection also need the vma-era shard_map, so on pre-vma jax every
    constraint targets the registered concrete mesh)."""

    manual_axes: tuple = ()
    shape: dict = {}


def get_abstract_mesh():
    """``jax.sharding.get_abstract_mesh`` where it exists; a no-manual-
    axes stand-in before abstract meshes (jax <= 0.4.x).  Keeps the TP
    layers' ``constrain``/``batch_axis`` — and with them TP generate()
    and the TP-sharded serve engine — working under plain-GSPMD jit on
    the pinned CPU-rig jax."""
    fn = getattr(jax.sharding, "get_abstract_mesh", None)
    if fn is not None:
        return fn()
    return _NoAbstractMesh()
