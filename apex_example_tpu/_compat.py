"""Version bridges for pre-vma jax (< 0.7).

The codebase targets the vma-typed shard_map era (``jax.typeof``,
``lax.axis_size``, ``lax.pcast``).  On older jax those APIs are absent but
the semantics have classic spellings; routing the handful of call sites
through this module keeps every path importable — and most of them
runnable — on both.  On vma-era jax each shim is exactly the new API.
"""

from __future__ import annotations

import jax
from jax import lax

_TYPEOF = getattr(jax, "typeof", None)

# True on vma-era jax (>= 0.7): shard-variance is typed and the pallas
# kernels' interpret-mode path can run (ops/_config.py keys off this).
HAS_VMA = _TYPEOF is not None

if not hasattr(lax, "pcast"):
    # Pre-vma jax: the old check_rep shard_map needs an explicit
    # replication rule per primitive, and `name` (ad_checkpoint's
    # checkpoint_name, used by the remat-annotated models) never got one
    # upstream.  It is rep-transparent — the standard rule is exact.
    try:  # pragma: no cover - version-dependent
        from jax._src.ad_checkpoint import name_p
        from jax.experimental import shard_map as _sm
        _sm.register_standard_check(name_p)
        _sm.register_standard_rewrite(name_p)
    except Exception:
        pass


def axis_size(axis_name):
    """``lax.axis_size``, or the classic ``psum(1)`` spelling before it
    existed (a compile-time constant either way)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def pcast(x, axis_name, to="varying"):
    """``lax.pcast`` where it exists; a no-op before variance typing (there
    is no vma to cast — old shard_map tracks replication per-eqn
    instead)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to=to)
    return x


def vma_of(x) -> frozenset:
    """The value's shard-variance set; empty on pre-vma jax (variance is
    untracked there, and every query degrades to 'invariant')."""
    if _TYPEOF is None:
        return frozenset()
    return getattr(_TYPEOF(x), "vma", frozenset())
