"""LARC — layerwise adaptive rate clipping (reference: apex/parallel/LARC.py).

The reference wraps a torch optimizer and rescales each param group's gradient
by ``trust_coefficient * ||p|| / (||g|| + wd * ||p||)`` (clipped at 1.0 in
"clip" mode) before the inner step.  Optax-native restatement: a
GradientTransformation chained *before* the inner optimizer; "layerwise"
means per-leaf of the param pytree, which matches torch's per-parameter
granularity.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class LARCState(NamedTuple):
    pass


def larc(trust_coefficient: float = 0.02, clip: bool = True,
         eps: float = 1e-8, weight_decay: float = 0.0,
         lr: float = None) -> optax.GradientTransformation:
    """Per-leaf adaptive LR scaling; chain as
    ``optax.chain(larc(...), inner)``.

    ``lr`` is the outer learning rate the inner transform will apply.  apex's
    clip mode computes ``decay = min(adaptive_lr / lr, 1)`` so the effective
    step is ``min(adaptive_lr, lr)``; since optax applies lr later in the
    chain, clip mode needs lr here to reproduce that semantics.
    """
    if clip and lr is None:
        raise ValueError("clip mode requires the outer lr "
                         "(apex: decay = min(adaptive_lr / group_lr, 1))")

    def init_fn(params):
        del params
        return LARCState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("larc requires params")

        def scale_one(g, p):
            pn = jnp.linalg.norm(p.astype(jnp.float32).ravel())
            gn = jnp.linalg.norm(g.astype(jnp.float32).ravel())
            adaptive = trust_coefficient * pn / (gn + weight_decay * pn + eps)
            # Zero-param tensors (fresh biases): leave the update alone.
            adaptive = jnp.where(pn > 0, adaptive, 1.0)
            adaptive = jnp.where(gn > 0, adaptive, 1.0)
            if clip:
                # apex clip mode: effective step min(adaptive_lr, lr); the
                # outer lr multiplies later in the chain, so clamp the RATIO.
                adaptive = jnp.minimum(adaptive / lr, 1.0)
            if weight_decay:
                g = g + weight_decay * p
            return (g.astype(jnp.float32) * adaptive).astype(g.dtype)

        return jax.tree_util.tree_map(scale_one, updates, params), state

    return optax.GradientTransformation(init_fn, update_fn)


class LARC:
    """apex-shaped facade over :func:`larc` for ctor-surface parity.

    ``weight_decay`` belongs HERE, not on the inner optimizer: apex's LARC
    zeroes the group's wd, folds it into the trust-ratio denominator
    (adaptive = trust·‖p‖/(‖g‖ + wd·‖p‖ + eps)) and scales (g + wd·p) by
    the ratio — wd applied after the scaling would be a different update.
    """

    def __init__(self, optimizer: optax.GradientTransformation,
                 trust_coefficient: float = 0.02, clip: bool = True,
                 eps: float = 1e-8, lr: float = None,
                 weight_decay: float = 0.0):
        self.transform = optax.chain(
            larc(trust_coefficient=trust_coefficient, clip=clip, eps=eps,
                 lr=lr, weight_decay=weight_decay),
            optimizer)

    def init(self, params):
        return self.transform.init(params)

    def update(self, grads, state, params=None):
        return self.transform.update(grads, state, params)
