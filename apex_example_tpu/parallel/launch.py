"""Multi-host launch wiring (layer L6, SURVEY.md §2/§3.3/§4.1).

The reference is launched one-process-per-GPU by ``torch.distributed.launch``
with the TCP rendezvous described by ``MASTER_ADDR/MASTER_PORT/RANK/
WORLD_SIZE``.  The TPU-native process model is one process per HOST:
``jax.distributed.initialize()`` performs the rendezvous, after which
``jax.devices()`` spans every chip in the slice and the mesh/collective
machinery works unchanged — the per-device fork of the reference collapses
into the runtime (SURVEY.md §4.1 "TPU equivalent").

Env contract (first match wins):

1. JAX-native: ``JAX_COORDINATOR_ADDRESS`` (+ optional
   ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID`` — on TPU pods both are
   inferred from the metadata server, so the address alone suffices).
2. Reference-parity (torch names, so existing launch scripts carry over):
   ``MASTER_ADDR`` + ``MASTER_PORT`` + ``WORLD_SIZE`` + ``RANK``.
   ``WORLD_SIZE``/``RANK`` here count **hosts**, not devices — the one
   semantic delta from torch.distributed.launch, documented rather than
   hidden.

With neither set this is a no-op and the framework runs single-process —
the same collapse rule train.py has always had.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax

_initialized = False


def _parse_env(env=None) -> Optional[dict]:
    """Extract jax.distributed.initialize kwargs from the environment, or
    None when no multi-host rendezvous is configured."""
    env = os.environ if env is None else env
    if env.get("JAX_COORDINATOR_ADDRESS"):
        kw = {"coordinator_address": env["JAX_COORDINATOR_ADDRESS"]}
        if env.get("JAX_NUM_PROCESSES"):
            kw["num_processes"] = int(env["JAX_NUM_PROCESSES"])
        if env.get("JAX_PROCESS_ID"):
            kw["process_id"] = int(env["JAX_PROCESS_ID"])
        return kw
    if env.get("MASTER_ADDR") and env.get("WORLD_SIZE"):
        if int(env["WORLD_SIZE"]) <= 1:
            return None          # degenerate single-host launch
        return {
            "coordinator_address":
                f'{env["MASTER_ADDR"]}:{env.get("MASTER_PORT", "12355")}',
            "num_processes": int(env["WORLD_SIZE"]),
            "process_id": int(env.get("RANK", "0")),
        }
    return None


def maybe_initialize_distributed(env=None) -> Tuple[int, int]:
    """Rendezvous if the environment asks for it; returns
    ``(process_index, process_count)``.

    Idempotent; must run before the first device use (the backend is
    fixed at first touch — same constraint as torch's init_process_group
    before CUDA calls, SURVEY.md §4.1).
    """
    global _initialized
    kw = _parse_env(env)
    if kw is not None and not _initialized:
        jax.distributed.initialize(**kw)
        _initialized = True
    return jax.process_index(), jax.process_count()


def is_main_process() -> bool:
    """The rank-0 predicate (reference: ``rank == 0`` guards around
    checkpoint writes and logging)."""
    return jax.process_index() == 0
