"""Data-parallel gradient reduction — the DDP-equivalent layer.

Reference (apex/parallel/distributed.py, SURVEY.md §3.2/§4.3): apex's
``DistributedDataParallel`` registers per-param backward hooks that assemble
~10M-element buckets in grad-ready order and fire ``ncclAllReduce`` overlapped
with the rest of backward; ``delay_allreduce=True`` instead does one flat
allreduce after backward.  The C++ ``apex_C`` flatten/unflatten extension
exists purely to feed NCCL contiguous buffers.

TPU-native design: the gradient allreduce is a ``lax.psum`` over the ``data``
mesh axis *inside* the jitted step.  XLA's latency-hiding scheduler decomposes
and overlaps the collective with the backward computation automatically, which
subsumes the hand-built bucketing (bucket assembly, ready-order tracking, and
the flatten extension have no TPU analog — the compiler owns buffer layout;
this is the documented why for csrc/flatten_unflatten.cpp in SURVEY.md §2.1).
``delay_allreduce`` semantics (single reduction at end of backward) are the
*default* semantics of psum-at-step-end; hence the flag is accepted and
recorded but changes nothing on TPU.

What remains meaningful from the ctor surface is kept with identical names and
faithful numerics:

- ``gradient_average``            — divide the summed grads by world size.
- ``gradient_predivide_factor``   — pre-divide locally by f, post-divide the
  sum by world/f (overflow headroom for fp16 sums).
- ``allreduce_always_fp32``       — upcast grads to fp32 for the reduction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu._compat import axis_size, vma_of
from apex_example_tpu.parallel.mesh import DATA_AXIS


@dataclasses.dataclass(frozen=True)
class DDPConfig:
    """Ctor-surface parity with apex.parallel.DistributedDataParallel.

    ``quantized_allreduce`` (ISSUE 13; EQuARX, PAPERS.md) goes beyond
    the reference surface: the gradient exchange rides int8.  Per
    ``quant_chunk``-element chunk, the devices agree on ONE shared
    max-abs scale (a pmax — every replica must quantize onto the same
    grid or the sum is meaningless), round their local chunk onto it,
    psum the integers with an int32 accumulator (world * 127 per
    element can never wrap), and multiply the sum back by the scale.
    The exchange bytes drop 4x (f32) / 2x (bf16) wire-side; the psum's
    accumulator width is an implementation detail of the reduction,
    exactly as NCCL's fp32 accumulation is for the reference.

    Error bound, documented and pinned by tests/test_parallel.py: each
    replica contributes a rounding error <= scale/2 per element, so
    ``|quantized - exact| <= world * scale / 2`` element-wise, with
    ``scale = max_over_replicas(chunk max-abs) / 127``.  Composition
    with ``allreduce_always_fp32`` is strict: the quantized path always
    scales/accumulates/dequantizes in f32 (there is nothing wider to
    upcast to), then restores the gradient dtype — so flipping
    allreduce_always_fp32 under quantization changes nothing, which is
    the only composition that cannot silently double-round.

    ``quantized_allreduce=False`` (the default) leaves the psum path
    byte-identical to the unquantized implementation.
    """
    gradient_average: bool = True
    gradient_predivide_factor: float = 1.0
    allreduce_always_fp32: bool = False
    quantized_allreduce: bool = False
    quant_chunk: int = 1024
    # Accepted for CLI/API parity; no-ops on TPU (see module docstring):
    delay_allreduce: bool = True
    message_size: int = 10_000_000


def allreduce_grads(grads: Any, config: DDPConfig = DDPConfig(),
                    axis_name: str = DATA_AXIS,
                    already_reduced: Optional[bool] = None) -> Any:
    """psum gradients over the data axis with apex's averaging semantics.

    Must run inside a ``shard_map``/``pmap`` context where ``axis_name`` is
    bound.  Equivalent position in the reference call stack: the DDP backward
    hooks / flat allreduce (SURVEY.md §4.3).

    ``already_reduced``: under vma-checked shard_map (the default, and what
    the engine uses) this is inferred per leaf from the aval — jax.grad wrt
    replicated params yields already-psum'd (invariant) grads.  Under
    ``check_vma=False`` vma information is absent, so callers must pass it
    explicitly (False for raw per-shard grads).
    """
    world = axis_size(axis_name)
    pre = config.gradient_predivide_factor
    post = (world / pre) if config.gradient_average else (1.0 / pre)

    def reduce_one(g):
        dt = g.dtype
        if already_reduced is None:
            vma = vma_of(g)
            reduced = axis_name not in vma
        else:
            reduced = already_reduced
        if reduced:
            # Already cross-replica-summed: under shard_map's vma semantics,
            # jax.grad of a shard-local loss w.r.t. *replicated* params
            # transposes the implicit replication into a psum — the allreduce
            # has effectively happened inside backward (and XLA overlaps it
            # there, exactly like the reference's bucketed hooks).  Only the
            # averaging convention remains to apply.
            if config.gradient_average:
                g = (g.astype(jnp.float32) / world).astype(dt)
            return g
        if config.quantized_allreduce:
            g = _quantized_psum(g, axis_name, config)
            if post != 1.0:
                g = g / post
            return g.astype(dt)
        if config.allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if pre != 1.0:
            g = g / pre
        g = lax.psum(g, axis_name)
        if post != 1.0:
            g = g / post
        return g.astype(dt)

    return jax.tree_util.tree_map(reduce_one, grads)


def _quantized_psum(g, axis_name: str, config: DDPConfig):
    """Shared-scale int8 chunk reduction (DDPConfig docstring).  Input
    may be pre-divided; output is the f32 SUM (the caller applies the
    averaging convention, same as the unquantized path).
    """
    from apex_example_tpu.quant import core as qcore
    chunk = max(int(config.quant_chunk), 1)
    pre = config.gradient_predivide_factor
    flat = g.astype(jnp.float32).reshape(-1)
    if pre != 1.0:
        flat = flat / pre
    n = flat.shape[0]
    pad = (-n) % chunk
    flat = jnp.pad(flat, (0, pad)).reshape(-1, chunk)
    # One scale per chunk, agreed across the axis: pmax of the local
    # max-abs.  Every replica quantizes onto the SAME grid, so the
    # integer psum is exact and the only error is each replica's
    # rounding (<= scale/2 per element per replica).
    scale = lax.pmax(qcore.abs_max_scale(flat, axis=1), axis_name)
    q = qcore.quantize_int8(flat, scale).astype(jnp.int32)
    total = lax.psum(q, axis_name)
    out = total.astype(jnp.float32) * scale
    return out.reshape(-1)[:n].reshape(g.shape)


def broadcast_from_zero(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """Make replica 0's values authoritative on all replicas.

    Reference: DDP's ctor broadcast of rank-0 params via flat_dist_call
    (SURVEY.md §4.1 "first collective").  In JAX, jit with replicated sharding
    already guarantees consistency, so this is only needed when state was
    constructed per-replica (e.g. distinct RNG); implemented as a masked psum.
    """
    idx = lax.axis_index(axis_name)

    def bcast(x):
        masked = jnp.where(idx == 0, x, jnp.zeros_like(x))
        return lax.psum(masked, axis_name)

    return jax.tree_util.tree_map(bcast, tree)


def reduce_mean(x: jnp.ndarray, axis_name: str = DATA_AXIS) -> jnp.ndarray:
    """Metric averaging (reference harness: reduce_tensor / allreduce-mean)."""
    return lax.pmean(x, axis_name)


class DistributedDataParallel:
    """Thin apex-shaped facade: holds the config, exposes the grad reduction.

    The reference version wraps the module and intercepts backward; pure
    functions have no backward to intercept, so this class just pairs a
    :class:`DDPConfig` with the functions above for callers that want the
    apex ctor spelling::

        ddp = DistributedDataParallel(delay_allreduce=True)
        grads = ddp.allreduce(grads)          # inside shard_map
    """

    def __init__(self, module: Any = None, message_size: int = 10_000_000,
                 delay_allreduce: bool = True, gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 allreduce_always_fp32: bool = False,
                 allreduce_trigger_params: Optional[Any] = None):
        del allreduce_trigger_params  # bucket tuning — no TPU analog
        self.module = module
        self.config = DDPConfig(
            gradient_average=gradient_average,
            gradient_predivide_factor=gradient_predivide_factor,
            allreduce_always_fp32=allreduce_always_fp32,
            delay_allreduce=delay_allreduce,
            message_size=message_size)

    def allreduce(self, grads: Any, axis_name: str = DATA_AXIS) -> Any:
        return allreduce_grads(grads, self.config, axis_name)

    def __call__(self, *args, **kwargs):
        if self.module is None:
            raise ValueError("no module wrapped")
        return self.module(*args, **kwargs)


class Reducer(DistributedDataParallel):
    """apex.parallel.Reducer analog: MANUAL gradient (or buffer) allreduce.

    The reference's Reducer (apex/parallel/__init__.py) is the opt-out from
    DDP's automatic backward hooks — the user wraps the module and calls
    ``reducer.reduce()`` themselves, e.g. once per N accumulation steps.
    Here gradients are explicit values, so the class is the same idea with
    the pytree passed in: call :meth:`reduce` inside shard_map whenever a
    reduction should happen.  Same facade as the DDP class; ``reduce`` is
    the apex-named spelling of ``allreduce``.
    """

    def __init__(self, module: Any = None, gradient_average: bool = True):
        super().__init__(module, gradient_average=gradient_average)

    reduce = DistributedDataParallel.allreduce
