"""apex.parallel-shaped surface: DDP, SyncBatchNorm, LARC, mesh utilities.

Reference: apex/parallel/__init__.py exports DistributedDataParallel,
SyncBatchNorm, convert_syncbn_model, LARC (SURVEY.md §3.2).
"""

from apex_example_tpu.parallel.mesh import (
    CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS, PIPE_AXIS, data_sharding,
    initialize_model_parallel, make_data_mesh, replicated)
from apex_example_tpu.parallel.context_parallel import (
    ring_attention_zigzag, zigzag_shard, zigzag_unshard,
    heads_to_seq, plain_attention, ring_attention, seq_to_heads,
    ulysses_attention)
from apex_example_tpu.parallel.distributed import (
    DDPConfig, DistributedDataParallel, Reducer, allreduce_grads,
    broadcast_from_zero, reduce_mean)
from apex_example_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm, convert_syncbn_model)
from apex_example_tpu.parallel.larc import LARC, larc
from apex_example_tpu.parallel.launch import (
    is_main_process, maybe_initialize_distributed)

__all__ = [
    "CONTEXT_AXIS", "DATA_AXIS", "MODEL_AXIS", "PIPE_AXIS", "DDPConfig",
    "DistributedDataParallel", "LARC", "Reducer", "SyncBatchNorm",
    "allreduce_grads",
    "broadcast_from_zero", "convert_syncbn_model", "data_sharding",
    "heads_to_seq", "initialize_model_parallel", "is_main_process", "larc",
    "make_data_mesh", "maybe_initialize_distributed", "plain_attention",
    "reduce_mean", "replicated", "ring_attention", "ring_attention_zigzag",
    "seq_to_heads", "zigzag_shard", "zigzag_unshard",
    "ulysses_attention",
]
