"""Device mesh construction (reference L0/L6: torch.distributed process groups).

The reference binds one process per GPU and builds NCCL communicators keyed by
env vars RANK/LOCAL_RANK/WORLD_SIZE (SURVEY.md §4.1).  On TPU the process
boundary collapses into the runtime: one process per host, all devices visible,
and parallelism is expressed as a named :class:`jax.sharding.Mesh` whose axes
the compiler lowers to ICI/DCN collectives.

Axis names used throughout the framework:

- ``data``  — data parallelism (the reference's DDP world).
- ``model`` — tensor parallelism (reference: apex.transformer parallel_state).
- ``pipe``  — pipeline parallelism stages.

``initialize_model_parallel`` mirrors apex.transformer.parallel_state's entry
point: world = pipe × data × model, data axis gets the leftovers.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
CONTEXT_AXIS = "context"   # sequence/context parallelism (ring / Ulysses)


def make_data_mesh(num_devices: Optional[int] = None,
                   devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the ``data`` axis — the DDP-equivalent topology."""
    if devices is None:
        devices = jax.devices()[:num_devices] if num_devices else jax.devices()
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def initialize_model_parallel(tensor_parallel: int = 1,
                              pipeline_parallel: int = 1,
                              context_parallel: int = 1,
                              devices: Optional[Sequence] = None) -> Mesh:
    """4-D mesh (pipe, data, context, model); data absorbs the leftovers.

    Reference: apex/transformer/parallel_state.py initialize_model_parallel
    builds TP/PP/DP process groups by slicing the global rank grid; here the
    same topology is one Mesh and the "groups" are its named axes.  TP is
    innermost (fastest-varying devices => ICI neighbours, matching Megatron's
    contiguous TP ranks), context parallelism next (with tp=1 the ring
    ppermute hops are ICI neighbours; with tp>1, CP peers sit tp positions
    apart — the usual Megatron group layout trade), pipeline outermost.
    The context axis has no
    reference analog (SURVEY.md §3.2: CP absent there) — it exists because
    long-context sharding is first-class here.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    denom = tensor_parallel * pipeline_parallel * context_parallel
    if n % denom:
        raise ValueError(
            f"world size {n} not divisible by tp*pp*cp = {denom}")
    data = n // denom
    arr = np.asarray(devices).reshape(
        pipeline_parallel, data, context_parallel, tensor_parallel)
    return Mesh(arr, (PIPE_AXIS, DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS))


def parse_serve_mesh(spec: str) -> "tuple[int, int]":
    """Parse serve.py's ``--mesh dp,tp`` value into ``(dp, tp)``.

    Two comma-separated positive integers: the data-axis size (replica
    batch sharding of the slot dimension) and the model-axis size
    (Megatron TP: weights and per-layer KV arenas shard over heads).
    ``"1,4"`` is pure TP, ``"2,4"`` the mixed mesh the virtual-device
    tests pin."""
    parts = spec.split(",")
    if len(parts) != 2:
        raise ValueError(f"--mesh wants 'dp,tp' (two comma-separated "
                         f"ints), got {spec!r}")
    try:
        dp, tp = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"--mesh wants 'dp,tp' (two comma-separated "
                         f"ints), got {spec!r}")
    if dp < 1 or tp < 1:
        raise ValueError(f"--mesh axes must be >= 1, got dp={dp} tp={tp}")
    return dp, tp


def serve_mesh(dp: int, tp: int,
               devices: Optional[Sequence] = None) -> Mesh:
    """The serving mesh: ``(pipe=1, data=dp, context=1, model=tp)``,
    built over exactly ``dp * tp`` devices (the standard 4-axis layout,
    so the TP layers' ``constrain`` points and ``batch_axis()`` work
    unchanged).  TP innermost — ICI neighbours — exactly like the
    training mesh."""
    if devices is None:
        devices = jax.devices()
    need = dp * tp
    if len(devices) < need:
        raise ValueError(f"serve mesh data={dp} x model={tp} needs "
                         f"{need} devices, have {len(devices)}")
    return initialize_model_parallel(tensor_parallel=tp,
                                     devices=list(devices)[:need])


def require_model_axis_match(mesh: Mesh, model_is_tp: bool) -> int:
    """Validate a model's ``tensor_parallel`` flag against the mesh's
    'model' axis; returns that axis's size.  Shared by the partially-manual
    compositions (TP×PP in transformer/bert_pipeline.py, CP×TP in
    workloads.py): both leave 'model' automatic inside shard_map, so a
    flag/mesh mismatch would otherwise fail far from its cause (or
    silently train unsharded)."""
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if tp > 1 and not model_is_tp:
        raise ValueError(f"mesh has '{MODEL_AXIS}' size {tp} but the model "
                         "was built without tensor_parallel=True")
    if model_is_tp and tp <= 1:
        raise ValueError("tensor_parallel model needs a mesh with a "
                         f"nontrivial '{MODEL_AXIS}' axis")
    return tp


def data_sharding(mesh: Mesh, *batch_axes: int, ndim: int = None):
    """NamedSharding that splits axis 0 (the batch) over ``data``."""
    spec = [None] * (ndim if ndim is not None else max(batch_axes, default=0) + 1)
    for a in batch_axes or (0,):
        spec[a] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
