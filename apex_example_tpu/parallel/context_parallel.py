"""Context parallelism: ring attention + Ulysses all-to-all attention.

Reference status (SURVEY.md §3.2/§6): the reference family has NO context/
sequence-dim attention parallelism — its long-sequence workload (C5) is
handled algorithmically by Transformer-XL recurrence.  Long-context sharding
is nonetheless first-class in this framework: these are the two standard
ways to run attention over sequences longer than one chip's HBM, built on
XLA collectives over ICI.

- :func:`ring_attention` — blockwise attention with the K/V shards rotating
  around the mesh axis via ``lax.ppermute`` (one neighbour hop per step, so
  the transfer rides ICI), merged with the flash-attention online-softmax
  rule in fp32.  Sequence length per device stays S/N; full S×S attention is
  never materialized.  The backward ring falls out of differentiating the
  scan (the transpose of ppermute is the reverse rotation).
- :func:`ulysses_attention` — the all-to-all form: exchange sequence shards
  for head shards (``lax.all_to_all``), run exact attention over the full
  sequence on H/N heads per device, exchange back.  Cheaper collectives for
  moderate S; requires num_heads % axis_size == 0.

Both must run inside shard_map with ``axis_name`` bound, operating on
[batch, seq/N, heads, head_dim] local shards, and agree with single-device
attention to float tolerance (tests/test_context_parallel.py).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu.parallel.mesh import CONTEXT_AXIS

__all__ = ["plain_attention", "ring_attention", "ring_attention_zigzag",
           "ulysses_attention", "seq_to_heads", "heads_to_seq",
           "zigzag_shard", "zigzag_unshard"]

_NEG_INF = -1e30  # finite mask sentinel: keeps exp() NaN-free on all-masked
                  # blocks (every causal row sees its own diagonal at step 0,
                  # so a real max is always established before masked blocks
                  # contribute exp(-1e30 - m) == 0)


def plain_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False,
                    scale: Optional[float] = None) -> jnp.ndarray:
    """Single-device reference attention, [B, S, H, D] — softmax in fp32
    (amp blacklist op, SURVEY.md §3.1)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        S_q, S_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.arange(S_k)[None, :] > jnp.arange(S_q)[:, None]
        logits = jnp.where(mask, _NEG_INF, logits)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str = CONTEXT_AXIS, causal: bool = False,
                   scale: Optional[float] = None,
                   use_flash: bool = True) -> jnp.ndarray:
    """Exact attention over a sequence sharded along ``axis_name``.

    Inputs are this device's [B, s, H, D] shards of the global [B, N*s, H, D]
    arrays, sharded contiguously (device i owns positions [i*s, (i+1)*s)).
    Each of the N steps scores the local queries against one K/V chunk, folds
    the block into fp32 running (acc, lse-normalizer, max) with the online
    softmax rule, and rotates the chunk to the next neighbour.  Equivalent
    to (but never materializing) full softmax(QKᵀ)V.

    ``use_flash=True`` (default) computes each chunk with the Pallas flash
    kernel via :func:`~apex_example_tpu.ops.attention.flash_attention_with_lse`
    and merges normalized per-chunk results by their logsumexp — so even the
    *per-chunk* S/N × S/N score tile stays in VMEM.  The kernel op itself
    falls back to the XLA reference off-TPU, so this path is safe everywhere;
    ``use_flash=False`` keeps the self-contained inline fold (also the test
    cross-check).

    With ``causal=True``, blocks entirely in the future are masked but the
    contiguous-layout ring still *computes* them (N−1 of 2N−1 block-steps
    wasted at worst, and the live work is skewed toward late devices) — use
    :func:`ring_attention_zigzag` for the load-balanced causal form.
    """
    if use_flash:
        return _ring_attention_flash(q, k, v, axis_name, causal, scale)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    scale_ = scale if scale is not None else 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q32 = q.astype(jnp.float32)

    def block(acc, l, m, kc, vc, t):
        """Fold one K/V chunk into the online-softmax state."""
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            kc.astype(jnp.float32)) * scale_
        if causal:
            # Global positions: the chunk at step t originated on device
            # (idx - t) mod n; mask keys strictly after each query.
            src = (idx - t) % n
            qpos = idx * s + jnp.arange(s)
            kpos = src * s + jnp.arange(s)
            logits = jnp.where(kpos[None, :] > qpos[:, None], _NEG_INF,
                               logits)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(logits - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = (acc * corr[..., None] +
               jnp.einsum("bhqk,bkhd->bhqd", p, vc.astype(jnp.float32)))
        return acc, l, m_new

    # Carry initials are device-varying (each device accumulates its own
    # queries' state); mark them for shard_map's vma-checked scan.
    vary = lambda x: lax.pcast(x, axis_name, to="varying")
    acc0 = vary(jnp.zeros((b, h, s, d), jnp.float32))
    l0 = vary(jnp.zeros((b, h, s), jnp.float32))
    m0 = vary(jnp.full((b, h, s), _NEG_INF, jnp.float32))

    def step(carry, t):
        acc, l, m, kc, vc = carry
        acc, l, m = block(acc, l, m, kc, vc, t)
        kc, vc = lax.ppermute((kc, vc), axis_name, perm)
        return (acc, l, m, kc, vc), None

    # n-1 rotated steps, then the final chunk folded without the (otherwise
    # discarded) trailing K/V rotation — one ICI exchange saved per call.
    (acc, l, m, kc, vc), _ = lax.scan(
        step, (acc0, l0, m0, k, v), jnp.arange(n - 1))
    acc, l, _ = block(acc, l, m, kc, vc, jnp.asarray(n - 1))
    out = acc / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _merge_lse(state, o, l):
    """Fold one normalized attention block (o, l) into the running
    (out fp32 (B,S,H,D), lse (B,H,S)) state: out' = w·out + w_blk·o with
    w = exp(lse − lse'), lse' = logaddexp(lse, l).  The single home of the
    numerically delicate combine used by both ring variants; fully-masked
    blocks arrive with l = −∞-ish and get weight exactly 0."""
    out, lse = state
    lse_new = jnp.logaddexp(lse, l)
    w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
    w_blk = jnp.exp(l - lse_new).transpose(0, 2, 1)[..., None]
    return out * w_old + o.astype(jnp.float32) * w_blk, lse_new


def _ring_attention_flash(q, k, v, axis_name, causal, scale):
    """Ring attention over flash-kernel chunks.

    Chunk t=0 is the local (diagonal) block — under ``causal`` it gets the
    kernel's static triangular mask (Sq == Sk per chunk, so bottom-right ==
    standard).  Every later chunk is either entirely past (src < idx, fully
    visible) or entirely future (fully masked): a whole-chunk validity
    select on the chunk's logsumexp (lse → −∞ kills its combine weight)
    expresses that without any in-kernel dynamic masking.  Merging
    normalized chunk outputs (o₁,lse₁)⊕(o₂,lse₂) =
    (w₁o₁+w₂o₂, logaddexp(lse₁,lse₂)), wᵢ = exp(lseᵢ−lse) — gradients flow
    through the weights into each chunk's lse, which the kernel's VJP
    absorbs into its Δ correction (ops/attention.py)."""
    from apex_example_tpu.ops.attention import flash_attention_with_lse

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    d = q.shape[-1]
    scale_ = scale if scale is not None else 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    o0, lse0 = flash_attention_with_lse(q, k, v, None, causal, scale_)
    out0 = o0.astype(jnp.float32)

    def step(carry, t):
        out, lse, kc, vc = carry
        kc, vc = lax.ppermute((kc, vc), axis_name, perm)
        ob, lb = flash_attention_with_lse(q, kc, vc, None, False, scale_)
        if causal:
            src = (idx - t) % n          # chunk t originated on device src
            lb = jnp.where(src < idx, lb, _NEG_INF)
        out, lse = _merge_lse((out, lse), ob, lb)
        return (out, lse, kc, vc), None

    (out, _, _, _), _ = lax.scan(step, (out0, lse0, k, v),
                                 jnp.arange(1, n))
    return out.astype(q.dtype)


def seq_to_heads(x: jnp.ndarray, axis_name: str = CONTEXT_AXIS,
                 seq_dim: int = 1, head_dim: int = 2) -> jnp.ndarray:
    """[B, S/N, H, D] → [B, S, H/N, D]: trade sequence shards for head
    shards (the Ulysses all-to-all)."""
    return lax.all_to_all(x, axis_name, split_axis=head_dim,
                          concat_axis=seq_dim, tiled=True)


def heads_to_seq(x: jnp.ndarray, axis_name: str = CONTEXT_AXIS,
                 seq_dim: int = 1, head_dim: int = 2) -> jnp.ndarray:
    """[B, S, H/N, D] → [B, S/N, H, D]: the inverse exchange."""
    return lax.all_to_all(x, axis_name, split_axis=seq_dim,
                          concat_axis=head_dim, tiled=True)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str = CONTEXT_AXIS, causal: bool = False,
                      scale: Optional[float] = None,
                      inner: Optional[Callable] = None) -> jnp.ndarray:
    """All-to-all sequence parallelism: exact attention, full sequence per
    device, H/N heads per device.

    ``inner`` swaps the attention kernel (defaults to
    :func:`plain_attention`; pass a Pallas flash kernel for production).
    A custom ``inner`` owns ALL attention semantics — combining it with
    ``causal``/``scale`` is rejected rather than silently ignored.
    """
    if q.shape[2] % lax.axis_size(axis_name):
        raise ValueError(
            f"num_heads {q.shape[2]} not divisible by axis "
            f"'{axis_name}' size {lax.axis_size(axis_name)}")
    if inner is not None and (causal or scale is not None):
        raise ValueError(
            "pass causal/scale inside your custom `inner` kernel; the "
            "flags only configure the default plain_attention")
    inner = inner or functools.partial(plain_attention, causal=causal,
                                       scale=scale)
    qh, kh, vh = (seq_to_heads(t, axis_name) for t in (q, k, v))
    out = inner(qh, kh, vh)
    return heads_to_seq(out, axis_name)


# --------------------------------------------------------------------------
# Zigzag causal ring attention.
# --------------------------------------------------------------------------

def zigzag_shard(x: jnp.ndarray, n: int, seq_dim: int = 1) -> jnp.ndarray:
    """Reorder a global sequence into zigzag layout: split into 2n chunks;
    device i owns chunks (i, 2n-1-i), concatenated.  Returns the full
    reordered array (shard it P(axis) afterwards); inverse: zigzag_unshard.

    Why zigzag: under causal masking with contiguous shards, early devices
    skip most blocks and late devices compute all of them — the per-step
    ppermute barrier makes every step as slow as the busiest device.  The
    zigzag pairing gives every device one early and one late chunk, so the
    per-step live work is identical everywhere (the standard load-balanced
    causal ring layout)."""
    s = x.shape[seq_dim]
    if s % (2 * n):
        raise ValueError(f"seq {s} not divisible by 2n={2 * n}")
    chunks = jnp.split(x, 2 * n, axis=seq_dim)
    order = [c for i in range(n) for c in (chunks[i], chunks[2 * n - 1 - i])]
    return jnp.concatenate(order, axis=seq_dim)


def zigzag_unshard(x: jnp.ndarray, n: int, seq_dim: int = 1) -> jnp.ndarray:
    """Inverse of :func:`zigzag_shard`."""
    chunks = jnp.split(x, 2 * n, axis=seq_dim)
    order = [None] * (2 * n)
    for i in range(n):
        order[i] = chunks[2 * i]
        order[2 * n - 1 - i] = chunks[2 * i + 1]
    return jnp.concatenate(order, axis=seq_dim)


def ring_attention_zigzag(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          axis_name: str = CONTEXT_AXIS,
                          scale: Optional[float] = None) -> jnp.ndarray:
    """Load-balanced CAUSAL ring attention over zigzag-sharded sequences.

    Local shards are [B, 2c, H, D]: the first half is global chunk ``idx``,
    the second half global chunk ``2n-1-idx`` (c = S_global / 2n) — produce
    them with :func:`zigzag_shard` + P(axis) sharding.  Per ring step the
    chunk-index algebra decides each of the four (q-chunk, kv-chunk) pairs
    statically or per-device:

    - (q_a, kv_b) is ALWAYS future (kv_b's global chunk ≥ n > q_a's) —
      statically skipped, zero cost.
    - (q_b, kv_a) is ALWAYS past — computed in full every step.
    - of (q_a, kv_a) and (q_b, kv_b), exactly one is live per step
      (src < idx vs src > idx) — a ``lax.cond`` computes only that one, so
      every device runs the same amount of kernel work each step.

    Per-chunk results merge by logsumexp exactly like
    :func:`ring_attention`'s flash path.  Causal-only by construction (the
    layout exists to balance the causal mask; use ring_attention for the
    dense case).
    """
    from apex_example_tpu.ops.attention import flash_attention_with_lse

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s2, h, d = q.shape
    if s2 % 2:
        raise ValueError(f"zigzag local seq must be even, got {s2}")
    c = s2 // 2
    scale_ = scale if scale is not None else 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]
    split = lambda t: (t[:, :c], t[:, c:])
    qa, qb = split(q)

    def attend(qc, kc, vc, causal):
        o, l = flash_attention_with_lse(qc, kc, vc, None, causal, scale_)
        return o.astype(jnp.float32), l

    # Step 0: both diagonals causal, plus the always-past (q_b, kv_a).
    ka0, kb0 = split(k)
    va0, vb0 = split(v)
    state_a = attend(qa, ka0, va0, True)
    state_b = _merge_lse(attend(qb, kb0, vb0, True),
                         *attend(qb, ka0, va0, False))

    def step(carry, t):
        state_a, state_b, kc, vc = carry
        kc, vc = lax.ppermute((kc, vc), axis_name, perm)
        ka, kb = split(kc)
        va, vb = split(vc)
        src = (idx - t) % n
        # Always-past pair.
        state_b = _merge_lse(state_b, *attend(qb, ka, va, False))

        # Exactly one of (q_a, kv_a) / (q_b, kv_b) is live.
        def a_live(sa, sb):
            return _merge_lse(sa, *attend(qa, ka, va, False)), sb

        def b_live(sa, sb):
            return sa, _merge_lse(sb, *attend(qb, kb, vb, False))

        state_a, state_b = lax.cond(src < idx, a_live, b_live,
                                    state_a, state_b)
        return (state_a, state_b, kc, vc), None

    (state_a, state_b, _, _), _ = lax.scan(
        step, (state_a, state_b, k, v), jnp.arange(1, n))
    out = jnp.concatenate([state_a[0], state_b[0]], axis=1)
    return out.astype(q.dtype)
