"""SyncBatchNorm: cross-replica batch normalization.

Reference (apex/parallel/{sync_batchnorm,optimized_sync_batchnorm}.py +
csrc/syncbn.cpp/welford.cu; SURVEY.md §4.4): local Welford statistics, an
NCCL allreduce of (count, mean, M2) across the process group, normalization
with the global stats, and a matching backward that allreduces the two
gradient sums.

TPU-native design: a Flax module whose statistics cross the ``data`` mesh axis
via ``lax.psum`` *inside* the jitted step — the backward reductions come from
differentiating psum (transpose of psum is psum), so no hand-written backward
is needed.  The Welford merge across shards is exact:

    global_mean = Σ_d sum_d / Σ_d n_d
    global_M2   = Σ_d [ M2_d + n_d (mean_d − global_mean)² ]

Numerics match torch.nn.BatchNorm2d semantics (the golden in our tests):
normalization uses biased variance, running_var stores the unbiased estimate,
``momentum`` is the *new-stat weight* (torch convention, default 0.1 — note
flax's BatchNorm uses the opposite convention).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from apex_example_tpu._compat import axis_size


class SyncBatchNorm(nn.Module):
    """Drop-in BatchNorm with optional cross-replica stat reduction.

    With ``axis_name=None`` this is plain BatchNorm (torch semantics).  With
    ``axis_name="data"`` inside shard_map/pmap, batch statistics are the exact
    global-batch statistics — the invariant the reference's two-GPU unit test
    checks (N-shard SyncBN == full-batch BN; SURVEY.md §5).
    """

    use_running_average: Optional[bool] = None
    axis_name: Optional[str] = None
    momentum: float = 0.1          # torch convention: weight of the new stat
    epsilon: float = 1e-5
    dtype: Optional[jnp.dtype] = None       # I/O dtype; None → follow input
    stats_dtype: Optional[jnp.dtype] = None  # math/stats dtype; None → fp32
    param_dtype: jnp.dtype = jnp.float32
    use_bias: bool = True
    use_scale: bool = True
    # Route training-mode BN through the custom-VJP Pallas kernel pair
    # (ops/batch_norm.py — the reference's welford.cu analog).  Measured on
    # the v5e-1 rig this LOSES ~44% C2 throughput (2579→1447 img/s): XLA
    # already fuses the stat/backward reduces into the surrounding conv
    # epilogues and elementwise chains, and the opaque kernel boundary
    # forces relayout copies (~40 ms/step of %copy in the trace) — so the
    # XLA composite form below stays the default.  The kernel path remains
    # for parity evidence and for shapes/backends where XLA fuses worse.
    fused_kernel: bool = False

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        # Dtype contract (the reference's keep_batchnorm_fp32 realized the
        # way cuDNN does: half I/O, fp32 math/params/stats — NOT fp32 I/O).
        # ``stats_dtype`` (policy.bn_dtype) is where moments/normalization
        # run; the output follows the *input* dtype so BN fuses into the
        # surrounding bf16 conv/relu chain instead of materializing fp32
        # activations in HBM (profiled: fp32 BN I/O cost ~25% of the O2
        # ResNet-50 step in convert_element_type fusions alone).
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        feat = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(feat, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(feat, jnp.float32))

        md = jnp.dtype(self.stats_dtype or jnp.float32)
        scale = (self.param("scale", nn.initializers.ones, (feat,),
                            self.param_dtype).astype(jnp.float32)
                 if self.use_scale else jnp.ones(feat, jnp.float32))
        bias = (self.param("bias", nn.initializers.zeros, (feat,),
                           self.param_dtype).astype(jnp.float32)
                if self.use_bias else jnp.zeros(feat, jnp.float32))
        out_dtype = self.dtype or x.dtype

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
            inv = lax.rsqrt(var + self.epsilon).astype(md)
            y = (x.astype(md) - mean.astype(md)) * (inv * scale.astype(md))
            y = y + bias.astype(md)
            return y.astype(out_dtype)

        # Training mode.  Moment ACCUMULATION is always fp32 — Σx/Σx² over
        # ~10⁶ bf16 values cancels catastrophically in bf16 (the reference's
        # cuDNN path likewise never lowers BN stat precision).  The pass is
        # centered on the running mean (a per-channel constant, identical on
        # every replica): shifted moments are exact for any constant shift,
        # and with c tracking the batch mean the Σ(x−c)² accumulation no
        # longer cancels catastrophically when |mean| ≫ std.
        c = ra_mean.value.astype(jnp.float32)
        axis = None if self.is_initializing() else self.axis_name

        if self.fused_kernel:
            # Custom-VJP kernel pair (one Pallas pass fwd, one bwd); the two
            # cross-replica psums live inside batch_norm_train.
            from apex_example_tpu.ops.batch_norm import batch_norm_train
            y, mean, var = batch_norm_train(x, scale, bias, c, axis,
                                            self.epsilon, md, out_dtype)
            n = 1
            for a in reduce_axes:
                n *= x.shape[a]
            if axis is not None:
                n *= axis_size(axis)
        else:
            # XLA composite form: one fused (Σ(x-c), Σ(x-c)²) read, psum
            # Welford merge, elementwise apply.  XLA fuses the stat reduces
            # into the producing conv's epilogue and the apply into the
            # consuming chain — measured faster than the opaque kernel
            # boundary on v5e (see ``fused_kernel``).
            n_local = 1
            for a in reduce_axes:
                n_local *= x.shape[a]
            xc = x.astype(jnp.float32) - c
            local_sum = jnp.sum(xc, axis=reduce_axes)
            local_sumsq = jnp.sum(jnp.square(xc), axis=reduce_axes)
            local_mean_c = local_sum / n_local          # E[x] − c, locally
            local_m2 = local_sumsq - jnp.square(local_mean_c) * n_local

            if axis is not None:
                # Cross-replica Welford merge (reference: syncbn allreduce of
                # (count, mean, M2); here two psums over the mesh axis).
                world = axis_size(axis)
                n = n_local * world
                mean_c = lax.psum(local_sum, axis) / n
                m2 = lax.psum(
                    local_m2 + n_local * jnp.square(local_mean_c - mean_c),
                    axis)
            else:
                n = n_local
                mean_c, m2 = local_mean_c, local_m2
            mean = c + mean_c
            # E[x²]−E[x]² can go fractionally negative under cancellation.
            var = jnp.maximum(m2 / n, 0.0)

            inv = lax.rsqrt(var + self.epsilon).astype(md)
            y = (x.astype(md) - mean.astype(md)) * (inv * scale.astype(md))
            y = y + bias.astype(md)

        if not self.is_initializing():
            m = self.momentum
            unbiased = var * (jnp.float32(n) / max(n - 1, 1))
            ra_mean.value = (1 - m) * ra_mean.value + m * mean
            ra_var.value = (1 - m) * ra_var.value + m * unbiased

        return y.astype(out_dtype)


def convert_syncbn_model(module: nn.Module,
                         axis_name: str = "data") -> nn.Module:
    """Reference parity: apex.parallel.convert_syncbn_model recursively swaps
    nn.BatchNorm for SyncBatchNorm.  Flax modules are immutable dataclasses,
    so models in this framework expose a ``bn_axis_name`` field and conversion
    is a clone with the mesh axis bound.
    """
    if not hasattr(module, "bn_axis_name"):
        raise TypeError(
            f"{type(module).__name__} does not expose bn_axis_name; "
            "only models built with framework norm layers can be converted")
    return module.clone(bn_axis_name=axis_name)
