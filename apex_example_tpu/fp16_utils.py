"""Legacy fp16 utilities: the predecessor API to amp (SURVEY.md:129 —
``FP16_Optimizer``, manual master-weight management, ``network_to_half``,
``prep_param_lists``; reference layout ``apex/fp16_utils/{fp16_optimizer,
loss_scaler,fp16util}.py``).

The reference kept this surface for users who managed mixed precision by
hand before ``amp.initialize`` existed.  The TPU-native restatement is
functional: instead of an object that mutates ``.param_groups`` in place,
``FP16_Optimizer`` is an init/step pair over an explicit state pytree —
the same shape as every optimizer in this framework (optim/fused.py), so it
drops into the engine unchanged.  Half precision on TPU means bf16 (fp16 is
supported end-to-end for parity; the dynamic scaler exists for it).

What maps where:

  apex.fp16_utils.network_to_half(net)      -> network_to_half(model_or_tree)
  apex.fp16_utils.prep_param_lists(model)   -> prep_param_lists(params)
  master_params_to_model_params(m, M)       -> master_to_model(masters, like)
  model_grads_to_master_grads(m, M)         -> grads_to_master(grads)
  apex.fp16_utils.FP16_Optimizer            -> FP16_Optimizer (init/step)
  apex.fp16_utils.LossScaler                -> amp.make_scaler(dynamic=False)
  apex.fp16_utils.DynamicLossScaler         -> amp.make_scaler(dynamic=True)
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_example_tpu.amp.scaler import (ScalerState, load_state_dict,
                                         scale_loss, select_tree,
                                         state_dict, unscale_grads)
from apex_example_tpu.amp.scaler import update as update_scaler


def network_to_half(model_or_tree, half_dtype=jnp.bfloat16):
    """Convert a model (or a param pytree) to half precision.

    Reference: fp16util.network_to_half — wraps the net so inputs/weights run
    in half while BatchNorm stays fp32.  Framework models expose dtype fields,
    so conversion is a functional clone: compute dtype goes half, BN stats
    stay fp32 (``bn_dtype``) exactly like the reference's BN_convert_float.
    Param pytrees are cast leaf-wise.
    """
    if hasattr(model_or_tree, "clone") and hasattr(model_or_tree, "dtype"):
        kw = {"dtype": half_dtype}
        if hasattr(model_or_tree, "bn_dtype"):
            kw["bn_dtype"] = jnp.float32
        return model_or_tree.clone(**kw)
    return jax.tree_util.tree_map(
        lambda p: p.astype(half_dtype)
        if jnp.issubdtype(p.dtype, jnp.floating) else p, model_or_tree)


def prep_param_lists(params) -> Tuple[Any, Any]:
    """(model_params_half, master_params_fp32) from a half param tree.

    Reference: fp16util.prep_param_lists — creates the fp32 master copies the
    legacy flow updates in the optimizer.
    """
    masters = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32)
        if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
    return params, masters


def master_to_model(masters, like):
    """Cast fp32 masters back onto the model's (half) dtypes.

    Reference: fp16util.master_params_to_model_params."""
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype), masters, like)


def grads_to_master(grads):
    """Upcast half model grads to fp32 master grads.

    Reference: fp16util.model_grads_to_master_grads."""
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32)
        if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)


class FP16State(NamedTuple):
    masters: Any              # fp32 master weights
    inner_state: Any          # wrapped optimizer's state over the masters
    scaler: ScalerState


class FP16_Optimizer:
    """Manual master-weight mixed precision: the legacy flow as init/step.

    Reference: fp16_utils/fp16_optimizer.py — wraps any optimizer; keeps fp32
    masters; ``backward()`` scales the loss, ``step()`` unscales, checks for
    inf/nan, skips on overflow, updates masters, writes halves back.  Here
    the same contract is one pure function:

        opt = FP16_Optimizer(FusedSGD(lr=0.1), dynamic_loss_scale=True)
        state = opt.init(half_params)
        loss_scaled = opt.scale_loss(loss, state)        # 'backward()'
        half_params, state = opt.step(half_grads, state) # 'step()'

    The step is jit/shard_map-safe: the overflow skip is a where-select, not
    host control flow (the same mechanism as engine.py's train step).
    """

    def __init__(self, inner, static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None):
        self.inner = inner
        kw = dict(dynamic_loss_args or {})
        if dynamic_loss_scale:
            self.scaler0 = DynamicLossScaler(
                init_scale=kw.get("init_scale", 2.0 ** 16),
                scale_factor=kw.get("scale_factor", 2.0),
                scale_window=kw.get("scale_window", 2000))
        else:
            self.scaler0 = LossScaler(static_loss_scale)

    def init(self, half_params) -> FP16State:
        _, masters = prep_param_lists(half_params)
        return FP16State(masters=masters,
                         inner_state=self.inner.init(masters),
                         scaler=self.scaler0)

    def scale_loss(self, loss, state: FP16State):
        """The ``with amp.scale_loss``-less legacy form: loss * scale."""
        return scale_loss(loss, state.scaler)

    def step(self, half_grads, state: FP16State):
        """Unscale → finite-check → (maybe skipped) master update → halves."""
        grads, finite = unscale_grads(grads_to_master(half_grads),
                                      state.scaler)
        new_masters, new_inner = self.inner.apply(grads, state.inner_state,
                                                  state.masters)
        new_masters = select_tree(finite, new_masters, state.masters)
        new_inner = select_tree(finite, new_inner, state.inner_state)
        scaler = update_scaler(state.scaler, finite)
        half_params = master_to_model(new_masters, half_grads)
        return half_params, FP16State(new_masters, new_inner, scaler)

    # --- checkpoint surface (reference: FP16_Optimizer.state_dict) ---
    def state_dict(self, state: FP16State) -> dict:
        return {"scaler": state_dict(state.scaler)}

    def load_state_dict(self, state: FP16State, d: dict) -> FP16State:
        return state._replace(scaler=load_state_dict(state.scaler,
                                                     d["scaler"]))


# Legacy scaler names (reference: fp16_utils/loss_scaler.py).
def LossScaler(scale: float = 1.0) -> ScalerState:
    return ScalerState(scale=jnp.asarray(scale, jnp.float32),
                       growth_counter=jnp.asarray(0, jnp.int32),
                       dynamic=False, identity=(scale == 1.0))


def DynamicLossScaler(init_scale: float = 2.0 ** 16,
                      scale_factor: float = 2.0,
                      scale_window: int = 2000) -> ScalerState:
    return ScalerState(scale=jnp.asarray(init_scale, jnp.float32),
                       growth_counter=jnp.asarray(0, jnp.int32),
                       dynamic=True, growth_factor=scale_factor,
                       growth_interval=scale_window)
