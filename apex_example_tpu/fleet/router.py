"""The fleet router: admission + dispatch over N serve replicas.

Pure stdlib ON PURPOSE — **jax-free by contract** like
resilience/supervisor.py (graftlint's static rule proves the import
closure): routing must keep working while individual replicas' jax is
dying, so nothing here may touch the serve package.  Replica handles
(fleet/replica.py) are duck-typed, never imported.

What the router owns:

- **Dispatch policies** (``--policy``): ``round_robin`` (cycle the
  routable set), ``least_pending`` (the smallest queued backlog, from
  each replica's tailed/live gauges), ``least_kv`` (the least live KV
  — the tailed dtype-accurate ``kv_bytes_live`` byte gauge of a v12
  replica, falling back to the raw ``blocks_live`` block count for
  older children).  A replica is
  routable when its handle reports healthy/starting AND its circuit
  breaker admits traffic.  When nothing is routable the request parks
  in the router backlog and is re-dispatched as capacity returns —
  admission never silently drops.
- **Requeue-on-drain**: a replica exiting 75 hands its still-queued
  requests back with status "drained"; the router requeues each to a
  SIBLING, exactly once per drain report (a duplicate report of the
  same drain is counted, not re-dispatched).  Drains are the expected
  steady state under rolling restarts, so they never trip the breaker.
- **Deadline-aware retry**: a request lost to a replica crash is
  re-dispatched while its wall-clock deadline allows and the retry
  budget lasts; past either it terminates first-class (``timeout`` /
  ``failed``) instead of spinning.
- **Disaggregated roles** (ISSUE 15): a replica handle carrying
  ``role="prefill"`` receives prompts like any other; one carrying
  ``role="decode"`` is never dispatched to — its outbox reports the
  terminals for requests the KV-handoff SPOOL fed it.  A prefill
  replica's status-"handoff" event parks the uid on the spool (no
  re-route: the spool is the inter-role channel); a decode worker
  that acked a handoff and then died reports it ``lost``, and the
  router re-routes the request through a prefill replica from
  scratch.  The ``fleet_summary`` carries the disagg topology and
  redelivery accounting (``prefill_replicas`` / ``decode_replicas`` /
  ``handoffs`` / ``handoff_redelivered`` / ``in_spool``).
- **Circuit breaking**: a crashed or stalled replica's breaker opens
  (exponential backoff), half-opens after the backoff to admit ONE
  probe request, and closes again only when the probe completes ok —
  the classic pattern, deterministic enough to unit-test.
- **SLO plane** (ISSUE 16): armed with an ``slo`` spec, every
  fleet-terminal event is scored good/bad against the latency targets
  (latencies ride the v14 outbox/harvest events) and folded into
  event-count tumbling windows — one schema-v14 ``slo_window`` record
  per ``slo_window`` terminals (plus ``slo_breach`` past burn 1.0);
  replica heartbeat sketches merge into periodic ``fleet_rollup``
  records (fleet percentiles + per-replica p50 skew/straggler), and
  the ``fleet_summary`` carries ``slo_verdict`` / worst-window burn —
  what chaos scenarios fold into their pass/fail.

Every decision lands in the router's own schema-v10 stream: one
``route`` record per dispatch (policy, attempt, reason), a
``replica_state`` record per observed transition (with the
supervisor's exit ``classification`` when known), and a closing
``fleet_summary`` (per-status totals, retry/requeue accounting,
``lost`` — the zero-lost acceptance counter — fleet availability,
per-replica breakdown, routing-balance stats).  With ``trace=True``
the same stream carries hard-coded schema-v9 trace events (the
supervisor's pattern: clock_sync + instants/X spans on the "router"
row), and the router exports ``APEX_TRACE_ID`` so every replica tree
it spawns joins ONE Perfetto timeline.

Thread-safety: ``submit`` may be called from a load-generator thread
while the main thread polls; all shared state (``_replicas`` metadata
incl. breaker fields, ``_inflight``, ``_backlog``, ``_done``) is
guarded by ``_lock`` — annotated for graftlint's lock-discipline rule.
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# Keep in sync with apex_example_tpu/obs/schema.py (SCHEMA_VERSION) —
# jax-free contract forbids importing it (same stance as the
# supervisor's hard-coded records).
SCHEMA = 18
TRACE_ID_ENV = "APEX_TRACE_ID"

POLICIES = ("round_robin", "least_pending", "least_kv",
            "prefix_affinity")

# Statuses a replica can report that end a request for good at the
# fleet level (drained and lost are re-routed instead; "handoff" parks
# the uid on the KV spool — a decode replica's outbox finishes it;
# "migrated" (ISSUE 20) parks the same way on the live-migration spool
# — a PEER resumes the mid-flight request token-identically and its
# events finish the uid).
_TERMINAL = ("ok", "timeout", "shed", "cancelled", "failed", "rejected")

_SLO_MOD = None


def _load_slo():
    """obs/slo.py loaded by FILE PATH (cached): the module is stdlib
    self-contained by contract, so this never executes the jax-carrying
    package ``__init__`` chain — the metrics_lint _load_schema pattern.
    Loaded lazily, only when a router is armed with an --slo spec."""
    global _SLO_MOD
    if _SLO_MOD is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "obs", "slo.py")
        spec = importlib.util.spec_from_file_location("_fleet_slo", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _SLO_MOD = mod
    return _SLO_MOD


_PREFIX_MOD = None


def _load_prefix():
    """sched/prefix.py loaded by FILE PATH (cached), same stance as
    ``_load_slo``: the module is stdlib self-contained by the graftlint
    contract, so loading it never walks the jax-carrying package
    ``__init__``.  Only a prefix_affinity router pays the import."""
    global _PREFIX_MOD
    if _PREFIX_MOD is None:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir, "sched", "prefix.py")
        spec = importlib.util.spec_from_file_location(
            "_fleet_prefix", os.path.abspath(path))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _PREFIX_MOD = mod
    return _PREFIX_MOD


class _Stream:
    """Minimal JSONL writer (the jax-free contract rules out
    obs.JsonlSink — the supervisor carries the same copy, minus the
    lock: here a load-generator thread may submit() — and therefore
    emit route records — while the poll thread writes, so each line is
    one atomic write under an internal lock or the stream tears."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None                 # guarded-by: _lock

    def write(self, rec: Dict[str, Any]) -> None:
        if self.path is None:
            return
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            if self._fh is None:
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                self._fh = open(self.path, "w")
            self._fh.write(line)
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class _Meta:
    """Per-replica routing state.  Every field is guarded by the
    router's ``_lock`` (reached only through ``self._replicas``)."""

    def __init__(self, handle):
        self.handle = handle
        self.dispatches = 0
        self.inflight = 0
        self.counts: Dict[str, int] = {}
        self.health: Dict[str, Any] = {"state": "starting"}
        self.emitted_state: Optional[str] = None
        # Circuit breaker: closed -> open (backoff) -> half_open
        # (single probe) -> closed | open.
        self.breaker = "closed"
        self.fail_streak = 0
        self.opened_at = 0.0
        self.probe_uid: Optional[str] = None

    def bump(self, status: str) -> None:
        self.counts[status] = self.counts.get(status, 0) + 1


class FleetRouter:
    """Route request specs across replica handles; see module doc."""

    def __init__(self, replicas, policy: str = "round_robin",
                 metrics_jsonl: Optional[str] = None, sink=None,
                 run_id: Optional[str] = None, max_retries: int = 2,
                 breaker_backoff_s: float = 0.25,
                 breaker_backoff_max_s: float = 5.0,
                 stall_after_s: Optional[float] = None,
                 default_deadline_s: Optional[float] = None,
                 spool_timeout_s: Optional[float] = None,
                 slo=None, slo_window: int = 16,
                 slo_rollup_s: float = 2.0,
                 tenant_specs=None, prefix_block_size: int = 8,
                 rebalance_kv_ratio: Optional[float] = None,
                 rebalance_cooldown_s: float = 1.0,
                 trace: bool = False, log=print):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, "
                             f"got {policy!r}")
        if not replicas:
            raise ValueError("fleet needs at least one replica")
        self.policy = policy
        self.max_retries = int(max_retries)
        self.breaker_backoff_s = float(breaker_backoff_s)
        self.breaker_backoff_max_s = float(breaker_backoff_max_s)
        self.stall_after_s = stall_after_s
        self.default_deadline_s = default_deadline_s
        # Disagg self-healing (ISSUE 15): a uid parked on the spool
        # longer than this is presumed eaten by a decode worker that
        # died AFTER acking its claim (the one crash window the lease
        # cannot redeliver — the spool file is gone and no process
        # will ever report it) and is re-routed through a prefill
        # replica from scratch, under the normal retry budget.  None =
        # off; size it well past the handoff lease so live redelivery
        # always gets first go.
        self.spool_timeout_s = spool_timeout_s
        self.log = log
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self._stream = sink if sink is not None else _Stream(metrics_jsonl)
        # Reentrant: the SLO fold helpers (_slo_absorb /
        # _slo_close_window) take the lock themselves so the guard is
        # lexical, and their callers already hold it.
        self._lock = threading.RLock()
        self._order = [r.name for r in replicas]
        # Disagg roles (ISSUE 15): prompts route only to prefill-capable
        # replicas; decode replicas are harvested (their outbox carries
        # the spool-fed terminals) but never dispatched to.
        self._roles = {r.name: getattr(r, "role", "both")
                       for r in replicas}
        if all(role == "decode" for role in self._roles.values()):
            raise ValueError("fleet needs at least one prefill-capable "
                             "replica (every handle is role=decode)")
        self._replicas = {r.name: _Meta(r) for r in replicas}  # guarded-by: _lock
        self._inflight: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._backlog: deque = deque()                  # guarded-by: _lock
        self._done: Dict[str, str] = {}                 # guarded-by: _lock
        # uid -> replica still holding a LIVE booking for a uid that
        # terminated via an abandoned copy's late report (its own
        # report releases it — see _absorb's duplicate branch).
        self._stale: Dict[str, str] = {}                # guarded-by: _lock
        self._rr = 0
        self._submitted = 0
        self._retries = 0
        self._drained_requeued = 0
        self._duplicates = 0
        self._router_terminal = 0     # timeouts/failures decided HERE
        self._handoffs = 0            # uids parked on the KV spool
        self._handoff_redelivered = 0  # terminals from redelivered
        #                                handoff admissions (v13)
        # Live migration + elasticity (ISSUE 20, all guarded-by _lock):
        self._migrations = 0          # uids shipped mid-flight
        self._migration_completed = 0  # ...that reached a terminal
        self._migration_redelivered = 0  # terminals from redelivered
        #                                  migration admissions
        self._rebalance_migrations = 0  # migrations THIS router asked
        self._scale_up = 0            # autoscale events (note_autoscale)
        self._scale_down = 0
        self._retired: set = set()    # names out of the routable set
        # KV-pressure rebalance: when the hottest both-role replica's
        # kv_bytes_live exceeds rebalance_kv_ratio x the fleet mean,
        # ask it to migrate one live request to the spool (cooldown
        # hysteresis between asks).  None = off.
        if rebalance_kv_ratio is not None and rebalance_kv_ratio <= 1.0:
            raise ValueError(f"rebalance_kv_ratio must be > 1.0, "
                             f"got {rebalance_kv_ratio}")
        if rebalance_cooldown_s < 0:
            raise ValueError(f"rebalance_cooldown_s must be >= 0, "
                             f"got {rebalance_cooldown_s}")
        self.rebalance_kv_ratio = rebalance_kv_ratio
        self.rebalance_cooldown_s = float(rebalance_cooldown_s)
        self._last_rebalance = 0.0
        self.results: Dict[str, Dict[str, Any]] = {}    # uid -> final event
        # SLO plane (ISSUE 16): with a spec armed, every fleet-terminal
        # event is scored good/bad; verdicts accumulate in _slo_scored
        # (the PURE input summary_record's windows/verdict are computed
        # from — two summary calls agree bit-for-bit) while the window
        # fold in _slo_w backs the emitted slo_window/slo_breach
        # records at every slo_window-event boundary.
        self._slo = None
        self._slo_mod = None
        self.slo_window = int(slo_window)
        self.slo_rollup_s = float(slo_rollup_s)
        self._slo_scored: List[Optional[bool]] = []     # guarded-by: _lock
        self._slo_w: Optional[Dict[str, Any]] = None    # guarded-by: _lock
        self._slo_emitted = 0                           # guarded-by: _lock
        self._slo_last_rollup = time.time()
        if slo:
            if self.slo_window < 1:
                raise ValueError(f"slo_window must be >= 1, "
                                 f"got {slo_window}")
            self._slo_mod = _load_slo()
            self._slo = self._slo_mod._normalize_spec(slo)
        # Multi-tenant plane (ISSUE 19): with --tenants armed, every
        # fleet-terminal event also folds into its tenant's ledger —
        # per-tenant status counts plus (slo armed too) a per-tenant
        # scored list, so fleet_summary carries per-tenant availability
        # and SLO verdicts (the noisy_neighbor assertion surface).
        self._tenants = dict(tenant_specs) if tenant_specs else None
        self._tenant_counts: Dict[str, Dict[str, int]] = {}  # guarded-by: _lock
        self._tenant_scored: Dict[str, List[Optional[bool]]] = {}  # guarded-by: _lock
        # prefix_affinity routing state: block size must match the
        # replicas' KV page size or the chain keys never line up.
        if prefix_block_size < 1:
            raise ValueError(f"prefix_block_size must be >= 1, "
                             f"got {prefix_block_size}")
        self.prefix_block_size = int(prefix_block_size)
        self._prefix_mod = _load_prefix() \
            if policy == "prefix_affinity" else None
        self.scenario: Optional[str] = None
        self.verdict: Optional[str] = None
        self._t0 = time.perf_counter()
        # Trace continuity: the router's trace id is inherited from a
        # parent (APEX_TRACE_ID) or minted here, and EXPORTED so every
        # replica tree spawned after construction joins the timeline.
        self.trace_id = os.environ.get(TRACE_ID_ENV) or self.run_id
        self._tracing = bool(trace)
        # Own lock (not _lock: trace_event is called from inside and
        # outside _lock holders alike): a submit-thread route event and
        # a poll-thread state event racing the lazy clock_sync would
        # both write one — and trace_export --check requires EXACTLY
        # one per stream.
        self._trace_lock = threading.Lock()
        self._trace_synced = False
        if self._tracing:
            os.environ[TRACE_ID_ENV] = self.trace_id
        self._header()

    # --------------------------------------------------------- records

    def _header(self) -> None:
        config: Dict[str, Any] = {
            "policy": self.policy,
            "replicas": list(self._order),
            "max_retries": self.max_retries,
            "breaker_backoff_s": self.breaker_backoff_s,
            "stall_after_s": self.stall_after_s,
            "default_deadline_s": self.default_deadline_s}
        if self._slo is not None:
            # The SPEC announcement ci_gate --slo-stream keys on: a
            # stream with slo_window records but no announced spec (or
            # two) cannot be checked for verdict consistency.
            config["slo"] = dict(self._slo)
            config["slo_window"] = self.slo_window
        if self._tenants is not None:
            # Tenant-spec announcement (v17): ci_gate --tenant-stream
            # checks the fairness ledger against the budgets declared
            # HERE, not against out-of-band flags.
            tcfg: Dict[str, Any] = {}
            for name, ts in self._tenants.items():
                ent: Dict[str, Any] = {
                    "weight": float(getattr(ts, "weight", 1.0)),
                    "slo_class": getattr(ts, "slo_class", "batch")}
                budget = getattr(ts, "budget", None)
                if budget is not None:
                    ent["budget"] = int(budget)
                tcfg[name] = ent
            config["tenants"] = tcfg
        self._stream.write({
            "record": "run_header", "schema": SCHEMA, "time": time.time(),
            "run_id": self.run_id, "num_devices": 0, "process_index": 0,
            "platform": "fleet-router",
            "config": config})

    def _route_rec(self, uid: str, replica: str, attempt: int,
                   reason: str, from_replica: Optional[str]) -> None:
        rec: Dict[str, Any] = {
            "record": "route", "time": time.time(), "request_id": uid,
            "replica": replica, "policy": self.policy,
            "attempt": attempt, "reason": reason, "run_id": self.run_id}
        if from_replica:
            rec["from_replica"] = from_replica
        self._stream.write(rec)
        self.trace_event("i", "route",
                         args={"request_id": uid, "replica": replica,
                               "reason": reason})

    def _state_rec(self, replica: str, state: str,
                   health: Optional[Dict[str, Any]] = None,
                   detail: Optional[str] = None) -> None:
        rec: Dict[str, Any] = {
            "record": "replica_state", "time": time.time(),
            "replica": replica, "state": state, "run_id": self.run_id}
        if health:
            rec["tick"] = int(health.get("tick", 0))
            rec["pending"] = int(health.get("pending", 0))
            rec["blocks_live"] = int(health.get("blocks_live", 0))
            if health.get("classification"):
                rec["classification"] = str(health["classification"])
            if health.get("exit_code") is not None:
                rec["exit_code"] = int(health["exit_code"])
            # v15: re-emit the host-overhead fraction a --tick-profile
            # replica advertises, so fleet streams carry it even when
            # the children's own streams are not collected.
            if health.get("host_overhead_frac") is not None:
                rec["host_overhead_frac"] = float(
                    health["host_overhead_frac"])
            # v17: re-emit the prefix-cache advertisement and the
            # per-tenant admission ledger an armed replica heartbeats —
            # absent on unarmed replicas, so legacy streams are
            # byte-shaped as before.
            if health.get("prefix_keys") is not None:
                rec["prefix_keys"] = list(health["prefix_keys"])
                rec["prefix_shared_tokens"] = int(
                    health.get("prefix_shared_tokens", 0))
                rec["prefix_prompt_tokens"] = int(
                    health.get("prefix_prompt_tokens", 0))
            if health.get("tenant_admitted") is not None:
                rec["tenant_admitted"] = {
                    k: int(v) for k, v
                    in health["tenant_admitted"].items()}
        if detail:
            rec["detail"] = detail
        self._stream.write(rec)
        self.trace_event("i", "replica_state",
                         args={"replica": replica, "state": state})

    def trace_event(self, ph: str, name: str,
                    ts: Optional[float] = None,
                    dur: Optional[float] = None,
                    args: Optional[Dict[str, Any]] = None) -> None:
        """Hard-coded schema-v9 trace_event into the router stream
        (supervisor pattern — the jax-free contract forbids importing
        obs/trace.py, not matching it).  No-op unless ``trace=True``."""
        if not self._tracing:
            return
        with self._trace_lock:
            if not self._trace_synced:
                self._stream.write({
                    "record": "clock_sync", "time": time.time(),
                    "ts": time.perf_counter(), "trace_id": self.trace_id,
                    "run_id": self.run_id})
                self._trace_synced = True
        rec: Dict[str, Any] = {
            "record": "trace_event", "ph": ph, "name": name,
            "ts": time.perf_counter() if ts is None else ts,
            "tid": "router", "trace_id": self.trace_id,
            "run_id": self.run_id}
        if dur is not None:
            rec["dur"] = dur
        if args:
            rec["args"] = args
        self._stream.write(rec)

    # -------------------------------------------------------- breaker

    def _backoff(self, streak: int) -> float:
        return min(self.breaker_backoff_s * (2 ** max(streak - 1, 0)),
                   self.breaker_backoff_max_s)

    def _open_breaker(self, meta: _Meta) -> None:
        """Caller holds ``_lock`` (meta is only reachable through the
        guarded ``_replicas`` map)."""
        meta.breaker = "open"
        meta.fail_streak += 1
        meta.opened_at = time.time()
        meta.probe_uid = None

    def _routable(self, meta: _Meta, now: float) -> bool:
        """Caller holds ``_lock``."""
        if meta.health.get("state") not in ("starting", "healthy"):
            return False
        if meta.breaker == "closed":
            return True
        if meta.breaker == "open":
            if now - meta.opened_at >= self._backoff(meta.fail_streak):
                meta.breaker = "half_open"
                meta.probe_uid = None
                return True
            return False
        return meta.probe_uid is None          # half_open: one probe

    # ------------------------------------------------------- dispatch

    def _pick(self, metas: Dict[str, _Meta], now: float,
              avoid: Tuple[str, ...],
              refused: Tuple[str, ...],
              spec: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Policy selection over the routable set.  Caller holds
        ``_lock`` and passes the guarded ``_replicas`` map in (so the
        guarded name is only ever touched inside the lock).  ``avoid``
        is a preference (the replica a retry/requeue is leaving —
        routed back to only when it is the sole survivor); ``refused``
        is hard (it already refused this spec in this dispatch).
        ``spec`` is the request being placed — prefix_affinity scores
        candidates by it; the other policies ignore it."""
        names = [n for n in self._order
                 if n not in refused
                 and n not in self._retired
                 and self._roles.get(n, "both") != "decode"
                 and self._routable(metas[n], now)]
        preferred = [n for n in names if n not in avoid]
        names = preferred or names
        if not names:
            return None
        if self.policy == "round_robin":
            ordered = self._order[self._rr:] + self._order[:self._rr]
            for n in ordered:
                if n in names:
                    self._rr = (self._order.index(n) + 1) \
                        % len(self._order)
                    return n
            return None

        # least_kv keys on the dtype-accurate byte gauge a v12 replica
        # heartbeats (kv_bytes_live: int8 arenas report their true
        # footprint, so a quantized replica with the same block count
        # advertises the headroom it really has) — but ONLY when every
        # candidate reports it: a pre-v12 child carries no such field,
        # and letting its absence key as 0 bytes would route every
        # request to the oldest replica no matter how loaded it is.
        # Mixed fleets degrade to the block count for everyone.
        # prefix_affinity (v17): candidates are scored by how deep the
        # incoming prompt's block-chain keys overlap the hot-prefix
        # keys each replica ADVERTISES in its heartbeat
        # (replica_state.prefix_keys).  Deepest overlap wins — its KV
        # cache already holds the shared blocks, so routing there turns
        # the fleet's shared-prefix traffic into copy-on-write hits
        # instead of N cold recomputes.  Zero overlap everywhere (cold
        # keys, unarmed replicas, pre-v17 children) degrades to the
        # least_kv load key below — never a dead end.
        if self.policy == "prefix_affinity":
            mod = self._prefix_mod
            prompt = (spec or {}).get("prompt") or ()
            hashes = mod.chain_hashes(prompt, self.prefix_block_size) \
                if prompt else []

            def aff(n: str) -> int:
                adv = metas[n].health.get("prefix_keys")
                if not hashes or not adv:
                    return 0
                return mod.overlap(hashes, adv)
            best = max(aff(n) for n in names)
            if best > 0:
                names = [n for n in names if aff(n) == best]

        use_bytes = self.policy in ("least_kv", "prefix_affinity") \
            and all(metas[n].health.get("kv_bytes_live") is not None
                    for n in names)

        def load_key(n: str):
            if self.policy == "least_pending":
                load = metas[n].health.get("pending", 0)
            elif use_bytes:
                load = metas[n].health["kv_bytes_live"]
            else:
                load = metas[n].health.get("blocks_live", 0)
            return (load, metas[n].inflight, self._order.index(n))
        return min(names, key=load_key)

    def _dispatch(self, uid: str, reason: str,
                  exclude: Tuple[str, ...] = ()) -> Optional[str]:
        """Hand ``uid`` to a replica chosen by the policy; park it in
        the backlog when nothing is routable.  Returns the replica
        name, or None when backlogged/already-terminal."""
        refused: Tuple[str, ...] = ()
        while True:
            now = time.time()
            with self._lock:
                entry = self._inflight.get(uid)
                if entry is None:
                    return None                     # already terminal
                name = self._pick(self._replicas, now, exclude, refused,
                                  entry["spec"])
                if name is None:
                    self._backlog.append(uid)
                    return None
                meta = self._replicas[name]
                meta.dispatches += 1
                meta.inflight += 1
                if meta.breaker == "half_open":
                    meta.probe_uid = uid
                    # Entry-level probe stamp: meta.probe_uid is
                    # cleared by _open_breaker when the health refresh
                    # notices the crash/stall BEFORE the lost event is
                    # absorbed, so the no-charge probe_loss rule needs
                    # a marker that survives the breaker transition.
                    entry["probe"] = name
                else:
                    entry.pop("probe", None)
                entry["replica"] = name
                attempt = entry["attempts"]
                entry["attempts"] += 1
                handle = meta.handle
                spec = entry["spec"]
                src = entry.get("from")
            if handle.submit(spec):
                self._route_rec(uid, name, attempt, reason, src)
                return name
            # Refused at the door (draining/dead under us): undo the
            # booking, remember the refusal, try the next candidate.
            with self._lock:
                meta = self._replicas[name]
                meta.dispatches -= 1
                meta.inflight = max(meta.inflight - 1, 0)
                if meta.probe_uid == uid:
                    meta.probe_uid = None
                ent = self._inflight.get(uid)
                if ent is not None:
                    ent["replica"] = None
                    ent["attempts"] -= 1
                    ent.pop("probe", None)
            refused = refused + (name,)

    # --------------------------------------------------------- intake

    def submit(self, spec: Dict[str, Any]) -> None:
        """Admit one request spec (a plain dict with at least ``uid``,
        ``prompt`` and ``max_new_tokens``) and dispatch it."""
        uid = spec["uid"]
        deadline_s = spec.get("deadline_s", self.default_deadline_s)
        with self._lock:
            if uid in self._inflight or uid in self._done:
                raise ValueError(f"duplicate uid {uid!r}")
            self._inflight[uid] = {
                "spec": spec, "replica": None, "attempts": 0,
                "retries": 0, "from": None,
                "deadline": (time.time() + deadline_s)
                if deadline_s else None}
            self._submitted += 1
        self._dispatch(uid, "dispatch")

    # ----------------------------------------------------- elasticity

    def add_replica(self, handle) -> None:
        """Join a replica to the fleet mid-run (ISSUE 20: the elastic
        pool's scale-up action).  Routable immediately in state
        "starting" — the inbox/queue buffers until it speaks."""
        with self._lock:
            if handle.name in self._replicas:
                raise ValueError(f"duplicate replica {handle.name!r}")
            self._order.append(handle.name)
            self._roles[handle.name] = getattr(handle, "role", "both")
            self._replicas[handle.name] = _Meta(handle)
            self._retired.discard(handle.name)
        self._state_rec(handle.name, "starting")

    def retire_replica(self, name: str) -> None:
        """Remove a replica from the ROUTABLE set (scale-down).  It is
        still polled and harvested — late terminals, drain requeues and
        migrated events must keep landing — the caller owns the actual
        wind-down (typically ``interrupt(mode="migrate")`` so its live
        work ships to peers, then ``stop()``)."""
        with self._lock:
            if name not in self._replicas:
                raise ValueError(f"unknown replica {name!r}")
            self._retired.add(name)
        self._state_rec(name, "draining", detail="retired")

    def note_autoscale(self, direction: str, replica: str,
                       reason: str = "") -> None:
        """Record one elastic-pool action (ISSUE 20): the controller
        calls this alongside add_replica/retire_replica so the
        fleet_summary's scale_up_events/scale_down_events ledger — the
        autoscale_flap oscillation bound — reflects every decision."""
        if direction not in ("up", "down"):
            raise ValueError(f"autoscale direction must be up|down, "
                             f"got {direction!r}")
        with self._lock:
            if direction == "up":
                self._scale_up += 1
            else:
                self._scale_down += 1
        if self.log:
            self.log(f"fleet: autoscale {direction} -> {replica}"
                     + (f" ({reason})" if reason else ""))

    def backlog(self) -> int:
        """Work submitted but not yet admitted to a slot anywhere: the
        router's parked backlog plus every routable replica's reported
        ``pending`` gauge.  The elastic pool's primary scale signal
        (spool depth)."""
        with self._lock:
            return len(self._backlog) + sum(
                int(self._replicas[n].health.get("pending", 0) or 0)
                for n in self._order if n not in self._retired)

    def ttft_p50_ms(self) -> Optional[float]:
        """Fleet-wide TTFT p50 merged from the replicas' heartbeat
        sketches, or None when the SLO plane is unarmed / no sketch has
        samples yet.  The elastic pool's latency scale signal."""
        mod = self._slo_mod
        if mod is None:
            return None
        with self._lock:
            snaps = [self._replicas[n].health.get("slo_sketch")
                     for n in self._order]
        merged = None
        for snap in snaps:
            s = (snap or {}).get("ttft_ms")
            if not isinstance(s, dict) or not s.get("count"):
                continue
            if merged is not None and merged.get("alpha") != s.get("alpha"):
                continue                # mixed-resolution fleet: skip
            merged = mod.sketch_merge(merged, s) if merged is not None \
                else dict(s, buckets=dict(s["buckets"]))
        if merged is None:
            return None
        return float(mod.sketch_percentile(merged, 50))

    def _maybe_rebalance(self) -> None:
        """KV-pressure rebalance (ISSUE 20): when the hottest routable
        both-role replica's dtype-accurate ``kv_bytes_live`` gauge
        exceeds ``rebalance_kv_ratio`` x the fleet mean, ask its handle
        to migrate ONE live request to the spool (``migrate(1)``,
        asynchronous — the effect lands as a "migrated" event).  One
        ask per ``rebalance_cooldown_s``: hysteresis against chasing a
        gauge that is already moving."""
        now = time.time()
        if now - self._last_rebalance < self.rebalance_cooldown_s:
            return
        with self._lock:
            gauges = [(n, self._replicas[n].health.get("kv_bytes_live"))
                      for n in self._order
                      if n not in self._retired
                      and self._roles.get(n, "both") == "both"
                      and self._replicas[n].health.get("state")
                      == "healthy"]
        gauges = [(n, g) for n, g in gauges if g is not None]
        if len(gauges) < 2:
            return
        mean = sum(g for _, g in gauges) / len(gauges)
        if mean <= 0:
            return
        hot_name, hot = max(gauges, key=lambda t: (t[1], t[0]))
        if hot / mean < self.rebalance_kv_ratio:
            return
        with self._lock:
            handle = self._replicas[hot_name].handle
        migrate = getattr(handle, "migrate", None)
        if migrate is None:
            return
        try:
            migrate(1)
        except ValueError:
            return                      # no migration spool on it
        self._last_rebalance = now
        with self._lock:
            self._rebalance_migrations += 1
        if self.log:
            self.log(f"fleet: rebalance — migrating 1 from {hot_name} "
                     f"(kv skew {hot / mean:.2f}x mean)")

    # --------------------------------------------------------- absorb

    def _absorb(self, ev: Dict[str, Any]) -> None:
        uid = ev.get("uid")
        status = ev.get("status")
        src = ev.get("replica")
        with self._lock:
            entry = self._inflight.get(uid)
            if entry is None:
                # Late/duplicate report for an already-terminal uid (a
                # stall-rescued request's original copy finishing, a
                # replayed outbox line): counted, never re-applied.
                # Inflight accounting: decrement ONLY when this report
                # releases a booking still counted live (recorded in
                # _stale when the uid terminated from a different
                # replica) — a report from a replica whose booking was
                # already released at rescue/drain time must not eat an
                # unrelated request's slot (review finding, ISSUE 12).
                if uid in self._done:
                    self._duplicates += 1
                    if self._stale.get(uid) == src:
                        del self._stale[uid]
                        meta = self._replicas.get(src)
                        if meta is not None:
                            meta.inflight = max(meta.inflight - 1, 0)
                return
            meta = self._replicas.get(src or entry["replica"])
            if status in _TERMINAL:
                self._done[uid] = status
                self._tenant_fold(entry["spec"], status, ev)
                del self._inflight[uid]
                self.results[uid] = ev
                if self._slo is not None:
                    self._slo_absorb(status, ev)
                if entry.get("migrated"):
                    # v18: a request that was live-migrated at least
                    # once reached its terminal — the conservation
                    # counter drain_zero_evictions scores on.
                    self._migration_completed += 1
                if ev.get("redelivered"):
                    # v13/v18: this terminal came from a REDELIVERED
                    # spool admission — the crash-safe lease finished
                    # a request its first consumer dropped.
                    if entry.get("migrated"):
                        self._migration_redelivered += 1
                    else:
                        self._handoff_redelivered += 1
                if meta is not None:
                    meta.bump(status)
                    if entry["replica"] == src:
                        meta.inflight = max(meta.inflight - 1, 0)
                    elif entry["replica"] is not None:
                        # Terminal reported by an ABANDONED copy while
                        # another replica still holds a live booking:
                        # that booking is released when its own report
                        # arrives (the duplicate branch above).
                        self._stale[uid] = entry["replica"]
                    if meta.probe_uid == uid:
                        # The half-open probe's verdict: ok closes the
                        # breaker, anything else re-opens it.
                        if status == "ok":
                            meta.breaker = "closed"
                            meta.fail_streak = 0
                        else:
                            self._open_breaker(meta)
                        meta.probe_uid = None
                return
            if status == "handoff":
                # Disagg (ISSUE 15): the prefill replica cached the
                # prompt, sampled the first token and parked the KV on
                # the spool — its booking releases, but nothing
                # re-routes: the spool IS the channel, and a decode
                # replica's outbox will report the terminal status.
                if src is not None and entry["replica"] != src:
                    self._duplicates += 1
                    return
                entry["replica"] = None
                entry["from"] = src
                entry["stage"] = "spool"
                entry["spooled_at"] = time.time()
                self._handoffs += 1
                if meta is not None:
                    meta.inflight = max(meta.inflight - 1, 0)
                    meta.bump("handoff")
                    if meta.probe_uid == uid:
                        # The probe did its prefill job; the breaker
                        # closes on handoff like on ok.
                        meta.breaker = "closed"
                        meta.fail_streak = 0
                        meta.probe_uid = None
                return
            if status == "migrated":
                # Live migration (ISSUE 20): the source shipped the
                # MID-FLIGHT request — KV blocks, generated tokens,
                # sampler state — to the migration spool.  Its booking
                # releases but nothing re-routes: a peer's leased claim
                # resumes it token-identically and that peer's events
                # finish the uid (the handoff parking shape, plus a
                # sticky "migrated" mark so the terminal counts into
                # the migration conservation ledger).
                if src is not None and entry["replica"] != src:
                    self._duplicates += 1
                    return
                entry["replica"] = None
                entry["from"] = src
                entry["stage"] = "spool"
                entry["spooled_at"] = time.time()
                entry["migrated"] = True
                self._migrations += 1
                if meta is not None:
                    meta.inflight = max(meta.inflight - 1, 0)
                    meta.bump("migrated")
                    if meta.probe_uid == uid:
                        # Shipping its live work IS forward progress;
                        # the breaker closes on migrate like on ok.
                        meta.breaker = "closed"
                        meta.fail_streak = 0
                        meta.probe_uid = None
                return
            # drained / lost: the uid lives on — but only the replica
            # that currently holds it may hand it back (exactly-once
            # per drain: duplicate reports find the entry already
            # moved).  Exception: a SPOOL-stage uid has no holding
            # replica at all — a decode worker that acked its handoff
            # and then died reports it lost, and the router re-routes
            # it through a prefill replica from scratch (the spool file
            # is gone; claimed-but-unacked handoffs redeliver via the
            # lease instead and never reach this branch).
            spool_lost = status == "lost" \
                and entry.get("stage") == "spool" \
                and entry["replica"] is None
            if src is not None and entry["replica"] != src \
                    and not spool_lost:
                self._duplicates += 1
                return
            entry["replica"] = None
            entry["from"] = src
            entry.pop("stage", None)
            entry.pop("spooled_at", None)
            # A spool-lost migrated uid re-serves from scratch: its
            # migration never completed, so the sticky mark must not
            # count the re-serve's terminal into the migration ledger.
            entry.pop("migrated", None)
            probe_loss = status == "lost" and src is not None \
                and entry.pop("probe", None) == src
            if meta is not None:
                if not spool_lost:
                    meta.inflight = max(meta.inflight - 1, 0)
                meta.bump(status)
                if meta.probe_uid == uid:
                    self._open_breaker(meta)
                    meta.probe_uid = None
            now = time.time()
            if status == "drained":
                self._drained_requeued += 1
                action = "requeue_drain"
            else:                                        # lost
                if entry["deadline"] is not None \
                        and now > entry["deadline"]:
                    self._router_done(self._done, self._inflight,
                                      uid, "timeout", src)
                    return
                if probe_loss:
                    # A half-open probe that went down WITH its target
                    # was the ROUTER's gamble, not the request's fault:
                    # re-opening the breaker is the whole verdict, and
                    # the uid keeps its retry budget.  Charging it lets
                    # a permanently wedged replica (hang drill: never
                    # crashes, eats every probe for stall_after_s) burn
                    # the same request's max_retries through repeated
                    # probes until the router kills it "failed" — the
                    # PR-16 straggler-flake root cause.
                    action = "retry"
                elif entry["retries"] >= self.max_retries:
                    self._router_done(self._done, self._inflight,
                                      uid, "failed", src)
                    return
                else:
                    entry["retries"] += 1
                    self._retries += 1
                    action = "retry"
        self._dispatch(uid, action,
                       exclude=(src,) if src else ())

    def _router_done(self, done: Dict[str, str],
                     inflight: Dict[str, Dict[str, Any]], uid: str,
                     status: str, src: Optional[str]) -> None:
        """A terminal decision made by the ROUTER (deadline passed /
        retry budget exhausted).  The caller holds ``_lock`` and passes
        the guarded maps in."""
        done[uid] = status
        self._tenant_fold(inflight[uid]["spec"], status, {})
        del inflight[uid]
        self._router_terminal += 1
        self.results[uid] = {"uid": uid, "status": status,
                             "replica": src, "router_decided": True}
        if self._slo is not None:
            # Router-decided terminals (deadline timeout / retry budget
            # exhausted) are fleet failures too — scored bad like any
            # replica-reported non-ok.
            self._slo_absorb(status, {})

    # --------------------------------------------------------- tenants

    def _tenant_fold(self, spec: Optional[Dict[str, Any]], status: str,
                     ev: Dict[str, Any]) -> None:
        """Fold one fleet-terminal event into its tenant's ledger.
        Takes ``_lock`` (reentrant — callers already inside the absorb
        critical section just re-enter, the _slo_absorb idiom).  No-op
        unless --tenants armed, so legacy fleets pay nothing.  With an
        SLO spec armed too, the event is ALSO scored into the tenant's
        own list — the pure input the per-tenant verdicts in
        fleet_summary are computed from (same score_windows discipline
        as the fleet-level verdict, so two summary calls agree
        bit-for-bit)."""
        if self._tenants is None:
            return
        tenant = (spec or {}).get("tenant", "default")
        with self._lock:
            counts = self._tenant_counts.setdefault(tenant, {})
            counts[status] = counts.get(status, 0) + 1
            if self._slo is not None:
                verdict = self._slo_mod.score_event(
                    self._slo, status, ttft_ms=ev.get("ttft_ms"),
                    tpot_ms=ev.get("tpot_ms"))
                self._tenant_scored.setdefault(tenant, []).append(
                    verdict)

    # ------------------------------------------------------------- slo

    def _slo_absorb(self, status: str, ev: Dict[str, Any]) -> None:
        """Score one fleet-terminal event against the armed SLO spec
        and fold it into the current tumbling window.  Takes ``_lock``
        (reentrant — callers already inside the absorb critical section
        just re-enter).  Latencies ride the replica events themselves
        (``ttft_ms``/``tpot_ms``, v14 outbox/harvest fields); a
        router-decided terminal carries none and scores bad."""
        mod = self._slo_mod
        verdict = mod.score_event(self._slo, status,
                                  ttft_ms=ev.get("ttft_ms"),
                                  tpot_ms=ev.get("tpot_ms"))
        with self._lock:
            self._slo_scored.append(verdict)
            w = self._slo_w
            if w is None:
                w = self._slo_w = {
                    "requests": 0, "good": 0, "bad": 0, "counts": {},
                    "ttft": mod.sketch_new(mod.DEFAULT_ALPHA),
                    "tpot": mod.sketch_new(mod.DEFAULT_ALPHA)}
            w["requests"] += 1
            w["counts"][status] = w["counts"].get(status, 0) + 1
            if verdict is True:
                w["good"] += 1
            elif verdict is False:
                w["bad"] += 1
            if status == "ok":
                if ev.get("ttft_ms") is not None:
                    mod.sketch_add(w["ttft"], ev["ttft_ms"])
                if ev.get("tpot_ms") is not None:
                    mod.sketch_add(w["tpot"], ev["tpot_ms"])
            if w["requests"] >= self.slo_window:
                self._slo_close_window()

    def _slo_close_window(self) -> None:
        """Emit the current window as an ``slo_window`` record (plus an
        ``slo_breach`` past burn 1.0).  Takes ``_lock`` (reentrant; the
        stream's internal lock never takes ours, so writing here cannot
        deadlock).  Windows are event-count tumbling (every
        ``slo_window`` fleet-terminal events) — deterministic for a
        fixed workload, unlike wall-clock windows."""
        mod = self._slo_mod
        with self._lock:
            w = self._slo_w
            if w is None or w["requests"] == 0:
                return
            self._slo_w = None
            idx = self._slo_emitted
            self._slo_emitted += 1
        burn = mod.burn_rate(w["good"], w["bad"],
                             self._slo["availability"])
        rec: Dict[str, Any] = {
            "record": "slo_window", "time": time.time(),
            "window": idx, "requests": w["requests"],
            "good": w["good"], "bad": w["bad"], "burn_rate": burn,
            "counts": dict(w["counts"]), "run_id": self.run_id}
        if w["ttft"]["count"]:
            rec["ttft_ms"] = mod.sketch_summary(w["ttft"])
        if w["tpot"]["count"]:
            rec["tpot_ms"] = mod.sketch_summary(w["tpot"])
        self._stream.write(rec)
        if burn > 1.0:
            self._stream.write({
                "record": "slo_breach", "time": time.time(),
                "window": idx, "burn_rate": burn,
                "requests": w["requests"], "good": w["good"],
                "bad": w["bad"],
                "budget": 1.0 - self._slo["availability"],
                "run_id": self.run_id})

    def _slo_rollup(self, force: bool = False) -> None:
        """Merge the replicas' heartbeat latency sketches
        (``replica_state.slo_sketch``, tailed into each meta's health
        snapshot) into one fleet-level ``fleet_rollup`` record —
        cross-replica percentiles without re-pooling raw samples, plus
        per-replica p50 skew and the straggler's name.  Wall-clock
        rate-limited to ``slo_rollup_s`` (``force`` bypasses the
        limiter — the close-time last-chance rollup); emitted only when
        at least one replica contributed data (determinism tests
        compare score dicts, never rollup timing)."""
        now = time.time()
        if not force and now - self._slo_last_rollup < self.slo_rollup_s:
            return
        self._slo_last_rollup = now
        mod = self._slo_mod
        with self._lock:
            snaps = [(n, self._replicas[n].health.get("slo_sketch"))
                     for n in self._order]
        merged: Dict[str, Any] = {}
        per_replica: Dict[str, Any] = {}
        for name, sk in snaps:
            if not isinstance(sk, dict):
                continue
            for key in ("ttft_ms", "tpot_ms"):
                s = sk.get(key)
                if not isinstance(s, dict) or not s.get("count"):
                    continue
                if key in merged and merged[key]["alpha"] != s["alpha"]:
                    continue        # unmergeable error bounds: skip
                merged[key] = mod.sketch_merge(merged[key], s) \
                    if key in merged \
                    else dict(s, buckets=dict(s["buckets"]))
                if key == "ttft_ms":
                    per_replica[name] = {
                        "count": int(s["count"]),
                        "p50": mod.sketch_percentile(s, 50)}
        total = sum(v["count"] for v in per_replica.values())
        if total == 0:
            return
        rec: Dict[str, Any] = {
            "record": "fleet_rollup", "time": now,
            "replicas": len(per_replica), "count": total,
            "per_replica": per_replica, "run_id": self.run_id}
        if "ttft_ms" in merged:
            rec["ttft_ms"] = mod.sketch_summary(merged["ttft_ms"])
        if "tpot_ms" in merged:
            rec["tpot_ms"] = mod.sketch_summary(merged["tpot_ms"])
        if len(per_replica) >= 2:
            p50s = sorted((v["p50"], n) for n, v in per_replica.items())
            med = p50s[len(p50s) // 2][0]
            if med > 0:
                rec["skew"] = round(p50s[-1][0] / med, 3)
                rec["straggler"] = p50s[-1][1]
        self._stream.write(rec)

    # ----------------------------------------------------------- poll

    def _refresh_health(self) -> None:
        """Snapshot every handle's health (outside the lock — proc
        handles do bounded file tails) and act on transitions: crashed
        replicas open their breaker and surface their in-flight uids
        as lost; stalled replicas (no progress for ``stall_after_s``
        while holding work) are treated the same."""
        snaps = []
        with self._lock:
            handles = [(n, self._replicas[n].handle)
                       for n in self._order]
        for name, handle in handles:
            snaps.append((name, handle.state()))
        rescue: List[Dict[str, Any]] = []
        for name, snap in snaps:
            with self._lock:
                meta = self._replicas[name]
                meta.health = snap
                state = snap.get("state")
                stalled = (self.stall_after_s is not None
                           and state == "healthy" and meta.inflight > 0
                           and snap.get("progress_age_s", 0.0)
                           > self.stall_after_s)
                if stalled:
                    state = "stalled"
                    meta.health = dict(snap, state="stalled")
                newly_down = state in ("crashed", "stalled") \
                    and meta.emitted_state not in ("crashed", "stalled")
                if newly_down:
                    self._open_breaker(meta)
                    # Everything this replica holds is not coming
                    # back on its own: surface as lost for the
                    # deadline-aware retry path.  (A crashed
                    # ThreadReplica reports its own lost set via
                    # poll(); the src-match guard in _absorb dedupes.)
                    if state == "stalled":
                        rescue.extend(
                            {"uid": u, "status": "lost",
                             "replica": name}
                            for u, e in self._inflight.items()
                            if e["replica"] == name)
                emit = state != meta.emitted_state
                if emit:
                    meta.emitted_state = state
            if emit:
                self._state_rec(name, state, snap)
                if self.log and state in ("crashed", "stalled"):
                    self.log(f"fleet: replica {name} {state} "
                             f"(breaker open)")
        for ev in rescue:
            self._absorb(ev)

    def poll(self) -> int:
        """One router turn: refresh health, harvest replica events,
        requeue/retry, drain the backlog.  Returns the number of
        events absorbed."""
        self._refresh_health()
        if self._slo is not None:
            self._slo_rollup()
        if self.rebalance_kv_ratio is not None:
            self._maybe_rebalance()
        with self._lock:
            handles = [(n, self._replicas[n].handle)
                       for n in self._order]
        events: List[Dict[str, Any]] = []
        for name, handle in handles:
            for ev in handle.poll():
                ev.setdefault("replica", name)
                events.append(ev)
        for ev in events:
            self._absorb(ev)
        # Backlog: one dispatch attempt per uid per poll (a failed
        # attempt re-parks it).
        with self._lock:
            parked = list(self._backlog)
            self._backlog.clear()
        now = time.time()
        for uid in parked:
            expired = False
            with self._lock:
                entry = self._inflight.get(uid)
                if entry is None:
                    continue
                if entry["deadline"] is not None \
                        and now > entry["deadline"]:
                    self._router_done(self._done, self._inflight,
                                      uid, "timeout", None)
                    expired = True
            if not expired:
                self._dispatch(uid, "backlog")
        # Stale-spool sweep: a uid whose handoff was acked by a worker
        # that then died leaves NO claim to redeliver and NO process to
        # report it lost (a crashed ThreadReplica reports its acked
        # set; a kill -9'd proc child cannot) — presumed lost after
        # spool_timeout_s and re-routed through prefill from scratch.
        if self.spool_timeout_s is not None:
            now = time.time()
            with self._lock:
                stale = [u for u, e in self._inflight.items()
                         if e.get("stage") == "spool"
                         and now - e.get("spooled_at", now)
                         > self.spool_timeout_s]
            for uid in stale:
                if self.log:
                    self.log(f"fleet: {uid} stale on the spool "
                             f"(> {self.spool_timeout_s}s) — "
                             "re-routing through prefill")
                self._absorb({"uid": uid, "status": "lost",
                              "replica": None})
        return len(events)

    def done(self) -> bool:
        with self._lock:
            return not self._inflight

    def replica_state(self, name: str) -> Optional[str]:
        """The ROUTER's view of one replica (breaker/stall verdicts
        included — a stalled replica reports "healthy" about itself)."""
        with self._lock:
            meta = self._replicas.get(name)
            return meta.emitted_state if meta is not None else None

    def run(self, timeout_s: float = 120.0,
            poll_interval_s: float = 0.01) -> bool:
        """Poll until every submitted uid is terminal (True) or the
        timeout passes (False — the leftovers count as ``lost``)."""
        t0 = time.time()
        while time.time() - t0 < timeout_s:
            self.poll()
            if self.done():
                return True
            time.sleep(poll_interval_s)
        return self.done()

    # -------------------------------------------------------- summary

    def summary_record(self) -> Dict[str, Any]:
        with self._lock:
            done = dict(self._done)
            lost = len(self._inflight)
            per_replica: Dict[str, Any] = {}
            dispatches: Dict[str, int] = {}
            for name in self._order:
                meta = self._replicas[name]
                per_replica[name] = dict(meta.counts)
                per_replica[name]["dispatches"] = meta.dispatches
                ok_r = meta.counts.get("ok", 0)
                # A handed-off request continues on a decode replica,
                # and a migrated one on a peer — like a drain they
                # leave this replica's availability denominator (the
                # destination owns the outcome).
                owned = sum(v for k, v in meta.counts.items()
                            if k not in ("drained", "lost", "handoff",
                                         "migrated"))
                per_replica[name]["availability"] = round(
                    ok_r / owned, 3) if owned else 1.0
                per_replica[name]["state"] = \
                    meta.health.get("state", "?")
                role = self._roles.get(name, "both")
                if role != "both":
                    per_replica[name]["role"] = role
                dispatches[name] = meta.dispatches
            submitted = self._submitted
            retries = self._retries
            requeued = self._drained_requeued
            dups = self._duplicates
            handoffs = self._handoffs
            redelivered = self._handoff_redelivered
            in_spool = sum(1 for e in self._inflight.values()
                           if e.get("stage") == "spool")
            migrations = self._migrations
            migration_completed = self._migration_completed
            migration_redelivered = self._migration_redelivered
            rebalanced = self._rebalance_migrations
            scale_up = self._scale_up
            scale_down = self._scale_down
            slo_scored = list(self._slo_scored)
            tenant_counts = {t: dict(c) for t, c
                             in self._tenant_counts.items()}
            tenant_scored = {t: list(s) for t, s
                             in self._tenant_scored.items()}
            health_snaps = [dict(self._replicas[n].health)
                            for n in self._order]
        ok = sum(1 for s in done.values() if s == "ok")
        terminal = len(done)
        counts = {s: sum(1 for v in done.values() if v == s)
                  for s in _TERMINAL}
        # Balance skew over DISPATCHABLE replicas only: decode workers
        # are never routed prompts, so counting their structural zeros
        # would read every disagg fleet as imbalanced.
        vals = [v for n, v in dispatches.items()
                if self._roles.get(n, "both") != "decode"]
        mean = sum(vals) / len(vals) if vals else 0.0
        skew = round(max(vals) / mean, 3) if mean else 0.0
        rec: Dict[str, Any] = {
            "record": "fleet_summary",
            "time": time.time(),
            "replicas": len(self._order),
            "requests": submitted,
            "policy": self.policy,
            "duration_s": round(time.perf_counter() - self._t0, 3),
            "completed": counts["ok"],
            "failed": counts["failed"],
            "timed_out": counts["timeout"],
            "shed": counts["shed"],
            "cancelled": counts["cancelled"],
            "rejected": counts["rejected"],
            "drained_requeued": requeued,
            "retries": retries,
            "duplicates": dups,
            "lost": lost,
            "availability": round(ok / terminal, 3) if terminal else 1.0,
            "per_replica": per_replica,
            "routing": {"dispatches": dispatches,
                        "balance_skew": skew},
            "run_id": self.run_id,
        }
        n_prefill = sum(1 for r in self._roles.values()
                        if r == "prefill")
        n_decode = sum(1 for r in self._roles.values() if r == "decode")
        if n_prefill or n_decode:
            # v13 disagg topology fields: only a disaggregated fleet
            # carries them, so homogeneous streams stay byte-stable.
            rec["prefill_replicas"] = n_prefill
            rec["decode_replicas"] = n_decode
            rec["handoffs"] = handoffs
            rec["handoff_redelivered"] = redelivered
            rec["in_spool"] = in_spool
        if migrations:
            # v18 migration conservation ledger: only a fleet that
            # actually live-migrated carries these, so legacy streams
            # stay byte-stable.
            rec["migrations"] = migrations
            rec["migration_completed"] = migration_completed
            rec["migration_redelivered"] = migration_redelivered
            if rebalanced:
                rec["rebalance_migrations"] = rebalanced
            if "in_spool" not in rec:
                rec["in_spool"] = in_spool
        if scale_up or scale_down:
            # v18 autoscale ledger (the autoscale_flap oscillation
            # bound) — absent on fixed-size fleets.
            rec["scale_up_events"] = scale_up
            rec["scale_down_events"] = scale_down
        if self._slo is not None:
            # v14 SLO verdict: computed PURELY from the scored-event
            # list (score_windows chunks it exactly as the emission
            # windows did), so the two summary_record calls in
            # close()'s path agree and match the emitted records.
            mod = self._slo_mod
            wins = mod.score_windows(slo_scored, self.slo_window,
                                     self._slo["availability"])
            breaches = sum(1 for w in wins if w["burn_rate"] > 1.0)
            wi, wb = mod.worst_window(wins)
            rec["slo_verdict"] = "fail" if breaches else "pass"
            rec["slo_windows"] = len(wins)
            rec["slo_breaches"] = breaches
            rec["slo_worst_burn"] = wb
            if wi is not None:
                rec["slo_worst_window"] = wi
        if self._tenants is not None:
            # v17 per-tenant ledger: status counts + availability per
            # tenant, the spec's declared shape (weight/class/budget),
            # admitted tokens folded from the replicas' heartbeat
            # ledgers, and — SLO armed — a per-tenant verdict computed
            # PURELY from the tenant's scored list (same score_windows
            # discipline as the fleet verdict: two summary calls agree
            # bit-for-bit).  This block is the noisy_neighbor
            # assertion surface: fair keeps the victim's verdict
            # "pass" where FIFO demonstrably breaches it.
            admitted: Dict[str, int] = {}
            for h in health_snaps:
                for t, v in (h.get("tenant_admitted") or {}).items():
                    admitted[t] = admitted.get(t, 0) + int(v)
            tnames = list(self._tenants)
            for extra in (tenant_counts, admitted):
                for t in extra:
                    if t not in tnames:
                        tnames.append(t)
            tenants_rec: Dict[str, Any] = {}
            for t in tnames:
                c = tenant_counts.get(t, {})
                ok_t = c.get("ok", 0)
                term_t = sum(c.values())
                ent: Dict[str, Any] = {
                    "counts": c,
                    "availability": round(ok_t / term_t, 3)
                    if term_t else 1.0}
                ts = self._tenants.get(t)
                if ts is not None:
                    ent["weight"] = float(getattr(ts, "weight", 1.0))
                    ent["slo_class"] = getattr(ts, "slo_class", "batch")
                    budget = getattr(ts, "budget", None)
                    if budget is not None:
                        ent["budget"] = int(budget)
                if t in admitted:
                    ent["admitted_tokens"] = admitted[t]
                if self._slo is not None:
                    mod = self._slo_mod
                    wins = mod.score_windows(
                        tenant_scored.get(t, []), self.slo_window,
                        self._slo["availability"])
                    t_breaches = sum(1 for w in wins
                                     if w["burn_rate"] > 1.0)
                    ent["slo_verdict"] = "fail" if t_breaches \
                        else "pass"
                    ent["slo_breaches"] = t_breaches
                tenants_rec[t] = ent
            rec["tenants"] = tenants_rec
        # v17 fleet-level prefix hit rate: raw reuse counters summed
        # over every advertising replica's latest heartbeat — absent
        # entirely on unarmed fleets (byte-stable legacy streams).
        shared_tok = prompt_tok = 0
        prefix_armed = False
        for h in health_snaps:
            if h.get("prefix_prompt_tokens") is not None:
                prefix_armed = True
                shared_tok += int(h.get("prefix_shared_tokens", 0))
                prompt_tok += int(h.get("prefix_prompt_tokens", 0))
        if prefix_armed:
            rec["prefix_hit_rate"] = round(shared_tok / prompt_tok, 4) \
                if prompt_tok else 0.0
        if self.scenario:
            rec["scenario"] = self.scenario
        if self.verdict:
            rec["verdict"] = self.verdict
        return rec

    def close(self) -> Dict[str, Any]:
        """Write the fleet_summary and close the stream; returns the
        summary record."""
        # Last-chance re-snapshot: a short run's final heartbeat (the
        # one carrying nonzero sketches / a settled overhead fraction)
        # often lands AFTER the last poll, so poll state() once more
        # now.  Only the slo_sketch key is folded back into health and
        # only profiler-armed replicas get a closing replica_state —
        # close-time is not the place to act on state transitions, and
        # an unarmed fleet's stream is byte-shaped as before.
        with self._lock:
            handles = [(n, self._replicas[n].handle)
                       for n in self._order]
        for name, handle in handles:
            try:
                snap = handle.state()
            except Exception:
                continue
            if not isinstance(snap, dict):
                continue
            if self._slo is not None and "slo_sketch" in snap:
                with self._lock:
                    meta = self._replicas[name]
                    meta.health = dict(
                        meta.health, slo_sketch=snap["slo_sketch"])
            # v17: the FINAL prefix counters / tenant ledger are what
            # the summary's prefix_hit_rate and admitted_tokens should
            # reflect — a short run's last heartbeat (the one with the
            # settled totals) often lands after the last poll.
            late = {k: snap[k] for k in
                    ("prefix_keys", "prefix_shared_tokens",
                     "prefix_prompt_tokens", "tenant_admitted")
                    if k in snap}
            if late:
                with self._lock:
                    meta = self._replicas[name]
                    meta.health = dict(meta.health, **late)
            if snap.get("host_overhead_frac") is not None:
                # v15: the cumulative fraction is only meaningful once
                # the run is over — state transitions rarely fire late
                # enough to re-emit it, so the closing record is what
                # fleet_report and perf_ledger actually rank on.
                with self._lock:
                    state = self._replicas[name].emitted_state \
                        or "healthy"
                self._state_rec(name, state, snap)
        if self._slo is not None:
            self._slo_rollup(force=True)
            # Trailing partial window: emitted before the summary so
            # the stream's slo_window count matches the summary's
            # windows field (score_windows includes the partial too).
            with self._lock:
                self._slo_close_window()
        rec = self.summary_record()
        self._stream.write(rec)
        self._stream.close()
        return rec
