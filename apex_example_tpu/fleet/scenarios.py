"""Scripted chaos scenarios, scored on fleet availability.

Pure stdlib ON PURPOSE (jax-free by contract, like the rest of
fleet/): a scenario is a deterministic script over a
:class:`~FleetRouter` and its replica handles — both duck-typed, never
imported — that ends in a ``fleet_summary`` carrying the scenario name
and a pass/fail ``verdict``.  ROADMAP item 5's point is exactly this:
"handles many scenarios" becomes an executable, regression-tested
number instead of a claim.

``rolling_restart``  SIGTERM each replica in turn under sustained load
                     (``interrupt()``: drain -> exit 75 -> supervised
                     restart for ProcReplica; drain -> engine rebuild
                     for ThreadReplica).  Scored on ZERO lost requests:
                     every submitted uid reaches exactly one
                     non-drained terminal status and fleet availability
                     is 1.0 — drains requeue to siblings, nothing
                     falls on the floor.
``crash_storm``      k replicas die mid-serve via ``--inject-fault
                     crash@tick`` (armed by the caller on the replica /
                     its serve child).  The router circuit-breaks the
                     dead replicas and deadline-aware-retries what they
                     held; the scenario restarts each crashed replica
                     once (playing supervisor for the in-process
                     transport) so the breaker's half-open probe path
                     runs too.
``straggler``        one replica hangs (``--inject-fault hang@tick``)
                     without crashing — the classic silent wedge.  The
                     router's stall detector (``stall_after_s``) opens
                     its breaker and rescues its in-flight requests
                     onto healthy siblings.
``prefill_crash``    disagg (ISSUE 15): the prefill role dies mid-
                     serve (``crash@tick``).  Requests it held come
                     back ``lost`` and re-route once the scenario
                     restarts it (a supervised child restarts itself);
                     requests already on the spool keep decoding
                     untouched — zero lost, and the handoffs that
                     were in flight at the crash must still conserve.
``decode_crash_midspool``  disagg (ISSUE 15): a decode worker crashes
                     holding claimed-but-unacked handoffs (the
                     ``handoff_crash_preack`` drill).  Nobody
                     restarts it — a PEER decode worker must reclaim
                     the expired leases and finish the redelivered
                     handoffs (scored: zero lost, every uid exactly
                     one non-drained terminal, ``handoff_redelivered``
                     > 0 — the peer really did the work).
``noisy_neighbor``   multi-tenant admission chaos (ISSUE 19): one
                     tenant floods the fleet while a small interactive
                     tenant ("the victim") carries virtual-step
                     deadlines.  Run with fair scheduling armed the
                     scenario passes iff the victim's per-tenant SLO
                     verdict is "pass" at availability 1.0; run with
                     ``expect_breach=True`` (the FIFO control arm) it
                     passes iff the victim DEMONSTRABLY breaches —
                     asserting both directions is what proves the DWRR
                     lane did the work.  Deadlines are virtual engine
                     steps, so both verdicts are bit-reproducible.
``tenant_burst_starvation``  a bursty batch tenant lands its whole
                     backlog ahead of a deadline-carrying tenant in
                     submission order; weighted fair admission must
                     still run the victim inside its deadline window —
                     scored on the victim's per-tenant verdict and
                     availability 1.0 with zero lost overall.
``prefix_heavy``     prefix-affinity routing (ISSUE 19): each tenant
                     re-sends prompts sharing its own warm prefix,
                     wave by wave, with the per-wave submission order
                     ROTATED so load-based policies scatter tenants
                     across replicas while ``prefix_affinity`` follows
                     the advertised hot-prefix keys.  Scored on zero
                     lost at full availability with the fleet
                     ``prefix_hit_rate`` measured (and clearing
                     ``min_hit_rate`` when given) — the
                     affinity-vs-least_pending strict comparison is the
                     caller's double run over the same spec stream.
``drain_zero_evictions``  live migration (ISSUE 20): rolling restart
                     where every ``interrupt(mode="migrate")`` ships
                     the replica's LIVE slots to the migration spool
                     for a peer to resume token-identically — nothing
                     is evicted, nothing re-prefills from scratch.
                     Scored on zero lost at availability 1.0 (an
                     eviction would surface as a non-ok terminal) with
                     migrations actually flowing, landing as terminals,
                     and the spool drained at close.
``migrate_under_crash_storm``  live migration (ISSUE 20): the
                     DESTINATION dies in the ack-crash window — a
                     drained source ships mid-flight requests, the
                     armed peer claims and crashes between
                     ``admit_migrated`` and ack (the
                     ``handoff_crash_preack`` drill on its migration
                     intake), nobody restarts it, and the surviving
                     peers must reclaim the expired leases and finish
                     the redelivered payloads exactly once.  Staged
                     deterministically: only the source runs at drain
                     time (outbound-only spool, so it cannot reclaim
                     its own payloads), only the doomed destination
                     polls at claim time.
``autoscale_flap``   elastic pools (ISSUE 20): bursty load with idle
                     gaps against an elastic controller stepping in
                     the drive loop.  The controller must track the
                     bursts (>= 1 scale-up) without oscillating past
                     the hysteresis bound — total scale events stay
                     under the cap, the pool ends inside [min, max],
                     and retiring a replica never kills its work.
``none``             no chaos: route, serve, summarize (the baseline
                     the chaos scores are read against).

Determinism: ThreadReplica ticks only when work exists, so with the
workload pre-submitted before ``start()`` the engine-tick evolution —
and therefore which requests a ``crash@tick`` takes down — is a pure
function of the request stream.  In-process scenario SCORES (status
counts, retries, availability) are exactly reproducible; subprocess
scenarios are scored on invariants (zero lost, availability 1.0) that
hold regardless of host timing.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

SCENARIOS = ("none", "rolling_restart", "crash_storm", "straggler",
             "prefill_crash", "decode_crash_midspool",
             "noisy_neighbor", "tenant_burst_starvation",
             "prefix_heavy", "drain_zero_evictions",
             "migrate_under_crash_storm", "autoscale_flap")


def synthetic_specs(n: int, *, vocab_size: int = 256, seed: int = 0,
                    prompt_len=(3, 8), max_new=(3, 10),
                    temperature: float = 0.0, top_k: int = 0,
                    eos_id: Optional[int] = None,
                    deadline_s: Optional[float] = None,
                    deadline_step: Optional[int] = None,
                    tenant: Optional[str] = None,
                    shared_prefix: int = 0,
                    uid_prefix: str = "fl") -> List[Dict[str, Any]]:
    """Deterministic request specs for the router (plain dicts — the
    jax-free counterpart of serve/loadgen.synthetic_requests, which
    this module must not import).  Uids are ``<prefix>-0000``-style and
    unique per prefix; the router stamps arrival itself, so there is no
    virtual-step staggering here — fleet arrivals are wall-clock.

    v17 multi-tenant knobs: ``tenant`` stamps every spec (the replica's
    make_request threads it onto the Request, the router folds
    terminals into that tenant's ledger); ``shared_prefix`` prepends
    one common N-token prefix drawn ONCE from the same stream — per
    (seed, shared_prefix) deterministic, so two tenants with different
    seeds get DISJOINT warm sets (what prefix_affinity routes on);
    ``deadline_step`` is an absolute virtual-step deadline on the
    serving engine — the bit-reproducible breach mechanism the
    noisy_neighbor verdicts rely on (no wall clocks involved)."""
    if n < 1:
        raise ValueError(f"need n >= 1 specs, got {n}")
    if shared_prefix < 0:
        raise ValueError(f"shared_prefix must be >= 0, "
                         f"got {shared_prefix}")
    rnd = random.Random(seed)
    prefix = [rnd.randrange(vocab_size) for _ in range(shared_prefix)]
    out: List[Dict[str, Any]] = []
    for i in range(n):
        p = rnd.randint(prompt_len[0], prompt_len[1])
        m = rnd.randint(max_new[0], max_new[1])
        spec: Dict[str, Any] = {
            "uid": f"{uid_prefix}-{i:04d}",
            "prompt": prefix + [rnd.randrange(vocab_size)
                                for _ in range(p)],
            "max_new_tokens": m,
            "temperature": temperature,
            "top_k": top_k,
        }
        if eos_id is not None:
            spec["eos_id"] = eos_id
        if deadline_s is not None:
            spec["deadline_s"] = deadline_s
        if deadline_step is not None:
            spec["deadline_step"] = int(deadline_step)
        if tenant is not None:
            spec["tenant"] = tenant
        out.append(spec)
    return out


def _drive(router, until, timeout_s: float,
           poll_interval_s: float = 0.02) -> bool:
    t0 = time.time()
    while True:
        router.poll()
        if until():
            return True
        if time.time() - t0 >= timeout_s:
            return False
        time.sleep(poll_interval_s)


def _wait_up(router, replica, timeout_s: float) -> bool:
    """Poll the router until ``replica`` is healthy AND addressable
    (a ProcReplica has no child pid to signal until its first
    heartbeat lands — interrupting earlier would be a no-op)."""
    def up():
        st = replica.state()
        return st.get("state") == "healthy" \
            and st.get("pid") is not None
    return _drive(router, up, timeout_s)


def _wait_live(router, replica, timeout_s: float) -> bool:
    """Poll the router until ``replica`` is healthy and actually HOLDS
    mid-flight work (live KV blocks) — the precondition for a
    migrate-mode interrupt to ship anything.  Interrupting an idle
    replica is a valid drain but a vacuous migration test."""
    def live():
        st = replica.state()
        return st.get("state") == "healthy" \
            and st.get("blocks_live", 0) > 0
    return _drive(router, live, timeout_s)


def _wait_restarted(router, replica, restarts_before: int,
                    timeout_s: float) -> bool:
    """Poll the router (load keeps flowing) until ``replica`` has been
    restarted past ``restarts_before`` AND reports healthy again."""
    def back():
        st = replica.state()
        return st.get("restarts", 0) > restarts_before \
            and st.get("state") == "healthy"
    return _drive(router, back, timeout_s)


def _finish(router, name: str, *, availability_min: float,
            checks: Optional[Dict[str, bool]] = None,
            summary_checks: Optional[Dict[str, Any]] = None,
            slo_gate: bool = True) -> Dict[str, Any]:
    """Score the run: verdict "pass" iff nothing was lost, fleet
    availability clears the bar, and every scenario-specific check
    held.  ``summary_checks`` maps check names to predicates over the
    summary record (for invariants only computable at close, like the
    disagg redelivery count).  ``slo_gate=False`` drops the global SLO
    verdict from the score — the noisy_neighbor CONTROL arm expects a
    breach, so the fleet-level fail is the point, not a defect.
    Writes the fleet_summary and closes the router stream."""
    summary = router.summary_record()
    checks = dict(checks or {})
    for key, predicate in (summary_checks or {}).items():
        checks[key] = bool(predicate(summary))
    # v14: an armed SLO spec folds into the scenario score — a chaos
    # run that "passed" on conservation but burned through its error
    # budget in some window is a fail (absent without --slo, so
    # unarmed scenarios score exactly as before).
    ok = (summary["lost"] == 0
          and summary["availability"] >= availability_min
          and (not slo_gate or summary.get("slo_verdict") != "fail")
          and all((checks or {}).values()))
    router.scenario = name
    router.verdict = "pass" if ok else "fail"
    if router.log:
        failed = [k for k, v in (checks or {}).items() if not v]
        router.log(f"scenario {name}: {router.verdict}  "
                   f"availability={summary['availability']}  "
                   f"lost={summary['lost']}  "
                   f"retries={summary['retries']}  "
                   f"requeued={summary['drained_requeued']}"
                   + (f"  failed_checks={failed}" if failed else ""))
    return router.close()


def run_none(router, replicas, specs, *, timeout_s: float = 120.0,
             availability_min: float = 1.0) -> Dict[str, Any]:
    t0 = time.perf_counter()
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    for spec in specs:
        router.submit(spec)
    done = _drive(router, router.done, timeout_s)
    router.trace_event("X", "scenario:none", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "none", availability_min=availability_min,
                   checks={"completed_in_time": done})


def run_rolling_restart(router, replicas, specs, *,
                        timeout_s: float = 120.0,
                        settle_timeout_s: float = 60.0,
                        availability_min: float = 1.0) -> Dict[str, Any]:
    """Restart every replica in turn while load keeps arriving; zero
    lost requests required.  The spec stream is split into one wave per
    restart plus a lead-in and a tail, so each drain happens with
    requests queued behind it — the requeue-on-drain path MUST run for
    the score to mean anything (asserted via ``drained_requeued`` when
    any wave was pending at interrupt time)."""
    t0 = time.perf_counter()
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    waves = len(replicas) + 2
    per = max(len(specs) // waves, 1)
    chunks = [specs[i * per:(i + 1) * per] for i in range(waves - 1)]
    chunks.append(specs[(waves - 1) * per:])
    for spec in chunks[0]:
        router.submit(spec)
    restarted_all = True
    for i, replica in enumerate(replicas):
        for spec in chunks[i + 1]:
            router.submit(spec)
        restarted_all &= _wait_up(router, replica, settle_timeout_s)
        before = replica.state().get("restarts", 0)
        router.trace_event("i", "interrupt",
                           args={"replica": replica.name})
        replica.interrupt()
        restarted_all &= _wait_restarted(router, replica, before,
                                         settle_timeout_s)
    for spec in chunks[-1]:
        router.submit(spec)
    done = _drive(router, router.done, timeout_s)
    router.trace_event("X", "scenario:rolling_restart", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "rolling_restart",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "every_replica_restarted": restarted_all})


def run_crash_storm(router, replicas, specs, *,
                    crashed_names: List[str],
                    timeout_s: float = 120.0,
                    restart_crashed: bool = True,
                    availability_min: float = 1.0) -> Dict[str, Any]:
    """k replicas are pre-armed (by the caller) with ``crash@tick``
    drills; the scenario submits the full workload up front (the
    deterministic tick evolution), lets the storm happen, restarts each
    crashed replica once, and requires every request to land ok via the
    retry path."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    observed: set = set()
    restarted: set = set()

    def storm_over():
        for replica in replicas:
            if replica.name not in crashed_names:
                continue
            if replica.name not in observed:
                st = replica.state()
                # Either transport's proof the drill actually fired: an
                # in-process replica parks in state "crashed"; a
                # supervised one is restarted quickly, but its
                # supervisor's restart record classifies the death
                # (v10) and the handle surfaces it.  Without this a
                # drill armed past the workload's last tick would
                # never fire and the scenario would score a storm that
                # never happened (review finding, ISSUE 12).
                if st.get("state") == "crashed" \
                        or st.get("classification") in ("crashed",
                                                        "stall_killed"):
                    observed.add(replica.name)
            if restart_crashed and replica.name in observed \
                    and replica.name not in restarted:
                router.trace_event("i", "scenario_restart",
                                   args={"replica": replica.name})
                replica.restart()
                restarted.add(replica.name)
        return router.done()

    done = _drive(router, storm_over, timeout_s)
    router.trace_event("X", "scenario:crash_storm", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "crash_storm",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "every_crash_observed":
                               observed >= set(crashed_names)})


def run_straggler(router, replicas, specs, *,
                  straggler_name: str,
                  timeout_s: float = 120.0,
                  availability_min: float = 1.0) -> Dict[str, Any]:
    """One replica is pre-armed with a ``hang@tick`` drill and the
    router with ``stall_after_s``: the wedge never crashes, the stall
    detector must notice the stopped heartbeat and rescue the hung
    replica's requests onto siblings."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    stalled_seen = {"v": False}

    def until():
        if not stalled_seen["v"]:
            for replica in replicas:
                if replica.name == straggler_name:
                    # The router's view, not the handle's: the stall
                    # verdict lives in the breaker/health layer.
                    stalled_seen["v"] = router.replica_state(
                        straggler_name) == "stalled"
        return router.done()

    done = _drive(router, until, timeout_s)
    router.trace_event("X", "scenario:straggler", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "straggler",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "stall_detected": stalled_seen["v"]})


def run_prefill_crash(router, replicas, specs, *,
                      crashed_name: str,
                      timeout_s: float = 120.0,
                      restart_crashed: bool = True,
                      availability_min: float = 1.0) -> Dict[str, Any]:
    """Disagg chaos (ISSUE 15): the PREFILL role dies mid-serve via a
    pre-armed ``crash@tick`` drill.  Requests it held (queued or
    mid-prefill) come back ``lost`` and the router re-routes them once
    the replica returns (the scenario restarts the in-process replica;
    a supervised child's supervisor does it on its own); requests
    already handed off keep decoding untouched.  Scored on zero lost
    plus the crash really firing and handoffs really flowing."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    observed: set = set()
    restarted: set = set()

    def crash_seen():
        replica = next(r for r in replicas if r.name == crashed_name)
        if crashed_name not in observed:
            st = replica.state()
            if st.get("state") == "crashed" \
                    or st.get("classification") in ("crashed",
                                                    "stall_killed"):
                observed.add(crashed_name)
        if restart_crashed and crashed_name in observed \
                and crashed_name not in restarted:
            router.trace_event("i", "scenario_restart",
                               args={"replica": crashed_name})
            replica.restart()
            restarted.add(crashed_name)
        return router.done()

    done = _drive(router, crash_seen, timeout_s)
    router.trace_event("X", "scenario:prefill_crash", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "prefill_crash",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "crash_observed": crashed_name in observed},
                   summary_checks={
                       "handoffs_flowed":
                           lambda s: s.get("handoffs", 0) > 0,
                       "spool_drained":
                           lambda s: s.get("in_spool", 0) == 0})


def run_decode_crash_midspool(router, replicas, specs, *,
                              crashed_name: str,
                              timeout_s: float = 120.0,
                              availability_min: float = 1.0
                              ) -> Dict[str, Any]:
    """Disagg chaos (ISSUE 15): a decode worker crashes in the
    ack-crash window (the caller arms ``handoff_crash_preack`` on it)
    while holding claimed-but-unacked handoffs.  Nobody restarts it —
    the PEER decode workers must reclaim its expired leases, admit the
    redelivered handoffs and finish them.  Scored on zero lost, every
    uid exactly one non-drained terminal status, the crash really
    firing, and at least one terminal coming from a REDELIVERED
    admission (the peer provably did the reclaimed work)."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    observed: set = set()

    def crash_seen():
        if crashed_name not in observed:
            replica = next(r for r in replicas
                           if r.name == crashed_name)
            st = replica.state()
            if st.get("state") == "crashed" \
                    or st.get("classification") in ("crashed",
                                                    "stall_killed"):
                observed.add(crashed_name)
        return router.done()

    done = _drive(router, crash_seen, timeout_s)
    router.trace_event("X", "scenario:decode_crash_midspool", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "decode_crash_midspool",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "crash_observed": crashed_name in observed},
                   summary_checks={
                       "peer_redelivered":
                           lambda s: s.get("handoff_redelivered",
                                           0) > 0,
                       "spool_drained":
                           lambda s: s.get("in_spool", 0) == 0})


def _tenant_entry(summary: Dict[str, Any],
                  tenant: str) -> Dict[str, Any]:
    return (summary.get("tenants") or {}).get(tenant) or {}


def run_noisy_neighbor(router, replicas, specs, *,
                       victim: str,
                       expect_breach: bool = False,
                       timeout_s: float = 120.0,
                       availability_min: float = 1.0
                       ) -> Dict[str, Any]:
    """Multi-tenant admission chaos (ISSUE 19): the spec stream puts a
    flooding tenant's whole backlog AHEAD of a small interactive
    tenant whose requests carry virtual-step deadlines.  Everything is
    pre-submitted before ``start()`` (the crash_storm discipline), so
    which victim requests expire is a pure function of the stream —
    both arms of the verdict are bit-reproducible.

    Fair arm (default): the replicas run with --tenants armed; DWRR
    admits the interactive victim ahead of the flood and the scenario
    passes iff the victim's per-tenant SLO verdict is "pass" at
    per-tenant availability 1.0 (zero lost, fleet availability >=
    ``availability_min``).

    Control arm (``expect_breach=True``): same stream, FIFO replicas
    (no --tenants on the engine; the ROUTER keeps tenant_specs so the
    per-tenant ledger still folds).  The scenario passes iff the
    victim DEMONSTRABLY breaches — verdict "fail" with per-tenant
    availability < 1.0.  Asserting both arms is what proves fair
    admission, not workload slack, saved the victim."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    done = _drive(router, router.done, timeout_s)
    router.trace_event("X", "scenario:noisy_neighbor", ts=t0,
                       dur=time.perf_counter() - t0)
    if expect_breach:
        return _finish(
            router, "noisy_neighbor",
            availability_min=0.0, slo_gate=False,
            checks={"completed_in_time": done},
            summary_checks={
                "victim_breached": lambda s:
                    _tenant_entry(s, victim).get("slo_verdict")
                    == "fail",
                "victim_impacted": lambda s:
                    _tenant_entry(s, victim).get("availability", 1.0)
                    < 1.0})
    return _finish(
        router, "noisy_neighbor",
        availability_min=availability_min,
        checks={"completed_in_time": done},
        summary_checks={
            "victim_slo_pass": lambda s:
                _tenant_entry(s, victim).get("slo_verdict") == "pass",
            "victim_available": lambda s:
                _tenant_entry(s, victim).get("availability") == 1.0})


def run_tenant_burst_starvation(router, replicas, specs, *,
                                victim: str,
                                timeout_s: float = 120.0,
                                availability_min: float = 1.0
                                ) -> Dict[str, Any]:
    """A bursty batch tenant lands its whole backlog ahead of the
    deadline-carrying ``victim`` in submission order (the caller
    builds the stream that way); weighted fair admission must still
    run the victim inside its virtual deadline window.  Scored on the
    victim's per-tenant SLO verdict and availability 1.0, zero lost
    overall — pre-submitted stream, so bit-reproducible like
    noisy_neighbor's fair arm."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    done = _drive(router, router.done, timeout_s)
    router.trace_event("X", "scenario:tenant_burst_starvation", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(
        router, "tenant_burst_starvation",
        availability_min=availability_min,
        checks={"completed_in_time": done},
        summary_checks={
            "victim_slo_pass": lambda s:
                _tenant_entry(s, victim).get("slo_verdict") == "pass",
            "victim_available": lambda s:
                _tenant_entry(s, victim).get("availability") == 1.0})


def run_prefix_heavy(router, replicas, specs, *,
                     timeout_s: float = 120.0,
                     availability_min: float = 1.0,
                     min_hit_rate: Optional[float] = None
                     ) -> Dict[str, Any]:
    """Prefix-affinity routing drill (ISSUE 19): specs are partitioned
    by tenant and submitted WAVE BY WAVE — one spec per tenant per
    wave, drive to done between waves so every replica's hot-prefix
    advertisement is settled before the next wave routes.  The
    per-wave submission order rotates by wave index: a load-based
    policy (least_pending tie-breaks on live bookings) scatters each
    tenant across replicas wave over wave, while ``prefix_affinity``
    follows the advertised chain keys and keeps every tenant's warm
    set on one replica.  Scored on zero lost at full availability
    with the fleet ``prefix_hit_rate`` measured (and clearing
    ``min_hit_rate`` when given); the strict affinity-beats-
    least_pending comparison is the caller's double run over the SAME
    spec stream — same waves, same rotation, only the policy
    differs."""
    t0 = time.perf_counter()
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    by_tenant: Dict[str, List[Dict[str, Any]]] = {}
    for spec in specs:
        by_tenant.setdefault(
            spec.get("tenant", "default"), []).append(spec)
    tnames = list(by_tenant)
    waves_done = True
    wave = 0
    while any(by_tenant.values()):
        pivot = wave % len(tnames)
        for name in tnames[pivot:] + tnames[:pivot]:
            if by_tenant[name]:
                router.submit(by_tenant[name].pop(0))
        waves_done &= _drive(router, router.done, timeout_s)
        wave += 1
    router.trace_event("X", "scenario:prefix_heavy", ts=t0,
                       dur=time.perf_counter() - t0)
    summary_checks: Dict[str, Any] = {
        "hit_rate_measured": lambda s: "prefix_hit_rate" in s}
    if min_hit_rate is not None:
        summary_checks["hit_rate_cleared"] = \
            lambda s: s.get("prefix_hit_rate", 0.0) >= min_hit_rate
    return _finish(router, "prefix_heavy",
                   availability_min=availability_min,
                   checks={"completed_in_time": waves_done},
                   summary_checks=summary_checks)


def run_drain_zero_evictions(router, replicas, specs, *,
                             timeout_s: float = 120.0,
                             settle_timeout_s: float = 60.0,
                             availability_min: float = 1.0
                             ) -> Dict[str, Any]:
    """Rolling restart WITHOUT killing a single request (ISSUE 20):
    every replica is interrupted in turn in ``mode="migrate"`` — its
    live slots ship to the migration spool (storage-dtype-exact KV +
    cursor + sampler state) and a peer, or the rebuilt replica itself,
    resumes them token-identically; only the un-admitted queue requeues
    as "drained".  Each interrupt waits for the replica to actually
    hold live work first, so the migration path provably runs.  Scored
    on zero lost at availability 1.0 (an eviction would surface as a
    non-ok terminal), migrations flowing AND landing as terminals, and
    the spool drained at close."""
    t0 = time.perf_counter()
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    waves = len(replicas) + 2
    per = max(len(specs) // waves, 1)
    chunks = [specs[i * per:(i + 1) * per] for i in range(waves - 1)]
    chunks.append(specs[(waves - 1) * per:])
    for spec in chunks[0]:
        router.submit(spec)
    cycled_all = True
    for i, replica in enumerate(replicas):
        for spec in chunks[i + 1]:
            router.submit(spec)
        cycled_all &= _wait_up(router, replica, settle_timeout_s)
        cycled_all &= _wait_live(router, replica, settle_timeout_s)
        before = replica.state().get("restarts", 0)
        router.trace_event("i", "interrupt_migrate",
                           args={"replica": replica.name})
        replica.interrupt(mode="migrate")
        cycled_all &= _wait_restarted(router, replica, before,
                                      settle_timeout_s)
    for spec in chunks[-1]:
        router.submit(spec)
    done = _drive(router, router.done, timeout_s)
    router.trace_event("X", "scenario:drain_zero_evictions", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "drain_zero_evictions",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "every_replica_cycled": cycled_all},
                   summary_checks={
                       "migrations_flowed":
                           lambda s: s.get("migrations", 0) > 0,
                       "migrations_landed":
                           lambda s: s.get("migration_completed",
                                           0) > 0,
                       "spool_drained":
                           lambda s: s.get("in_spool", 0) == 0})


def run_migrate_under_crash_storm(router, replicas, specs, *,
                                  source_name: str,
                                  crashed_name: str,
                                  timeout_s: float = 120.0,
                                  settle_timeout_s: float = 60.0,
                                  availability_min: float = 1.0
                                  ) -> Dict[str, Any]:
    """Live-migration chaos (ISSUE 20): the migration DESTINATION dies
    in the ack-crash window and the payloads must still land exactly
    once.  Deterministically staged so the doomed replica provably
    claims first:

    1. Everything is pre-submitted, then only ``source_name`` starts.
       The caller built it OUTBOUND-only on the migration spool
       (``migrate_intake=False``) so it can never reclaim its own
       payloads after the drain.
    2. Once the source holds live slots it is interrupted in
       ``mode="migrate"`` — mid-flight requests ship to the spool.
    3. ``crashed_name`` starts next, the ONLY polling peer.  The
       caller armed ``handoff_crash_preack`` on it: it claims, admits
       the first payload, and dies before the ack — the claim (and
       any others it held) survive on disk under its lease.
    4. Nobody restarts it.  The remaining peers start last and must
       wait out the lease, reclaim, and finish the redelivered
       payloads — scored on zero lost, availability 1.0, migrations
       flowing, ``migration_redelivered`` > 0 (a peer provably did
       reclaimed work), and the spool drained."""
    t0 = time.perf_counter()
    source = next(r for r in replicas if r.name == source_name)
    dest = next(r for r in replicas if r.name == crashed_name)
    rest = [r for r in replicas
            if r.name not in (source_name, crashed_name)]
    for spec in specs:
        router.submit(spec)
    source.start()
    staged = _wait_up(router, source, settle_timeout_s)
    staged &= _wait_live(router, source, settle_timeout_s)
    before = source.state().get("restarts", 0)
    router.trace_event("i", "interrupt_migrate",
                       args={"replica": source_name})
    source.interrupt(mode="migrate")
    staged &= _wait_restarted(router, source, before, settle_timeout_s)
    dest.start()
    observed: set = set()

    def crash_seen():
        if crashed_name not in observed:
            st = dest.state()
            if st.get("state") == "crashed" \
                    or st.get("classification") in ("crashed",
                                                    "stall_killed"):
                observed.add(crashed_name)
        return crashed_name in observed

    staged &= _drive(router, crash_seen, settle_timeout_s)
    for replica in rest:
        replica.start()
    done = _drive(router, router.done, timeout_s)
    router.trace_event("X", "scenario:migrate_under_crash_storm",
                       ts=t0, dur=time.perf_counter() - t0)
    return _finish(router, "migrate_under_crash_storm",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "staged_in_order": staged,
                           "crash_observed": crashed_name in observed},
                   summary_checks={
                       "migrations_flowed":
                           lambda s: s.get("migrations", 0) > 0,
                       "peer_redelivered":
                           lambda s: s.get("migration_redelivered",
                                           0) > 0,
                       "spool_drained":
                           lambda s: s.get("in_spool", 0) == 0})


def run_autoscale_flap(router, replicas, specs, *, pool,
                       bursts: int = 3,
                       gap_s: float = 0.5,
                       max_scale_events: Optional[int] = None,
                       timeout_s: float = 120.0,
                       availability_min: float = 1.0
                       ) -> Dict[str, Any]:
    """Elastic-pool hysteresis drill (ISSUE 20): the workload arrives
    in ``bursts`` separated by idle gaps — the classic flap inducer.
    ``pool`` is the duck-typed elastic controller (fleet.py's
    ElasticPool): ``pool.step()`` interleaves with every router poll,
    exactly the fleet drive loop's cadence.  The controller must track
    the bursts (>= 1 scale-up over the run) WITHOUT oscillating past
    the hysteresis bound: total scale events (up + down) stay <=
    ``max_scale_events`` (default ``2 * bursts`` — at most one
    up/down pair per burst), and the pool ends inside its [min, max]
    bounds.  Scored at availability 1.0 with zero lost — retiring a
    replica must never kill its work (migrate-drain or graceful
    stop)."""
    t0 = time.perf_counter()
    if max_scale_events is None:
        max_scale_events = 2 * bursts
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    per = max(len(specs) // bursts, 1)
    chunks = [specs[i * per:(i + 1) * per] for i in range(bursts - 1)]
    chunks.append(specs[(bursts - 1) * per:])

    def drive_pool(until, budget_s):
        t = time.time()
        while True:
            router.poll()
            pool.step()
            if until():
                return True
            if time.time() - t >= budget_s:
                return False
            time.sleep(0.02)

    done = True
    for i, chunk in enumerate(chunks):
        for spec in chunk:
            router.submit(spec)
        done &= drive_pool(router.done, timeout_s)
        if i < len(chunks) - 1:
            # Idle gap: the scale-down side of the hysteresis gets its
            # chance to fire (and to flap — which the bound punishes).
            drive_pool(lambda: False, gap_s)
    bounds_ok = pool.within_bounds()
    router.trace_event("X", "scenario:autoscale_flap", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "autoscale_flap",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "pool_within_bounds": bounds_ok},
                   summary_checks={
                       "scaled_up":
                           lambda s: s.get("scale_up_events", 0) >= 1,
                       "no_flap":
                           lambda s: s.get("scale_up_events", 0)
                           + s.get("scale_down_events", 0)
                           <= max_scale_events})


def run_scenario(name: str, router, replicas, specs,
                 **kw) -> Dict[str, Any]:
    """Dispatch by scenario name (the ``fleet.py --scenario`` surface)."""
    if name not in SCENARIOS:
        raise ValueError(f"unknown scenario {name!r} "
                         f"(expected one of {SCENARIOS})")
    fn = {"none": run_none,
          "rolling_restart": run_rolling_restart,
          "crash_storm": run_crash_storm,
          "straggler": run_straggler,
          "prefill_crash": run_prefill_crash,
          "decode_crash_midspool": run_decode_crash_midspool,
          "noisy_neighbor": run_noisy_neighbor,
          "tenant_burst_starvation": run_tenant_burst_starvation,
          "prefix_heavy": run_prefix_heavy,
          "drain_zero_evictions": run_drain_zero_evictions,
          "migrate_under_crash_storm": run_migrate_under_crash_storm,
          "autoscale_flap": run_autoscale_flap}[name]
    return fn(router, replicas, specs, **kw)
