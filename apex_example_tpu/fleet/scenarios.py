"""Scripted chaos scenarios, scored on fleet availability.

Pure stdlib ON PURPOSE (jax-free by contract, like the rest of
fleet/): a scenario is a deterministic script over a
:class:`~FleetRouter` and its replica handles — both duck-typed, never
imported — that ends in a ``fleet_summary`` carrying the scenario name
and a pass/fail ``verdict``.  ROADMAP item 5's point is exactly this:
"handles many scenarios" becomes an executable, regression-tested
number instead of a claim.

``rolling_restart``  SIGTERM each replica in turn under sustained load
                     (``interrupt()``: drain -> exit 75 -> supervised
                     restart for ProcReplica; drain -> engine rebuild
                     for ThreadReplica).  Scored on ZERO lost requests:
                     every submitted uid reaches exactly one
                     non-drained terminal status and fleet availability
                     is 1.0 — drains requeue to siblings, nothing
                     falls on the floor.
``crash_storm``      k replicas die mid-serve via ``--inject-fault
                     crash@tick`` (armed by the caller on the replica /
                     its serve child).  The router circuit-breaks the
                     dead replicas and deadline-aware-retries what they
                     held; the scenario restarts each crashed replica
                     once (playing supervisor for the in-process
                     transport) so the breaker's half-open probe path
                     runs too.
``straggler``        one replica hangs (``--inject-fault hang@tick``)
                     without crashing — the classic silent wedge.  The
                     router's stall detector (``stall_after_s``) opens
                     its breaker and rescues its in-flight requests
                     onto healthy siblings.
``prefill_crash``    disagg (ISSUE 15): the prefill role dies mid-
                     serve (``crash@tick``).  Requests it held come
                     back ``lost`` and re-route once the scenario
                     restarts it (a supervised child restarts itself);
                     requests already on the spool keep decoding
                     untouched — zero lost, and the handoffs that
                     were in flight at the crash must still conserve.
``decode_crash_midspool``  disagg (ISSUE 15): a decode worker crashes
                     holding claimed-but-unacked handoffs (the
                     ``handoff_crash_preack`` drill).  Nobody
                     restarts it — a PEER decode worker must reclaim
                     the expired leases and finish the redelivered
                     handoffs (scored: zero lost, every uid exactly
                     one non-drained terminal, ``handoff_redelivered``
                     > 0 — the peer really did the work).
``none``             no chaos: route, serve, summarize (the baseline
                     the chaos scores are read against).

Determinism: ThreadReplica ticks only when work exists, so with the
workload pre-submitted before ``start()`` the engine-tick evolution —
and therefore which requests a ``crash@tick`` takes down — is a pure
function of the request stream.  In-process scenario SCORES (status
counts, retries, availability) are exactly reproducible; subprocess
scenarios are scored on invariants (zero lost, availability 1.0) that
hold regardless of host timing.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

SCENARIOS = ("none", "rolling_restart", "crash_storm", "straggler",
             "prefill_crash", "decode_crash_midspool")


def synthetic_specs(n: int, *, vocab_size: int = 256, seed: int = 0,
                    prompt_len=(3, 8), max_new=(3, 10),
                    temperature: float = 0.0, top_k: int = 0,
                    eos_id: Optional[int] = None,
                    deadline_s: Optional[float] = None,
                    uid_prefix: str = "fl") -> List[Dict[str, Any]]:
    """Deterministic request specs for the router (plain dicts — the
    jax-free counterpart of serve/loadgen.synthetic_requests, which
    this module must not import).  Uids are ``<prefix>-0000``-style and
    unique per prefix; the router stamps arrival itself, so there is no
    virtual-step staggering here — fleet arrivals are wall-clock."""
    if n < 1:
        raise ValueError(f"need n >= 1 specs, got {n}")
    rnd = random.Random(seed)
    out: List[Dict[str, Any]] = []
    for i in range(n):
        p = rnd.randint(prompt_len[0], prompt_len[1])
        m = rnd.randint(max_new[0], max_new[1])
        spec: Dict[str, Any] = {
            "uid": f"{uid_prefix}-{i:04d}",
            "prompt": [rnd.randrange(vocab_size) for _ in range(p)],
            "max_new_tokens": m,
            "temperature": temperature,
            "top_k": top_k,
        }
        if eos_id is not None:
            spec["eos_id"] = eos_id
        if deadline_s is not None:
            spec["deadline_s"] = deadline_s
        out.append(spec)
    return out


def _drive(router, until, timeout_s: float,
           poll_interval_s: float = 0.02) -> bool:
    t0 = time.time()
    while True:
        router.poll()
        if until():
            return True
        if time.time() - t0 >= timeout_s:
            return False
        time.sleep(poll_interval_s)


def _wait_up(router, replica, timeout_s: float) -> bool:
    """Poll the router until ``replica`` is healthy AND addressable
    (a ProcReplica has no child pid to signal until its first
    heartbeat lands — interrupting earlier would be a no-op)."""
    def up():
        st = replica.state()
        return st.get("state") == "healthy" \
            and st.get("pid") is not None
    return _drive(router, up, timeout_s)


def _wait_restarted(router, replica, restarts_before: int,
                    timeout_s: float) -> bool:
    """Poll the router (load keeps flowing) until ``replica`` has been
    restarted past ``restarts_before`` AND reports healthy again."""
    def back():
        st = replica.state()
        return st.get("restarts", 0) > restarts_before \
            and st.get("state") == "healthy"
    return _drive(router, back, timeout_s)


def _finish(router, name: str, *, availability_min: float,
            checks: Optional[Dict[str, bool]] = None,
            summary_checks: Optional[Dict[str, Any]] = None
            ) -> Dict[str, Any]:
    """Score the run: verdict "pass" iff nothing was lost, fleet
    availability clears the bar, and every scenario-specific check
    held.  ``summary_checks`` maps check names to predicates over the
    summary record (for invariants only computable at close, like the
    disagg redelivery count).  Writes the fleet_summary and closes the
    router stream."""
    summary = router.summary_record()
    checks = dict(checks or {})
    for key, predicate in (summary_checks or {}).items():
        checks[key] = bool(predicate(summary))
    # v14: an armed SLO spec folds into the scenario score — a chaos
    # run that "passed" on conservation but burned through its error
    # budget in some window is a fail (absent without --slo, so
    # unarmed scenarios score exactly as before).
    ok = (summary["lost"] == 0
          and summary["availability"] >= availability_min
          and summary.get("slo_verdict") != "fail"
          and all((checks or {}).values()))
    router.scenario = name
    router.verdict = "pass" if ok else "fail"
    if router.log:
        failed = [k for k, v in (checks or {}).items() if not v]
        router.log(f"scenario {name}: {router.verdict}  "
                   f"availability={summary['availability']}  "
                   f"lost={summary['lost']}  "
                   f"retries={summary['retries']}  "
                   f"requeued={summary['drained_requeued']}"
                   + (f"  failed_checks={failed}" if failed else ""))
    return router.close()


def run_none(router, replicas, specs, *, timeout_s: float = 120.0,
             availability_min: float = 1.0) -> Dict[str, Any]:
    t0 = time.perf_counter()
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    for spec in specs:
        router.submit(spec)
    done = _drive(router, router.done, timeout_s)
    router.trace_event("X", "scenario:none", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "none", availability_min=availability_min,
                   checks={"completed_in_time": done})


def run_rolling_restart(router, replicas, specs, *,
                        timeout_s: float = 120.0,
                        settle_timeout_s: float = 60.0,
                        availability_min: float = 1.0) -> Dict[str, Any]:
    """Restart every replica in turn while load keeps arriving; zero
    lost requests required.  The spec stream is split into one wave per
    restart plus a lead-in and a tail, so each drain happens with
    requests queued behind it — the requeue-on-drain path MUST run for
    the score to mean anything (asserted via ``drained_requeued`` when
    any wave was pending at interrupt time)."""
    t0 = time.perf_counter()
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    waves = len(replicas) + 2
    per = max(len(specs) // waves, 1)
    chunks = [specs[i * per:(i + 1) * per] for i in range(waves - 1)]
    chunks.append(specs[(waves - 1) * per:])
    for spec in chunks[0]:
        router.submit(spec)
    restarted_all = True
    for i, replica in enumerate(replicas):
        for spec in chunks[i + 1]:
            router.submit(spec)
        restarted_all &= _wait_up(router, replica, settle_timeout_s)
        before = replica.state().get("restarts", 0)
        router.trace_event("i", "interrupt",
                           args={"replica": replica.name})
        replica.interrupt()
        restarted_all &= _wait_restarted(router, replica, before,
                                         settle_timeout_s)
    for spec in chunks[-1]:
        router.submit(spec)
    done = _drive(router, router.done, timeout_s)
    router.trace_event("X", "scenario:rolling_restart", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "rolling_restart",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "every_replica_restarted": restarted_all})


def run_crash_storm(router, replicas, specs, *,
                    crashed_names: List[str],
                    timeout_s: float = 120.0,
                    restart_crashed: bool = True,
                    availability_min: float = 1.0) -> Dict[str, Any]:
    """k replicas are pre-armed (by the caller) with ``crash@tick``
    drills; the scenario submits the full workload up front (the
    deterministic tick evolution), lets the storm happen, restarts each
    crashed replica once, and requires every request to land ok via the
    retry path."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    observed: set = set()
    restarted: set = set()

    def storm_over():
        for replica in replicas:
            if replica.name not in crashed_names:
                continue
            if replica.name not in observed:
                st = replica.state()
                # Either transport's proof the drill actually fired: an
                # in-process replica parks in state "crashed"; a
                # supervised one is restarted quickly, but its
                # supervisor's restart record classifies the death
                # (v10) and the handle surfaces it.  Without this a
                # drill armed past the workload's last tick would
                # never fire and the scenario would score a storm that
                # never happened (review finding, ISSUE 12).
                if st.get("state") == "crashed" \
                        or st.get("classification") in ("crashed",
                                                        "stall_killed"):
                    observed.add(replica.name)
            if restart_crashed and replica.name in observed \
                    and replica.name not in restarted:
                router.trace_event("i", "scenario_restart",
                                   args={"replica": replica.name})
                replica.restart()
                restarted.add(replica.name)
        return router.done()

    done = _drive(router, storm_over, timeout_s)
    router.trace_event("X", "scenario:crash_storm", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "crash_storm",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "every_crash_observed":
                               observed >= set(crashed_names)})


def run_straggler(router, replicas, specs, *,
                  straggler_name: str,
                  timeout_s: float = 120.0,
                  availability_min: float = 1.0) -> Dict[str, Any]:
    """One replica is pre-armed with a ``hang@tick`` drill and the
    router with ``stall_after_s``: the wedge never crashes, the stall
    detector must notice the stopped heartbeat and rescue the hung
    replica's requests onto siblings."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    stalled_seen = {"v": False}

    def until():
        if not stalled_seen["v"]:
            for replica in replicas:
                if replica.name == straggler_name:
                    # The router's view, not the handle's: the stall
                    # verdict lives in the breaker/health layer.
                    stalled_seen["v"] = router.replica_state(
                        straggler_name) == "stalled"
        return router.done()

    done = _drive(router, until, timeout_s)
    router.trace_event("X", "scenario:straggler", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "straggler",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "stall_detected": stalled_seen["v"]})


def run_prefill_crash(router, replicas, specs, *,
                      crashed_name: str,
                      timeout_s: float = 120.0,
                      restart_crashed: bool = True,
                      availability_min: float = 1.0) -> Dict[str, Any]:
    """Disagg chaos (ISSUE 15): the PREFILL role dies mid-serve via a
    pre-armed ``crash@tick`` drill.  Requests it held (queued or
    mid-prefill) come back ``lost`` and the router re-routes them once
    the replica returns (the scenario restarts the in-process replica;
    a supervised child's supervisor does it on its own); requests
    already handed off keep decoding untouched.  Scored on zero lost
    plus the crash really firing and handoffs really flowing."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    observed: set = set()
    restarted: set = set()

    def crash_seen():
        replica = next(r for r in replicas if r.name == crashed_name)
        if crashed_name not in observed:
            st = replica.state()
            if st.get("state") == "crashed" \
                    or st.get("classification") in ("crashed",
                                                    "stall_killed"):
                observed.add(crashed_name)
        if restart_crashed and crashed_name in observed \
                and crashed_name not in restarted:
            router.trace_event("i", "scenario_restart",
                               args={"replica": crashed_name})
            replica.restart()
            restarted.add(crashed_name)
        return router.done()

    done = _drive(router, crash_seen, timeout_s)
    router.trace_event("X", "scenario:prefill_crash", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "prefill_crash",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "crash_observed": crashed_name in observed},
                   summary_checks={
                       "handoffs_flowed":
                           lambda s: s.get("handoffs", 0) > 0,
                       "spool_drained":
                           lambda s: s.get("in_spool", 0) == 0})


def run_decode_crash_midspool(router, replicas, specs, *,
                              crashed_name: str,
                              timeout_s: float = 120.0,
                              availability_min: float = 1.0
                              ) -> Dict[str, Any]:
    """Disagg chaos (ISSUE 15): a decode worker crashes in the
    ack-crash window (the caller arms ``handoff_crash_preack`` on it)
    while holding claimed-but-unacked handoffs.  Nobody restarts it —
    the PEER decode workers must reclaim its expired leases, admit the
    redelivered handoffs and finish them.  Scored on zero lost, every
    uid exactly one non-drained terminal status, the crash really
    firing, and at least one terminal coming from a REDELIVERED
    admission (the peer provably did the reclaimed work)."""
    t0 = time.perf_counter()
    for spec in specs:
        router.submit(spec)
    for replica in replicas:
        replica.start()                 # idempotent on both transports
    observed: set = set()

    def crash_seen():
        if crashed_name not in observed:
            replica = next(r for r in replicas
                           if r.name == crashed_name)
            st = replica.state()
            if st.get("state") == "crashed" \
                    or st.get("classification") in ("crashed",
                                                    "stall_killed"):
                observed.add(crashed_name)
        return router.done()

    done = _drive(router, crash_seen, timeout_s)
    router.trace_event("X", "scenario:decode_crash_midspool", ts=t0,
                       dur=time.perf_counter() - t0)
    return _finish(router, "decode_crash_midspool",
                   availability_min=availability_min,
                   checks={"completed_in_time": done,
                           "crash_observed": crashed_name in observed},
                   summary_checks={
                       "peer_redelivered":
                           lambda s: s.get("handoff_redelivered",
                                           0) > 0,
                       "spool_drained":
                           lambda s: s.get("in_spool", 0) == 0})


def run_scenario(name: str, router, replicas, specs,
                 **kw) -> Dict[str, Any]:
    """Dispatch by scenario name (the ``fleet.py --scenario`` surface)."""
    if name not in SCENARIOS:
        raise ValueError(f"unknown scenario {name!r} "
                         f"(expected one of {SCENARIOS})")
    fn = {"none": run_none,
          "rolling_restart": run_rolling_restart,
          "crash_storm": run_crash_storm,
          "straggler": run_straggler,
          "prefill_crash": run_prefill_crash,
          "decode_crash_midspool": run_decode_crash_midspool}[name]
    return fn(router, replicas, specs, **kw)
