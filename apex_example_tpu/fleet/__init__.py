"""apex_example_tpu.fleet — a jax-free router over N serve replicas.

The fleet stratum composes five prior strata into a multi-replica
deployment (ROADMAP item 5): the supervisor's drain/EX_TEMPFAIL
contract, deterministic fault injection, the burst load generator, the
paged-KV serve engine and cross-restart trace continuity — and scores
the result as a fleet-level availability number under scripted chaos.

- ``fleet/replica.py``    replica handles: an in-process
  :class:`ThreadReplica` over a real ``ServeEngine`` and a
  :class:`ProcReplica` spawning ``tools/supervise.py``-wrapped
  ``serve.py`` children fed through a file-based inbox/outbox.
- ``fleet/router.py``     :class:`FleetRouter`: dispatch policies
  (round_robin / least_pending / least_kv), requeue-on-drain,
  deadline-aware retry, circuit breaking; schema-v10
  ``route``/``replica_state``/``fleet_summary`` records.
- ``fleet/scenarios.py``  scripted chaos (``rolling_restart``,
  ``crash_storm``, ``straggler``) scored into ``fleet_summary``.

Like ``resilience/supervisor.py``, the three modules are **jax-free by
contract** (graftlint-proved) and carry NO package imports, so
``fleet.py`` (the CLI) loads them by file path on hosts without jax;
importing THIS package is the in-process convenience surface (jax is
already loaded by then via ``apex_example_tpu/__init__``).
``tools/fleet_report.py`` renders the router stream.
"""

from apex_example_tpu.fleet.replica import (STATES, ProcReplica,
                                            ThreadReplica,
                                            newest_attempt_path,
                                            tail_records)
from apex_example_tpu.fleet.router import POLICIES, FleetRouter
from apex_example_tpu.fleet.scenarios import (SCENARIOS, run_scenario,
                                              synthetic_specs)

__all__ = [
    "FleetRouter", "POLICIES", "ProcReplica", "SCENARIOS", "STATES",
    "ThreadReplica", "newest_attempt_path", "run_scenario",
    "synthetic_specs", "tail_records",
]
