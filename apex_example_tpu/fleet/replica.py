"""Replica handles for the fleet router: two transports, one contract.

Pure stdlib ON PURPOSE — like resilience/supervisor.py, this module is
**jax-free by contract** (graftlint's static ``jax-free`` rule proves
the whole import closure): the fleet router's job includes surviving
replicas whose jax just died, so the routing layer itself must run on a
bare host.  ``fleet.py`` (the CLI) loads this file by path; importing
it through the package works too once jax is already in the process
(tests, the in-process transport).

A replica handle is whatever the router can ``submit`` to and ``poll``
— the contract is duck-typed, never imported:

``submit(spec) -> bool``   hand one request spec (a plain dict:
                           uid/prompt/max_new_tokens/temperature/top_k/
                           eos_id/deadline_s) to the replica; False
                           means the replica cannot take it right now
                           (draining/dead) and the router re-routes.
``poll() -> [dict]``       terminal events since the last poll:
                           ``{"uid", "status", ...}`` with status one
                           of the serve Completion statuses plus
                           ``lost`` (the replica died holding it — the
                           router's deadline-aware retry input).
``state() -> dict``        a health snapshot: ``state`` (one of
                           :data:`STATES`), ``tick``, ``pending``,
                           ``blocks_live``, ``last_progress``, ``pid``.
``interrupt()``            the rolling-restart chaos action: drain and
                           come back (SIGTERM the serve child / drain
                           the in-process engine and rebuild it).

Two transports:

- :class:`ThreadReplica` wraps a REAL ``ServeEngine`` in-process and
  drives it on a daemon thread.  The engine is built by a caller-
  supplied factory (this module must not import the serve package), so
  token-identity and routing tests ride the session's existing
  SLOTS=4/MAX_LEN=32 compiled decode program — zero new compiles.  The
  drive loop ticks ONLY when work exists (no idle virtual ticks), so a
  ``FaultPlan`` armed at tick N fires at a workload-deterministic
  point: in-process chaos scenarios score deterministically.
- :class:`ProcReplica` spawns a ``tools/supervise.py``-wrapped
  ``serve.py`` child fed through a file-based request INBOX and
  reporting through an append-only completion OUTBOX (``--inbox`` /
  ``--outbox`` on serve.py).  The inbox is replayed and the outbox
  consulted on every supervised restart, so a crashed child re-serves
  exactly the uids that never reached a terminal status — the
  transport self-heals without router involvement, and the router only
  re-routes when the supervisor itself gives up.  Health is tailed
  from the child's metrics JSONL (``replica_state`` heartbeats: last
  tick, queue depth, ``blocks_live`` — the ``least_kv`` policy input)
  and from the supervisor's stream (``restart`` records carry the v10
  exit ``classification``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# Replica lifecycle states the router keys on.  "healthy" accepts
# traffic; "starting" is pre-first-heartbeat (routable — the inbox
# buffers); the rest do not accept new dispatches.
STATES = ("starting", "healthy", "draining", "restarting", "crashed",
          "stopped")

# Keep in sync with apex_example_tpu/serve/queue.py STATUSES — this
# module must not import it (jax-free contract; the serve package pulls
# jax through apex_example_tpu/__init__).  "lost" is fleet-local: the
# replica died holding the request and nobody will ever report it.
TERMINAL_STATUSES = ("ok", "timeout", "shed", "cancelled", "failed",
                     "drained", "rejected", "lost")

TRACE_ID_ENV = "APEX_TRACE_ID"

_TAIL_BYTES = 256 * 1024


def tail_records(path: Optional[str], want: str,
                 tail_bytes: int = _TAIL_BYTES) -> List[Dict[str, Any]]:
    """The ``record == want`` dicts in the bounded TAIL of a JSONL file
    (file order preserved).  Tolerates a missing file, a torn final
    line and the torn first line of the tail window — the supervisor's
    tail_last_step contract, generalized."""
    if not path or not os.path.exists(path):
        return []
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - tail_bytes))
            chunk = fh.read().decode("utf-8", errors="replace")
    except OSError:  # pragma: no cover
        return []
    out: List[Dict[str, Any]] = []
    for line in chunk.splitlines():
        line = line.strip()
        if not line or f'"{want}"' not in line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("record") == want:
            out.append(rec)
    return out


def newest_attempt_path(base: Optional[str]) -> Optional[str]:
    """Where a supervised child is writing NOW: the highest-numbered
    existing ``base.attemptK`` sibling, else ``base`` — the read-side
    mirror of the supervisor's per-attempt metrics rotation."""
    if not base:
        return None
    best, best_n = (base, 0) if os.path.exists(base) else (None, -1)
    parent = os.path.dirname(base) or "."
    prefix = os.path.basename(base) + ".attempt"
    try:
        names = os.listdir(parent)
    except OSError:  # pragma: no cover
        return best
    for name in names:
        if name.startswith(prefix) and name[len(prefix):].isdigit():
            n = int(name[len(prefix):])
            if n > best_n:
                best, best_n = os.path.join(parent, name), n
    return best


# ====================================================== in-process

class ThreadReplica:
    """A real ``ServeEngine`` behind the replica contract.

    ``engine_factory()`` builds a fresh engine with an OPEN queue (the
    caller owns model/params/geometry, so this module stays jax-free);
    ``make_request(spec)`` turns a router spec dict into the engine's
    ``Request`` type.  ``fault`` is an optional serve ``FaultPlan``
    attached to each engine this replica builds — a plan that already
    fired stays inert across restarts, matching the supervisor's
    drop-flag-on-restart semantics for one-shot drills.  Handoff-kind
    plans (``handoff_crash_preack``; resilience/faults.py) fire in the
    decode drive loop instead of the engine, like serve.py.

    Roles (ISSUE 15): ``role="prefill"`` wraps a prefill-role engine —
    queue-driven exactly like "both", its terminal events just carry
    status "handoff" (the router parks those uids on the spool).
    ``role="decode"`` has no queue at all: ``transport_factory()``
    builds its leased spool consumer (serve/disagg.FileTransport with
    ``worker=<replica name>``, supplied by the caller — this module
    imports nothing), the drive loop polls/claims/admits/acks, and
    ``submit`` always refuses (the router never dispatches prompts to
    a decode worker).  A rebuilt decode replica gets a FRESH transport
    under the same worker id, so it adopts its own pre-crash claims.

    The drive thread ticks the engine only when the queue or a slot
    holds work, so virtual time does not advance while idle — a
    ``crash@tick`` drill fires at a point determined by the workload,
    not by host speed.  Any exception escaping ``engine.step()`` IS a
    crash (slot-level isolation already contained everything
    containable): the replica drains its queue and live slots into
    ``lost`` events and parks in state "crashed" until ``restart()``.
    A DECODE crash reports as lost only the uids whose handoffs were
    already acked (their spool files are gone for good); claimed-but-
    unacked handoffs stay on disk, where a peer's lease reclaim — or
    this replica's own restart — redelivers them.
    """

    def __init__(self, name: str, engine_factory: Callable[[], Any],
                 make_request: Optional[Callable[[Dict[str, Any]],
                                                 Any]] = None,
                 fault=None, role: str = "both",
                 transport_factory: Optional[Callable[[], Any]] = None,
                 migrate_factory: Optional[Callable[[], Any]] = None,
                 migrate_intake: bool = True):
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, "
                             f"got {role!r}")
        if role == "decode" and transport_factory is None:
            raise ValueError("a decode-role ThreadReplica needs a "
                             "transport_factory (its intake is the "
                             "handoff spool, not the queue)")
        if migrate_factory is not None and role != "both":
            raise ValueError("live migration (ISSUE 20) needs the "
                             "interleaved engine: only a both-role "
                             "ThreadReplica takes a migrate_factory")
        self.name = name
        self.role = role
        self._factory = engine_factory
        self._make_request = make_request
        self._transport_factory = transport_factory
        # Handoff drills belong to the decode drive loop; everything
        # else is the engine's (tick-indexed) business.  Only the
        # ack-crash drill is expressible HERE — producer-side drills
        # (handoff_torn/sentinel_lost) live on the transport the
        # engine factory builds, and a silently-inert drill would
        # score a chaos run that never happened (the serve.py stance).
        handoff_kind = str(getattr(fault, "kind", "")).startswith(
            ("handoff_", "sentinel_"))
        # handoff_crash_preack is the only drill expressible here, in
        # two intake loops: a decode replica's spool intake, and (ISSUE
        # 20) a both-role replica's MIGRATION intake — the destination
        # dying between admit_migrated and ack, the lease-redelivery
        # window migrate_under_crash_storm scores.
        preack_ok = getattr(fault, "kind", "") == "handoff_crash_preack" \
            and (role == "decode"
                 or (role == "both" and migrate_factory is not None))
        if handoff_kind and not preack_ok:
            raise ValueError(
                f"{name}: ThreadReplica cannot express the "
                f"{fault.kind!r} drill (decode replicas and migration-"
                "armed both replicas take handoff_crash_preack; arm "
                "producer-side drills on the transport inside the "
                "engine factory)")
        self._fault = None if handoff_kind else fault
        self._handoff_fault = fault if handoff_kind else None
        self._migrate_factory = migrate_factory
        # migrate_intake=False makes the replica OUTBOUND-only on the
        # migration spool: it can ship (interrupt(mode="migrate") /
        # migrate(n)) but never claims — the shape for a source being
        # permanently retired, and for deterministic chaos scripts that
        # must control which peer claims.
        self.migrate_intake = bool(migrate_intake)
        self.restarts = 0
        self._lock = threading.Lock()
        self._state = "starting"                # guarded-by: _lock
        self._events: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._consumed = 0
        self._stopping = False                  # guarded-by: _lock
        self._interrupted = False               # guarded-by: _lock
        self._interrupt_mode = "drain"          # guarded-by: _lock
        self._migrate_ask = 0                   # guarded-by: _lock
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._progress = time.perf_counter()
        self.engine = engine_factory()
        self.transport = transport_factory() \
            if transport_factory is not None else None
        # The live-migration spool (ISSUE 20): outbound on
        # interrupt(mode="migrate") / migrate(n), inbound every drive
        # iteration — the same leased claim/ack machinery as the
        # handoff spool, under this replica's worker id.
        self.migrate_tx = migrate_factory() \
            if migrate_factory is not None else None
        if self._fault is not None:
            self.engine.fault = self._fault

    # ------------------------------------------------------- contract

    def submit(self, spec: Dict[str, Any]) -> bool:
        if self.role == "decode":
            return False                # intake is the handoff spool
        with self._lock:
            if self._state not in ("starting", "healthy"):
                return False
            eng = self.engine
        try:
            eng.queue.submit(self._make_request(spec))
        except RuntimeError:            # queue closed under us (drain)
            return False
        self._wake.set()
        return True

    def poll(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._events = self._events, []
        return out

    def state(self) -> Dict[str, Any]:
        with self._lock:
            st = self._state
            eng = self.engine
        # v17: lane-parked requests have left queue.pending()'s view
        # but are still backlog — a tenancy-armed engine reports both
        # through unadmitted() (duck-typed like the gauges below).
        pend_fn = getattr(eng, "unadmitted", None)
        out = {
            "name": self.name,
            "state": st,
            "tick": eng.step_count,
            "pending": pend_fn() if pend_fn is not None
            else eng.queue.pending(),
            "blocks_live": eng.pool.blocks_live(),
            # v12: dtype-accurate bytes (int8 arenas + scales count
            # their true footprint) — what least_kv prefers, so a
            # quantized replica advertises its real headroom.
            "kv_bytes_live": eng.pool.kv_bytes_live(),
            # Seconds since the last completed tick — each transport
            # computes the age in ITS OWN clock domain (perf_counter
            # here, heartbeat wall-time for ProcReplica), so the router
            # never subtracts across domains.
            "progress_age_s": time.perf_counter() - self._progress,
            "pid": os.getpid(),
            "restarts": self.restarts,
        }
        # v14: cumulative SLO latency sketches (duck-typed — only a
        # --slo-armed ServeEngine grows them); the router merges these
        # into fleet_rollup records.
        sketch_fn = getattr(eng, "slo_sketch", None)
        sk = sketch_fn() if sketch_fn is not None else None
        if sk is not None:
            out["slo_sketch"] = sk
        # v15: cumulative host-overhead fraction (duck-typed the same
        # way — only a --tick-profile-armed engine reports one);
        # fleet_report ranks replicas by it.
        frac_fn = getattr(eng, "host_overhead_frac", None)
        frac = frac_fn() if frac_fn is not None else None
        if frac is not None:
            out["host_overhead_frac"] = frac
        # v17: the prefix-cache advertisement (--advertise-prefixes)
        # and per-tenant admitted-token ledger (--tenants) — the
        # prefix_affinity routing inputs and the fleet's budget
        # accounting, both absent unarmed.
        adv_fn = getattr(eng, "prefix_advert", None)
        adv = adv_fn() if adv_fn is not None else None
        if adv is not None:
            out.update(adv)
        ta_fn = getattr(eng, "tenant_admitted", None)
        ta = ta_fn() if ta_fn is not None else None
        if ta is not None:
            out["tenant_admitted"] = ta
        return out

    # ------------------------------------------------------ lifecycle

    def start(self) -> "ThreadReplica":
        """Launch the drive thread (idempotent while one is running).
        Callable before OR after submits — pre-loading the queue then
        starting gives chaos scenarios a fully deterministic tick
        evolution."""
        if self._thread is not None and self._thread.is_alive():
            return self
        with self._lock:
            self._state = "healthy"
        self._thread = threading.Thread(
            target=self._drive, name=f"replica-{self.name}", daemon=True)
        self._thread.start()
        return self

    def interrupt(self, mode: str = "drain") -> None:
        """The rolling-restart action: drain (queued requests come back
        as status "drained" for the router to requeue on siblings),
        then rebuild the engine and return to "healthy" — the
        in-process equivalent of SIGTERM -> exit 75 -> supervised
        restart.

        ``mode="migrate"`` (ISSUE 20) is drain WITHOUT eviction: live
        slots ship to the migration spool (status "migrated") for a
        peer to resume token-identically, instead of finishing here or
        deadline-evicting — the rolling restart that kills no request.
        Needs a ``migrate_factory``."""
        if mode not in ("drain", "migrate"):
            raise ValueError(f"interrupt mode must be drain|migrate, "
                             f"got {mode!r}")
        if mode == "migrate" and self.migrate_tx is None:
            raise ValueError(f"{self.name}: interrupt(mode='migrate') "
                             "needs a migrate_factory (the live-"
                             "migration spool)")
        with self._lock:
            self._interrupted = True
            self._interrupt_mode = mode
            self._state = "draining"    # stop routing to us NOW
        self._wake.set()

    def migrate(self, n: int = 1) -> None:
        """Router-driven rebalance (ISSUE 20): ask the drive thread to
        ship up to ``n`` live requests — deepest fill first (the most
        KV relief per payload), index tie-break — to the migration
        spool at the next tick boundary.  Asynchronous by design: the
        engine is only touched from its own thread, so the effect
        lands as a "migrated" terminal event plus a kv_bytes_live drop
        in a later state() snapshot."""
        if self.migrate_tx is None:
            raise ValueError(f"{self.name}: migrate() needs a "
                             "migrate_factory (the live-migration "
                             "spool)")
        if n < 1:
            raise ValueError(f"migrate n must be >= 1, got {n}")
        with self._lock:
            self._migrate_ask += n
        self._wake.set()

    def restart(self) -> None:
        """Bring a crashed replica back with a fresh engine (the
        scenario script plays supervisor for the in-process
        transport).  The factory's compiled decode step is cached on
        the module-clone config, so no recompile happens here."""
        with self._lock:
            if self._state not in ("crashed", "stopped"):
                raise RuntimeError(
                    f"{self.name}: restart from state {self._state!r}")
        self._rebuild()
        self.start()

    def stop(self, timeout_s: float = 30.0) -> None:
        """Graceful shutdown: close the queue, let in-flight requests
        finish, join the thread."""
        with self._lock:
            self._stopping = True
            eng = self.engine
        eng.queue.close()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout_s)

    # ------------------------------------------------------- internals

    def _rebuild(self) -> None:
        eng = self._factory()
        if self._fault is not None:
            eng.fault = self._fault     # already-fired plans stay inert
        transport = self._transport_factory() \
            if self._transport_factory is not None else None
        migrate_tx = self._migrate_factory() \
            if self._migrate_factory is not None else None
        with self._lock:
            self.engine = eng
            # A fresh transport under the SAME worker id adopts this
            # replica's pre-crash claims on its first poll — the
            # restarted-worker redelivery path (migration spool
            # included).
            self.transport = transport
            self.migrate_tx = migrate_tx
            self._consumed = 0
            self._interrupted = False
            self._interrupt_mode = "drain"
            self._migrate_ask = 0
        self.restarts += 1

    def _emit(self, events: List[Dict[str, Any]]) -> None:
        if events:
            with self._lock:
                self._events.extend(events)

    def _harvest(self, eng) -> None:
        comps = eng.completions
        new = comps[self._consumed:]
        self._consumed = len(comps)
        redelivered = getattr(eng, "handoff_redelivered", ())
        mig_redelivered = getattr(eng, "migration_redelivered", ())
        with_tenant = getattr(eng, "sched", None) is not None
        events = []
        for c in new:
            ev = {"uid": c.request.uid, "status": c.status,
                  "tokens": [int(t) for t in c.tokens],
                  "finish_reason": c.finish_reason,
                  "tick": c.finished_step, "replica": self.name,
                  # v14: per-request latencies ride every terminal
                  # event (None-safe) — the router's SLO plane scores
                  # them against --slo targets without re-deriving
                  # timing from the engine.
                  "ttft_ms": None if c.ttft_s is None
                  else c.ttft_s * 1e3,
                  "tpot_ms": None if c.tpot_s is None
                  else c.tpot_s * 1e3}
            if with_tenant:
                # v17: the lane rides every terminal event so the
                # router's per-tenant SLO windows never re-derive it.
                ev["tenant"] = getattr(c.request, "tenant", "default")
            if c.request.uid in redelivered \
                    or c.request.uid in mig_redelivered:
                ev["redelivered"] = True
            events.append(ev)
        self._emit(events)

    def _drive(self) -> None:
        if self.role == "decode":
            self._drive_decode()
            return
        eng = self.engine
        mig_pending: List[Any] = []
        mig_unacked: set = set()        # admitted, claim still on disk
        mig_admits = 0
        while True:
            with self._lock:
                stopping = self._stopping
                interrupted = self._interrupted
                mode = self._interrupt_mode
                ask, self._migrate_ask = self._migrate_ask, 0
            if interrupted:
                if mode == "migrate" and self.migrate_tx is not None:
                    # Drain WITHOUT eviction (ISSUE 20): live slots
                    # ship to the migration spool for a peer to resume
                    # token-identically; only the un-admitted queue
                    # requeues as "drained".  Deferred inbound claims
                    # stay on disk for the fresh transport / a peer.
                    eng.drain("fleet-interrupt",
                              migrate=self.migrate_tx.send)
                else:
                    eng.drain("fleet-interrupt")
                self._harvest(eng)      # drained/migrated included
                self._rebuild()
                eng = self.engine
                mig_pending = []
                mig_unacked = set()
                with self._lock:
                    self._state = "healthy"
                continue
            mig_tx = self.migrate_tx
            try:
                if mig_tx is not None and self.migrate_intake \
                        and not stopping:
                    # (A stopping — e.g. autoscale-retired — replica
                    # never claims NEW work; payloads it already holds
                    # still drain below.)
                    # Inbound migrations ride the drive loop like the
                    # decode role's handoff intake: poll/claim, renew
                    # deferred admissions (a full pool must not forfeit
                    # a live request to a peer), admit in order, ack.
                    mig_pending.extend(mig_tx.poll())
                    if mig_pending:
                        renew = getattr(mig_tx, "renew", None)
                        if renew is not None:
                            renew(mig_pending)
                    while mig_pending \
                            and eng.admit_handoff(mig_pending[0]):
                        h = mig_pending.pop(0)
                        mig_admits += 1
                        fault = self._handoff_fault
                        if fault is not None \
                                and fault.kind == "handoff_crash_preack" \
                                and fault.due(mig_admits):
                            fault.take()
                            mig_unacked.add(h.uid)
                            raise RuntimeError(
                                f"injected handoff_crash_preack at "
                                f"migration admit {mig_admits} (uid "
                                f"{h.uid} admitted, never acked)")
                        mig_tx.ack(h)
                if mig_tx is not None and ask:
                    # Rebalance ask: ship the deepest-fill live slots
                    # (most KV relief per payload; index tie-break
                    # keeps it deterministic).
                    live = sorted(
                        eng.pool.live,
                        key=lambda j: (-eng.pool.slots[j].cursor, j))
                    for i in live[:ask]:
                        h = eng.extract_live(
                            eng.pool.slots[i].request.uid)
                        if h is not None:
                            mig_tx.send(h)
                    self._harvest(eng)  # the "migrated" terminals
                # v17: a tenancy-armed engine's work view spans intake
                # AND lanes (work_drained/unadmitted); legacy engines
                # fall back to the queue alone (duck-typed like
                # state()'s gauges).
                wd_fn = getattr(eng, "work_drained", None)
                pend_fn = getattr(eng, "runnable_backlog", None)
                if (wd_fn() if wd_fn is not None
                        else eng.queue.drained()) \
                        and not eng.pool.any_live() and not mig_pending:
                    with self._lock:
                        self._state = "stopped"
                    return
                if (pend_fn() if pend_fn is not None
                        else eng.queue.pending()) == 0 \
                        and not eng.pool.any_live():
                    if stopping and not mig_pending:
                        with self._lock:
                            self._state = "stopped"
                        return
                    # Idle: wait for work WITHOUT ticking — virtual
                    # time must not advance, or tick-armed drills would
                    # fire at host-speed-dependent points.
                    self._wake.wait(0.005)
                    self._wake.clear()
                    continue
                eng.step()
                self._progress = time.perf_counter()
            except BaseException as e:  # noqa: BLE001 — a crash IS the event
                lost = [r.uid for r in eng.queue.drain()]
                sched = getattr(eng, "sched", None)
                if sched is not None:
                    lost += [r.uid for r in sched.drain()]
                lost += [eng.pool.slots[i].request.uid
                         for i in eng.pool.live]
                self._harvest(eng)
                done = {c.request.uid for c in eng.completions}
                # Migration claims that were never acked survive on
                # disk — the lease expires and a peer redelivers them,
                # so reporting those uids lost would double-count
                # (mirror of the decode role's acked-only rule).
                self._emit([{"uid": u, "status": "lost",
                             "replica": self.name,
                             "error": f"{type(e).__name__}: {e}"}
                            for u in lost
                            if u not in mig_unacked and u not in done])
                with self._lock:
                    self._state = "crashed"
                return
            self._harvest(eng)

    def _drive_decode(self) -> None:
        """The decode-role drive loop: poll/claim the spool, admit in
        order, ack at admission, tick while slots are live.  Exit when
        the transport is finished (sentinel + empty spool) or the
        replica is stopping with nothing pending.  The
        ``handoff_crash_preack`` drill raises between the Nth admit and
        its ack — the claim survives for redelivery."""
        eng = self.engine
        tx = self.transport
        pending: List[Any] = []
        acked: set = set()              # uids whose claim was deleted
        admits = 0
        while True:
            with self._lock:
                stopping = self._stopping
                interrupted = self._interrupted
            if interrupted:
                # Decode interrupt: finish in-flight (its queue holds
                # nothing), leave unacked claims on disk for the fresh
                # transport / any peer, rebuild.
                eng.drain("fleet-interrupt")
                self._harvest(eng)
                self._rebuild()
                eng, tx = self.engine, self.transport
                pending, acked = [], set()
                with self._lock:
                    self._state = "healthy"
                continue
            try:
                pending.extend(tx.poll())
                if pending:
                    # Deferred admissions must not forfeit their claims
                    # to a peer: renew the leases each tick (duck-typed
                    # — the transport owns the mechanics).
                    renew = getattr(tx, "renew", None)
                    if renew is not None:
                        renew(pending)
                while pending and eng.admit_handoff(pending[0]):
                    handoff = pending.pop(0)
                    admits += 1
                    fault = self._handoff_fault
                    if fault is not None \
                            and fault.kind == "handoff_crash_preack" \
                            and fault.due(admits):
                        fault.take()
                        raise RuntimeError(
                            f"injected handoff_crash_preack at admit "
                            f"{admits} (uid {handoff.uid} admitted, "
                            "never acked)")
                    tx.ack(handoff)
                    acked.add(handoff.uid)
                if eng.pool.any_live():
                    eng.step()
                    self._progress = time.perf_counter()
                else:
                    if not pending and (stopping or tx.finished()):
                        self._harvest(eng)
                        with self._lock:
                            self._state = "stopped"
                        return
                    self._wake.wait(0.01)
                    self._wake.clear()
                self._harvest(eng)
            except BaseException as e:  # noqa: BLE001 — a crash IS the event
                self._harvest(eng)
                # Only acked-and-unfinished uids are lost for good (the
                # spool file is deleted and nobody else ever saw the
                # payload); claimed-but-unacked handoffs redeliver via
                # the lease, so reporting them lost would double-count.
                done = {c.request.uid for c in eng.completions}
                lost = [eng.pool.slots[i].request.uid
                        for i in eng.pool.live
                        if eng.pool.slots[i].request.uid in acked
                        and eng.pool.slots[i].request.uid not in done]
                self._emit([{"uid": u, "status": "lost",
                             "replica": self.name,
                             "error": f"{type(e).__name__}: {e}"}
                            for u in lost])
                with self._lock:
                    self._state = "crashed"
                return


# ====================================================== subprocess

class ProcReplica:
    """A ``tools/supervise.py``-wrapped ``serve.py`` child behind the
    replica contract.

    Filesystem layout under ``workdir`` (all replica-private):

    - ``inbox.jsonl``    router-appended request specs + a final
                         ``{"close": true}`` sentinel; every attempt
                         replays it from byte 0;
    - ``outbox.jsonl``   child-appended terminal events (append-mode,
                         so it SURVIVES restarts — the restarted child
                         reads it to skip already-terminal uids:
                         crash-safe exactly-once);
    - ``serve.jsonl``    the child's metrics stream (rotated
                         ``.attemptK`` by the supervisor) — tailed for
                         ``replica_state`` heartbeats;
    - ``sup.jsonl``      the supervisor's own stream — tailed for
                         ``restart`` records (exit classification).

    ``serve_args`` extends the child argv (geometry, --trace, a
    ``--inject-fault`` drill for crash/straggler scenarios — the
    supervisor strips it on restart, handoff drills included: a
    restarted decode worker replays the spool from its claim set, so
    an operation-ordinal drill would re-fire — and sharding flags: a
    ``--mesh dp,tp`` child serves TP-sharded and its heartbeats carry
    the dtype-accurate ``kv_bytes_live`` gauge ``least_kv`` prefers).
    The spawned tree joins the router's trace via the
    ``APEX_TRACE_ID`` environment handoff.

    Roles (ISSUE 15): ``role="prefill"`` children run ``--role
    prefill`` over a shared ``spool_dir`` (inbox-fed as usual — the
    router routes prompts to them; each handoff lands in the outbox as
    status "handoff"); ``role="decode"`` children run ``--role
    decode`` with NO inbox — the spool is their intake — and report
    terminals through the outbox alone, so ``submit`` always refuses
    and ``close`` is a no-op (a decode child exits when the spool
    closes and drains)."""

    def __init__(self, name: str, workdir: str, repo_root: str,
                 serve_args: Optional[List[str]] = None,
                 supervise_args: Optional[List[str]] = None,
                 python: str = sys.executable,
                 stale_after_s: float = 30.0,
                 role: str = "both",
                 spool_dir: Optional[str] = None):
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, "
                             f"got {role!r}")
        if role != "both" and not spool_dir:
            raise ValueError(f"a {role}-role ProcReplica needs the "
                             "shared spool_dir")
        self.name = name
        self.role = role
        self.spool_dir = spool_dir
        self.workdir = os.path.join(workdir, name)
        os.makedirs(self.workdir, exist_ok=True)
        self.repo_root = repo_root
        self.python = python
        self.stale_after_s = stale_after_s
        self.inbox = os.path.join(self.workdir, "inbox.jsonl")
        self.outbox = os.path.join(self.workdir, "outbox.jsonl")
        self.child_metrics = os.path.join(self.workdir, "serve.jsonl")
        self.sup_metrics = os.path.join(self.workdir, "sup.jsonl")
        self.serve_args = list(serve_args or [])
        self.supervise_args = list(supervise_args or [])
        self.proc: Optional[subprocess.Popen] = None
        self._inbox_fh = None
        self._outbox_pos = 0
        self._routed: List[str] = []
        self._terminal: set = set()
        self._lost_reported = False
        self._closed = False
        # Health-tail cache keyed by (mtime, size): the router polls
        # state() every ~10 ms but heartbeats land every --heartbeat-s
        # — re-reading an unchanged 256 KB tail per poll is pure waste.
        self._tail_cache: Dict[str, Any] = {}

    # ------------------------------------------------------ lifecycle

    def argv(self) -> List[str]:
        sup = os.path.join(self.repo_root, "tools", "supervise.py")
        srv = os.path.join(self.repo_root, "serve.py")
        child = [self.python, srv]
        if self.role != "decode":
            # A decode child's intake is the spool, never an inbox.
            child += ["--inbox", self.inbox]
        child += ["--outbox", self.outbox,
                  "--replica-id", self.name,
                  "--metrics-jsonl", self.child_metrics]
        if self.role != "both":
            child += ["--role", self.role,
                      "--handoff-dir", self.spool_dir]
        return ([self.python, sup, "--no-resume",
                 "--metrics-jsonl", self.sup_metrics,
                 "--drop-flag-on-restart=--inject-fault"]
                + self.supervise_args
                + ["--"] + child + self.serve_args)

    def start(self) -> "ProcReplica":
        """Spawn the supervised serve tree (idempotent while it runs).
        The environment is inherited as-is: the router/CLI sets
        APEX_TRACE_ID in os.environ before spawning, so the whole tree
        (supervisor -> serve child -> restarts) joins ONE trace."""
        if self.proc is not None and self.proc.poll() is None:
            return self
        self.proc = subprocess.Popen(self.argv())
        return self

    def _inbox(self):
        # Lazy: submits are legal before start() (the child replays the
        # inbox from byte 0 whenever it comes up).
        if self._inbox_fh is None:
            self._inbox_fh = open(self.inbox, "a")
        return self._inbox_fh

    def submit(self, spec: Dict[str, Any]) -> bool:
        if self.role == "decode":
            return False                # intake is the handoff spool
        if self._closed or (self.proc is not None
                            and self.proc.poll() is not None):
            return False
        fh = self._inbox()
        fh.write(json.dumps(spec, separators=(",", ":")) + "\n")
        fh.flush()
        self._routed.append(spec["uid"])
        return True

    def close(self) -> None:
        """End-of-stream sentinel: the child finishes what is queued
        and exits 0; the supervisor sees done.  A decode child has no
        inbox — it exits once the SPOOL closes (the prefill child's
        clean exit writes that sentinel) and drains."""
        if self.role == "decode":
            self._closed = True
            return
        if not self._closed:
            fh = self._inbox()
            fh.write('{"close": true}\n')
            fh.flush()
            self._closed = True

    def wait(self, timeout_s: float = 120.0) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            return self.proc.wait(timeout_s)
        except subprocess.TimeoutExpired:  # pragma: no cover
            return None

    def terminate(self) -> None:
        """Tear the whole supervised tree down (fleet shutdown, not
        chaos: SIGTERM to the supervisor forwards to the child AND
        stops the restart loop)."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()

    # ---------------------------------------------------------- chaos

    def _tail_cached(self, path: Optional[str],
                     want: str) -> List[Dict[str, Any]]:
        """``tail_records`` behind an (mtime, size) cache — unchanged
        files cost one stat per poll instead of a 256 KB re-read."""
        if not path:
            return []
        try:
            st = os.stat(path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            return []
        key = (path, want)
        cached = self._tail_cache.get(key)
        if cached is not None and cached[0] == sig:
            return cached[1]
        recs = tail_records(path, want)
        self._tail_cache[key] = (sig, recs)
        return recs

    def child_pid(self) -> Optional[int]:
        """The serve child's pid, from its newest heartbeat."""
        path = newest_attempt_path(self.child_metrics)
        beats = self._tail_cached(path, "replica_state")
        return int(beats[-1]["pid"]) if beats and "pid" in beats[-1] \
            else None

    def interrupt(self, mode: str = "drain") -> Optional[int]:
        """The rolling-restart action: SIGTERM the serve CHILD (not the
        supervisor) — it drains, exits 75, and the supervisor restarts
        it promptly with the metrics stream rotated.  Returns the pid
        signalled (the caller waits for a heartbeat from a DIFFERENT
        pid to confirm the restart landed).

        ``mode`` keeps the ThreadReplica contract shape; for a
        subprocess the drain behavior is decided by the CHILD's
        ``--migrate-dir`` flag (armed at spawn), so both modes send the
        same SIGTERM — a child with a migration spool already drains
        without eviction.

        Idempotent across the restart window (ISSUE 20 satellite): the
        newest heartbeat keeps advertising the OLD attempt's pid until
        the restarted child speaks, so a second interrupt() during an
        in-progress drain or restart would re-SIGTERM a stale — and
        possibly recycled — pid.  The attempt-generation check is
        state()'s draining/restarting detection (last restart record
        newer than the last heartbeat); while it holds, this is a
        no-op returning None."""
        if mode not in ("drain", "migrate"):
            raise ValueError(f"interrupt mode must be drain|migrate, "
                             f"got {mode!r}")
        st = self.state()
        if st["state"] != "healthy":
            return None                 # drain/restart already in flight
        pid = st.get("pid")
        if pid is not None:
            try:
                os.kill(int(pid), signal.SIGTERM)
            except OSError:  # pragma: no cover — raced a crash
                return None
        return pid if pid is None else int(pid)

    # ------------------------------------------------------- contract

    def poll(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        try:
            with open(self.outbox) as fh:
                fh.seek(self._outbox_pos)
                chunk = fh.read()
                # Only consume complete lines; a torn tail is re-read
                # whole on the next poll.
                consumed = chunk.rfind("\n") + 1
                self._outbox_pos += consumed
                for line in chunk[:consumed].splitlines():
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(ev, dict) and "uid" in ev:
                        if ev.get("status") != "drained":
                            self._terminal.add(ev["uid"])
                        ev.setdefault("replica", self.name)
                        out.append(ev)
        except OSError:
            pass                        # child has not opened it yet
        # Supervisor gone (restart budget exhausted, or done): whatever
        # we routed that never reached a terminal status is lost —
        # reported once, for the router's deadline-aware retry.
        if self.proc is not None and self.proc.poll() is not None \
                and self.proc.returncode != 0 and not self._lost_reported:
            self._lost_reported = True
            for uid in self._routed:
                if uid not in self._terminal:
                    out.append({"uid": uid, "status": "lost",
                                "replica": self.name,
                                "error": "supervised replica exited "
                                         f"{self.proc.returncode}"})
        return out

    def state(self) -> Dict[str, Any]:
        st = "healthy"
        rc = self.proc.poll() if self.proc is not None else None
        path = newest_attempt_path(self.child_metrics)
        beats = self._tail_cached(path, "replica_state")
        beat = beats[-1] if beats else {}
        restarts = self._tail_cached(self.sup_metrics, "restart")
        if rc is not None:
            st = "stopped" if rc == 0 else "crashed"
        elif not beats:
            st = "starting"
        elif beat.get("state") == "draining":
            st = "draining"
        elif restarts and "time" in restarts[-1] \
                and "time" in beat \
                and restarts[-1]["time"] > beat["time"]:
            # The supervisor decided a restart after the last heartbeat:
            # the next attempt has not spoken yet.
            st = "restarting"
        out: Dict[str, Any] = {
            "name": self.name,
            "state": st,
            "tick": int(beat.get("tick", 0)),
            "pending": int(beat.get("pending", 0)),
            "blocks_live": int(beat.get("blocks_live", 0)),
            # v12 heartbeats carry the dtype-accurate byte gauge.  A
            # pre-v12 child's heartbeat lacks it — reported as None
            # (NOT 0: an absent gauge must not read as an empty
            # replica), which degrades the router's least_kv to the
            # block count for the whole candidate set.
            "kv_bytes_live": int(beat["kv_bytes_live"])
            if "kv_bytes_live" in beat else None,
            "progress_age_s": (time.time() - float(beat["time"]))
            if "time" in beat else 0.0,
            "pid": beat.get("pid"),
            "restarts": len(restarts),
        }
        if restarts:
            out["classification"] = restarts[-1].get("classification")
            out["exit_code"] = restarts[-1].get("exit_code")
        # v14: a --slo child's heartbeats carry its cumulative latency
        # sketches; absent on pre-v14 (or unarmed) children — never
        # synthesized, so the router's rollup only merges real data.
        if "slo_sketch" in beat:
            out["slo_sketch"] = beat["slo_sketch"]
        # v15: same passthrough for the host-overhead fraction a
        # --tick-profile child advertises.
        if "host_overhead_frac" in beat:
            out["host_overhead_frac"] = beat["host_overhead_frac"]
        # v17: prefix-cache advertisement + per-tenant admission ledger
        # from an --advertise-prefixes / --tenants child; absent on
        # unarmed or pre-v17 children, never synthesized.
        for key in ("prefix_keys", "prefix_shared_tokens",
                    "prefix_prompt_tokens", "tenant_admitted"):
            if key in beat:
                out[key] = beat[key]
        return out
