"""ctypes bindings for the native host runtime (csrc/apex_tpu_host.cpp).

Division of labor mirrors the reference (SURVEY.md §2.1): device math is
XLA/Pallas; the *host* runtime — contiguous staging buffers (the apex_C
flatten/unflatten analog), the synthetic-data generator, uint8→float32
collate, and a double-buffered background prefetcher (the fast_collate +
CUDA-stream-prefetcher analog, SURVEY.md §3.5) — is C++.

The shared library is compiled lazily with g++ on first use and cached next
to the source; everything here degrades gracefully (``available()`` →
False) if no toolchain is present, and pure-Python fallbacks exist in
``apex_example_tpu.data.synthetic``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CSRC = os.path.join(_REPO, "csrc")
_SO = os.path.join(_CSRC, "libapex_tpu_host.so")

_lib = None
_lib_lock = threading.Lock()


def _build() -> Optional[str]:
    src = os.path.join(_CSRC, "apex_tpu_host.cpp")
    if not os.path.exists(src):
        return None
    if (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(src)):
        return _SO
    try:
        subprocess.run(["make", "-C", _CSRC], check=True,
                       capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.SubprocessError):
        return None
    return _SO if os.path.exists(_SO) else None


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        i64, u64, i32 = ctypes.c_int64, ctypes.c_uint64, ctypes.c_int32
        fp, u8p = ctypes.POINTER(ctypes.c_float), ctypes.POINTER(
            ctypes.c_uint8)
        i32p = ctypes.POINTER(i32)
        lib.apex_flatten_f32.restype = i64
        lib.apex_flatten_f32.argtypes = [ctypes.POINTER(fp),
                                         ctypes.POINTER(i64), i64, fp]
        lib.apex_unflatten_f32.restype = i64
        lib.apex_unflatten_f32.argtypes = [fp, ctypes.POINTER(fp),
                                           ctypes.POINTER(i64), i64]
        lib.apex_gen_u8.restype = None
        lib.apex_gen_u8.argtypes = [u64, u64, u8p, i64]
        lib.apex_gen_labels_i32.restype = None
        lib.apex_gen_labels_i32.argtypes = [u64, u64, i32p, i64, i32]
        lib.apex_collate_f32.restype = None
        lib.apex_collate_f32.argtypes = [u8p, i64, i64, i64, fp, fp, fp]
        lib.apex_prefetcher_new.restype = ctypes.c_void_p
        lib.apex_prefetcher_new.argtypes = [i64, i64, i64, i64, u64, fp, fp,
                                            i64]
        lib.apex_prefetcher_next.restype = i64
        lib.apex_prefetcher_next.argtypes = [ctypes.c_void_p, fp, i32p]
        lib.apex_prefetcher_free.restype = None
        lib.apex_prefetcher_free.argtypes = [ctypes.c_void_p]
        lib.apex_lm_prefetcher_new.restype = ctypes.c_void_p
        lib.apex_lm_prefetcher_new.argtypes = [i64, i64, i64, u64, i64, i32,
                                               i32, ctypes.c_float,
                                               ctypes.c_float]
        lib.apex_lm_prefetcher_next.restype = i64
        lib.apex_lm_prefetcher_next.argtypes = [ctypes.c_void_p, i32p, i32p,
                                                fp]
        lib.apex_lm_prefetcher_free.restype = None
        lib.apex_lm_prefetcher_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native library is present (built or buildable)."""
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


# ---------------------------------------------------------------------------
# apex_C analog
# ---------------------------------------------------------------------------

def flatten_f32(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Concatenate float32 arrays into one contiguous fp32 buffer (native).

    Reference: csrc/flatten_unflatten.cpp / ``apex_C.flatten`` — the staging
    step of bucketed collectives and of flat checkpoint/broadcast buffers.
    """
    lib = _load()
    arrays = [np.ascontiguousarray(a, dtype=np.float32) for a in arrays]
    if not arrays:      # keep native and numpy paths consistent
        return np.empty(0, np.float32)
    sizes = np.asarray([a.size for a in arrays], np.int64)
    out = np.empty(int(sizes.sum()), np.float32)
    if lib is None:        # pure-numpy fallback
        np.concatenate([a.ravel() for a in arrays], out=out)
        return out
    Srcs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
        *[_fptr(a) for a in arrays])
    n = lib.apex_flatten_f32(
        Srcs, sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(arrays), _fptr(out))
    assert n == out.size
    return out


def unflatten_f32(flat: np.ndarray,
                  shapes: Sequence[Tuple[int, ...]]) -> List[np.ndarray]:
    """Scatter a contiguous fp32 buffer back into arrays of ``shapes``."""
    lib = _load()
    flat = np.ascontiguousarray(flat, dtype=np.float32)
    outs = [np.empty(s, np.float32) for s in shapes]
    sizes = np.asarray([o.size for o in outs], np.int64)
    assert int(sizes.sum()) == flat.size, "shapes do not tile the buffer"
    if lib is None:
        off = 0
        for o in outs:
            o[...] = flat[off:off + o.size].reshape(o.shape)
            off += o.size
        return outs
    Dsts = (ctypes.POINTER(ctypes.c_float) * len(outs))(
        *[_fptr(o) for o in outs])
    lib.apex_unflatten_f32(
        _fptr(flat), Dsts,
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(outs))
    return outs


# ---------------------------------------------------------------------------
# Native synthetic generator + collate
# ---------------------------------------------------------------------------

def gen_u8(seed: int, start_index: int, n: int) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native host runtime unavailable; gate calls "
                           "with host_runtime.available()")
    out = np.empty(n, np.uint8)
    lib.apex_gen_u8(seed, start_index,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n)
    return out


def collate_f32(frames_u8: np.ndarray, mean: Sequence[float],
                std: Sequence[float]) -> np.ndarray:
    """uint8 [N, H, W, C] → normalized float32 NHWC (native fast_collate)."""
    lib = _load()
    frames_u8 = np.ascontiguousarray(frames_u8, dtype=np.uint8)
    n, h, w, c = frames_u8.shape
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    out = np.empty((n, h, w, c), np.float32)
    if lib is None:
        return ((frames_u8.astype(np.float32) / 255.0 - mean) / std)
    lib.apex_collate_f32(
        frames_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, h * w, c, _fptr(mean), _fptr(std), _fptr(out))
    return out


class NativePrefetcher:
    """Double-buffered background producer of normalized synthetic batches.

    The TPU-native analog of the reference harness's data prefetcher: a C++
    worker thread generates + collates batch i+1 while the device runs batch
    i.  Deterministic in (seed, batch index).  Use as an iterator:

        pf = NativePrefetcher(batch=256, image_size=224, num_classes=1000)
        for _ in range(steps):
            images, labels = next(pf)     # np.float32 NHWC, np.int32
        pf.close()
    """

    MEAN = (0.485, 0.456, 0.406)
    STD = (0.229, 0.224, 0.225)

    def __init__(self, batch: int, image_size: int, num_classes: int,
                 channels: int = 3, seed: int = 0, start_index: int = 0,
                 mean: Optional[Sequence[float]] = None,
                 std: Optional[Sequence[float]] = None,
                 copy: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError("native host runtime unavailable "
                               "(g++ build failed?)")
        mean = list(self.MEAN if mean is None else mean)
        std = list(self.STD if std is None else std)
        if len(mean) < channels or len(std) < channels:
            raise ValueError(
                f"need {channels} per-channel mean/std values, got "
                f"{len(mean)}/{len(std)}")
        self._lib = lib
        self.batch, self.channels = batch, channels
        self.image_size, self.num_classes = image_size, num_classes
        mean = np.asarray(mean[:channels], np.float32)
        std = np.asarray(std[:channels], np.float32)
        self._shape = (batch, image_size, image_size, channels)
        self._copy = copy
        if not copy:
            # Reused staging buffers only exist in view mode; copy mode
            # allocates fresh outputs per call and would leave these dead.
            self._img = np.empty(self._shape, np.float32)
            self._lab = np.empty((batch,), np.int32)
        self._h = lib.apex_prefetcher_new(
            batch, image_size * image_size, channels, num_classes, seed,
            _fptr(mean), _fptr(std), start_index)

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (images, labels): fresh arrays by default.

        With ``copy=False`` the returned arrays are VIEWS of internal
        buffers valid only until the next ``next()`` call.  That mode is
        unsafe to hand to JAX: on the CPU backend ``jnp.asarray``
        zero-copy-aliases large aligned numpy buffers and dispatch is
        async, so reusing the buffer can corrupt a still-pending step.
        Only use it when the consumer synchronously memcpys the data.
        """
        if self._h is None:
            raise StopIteration
        if self._copy:
            # Fresh output buffers per call: the native producer writes
            # straight into them, so fresh-array semantics cost no extra
            # host pass (vs fill-then-copy).
            img = np.empty(self._shape, np.float32)
            lab = np.empty((self.batch,), np.int32)
        else:
            img, lab = self._img, self._lab
        self._lib.apex_prefetcher_next(
            self._h, _fptr(img),
            lab.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return img, lab

    def close(self):
        if self._h is not None:
            self._lib.apex_prefetcher_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class NativeLMPrefetcher:
    """Background producer of LM / masked-LM token batches (C++ worker).

    The language-model counterpart of :class:`NativePrefetcher` (train.py
    ``--host-pipeline`` for ``bert_*``/``transformer_xl``): affine-bigram
    streams with the same learnable structure as
    ``data.synthetic.lm_batch``, deterministic in (seed, batch index),
    ``start_index`` resumes mid-stream.

    Yields ``(input_ids, labels, weights)`` int32/int32/float32 of shape
    (batch, seq_len):
      - ``mlm=True``: BERT 15% / 80-10-10 masking; labels hold the original
        token everywhere; weights are 1.0 exactly at masked positions.
      - ``mlm=False``: causal next-token form; labels are the shifted
        targets, weights all ones.
    """

    def __init__(self, batch: int, seq_len: int, vocab_size: int,
                 mlm: bool, mask_token_id: int = -1, seed: int = 0,
                 start_index: int = 0, mask_prob: float = 0.15,
                 noise_p: float = 0.1):
        lib = _load()
        if lib is None:
            raise RuntimeError("native host runtime unavailable "
                               "(g++ build failed?)")
        if mlm and mask_token_id < 0:
            raise ValueError("mlm=True needs a mask_token_id")
        self._lib = lib
        self.batch, self.seq_len = batch, seq_len
        self._h = lib.apex_lm_prefetcher_new(
            batch, seq_len, vocab_size, seed, start_index, int(mlm),
            mask_token_id, mask_prob, noise_p)

    def __iter__(self):
        return self

    def __next__(self):
        if self._h is None:
            raise StopIteration
        ids = np.empty((self.batch, self.seq_len), np.int32)
        lab = np.empty((self.batch, self.seq_len), np.int32)
        w = np.empty((self.batch, self.seq_len), np.float32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        self._lib.apex_lm_prefetcher_next(
            self._h, ids.ctypes.data_as(i32p), lab.ctypes.data_as(i32p),
            _fptr(w))
        return ids, lab, w

    def close(self):
        if self._h is not None:
            self._lib.apex_lm_prefetcher_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
