"""Weight quantization for the serve path (checkpoint-restore time).

serve.py restores (or random-inits) a params pytree, hands it to
:func:`quantize_params`, and the engine's compiled decode step calls
:func:`dequantize_tree` as its FIRST traced op — so int8/fp8 bytes are
what sit in HBM and stream into the step, and the dequant is a
scale-fused convert+multiply XLA folds into each consuming matmul.
Nothing about the model changes: the step function sees the same
f32 params it always did, one fused multiply later.

WHICH leaves quantize is an AMP-policy question, answered by the same
op-classification tables that drive O1 casting (amp/lists.py): a leaf
is mapped to its op class (``kernel`` -> dense, ``embedding`` ->
embedding, norm scale/bias -> layer_norm, anything else -> bias-like)
and only classes in ``lists.INT8_FUNCS`` quantize — layernorm
parameters, biases and the fp32 lm head bias stay high-precision
exactly like softmax/norms stay fp32 under O1 (amp/policy.QuantPolicy
is the bundled spelling).

Granularity: symmetric PER-CHANNEL scales —

- ``kernel`` [in, out] (and conv [..., in, out]): one scale per OUTPUT
  channel (max-abs over all input axes), the per-column scheme that
  keeps each output feature's dynamic range independent;
- ``embedding`` [vocab, hidden]: one scale per vocab ROW (each row is
  gathered whole per token, and rows differ in norm far more than
  hidden channels do).

A quantized leaf is replaced by a ``{"qvalue", "scale"}`` dict (both
jax arrays, so the pytree flattens straight through jit);
``dequantize_tree`` restores the original structure/dtype in-trace.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from apex_example_tpu.amp import lists
from apex_example_tpu.quant import core

MODES = ("int8", "fp8")

# Leaf name -> the amp/lists op class whose quant eligibility applies.
_LEAF_OP_CLASS = {
    "kernel": "dense",
    "embedding": "embedding",
    "scale": "layer_norm",
    "bias": "layer_norm",
}


def _leaf_op_class(path) -> str:
    name = getattr(path[-1], "key", getattr(path[-1], "name",
                                            str(path[-1])))
    return _LEAF_OP_CLASS.get(name, "bias")


def is_quantized_leaf(x: Any) -> bool:
    return isinstance(x, dict) and "qvalue" in x and "scale" in x


def _channel_axes(path, ndim: int) -> Tuple[int, ...]:
    """Axes the max-abs reduces over (the complement of the scale
    axes): everything but the last for kernels, everything but the
    FIRST for embeddings (per-row)."""
    name = getattr(path[-1], "key", getattr(path[-1], "name",
                                            str(path[-1])))
    if name == "embedding":
        return tuple(range(1, ndim))
    return tuple(range(ndim - 1))


def quantize_params(params: Any, mode: str = "int8"
                    ) -> Tuple[Any, Dict[str, Any]]:
    """Quantize every eligible leaf of ``params``; returns
    ``(quantized_tree, stats)`` where stats feeds the ``quant_event``
    record (schema v11): tensor counts, byte totals, scale spread.
    """
    if mode not in MODES:
        raise ValueError(f"weight quant mode must be one of {MODES}, "
                         f"got {mode!r}")
    qmax = core.INT8_QMAX if mode == "int8" else core.FP8_QMAX
    stats = {"tensors": 0, "kept": 0, "bytes_before": 0,
             "bytes_after": 0, "scale_min": float("inf"),
             "scale_max": 0.0, "emulated": False}

    def one(path, leaf):
        nbytes = leaf.size * jnp.dtype(leaf.dtype).itemsize
        stats["bytes_before"] += int(nbytes)
        # issubdtype, not dtype.kind: bfloat16's numpy kind is 'V'
        # (void), and a kind check would silently skip every bf16 leaf.
        if (not jnp.issubdtype(leaf.dtype, jnp.floating) or leaf.ndim < 2
                or lists.quant_classify(_leaf_op_class(path)) != "quant"):
            stats["kept"] += 1
            stats["bytes_after"] += int(nbytes)
            return leaf
        axes = _channel_axes(path, leaf.ndim)
        # Scales keep the ORIGINAL param dtype: dequantize_tree reads
        # the output dtype off the scale leaf (a traced array cannot
        # carry a dtype string through the pytree).  Narrow BEFORE
        # quantizing — rounding must happen against the STORED scale
        # for the documented bound to hold (quant/core.py; same order
        # as quant/kv.quantize_write).
        scale = core.abs_max_scale(leaf, axis=axes,
                                   qmax=qmax).astype(leaf.dtype)
        # The f32 floor can flush to 0 in a narrower storage dtype
        # (fp16's tiny ~6e-8 >> SCALE_EPS): re-floor so an all-zero
        # channel quantizes to zeros, never 0/0 = NaN.
        scale = jnp.maximum(scale, jnp.finfo(leaf.dtype).tiny)
        if mode == "int8":
            q = core.quantize_int8(leaf, scale)
        else:
            q, emulated = core.quantize_fp8(leaf, scale)
            stats["emulated"] = stats["emulated"] or emulated
        stats["tensors"] += 1
        stats["bytes_after"] += int(
            q.size * jnp.dtype(q.dtype).itemsize
            + scale.size * jnp.dtype(scale.dtype).itemsize)
        smin = float(jnp.min(scale))
        smax = float(jnp.max(scale))
        stats["scale_min"] = min(stats["scale_min"], smin)
        stats["scale_max"] = max(stats["scale_max"], smax)
        return {"qvalue": q, "scale": scale}

    out = jax.tree_util.tree_map_with_path(one, params)
    if stats["tensors"] == 0:
        stats["scale_min"] = 0.0
    return out, stats


def dequantize_tree(params: Any) -> Any:
    """Restore a :func:`quantize_params` tree to plain arrays — called
    INSIDE the compiled step (the dequant is part of the traced
    program; the int8/fp8 leaves are its arguments)."""
    return jax.tree_util.tree_map(
        lambda x: core.dequantize(x["qvalue"], x["scale"],
                                  x["scale"].dtype)
        if is_quantized_leaf(x) else x,
        params, is_leaf=is_quantized_leaf)
