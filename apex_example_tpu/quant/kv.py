"""Quantized paged-KV helpers: the in-step scatter/gather numerics.

The block arena (serve/slots.py geometry, models/bert.py execution)
stores int8 K/V with BLOCK-RESIDENT scales: per layer, alongside each
``[NB, BS, H, D]`` int8 arena sits a ``[NB, BS]`` bf16 scale table —
one symmetric max-abs scale per cached token (the [H, D] vector a
block row holds).  Scales live AT block granularity in the arena, so
every block operation carries them for free:

- the tick's scatter writes ``quantize_write``'s int8 rows and their
  scales through the SAME flat block-table indices,
- a copy-on-write duplicates the scale rows with the payload rows
  (diverging a shared block must not re-derive scales the original
  tokens were quantized under),
- prefix sharing refs whole blocks, scales included — a shared system
  prompt's KV is quantized once and read by every sharer.

Per-token (not per-whole-block) scales are what make partial writes
composable: a block fills across several chunked-prefill ticks, and a
single running block scale would force requantization of rows written
under an earlier max.  bf16 scale storage halves the overhead vs f32
and costs <= 2^-9 relative scale error — quantization rounds against
the STORED scale (quant/core.py), so the round-trip bound still holds
exactly.

Per-token bytes at gpt_tiny geometry (H*D = 64): 64 int8 + 2 scale =
66 per K or V vs 128 bf16 — a 1.94x compression the ci_gate
``--quant-stream`` floor (>= 1.9x) keys on.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from apex_example_tpu.quant import core

KV_SCALE_DTYPE = jnp.bfloat16


def quantize_write(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize one tick's K (or V) span ``[S, C, H, D]`` for the arena
    scatter: returns ``(q int8 [S, C, H, D], scales KV_SCALE_DTYPE
    [S, C])`` — one max-abs scale per token over its [H, D] vector,
    rounded to storage precision BEFORE the division so dequant against
    the stored scale is exact to the int8 grid."""
    scale = core.abs_max_scale(x, axis=(-2, -1),
                               keepdims=False).astype(KV_SCALE_DTYPE)
    q = core.quantize_int8(x, scale[..., None, None])
    return q, scale


def dequantize_gather(q: jnp.ndarray, scale: jnp.ndarray,
                      dtype) -> jnp.ndarray:
    """Dequantize a gathered logical view ``[S, L, H, D]`` with its
    ``[S, L]`` scales — the scale-fused multiply the attention einsum
    consumes directly inside the compiled step."""
    return core.dequantize(q, scale[..., None, None], dtype)
