"""Symmetric low-bit quantization numerics — the shared substrate.

Everything in this module is pure element-wise jnp math, trace-safe
(no python branching on values) and cheap to run eagerly, so the same
functions serve three consumers at three call depths:

- **weights** (quant/weights.py): per-channel int8 / fp8 applied ONCE
  at checkpoint-restore time; dequant re-enters the compiled decode
  step as a scale-fused multiply (XLA fuses convert+mul into the
  consuming dot_general — the int8 bytes are what HBM streams).
- **paged KV** (models/bert.py slot_decode): per-token block scales
  quantized on the arena scatter, dequantized in the gathered
  attention inside the one compiled step.
- **gradients** (parallel/distributed.py): per-chunk shared-scale int8
  psum for DDP gradient exchange (EQuARX-shaped; PAPERS.md).

Scheme: symmetric max-abs.  ``scale = amax / Q`` with Q = 127 (int8)
or 448 (float8_e4m3 max normal); ``q = round(x / scale)`` clipped into
[-Q, Q]; ``dequant = q * scale``.  The clip matters: scales are stored
in a NARROWER dtype than the f32 amax (bf16 for KV block scales), and
a scale rounded DOWN makes ``amax / scale`` land just above Q — an
unclipped int8 cast would wrap to -Q.  Error bound (round-to-nearest):
``|x - dq| <= scale / 2`` element-wise for unclipped values and
``<= scale`` at the clipped extreme — tests/test_quant.py pins both as
pure-numpy assertions.

fp8: the rig's jax (0.4.37) carries ``jnp.float8_e4m3fn``; where a
deployment's jax lacks it, :func:`fp8_dtype` returns None and callers
fall back to EMULATED fp8 — values rounded onto the e4m3 grid but
stored in bf16 (value parity for accuracy studies, no byte win) — the
gate the ISSUE asks for instead of a hard dependency.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

INT8_QMAX = 127.0
# float8_e4m3fn: 4 exponent / 3 mantissa bits, max normal 448 (no inf).
FP8_QMAX = 448.0
# Floor for max-abs scales: an all-zero channel/block must quantize to
# zeros, not NaNs, and the floor is far below any scale a finite
# nonzero tensor produces.
SCALE_EPS = 1e-30


def fp8_dtype() -> Optional[jnp.dtype]:
    """The rig's fp8 storage dtype, or None when this jax predates it
    (callers then emulate on the e4m3 grid in bf16)."""
    dt = getattr(jnp, "float8_e4m3fn", None)
    return jnp.dtype(dt) if dt is not None else None


def abs_max_scale(x: jnp.ndarray, axis=None, qmax: float = INT8_QMAX,
                  keepdims: bool = True) -> jnp.ndarray:
    """Symmetric max-abs scale over ``axis`` (None = whole tensor),
    floored so all-zero slices stay finite."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                   keepdims=keepdims)
    return jnp.maximum(amax / qmax, SCALE_EPS)


def quantize_int8(x: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Round onto the int8 grid of ``scale`` (which may be narrower
    than f32 — the division uses the STORED scale so the round trip's
    error bound holds against it, not against an f32 ideal)."""
    q = jnp.round(x.astype(jnp.float32) / scale.astype(jnp.float32))
    return jnp.clip(q, -INT8_QMAX, INT8_QMAX).astype(jnp.int8)


def dequantize(q: jnp.ndarray, scale: jnp.ndarray,
               dtype=jnp.float32) -> jnp.ndarray:
    """``q * scale`` in f32, cast to ``dtype`` — the scale-fused
    multiply XLA folds into the consuming matmul/attention op."""
    return (q.astype(jnp.float32)
            * scale.astype(jnp.float32)).astype(dtype)


def quantize_fp8(x: jnp.ndarray,
                 scale: jnp.ndarray) -> Tuple[jnp.ndarray, bool]:
    """fp8(e4m3) cast of ``x / scale`` clipped into +-448.  Returns
    ``(q, emulated)``: with native fp8 support q is float8_e4m3fn;
    without, q holds the e4m3-grid values in bf16 (emulated=True)."""
    scaled = jnp.clip(x.astype(jnp.float32) / scale.astype(jnp.float32),
                      -FP8_QMAX, FP8_QMAX)
    dt = fp8_dtype()
    if dt is not None:
        return scaled.astype(dt), False
    # Emulation: round through the e4m3 value grid, keep bf16 storage.
    # bf16 has e4m3's exponent reach and MORE mantissa, so rounding via
    # a 3-bit mantissa mask is exact enough for parity studies.
    return _round_e4m3(scaled).astype(jnp.bfloat16), True


def _round_e4m3(x: jnp.ndarray) -> jnp.ndarray:
    """Round f32 values onto the e4m3 representable grid (emulation
    path only — no native fp8 dtype in this jax)."""
    # Snap the mantissa to 3 bits: scale each value so its exponent
    # aligns, round, and undo.  frexp/ldexp keep this exact in f32.
    m, e = jnp.frexp(x.astype(jnp.float32))
    m3 = jnp.round(m * 16.0) / 16.0          # 1+3 mantissa bits
    return jnp.ldexp(m3, e)
