"""Quantization stratum (ISSUE 13; ROADMAP item 3).

Three consumers, one numerics module:

- ``quant.weights`` — int8/fp8 per-channel weight quantization applied
  at checkpoint-restore time in serve.py; dequant runs inside the
  compiled decode step (scale-fused matmul).
- ``quant.kv`` — int8 paged-KV block scales: quantize on the arena
  scatter, dequantize in the gathered attention (models/bert.py),
  scales copied with their blocks under COW/prefix sharing.
- ``parallel/distributed.py`` consumes ``quant.core`` for the DDP
  quantized-allreduce mode (per-chunk shared-scale int8 psum).

Casting POLICY (which op classes may drop to int8) lives with the AMP
engine: amp/lists.INT8_FUNCS + amp/policy.QuantPolicy.
"""

from apex_example_tpu.quant import core, kv, weights
from apex_example_tpu.quant.core import (FP8_QMAX, INT8_QMAX,
                                         abs_max_scale, dequantize,
                                         fp8_dtype, quantize_fp8,
                                         quantize_int8)
from apex_example_tpu.quant.kv import (KV_SCALE_DTYPE, dequantize_gather,
                                       quantize_write)
from apex_example_tpu.quant.weights import (dequantize_tree,
                                            is_quantized_leaf,
                                            quantize_params)

__all__ = [
    "FP8_QMAX", "INT8_QMAX", "KV_SCALE_DTYPE", "abs_max_scale",
    "core", "dequantize", "dequantize_gather", "dequantize_tree",
    "fp8_dtype", "is_quantized_leaf", "kv", "quantize_fp8",
    "quantize_int8", "quantize_params", "quantize_write", "weights",
]
