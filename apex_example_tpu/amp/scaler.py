"""Loss scaling as traced state (reference: apex/amp/scaler.py LossScaler).

The reference's dynamic scaler lives on the host: it launches a CUDA kernel to
detect inf/nan, syncs the flag back, and python-side halves the scale / skips
``optimizer.step()``.  On TPU that host sync would stall the pipeline, so the
entire protocol — scale, finite-check, skip, backoff, growth — runs *inside*
the jitted step on traced values:

- ``ScalerState`` is a pytree carried in the train state.
- ``scale_loss``   multiplies the loss before ``jax.grad``.
- ``unscale_grads`` multiplies grads by 1/scale and returns an all-finite flag
  (the ``amp_C.multi_tensor_scale`` + overflow-check path, SURVEY.md §4.3).
- ``update``       applies the apex schedule: on overflow scale *= 0.5 and the
  step is skipped by the caller (select old params); after ``growth_interval``
  consecutive clean steps scale *= 2.

Defaults match the reference: init scale 2**16, growth interval 2000.  A
static scaler is the degenerate case (``dynamic=False``): scale is constant
and the finite check is elided so it costs nothing under bf16.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from apex_example_tpu.amp.policy import Policy


@struct.dataclass
class ScalerState:
    """Pytree state of the loss scaler; lives inside the train state."""
    scale: jnp.ndarray            # f32 scalar
    growth_counter: jnp.ndarray   # i32 scalar: consecutive finite steps
    dynamic: bool = struct.field(pytree_node=False, default=False)
    growth_interval: int = struct.field(pytree_node=False, default=2000)
    growth_factor: float = struct.field(pytree_node=False, default=2.0)
    backoff_factor: float = struct.field(pytree_node=False, default=0.5)
    # Static fact "scale is exactly 1.0 forever" (bf16 O1/O2 default).  The
    # scale held in the state is a *traced* array, so without this flag the
    # no-op unscale multiply stays in the compiled step as a full read+write
    # of every grad (XLA cannot constant-fold a dynamic scalar into the
    # opaque Pallas optimizer kernels that consume the grads).
    identity: bool = struct.field(pytree_node=False, default=False)


def make_scaler(policy: Policy,
                init_scale: float = 2.0 ** 16,
                growth_interval: int = 2000) -> ScalerState:
    if policy.uses_dynamic_scaling:
        return ScalerState(scale=jnp.asarray(init_scale, jnp.float32),
                           growth_counter=jnp.asarray(0, jnp.int32),
                           dynamic=True, growth_interval=growth_interval)
    static = policy.static_scale
    return ScalerState(scale=jnp.asarray(static, jnp.float32),
                       growth_counter=jnp.asarray(0, jnp.int32),
                       dynamic=False, identity=(static == 1.0))


def _pick(scaler, loss_id: int) -> "ScalerState":
    """Multi-loss support (reference: amp.initialize(..., num_losses=N) makes
    one LossScaler per loss; scale_loss takes loss_id — upstream exercises
    this in test_multiple_models_optimizers_losses.py).  A scaler argument
    may be a single ScalerState or a sequence of them."""
    if isinstance(scaler, (tuple, list)):
        return scaler[loss_id]
    return scaler


def scale_loss(loss: jnp.ndarray, scaler, loss_id: int = 0) -> jnp.ndarray:
    """``with amp.scale_loss(loss, opt) as scaled_loss`` — the enter half."""
    scaler = _pick(scaler, loss_id)
    if scaler.identity:
        return loss
    return loss * scaler.scale.astype(loss.dtype)


def all_finite(tree: Any) -> jnp.ndarray:
    """True iff every element of every leaf is finite."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack(
        [jnp.all(jnp.isfinite(l)) for l in leaves]).all()


def unscale_grads(grads: Any, scaler, loss_id: int = 0
                  ) -> Tuple[Any, jnp.ndarray]:
    """The ``scale_loss.__exit__`` half: grads /= scale, inf/nan check.

    Returns (unscaled_grads, grads_finite).  When the scaler is statically
    known to be the identity (bf16 O1/O2: static scale 1.0) the whole pass is
    elided — the multiply would otherwise survive compilation as a full HBM
    read+write of every grad, because the traced scale defeats constant
    folding (see ScalerState.identity).  The finite check is only
    materialized for dynamic scalers (callers gate on ``scaler.dynamic``).
    """
    scaler = _pick(scaler, loss_id)
    if scaler.identity and not scaler.dynamic:
        return grads, jnp.asarray(True)
    inv = (1.0 / scaler.scale)
    grads = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
    if scaler.dynamic:
        finite = all_finite(grads)
    else:
        finite = jnp.asarray(True)
    return grads, finite


def update(scaler, grads_finite: jnp.ndarray, loss_id: int = 0):
    """Apex growth/backoff schedule, fully traced (no host sync).

    With a sequence of scalers, only ``loss_id``'s entry is updated (each
    loss has its own overflow history); the full sequence is returned."""
    if isinstance(scaler, (tuple, list)):
        new = update(scaler[loss_id], grads_finite)
        return type(scaler)(
            new if i == loss_id else s for i, s in enumerate(scaler))
    if not scaler.dynamic:
        return scaler
    counter = jnp.where(grads_finite, scaler.growth_counter + 1,
                        jnp.zeros_like(scaler.growth_counter))
    grow = counter >= scaler.growth_interval
    new_scale = jnp.where(
        grads_finite,
        jnp.where(grow, scaler.scale * scaler.growth_factor, scaler.scale),
        scaler.scale * scaler.backoff_factor)
    counter = jnp.where(grow, jnp.zeros_like(counter), counter)
    return scaler.replace(scale=new_scale, growth_counter=counter)


def select_tree(pred: jnp.ndarray, on_true: Any, on_false: Any) -> Any:
    """Per-leaf ``where`` used for the skip-step path (apex: overflow =>
    optimizer.step() is skipped; here: select old state when not finite)."""
    return jax.tree_util.tree_map(
        lambda t, f: jnp.where(pred, t, f), on_true, on_false)


def state_dict(scaler) -> dict:
    """Serializable scaler state (reference: amp.state_dict(); the loss-scale
    survives checkpoint/resume — upstream tests this in test_checkpointing).
    A sequence of scalers (num_losses > 1) serializes each in order, the way
    apex's state_dict carries one ``loss_scalerN`` entry per loss."""
    if isinstance(scaler, (tuple, list)):
        return {"scalers": [state_dict(s) for s in scaler]}
    return {"scale": float(scaler.scale),
            "growth_counter": int(scaler.growth_counter),
            "dynamic": scaler.dynamic}


def load_state_dict(scaler, d: dict):
    if isinstance(scaler, (tuple, list)):
        if len(d["scalers"]) != len(scaler):
            raise ValueError(
                f"checkpoint carries {len(d['scalers'])} loss scalers but "
                f"this run was initialized with num_losses={len(scaler)}")
        return type(scaler)(
            load_state_dict(s, sd) for s, sd in zip(scaler, d["scalers"]))
    scale = float(d["scale"])
    return scaler.replace(
        scale=jnp.asarray(scale, jnp.float32),
        growth_counter=jnp.asarray(d["growth_counter"], jnp.int32),
        # Re-derive the static identity fact from the loaded value: a resumed
        # static scaler may carry a different scale than the fresh policy.
        identity=(not scaler.dynamic and scale == 1.0))
