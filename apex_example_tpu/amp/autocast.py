"""The O1 cast engine: white/blacklist tables → per-boundary dtypes.

Reference: apex/amp/{wrap,amp,utils}.py (SURVEY.md §3.1) — O1 monkey-patches
torch functions so each call casts its arguments per the op lists, leaving
the model itself fp32.  JAX traces pure functions, so the same semantics are
realized *structurally*: :func:`op_dtype` answers "what dtype does this op
class run in under this policy", and the framework's modules ask it at their
call-site boundaries.  :func:`module_dtypes` bundles the answers for the ops
our model families contain, and is what model builders consume.

Behavioral contract (and how O1 differs from its neighbors):

  op class      O0     O1                O2                O3
  conv/dense    fp32   half              half              half
  batch_norm    fp32   fp32 (I/O+stats)  half I/O,         half
                                         fp32 stats
  layer_norm    fp32   fp32 (I/O+stats)  half I/O,         half I/O
                                         fp32 stats        (fp32 stats: the
                                                           kernel contract)
  softmax       fp32   fp32              fp32              half
  loss          fp32   fp32              fp32              half-ish (logits
                                                           cast by caller)

Under O2 the *model* is half (minus BN stats) — casting is a property of
model construction, exactly apex's ``model.half()``.  Under O1 params stay
fp32 and only whitelisted boundaries drop to half.  O3 ignores the lists
entirely (pure half).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Optional, Tuple

import jax.numpy as jnp

from apex_example_tpu.amp import lists
from apex_example_tpu.amp.policy import Policy


# amp.handle.disable_casts analog (apex/amp/handle.py): inside the context,
# the O1 engine answers fp32 for every op class — the escape hatch for
# custom fp32 regions.  Casts resolve at TRACE time (python), and traces may
# run on several threads (parallel jit warmup), so the flag is thread-local
# exactly like the reference's.
_TLS = threading.local()


@contextlib.contextmanager
def disable_casts():
    """Run a (traced) region with O1 call-site casting forced to fp32."""
    saved = getattr(_TLS, "casts_disabled", False)
    _TLS.casts_disabled = True
    try:
        yield
    finally:
        _TLS.casts_disabled = saved


def op_dtype(policy: Policy, op: str,
             *operand_dtypes) -> Optional[jnp.dtype]:
    """The dtype op-class ``op`` runs in under ``policy``; None = no opinion
    (caller keeps its configured dtype).

    Only O1 (``cast_at_call_sites``) consults the lists — O0/O2/O3 configure
    dtypes at model construction, like the reference's whole-model cast.
    """
    if not policy.cast_at_call_sites:
        return None
    if getattr(_TLS, "casts_disabled", False):
        return jnp.dtype(jnp.float32)
    cls = lists.classify(op)
    if cls == "half":
        return policy.compute_dtype
    if cls == "float":
        return jnp.dtype(jnp.float32)
    if cls == "promote":
        if operand_dtypes:
            return jnp.result_type(*operand_dtypes)
        return None
    return None


def cast_args(policy: Policy, op: str, *arrays) -> Tuple:
    """Cast arrays per the op classification (identity when the policy has
    no opinion).  The call-site form of apex's wrapped functions."""
    dts = [a.dtype for a in arrays]
    d = op_dtype(policy, op, *dts)
    if d is None:
        return arrays if len(arrays) != 1 else arrays[0]
    out = tuple(a.astype(d) for a in arrays)
    return out if len(out) != 1 else out[0]


@dataclasses.dataclass(frozen=True)
class ModuleDtypes:
    """Resolved per-op-class dtypes for one policy — what model builders
    thread into module constructors."""
    compute: jnp.dtype        # conv/dense/matmul (whitelist)
    bn_io: jnp.dtype          # BatchNorm input/output
    bn_stats: jnp.dtype       # BatchNorm moment/normalization math
    ln_io: jnp.dtype          # LayerNorm input/output
    softmax: jnp.dtype        # attention probabilities / softmax math
    param: jnp.dtype          # parameter storage


def module_dtypes(policy: Policy) -> ModuleDtypes:
    """Derive every module-boundary dtype from the policy + op lists.

    O2/O3 reproduce the whole-model-cast semantics (bn_io follows the
    compute dtype; ``keep_batchnorm_fp32`` only keeps the *stats* fp32 —
    the way cuDNN realizes it).  O1 consults the lists: blacklisted norm
    ops run wholly in fp32, I/O included.
    """
    f32 = jnp.dtype(jnp.float32)
    if policy.cast_at_call_sites:      # O1
        conv = op_dtype(policy, "conv") or policy.compute_dtype
        return ModuleDtypes(
            compute=conv,
            bn_io=op_dtype(policy, "batch_norm") or conv,
            bn_stats=op_dtype(policy, "batch_norm") or policy.bn_dtype,
            ln_io=op_dtype(policy, "layer_norm") or conv,
            softmax=op_dtype(policy, "softmax") or conv,
            param=policy.param_dtype)
    half_everything = policy.opt_level == "O3"
    return ModuleDtypes(
        compute=policy.compute_dtype,
        bn_io=policy.compute_dtype,
        bn_stats=policy.bn_dtype,
        ln_io=policy.compute_dtype,
        softmax=(policy.compute_dtype if half_everything else f32),
        param=policy.param_dtype)
