"""amp op-classification lists + registration API.

Reference: apex/amp/lists/{functional,torch,tensor}_overrides.py (SURVEY.md
§3.1) — the whitelist (run in half: conv/mm/addmm...), blacklist (run in
fp32: softmax/log/exp/norm/loss...), and promote list (mixed-input ops take
the widest input dtype), consumed by the O1 monkey-patcher, plus the
``amp.register_{half,float,promote}_function`` extension points.

TPU-native restatement: JAX has no torch-function interception point, so the
lists are keyed by *op-class names* that the framework's modules consult at
call-site boundaries (amp/autocast.py).  The registration API mutates the
same tables, so user extensions work the way apex's do — the delta (module
boundary granularity, not individual tensor-method patching) is documented
in amp/policy.py.
"""

from __future__ import annotations

# Run in the half compute dtype (MXU ops — where the FLOPs are).
FP16_FUNCS = {
    "conv", "conv1d", "conv2d", "conv3d", "conv_transpose",
    "dense", "linear", "matmul", "mm", "bmm", "addmm", "einsum",
    "attention_scores", "attention_context", "embedding",
}

# Run in fp32 (numerically sensitive: large reductions, exp/log families,
# losses, normalization statistics).
FP32_FUNCS = {
    "softmax", "log_softmax", "batch_norm", "sync_batch_norm", "layer_norm",
    "group_norm", "instance_norm", "cross_entropy", "nll_loss", "mse_loss",
    "exp", "log", "pow", "sum", "mean", "var", "std", "norm", "cumsum",
    "softplus", "sigmoid_focal_loss", "gelu_fp32",
}

# Mixed-dtype inputs are promoted to the widest participating dtype.
PROMOTE_FUNCS = {
    "add", "sub", "mul", "div", "addcmul", "addcdiv", "cat", "stack",
    "where", "residual_add",
}

# Op classes whose WEIGHTS may drop to int8/fp8 for serving (ISSUE 13):
# the MXU-fed storage-bound classes — dense/conv kernels and embedding
# tables, where per-channel symmetric scales bound the error and the
# dequant fuses into the consuming matmul.  Everything outside this set
# (norm statistics, biases, softmax — the FP32_FUNCS sensitivity story)
# keeps its high-precision storage: quantizing a layernorm scale saves
# nothing and moves the normalization point.
INT8_FUNCS = {
    "conv", "conv1d", "conv2d", "conv3d", "conv_transpose",
    "dense", "linear", "matmul", "mm", "bmm", "addmm", "einsum",
    "embedding",
}


def register_half_function(name: str) -> None:
    """apex parity: ``amp.register_half_function(module, fn_name)`` — adds an
    op class to the whitelist (string-keyed here; there is no module object
    to patch)."""
    _move(name, FP16_FUNCS)


def register_float_function(name: str) -> None:
    _move(name, FP32_FUNCS)


def register_promote_function(name: str) -> None:
    _move(name, PROMOTE_FUNCS)


def register_quant_function(name: str) -> None:
    """Extension point mirroring the half/float registrations: mark an
    op class's weights as int8/fp8-eligible (quant/weights.py consults
    this at checkpoint-restore time).  Quant eligibility is orthogonal
    to the half/float COMPUTE classification, so this does not move the
    name between those tables."""
    INT8_FUNCS.add(name)


def _move(name: str, target: set) -> None:
    for s in (FP16_FUNCS, FP32_FUNCS, PROMOTE_FUNCS):
        s.discard(name)
    target.add(name)


def classify(name: str) -> str:
    """'half' | 'float' | 'promote' | 'none' for an op-class name."""
    if name in FP16_FUNCS:
        return "half"
    if name in FP32_FUNCS:
        return "float"
    if name in PROMOTE_FUNCS:
        return "promote"
    return "none"


def quant_classify(name: str) -> str:
    """'quant' | 'keep' for an op-class name: may this class's weights
    drop to int8/fp8 for serving?  FP32_FUNCS membership wins over an
    INT8_FUNCS entry — a class someone registered as numerically
    sensitive must never quantize."""
    if name in FP32_FUNCS:
        return "keep"
    return "quant" if name in INT8_FUNCS else "keep"
