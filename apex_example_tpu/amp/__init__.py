"""apex.amp-shaped frontend over the TPU-native policy engine.

``amp.initialize`` in the reference (apex/amp/frontend.py) mutates a torch
model/optimizer in place.  Here it returns the immutable pieces the jitted
train step consumes: a :class:`Policy` and a :class:`ScalerState`.  The rest
of the reference surface (``scale_loss``, ``state_dict``/``load_state_dict``,
``master_params``) maps onto the functions below.
"""

from apex_example_tpu.amp.autocast import (ModuleDtypes, cast_args,
                                           disable_casts, module_dtypes,
                                           op_dtype)
from apex_example_tpu.amp.lists import (quant_classify,
                                        register_float_function,
                                        register_half_function,
                                        register_promote_function,
                                        register_quant_function)
from apex_example_tpu.amp.policy import (Policy, QuantPolicy, get_policy,
                                         get_quant_policy,
                                         opt_level_table)
from apex_example_tpu.amp.scaler import (
    ScalerState, all_finite, load_state_dict, make_scaler, scale_loss,
    select_tree, state_dict, unscale_grads, update as update_scaler)

__all__ = [
    "ModuleDtypes", "Policy", "QuantPolicy", "ScalerState", "all_finite",
    "cast_args", "disable_casts", "get_policy", "get_quant_policy",
    "initialize", "load_state_dict", "make_scaler",
    "module_dtypes", "op_dtype", "opt_level_table", "quant_classify",
    "register_float_function", "register_half_function",
    "register_promote_function", "register_quant_function", "scale_loss",
    "select_tree", "state_dict", "unscale_grads", "update_scaler",
]


def initialize(opt_level: str = "O0", loss_scale=None,
               keep_batchnorm_fp32=None, half_dtype=None,
               init_scale: float = 2.0 ** 16, growth_interval: int = 2000,
               num_losses: int = 1):
    """apex-parity entry point: returns ``(policy, scaler_state)``.

    Reference: ``amp.initialize(model, optimizer, opt_level=..., ...)``.
    JAX models are pure, so there is no model/optimizer object to patch; the
    caller threads the policy into model construction (``compute_dtype`` etc.)
    and the scaler state into the train step.  See harness/train.py for the
    end-to-end wiring.

    ``num_losses > 1`` returns a tuple of independent scalers (a pytree);
    pass ``loss_id`` to ``scale_loss``/``unscale_grads``/``update_scaler``.
    The reference keeps one LossScaler per loss for the same reason: each
    loss has its own overflow history.  This form is for CUSTOM multi-loss
    train steps — the stock engine/workloads steps consume exactly one
    scaler (their TrainState and metrics read ``scaler.scale`` directly).
    """
    import jax.numpy as jnp
    policy = get_policy(opt_level, loss_scale=loss_scale,
                        keep_batchnorm_fp32=keep_batchnorm_fp32,
                        half_dtype=half_dtype or jnp.bfloat16)
    mk = lambda: make_scaler(policy, init_scale=init_scale,
                             growth_interval=growth_interval)
    if num_losses > 1:
        return policy, tuple(mk() for _ in range(num_losses))
    return policy, mk()
