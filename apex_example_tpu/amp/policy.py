"""Precision policies: the TPU-native restatement of apex.amp opt levels.

Reference semantics (apex/amp/frontend.py opt-level property table; SURVEY.md
§3.1): each of O0–O3 is a bundle of properties — ``cast_model_type``,
``patch_torch_functions``, ``keep_batchnorm_fp32``, ``master_weights``,
``loss_scale``.  The reference realizes them by mutating a torch model
(``.half()``), monkey-patching torch functions, and patching the optimizer.

TPU-native realization: JAX programs are pure functions traced once, so a
precision policy is *data threaded into the trace*, not a mutation.  A
:class:`Policy` carries the dtypes; models receive ``compute_dtype`` /
``param_dtype`` / ``bn_dtype`` at construction, the train step scales the loss
by ``scaler.scale`` and unscales grads.  There is nothing to patch — the policy
IS the configuration of the traced program.

dtype mapping (SURVEY.md §3.1 "TPU mapping"): fp16-on-GPU becomes bf16-on-TPU.
bf16 has fp32's exponent range, so overflow-driven *dynamic* loss scaling is
unnecessary for bf16 — O1/O2 default to static scale 1.0 on TPU.  The dynamic
scaler is still fully implemented (scaler.py) for API parity and for fp16
experiments; pass ``loss_scale="dynamic"`` to enable it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    """Bundle of precision properties for one opt level.

    Attributes:
      opt_level: "O0" | "O1" | "O2" | "O3" (apex names, kept for CLI parity).
      param_dtype: storage dtype of the *model* params.  When
        ``master_weights`` is True this is the dtype params are cast to at
        application time while fp32 masters are kept — in JAX we invert the
        arrangement: params are stored fp32 (they ARE the masters) and cast to
        ``compute_dtype`` inside the forward pass.  ``param_dtype`` therefore
        only drops below fp32 for O3 (no master weights).
      compute_dtype: dtype of matmuls/convs/activations (the MXU dtype).
      bn_dtype: dtype BatchNorm/LayerNorm statistics run in
        (``keep_batchnorm_fp32`` in the reference).
      master_weights: whether fp32 copies back the updates (O2).  With the
        fp32-params-as-masters arrangement this decides whether ``param_dtype``
        stays fp32.
      loss_scale: None => static 1.0; a float => static that value; "dynamic"
        => dynamic loss scaling (scaler.py).
      cast_at_call_sites: O1's per-op white/blacklist semantics.  JAX has no
        torch-function interception point; the honest equivalent is
        boundary-level casting driven by the op-classification tables in
        amp/lists.py.  When this flag is set, ``amp.module_dtypes(policy)``
        resolves each op class through those tables (whitelist → half,
        blacklist → fp32, promote → widest input) and the builders thread
        the results into model construction — so under O1 convs/dense run
        half while batch_norm/layer_norm/softmax run wholly fp32, unlike O2
        (whole model half, only norm *stats* fp32).  The semantic delta vs
        per-call monkey-patching (module-boundary granularity) is documented
        here rather than hidden; tests/test_amp.py pins the behavioral
        differences between O1, O2 and O3.
    """

    opt_level: str
    param_dtype: jnp.dtype
    compute_dtype: jnp.dtype
    bn_dtype: jnp.dtype
    master_weights: bool
    loss_scale: Union[None, float, str]
    cast_at_call_sites: bool = False

    @property
    def uses_dynamic_scaling(self) -> bool:
        return self.loss_scale == "dynamic"

    @property
    def static_scale(self) -> float:
        if self.loss_scale is None:
            return 1.0
        if self.loss_scale == "dynamic":
            raise ValueError("dynamic policy has no static scale")
        return float(self.loss_scale)

    def replace(self, **kw) -> "Policy":
        return dataclasses.replace(self, **kw)


def _mk(opt_level, param_dtype, compute_dtype, bn_dtype, master_weights,
        loss_scale, cast_at_call_sites=False):
    return Policy(opt_level, jnp.dtype(param_dtype), jnp.dtype(compute_dtype),
                  jnp.dtype(bn_dtype), master_weights, loss_scale,
                  cast_at_call_sites)


# The opt-level table (reference: apex/amp/frontend.py O0..O3 property dicts).
# half_dtype picks the reduced dtype: bf16 is TPU-native; fp16 kept selectable
# for parity experiments.
def opt_level_table(half_dtype=jnp.bfloat16):
    h = jnp.dtype(half_dtype)
    f = jnp.dtype(jnp.float32)
    return {
        # O0: pure fp32 no-op.
        "O0": _mk("O0", f, f, f, master_weights=False, loss_scale=None),
        # O1: params fp32, per-boundary casts, numerically-sensitive ops fp32.
        # Dynamic scaling in the reference; static 1.0 for bf16 (see module
        # docstring), dynamic when half_dtype is fp16.
        "O1": _mk("O1", f, h, f, master_weights=False,
                  loss_scale="dynamic" if h == jnp.float16 else None,
                  cast_at_call_sites=True),
        # O2: model compute in half except BN; fp32 master weights.
        "O2": _mk("O2", f, h, f, master_weights=True,
                  loss_scale="dynamic" if h == jnp.float16 else None),
        # O3: everything half, static scale 1.0 (speed ceiling / debugging).
        "O3": _mk("O3", h, h, h, master_weights=False, loss_scale=1.0),
    }


@dataclasses.dataclass(frozen=True)
class QuantPolicy:
    """The serving-time quantization bundle (ISSUE 13) — the AMP policy
    engine's answer to "what runs below bf16", the way :class:`Policy`
    answers "what runs below fp32".

    Attributes:
      weight_mode: "none" | "int8" | "fp8" — storage dtype of the
        quant-eligible weight classes (amp/lists.INT8_FUNCS; norms,
        biases and softmax stay high-precision per FP32_FUNCS, the same
        sensitivity tables O1 casting consults).
      kv_int8: store the paged KV arenas as int8 with bf16 per-token
        block scales (quant/kv.py).
      emulate_fp8: set by the builder when this jax has no native
        float8_e4m3fn — values ride the e4m3 grid in bf16 (accuracy
        parity, no byte win).

    The casting RULES stay in the op tables (lists.quant_classify);
    this dataclass is configuration, exactly like Policy vs lists.
    """

    weight_mode: str = "none"
    kv_int8: bool = False
    emulate_fp8: bool = False

    @property
    def weight_dtype_name(self) -> str:
        if self.weight_mode == "fp8":
            return "fp8_e4m3_emulated" if self.emulate_fp8 \
                else "float8_e4m3"
        return self.weight_mode if self.weight_mode != "none" \
            else "float32"

    @property
    def any_armed(self) -> bool:
        return self.kv_int8 or self.weight_mode != "none"


def get_quant_policy(weight_mode: str = "none",
                     kv_int8: bool = False) -> QuantPolicy:
    """Resolve a :class:`QuantPolicy`, detecting fp8 emulation (the
    gate on missing jnp.float8_e4m3fn the ISSUE requires instead of a
    hard dependency)."""
    if weight_mode not in ("none", "int8", "fp8"):
        raise ValueError(f"weight quant mode must be none|int8|fp8, "
                         f"got {weight_mode!r}")
    emulate = False
    if weight_mode == "fp8":
        from apex_example_tpu.quant import core as _qcore
        emulate = _qcore.fp8_dtype() is None
    return QuantPolicy(weight_mode=weight_mode, kv_int8=kv_int8,
                       emulate_fp8=emulate)


def get_policy(opt_level: str,
               loss_scale: Union[None, str, float] = None,
               keep_batchnorm_fp32: Optional[bool] = None,
               half_dtype=jnp.bfloat16) -> Policy:
    """Look up an opt level and apply the same overrides amp.initialize takes.

    Mirrors ``amp.initialize(opt_level=..., loss_scale=...,
    keep_batchnorm_fp32=...)`` (reference: apex/amp/frontend.py).  String
    "dynamic" or a number for ``loss_scale``; ``keep_batchnorm_fp32`` flips
    ``bn_dtype``.
    """
    table = opt_level_table(half_dtype)
    key = opt_level.upper()
    if key not in table:
        raise ValueError(
            f"Unexpected optimization level {opt_level!r}; options are "
            f"'O0', 'O1', 'O2', 'O3'.")
    p = table[key]
    if loss_scale is not None:
        if isinstance(loss_scale, str) and loss_scale != "dynamic":
            loss_scale = float(loss_scale)
        p = p.replace(loss_scale=loss_scale)
    if keep_batchnorm_fp32 is not None:
        p = p.replace(bn_dtype=jnp.dtype(jnp.float32) if keep_batchnorm_fp32
                      else p.compute_dtype)
    return p
