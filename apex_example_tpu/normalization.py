"""FusedLayerNorm / MixedFusedLayerNorm modules.

Reference: apex/normalization/fused_layer_norm.py — an nn.LayerNorm drop-in
whose forward/backward are the CUDA extension (SURVEY.md §3.4).  Here the
module wraps the Pallas ``layer_norm`` op (ops/layer_norm.py), which carries
its own custom VJP; on non-TPU backends it lowers to the XLA reference path.

MixedFusedLayerNorm semantics (half in/out, fp32 params and statistics) are
the ``dtype``/``param_dtype`` split: stats are always fp32 inside the kernel,
params default to fp32, output matches the input dtype.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from apex_example_tpu.ops.layer_norm import layer_norm, rms_norm


class FusedLayerNorm(nn.Module):
    """LayerNorm over the last axis, backed by the Pallas kernel."""

    epsilon: float = 1e-5
    dtype: Optional[jnp.dtype] = None       # output dtype (None: follow input)
    param_dtype: jnp.dtype = jnp.float32
    use_bias: bool = True
    use_scale: bool = True

    @nn.compact
    def __call__(self, x):
        feat = x.shape[-1]
        if self.use_scale:
            scale = self.param("scale", nn.initializers.ones, (feat,),
                               self.param_dtype)
        else:
            scale = jnp.ones((feat,), self.param_dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (feat,),
                              self.param_dtype)
        else:
            bias = jnp.zeros((feat,), self.param_dtype)
        y = layer_norm(x, scale, bias, self.epsilon)
        return y.astype(self.dtype) if self.dtype is not None else y


MixedFusedLayerNorm = FusedLayerNorm


class FusedRMSNorm(nn.Module):
    """RMSNorm over the last axis, backed by the Pallas kernel.

    Reference: the later apex ``FusedRMSNorm`` (same extension module as
    FusedLayerNorm, SURVEY.md §3.4) — LayerNorm without mean subtraction or
    bias; stats fp32, ``elementwise_affine`` ⇔ ``use_scale``.
    """

    epsilon: float = 1e-5
    dtype: Optional[jnp.dtype] = None       # output dtype (None: follow input)
    param_dtype: jnp.dtype = jnp.float32
    use_scale: bool = True

    @nn.compact
    def __call__(self, x):
        feat = x.shape[-1]
        if self.use_scale:
            scale = self.param("scale", nn.initializers.ones, (feat,),
                               self.param_dtype)
        else:
            scale = jnp.ones((feat,), self.param_dtype)
        y = rms_norm(x, scale, self.epsilon)
        return y.astype(self.dtype) if self.dtype is not None else y


MixedFusedRMSNorm = FusedRMSNorm
