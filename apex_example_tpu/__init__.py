"""apex_example_tpu — a TPU-native training framework.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities exercised by the
CUDA/NCCL reference ``enijkamp/apex_example`` (NVIDIA Apex mixed precision +
distributed data-parallel training; see SURVEY.md for the full reference
analysis).  Nothing here is a port: the compute path is jit/shard_map over a
named device mesh with XLA collectives, precision is a policy applied at trace
time (not monkey-patching), and the fused CUDA extensions are Pallas TPU
kernels.

Public surface (mirrors the reference's import points, SURVEY.md §2):

- ``apex_example_tpu.amp``       — O0–O3 precision policies + loss scaling
  (reference: ``apex/amp/`` frontend.py/scaler.py).
- ``apex_example_tpu.parallel``  — mesh-based data parallelism, SyncBatchNorm,
  LARC (reference: ``apex/parallel/``).
- ``apex_example_tpu.optim``     — FusedAdam / FusedLAMB / FusedSGD as optax
  gradient transformations backed by Pallas kernels (reference:
  ``apex/optimizers/``).
- ``apex_example_tpu.ops``       — Pallas kernels + XLA reference impls
  (reference: ``csrc/``).
- ``apex_example_tpu.normalization`` — FusedLayerNorm module (reference:
  ``apex/normalization/fused_layer_norm.py``).
- ``apex_example_tpu.models``    — ResNet-18/50, BERT-base, Transformer-XL in
  Flax (imported, not implemented, by the reference).
- ``apex_example_tpu.data``      — synthetic data pipelines (no datasets or
  network in this environment; see SURVEY.md §5).
"""

__version__ = "0.1.0"

from apex_example_tpu import amp  # noqa: F401
from apex_example_tpu import parallel  # noqa: F401
from apex_example_tpu import optim  # noqa: F401

optimizers = optim  # apex-compatible alias: ``apex.optimizers``
