"""Tenant specs: who is asking, what they may spend, how they are
judged (ISSUE 19).

Pure stdlib (jax-free by the graftlint contract): tenant specs are
parsed by serve.py AND by the fleet router/loadgen side, which must
run on hosts whose jax is the thing that died.

A ``--tenants`` spec is a ``;``-separated list of tenant clauses::

    name[:key=value[,key=value...]]

with keys

    weight=FLOAT   DWRR weight (relative admission share), default 1
    budget=INT     total token budget (prompt + max_new per admitted
                   request); omitted = unlimited
    class=STR      SLO class: ``interactive`` (TTFT-critical lane,
                   preempts batch admission) or ``batch`` (default)
    mix=FLOAT      loadgen arrival share (relative), default 1
    burst=INT      loadgen burst size for this tenant, default 1
    shared_prefix=INT
                   loadgen per-tenant shared warm prefix length,
                   default 0

e.g. ``--tenants "prod:weight=4,class=interactive;scraper:weight=1,budget=400"``.

Unknown tenants encountered at admission auto-lane with DEFAULT_SPEC
semantics (weight 1, no budget, batch) — a fleet never drops a request
because a replica's spec list lagged the router's.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

DEFAULT_TENANT = "default"

SLO_CLASSES = ("interactive", "batch")


@dataclass(frozen=True)
class TenantSpec:
    name: str
    weight: float = 1.0
    budget: Optional[int] = None     # total tokens; None = unlimited
    slo_class: str = "batch"
    # loadgen-only shaping knobs (ignored by the scheduler):
    mix: float = 1.0
    burst: int = 1
    shared_prefix: int = 0


DEFAULT_SPEC = TenantSpec(name=DEFAULT_TENANT)

_KEYS = ("weight", "budget", "class", "mix", "burst", "shared_prefix")


def parse_tenants(spec: str) -> Dict[str, TenantSpec]:
    """Parse a ``--tenants`` spec into an ordered name->TenantSpec map.

    Raises ValueError with a pointed message on malformed input —
    serve.py/fleet.py turn that into a SystemExit at flag-validation
    time, before any engine spins up.
    """
    out: Dict[str, TenantSpec] = {}
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        name, _, body = clause.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"--tenants: empty tenant name in {clause!r}")
        if name in out:
            raise ValueError(f"--tenants: duplicate tenant {name!r}")
        kw: Dict[str, object] = {}
        if body:
            for item in body.split(","):
                item = item.strip()
                if not item:
                    continue
                key, eq, val = item.partition("=")
                key = key.strip()
                val = val.strip()
                if not eq or not val:
                    raise ValueError(
                        f"--tenants: expected key=value, got {item!r} "
                        f"in tenant {name!r}")
                if key not in _KEYS:
                    raise ValueError(
                        f"--tenants: unknown key {key!r} in tenant "
                        f"{name!r} (known: {', '.join(_KEYS)})")
                if key == "weight":
                    kw["weight"] = float(val)
                    if kw["weight"] <= 0:
                        raise ValueError(
                            f"--tenants: weight must be > 0 in tenant "
                            f"{name!r}, got {val}")
                elif key == "budget":
                    kw["budget"] = int(val)
                    if kw["budget"] < 0:
                        raise ValueError(
                            f"--tenants: budget must be >= 0 in tenant "
                            f"{name!r}, got {val}")
                elif key == "class":
                    if val not in SLO_CLASSES:
                        raise ValueError(
                            f"--tenants: class must be one of "
                            f"{'|'.join(SLO_CLASSES)} in tenant "
                            f"{name!r}, got {val!r}")
                    kw["slo_class"] = val
                elif key == "mix":
                    kw["mix"] = float(val)
                    if kw["mix"] <= 0:
                        raise ValueError(
                            f"--tenants: mix must be > 0 in tenant "
                            f"{name!r}, got {val}")
                elif key == "burst":
                    kw["burst"] = int(val)
                    if kw["burst"] < 1:
                        raise ValueError(
                            f"--tenants: burst must be >= 1 in tenant "
                            f"{name!r}, got {val}")
                elif key == "shared_prefix":
                    kw["shared_prefix"] = int(val)
                    if kw["shared_prefix"] < 0:
                        raise ValueError(
                            f"--tenants: shared_prefix must be >= 0 in "
                            f"tenant {name!r}, got {val}")
        out[name] = TenantSpec(name=name, **kw)
    if not out:
        raise ValueError("--tenants: no tenants in spec")
    return out


def tenant_names(specs: Dict[str, TenantSpec]) -> List[str]:
    """Spec order = lane visit order (and loadgen substream index
    order) — insertion-ordered dicts make this deterministic."""
    return list(specs)
